"""Bench trend gate (tools/bench_trend.py): per-(metric, backend)
baselines with median+MAD noise bands over fabricated BENCH_HISTORY
files — a 20% throughput regression must gate, an in-band wiggle must
not, and ``backend: unavailable`` diagnostic rows must be tolerated."""

import importlib
import json
import os
import sys

import pytest

HERE = os.path.dirname(os.path.abspath(__file__))


def _trend():
    sys.path.insert(0, os.path.join(os.path.dirname(HERE), "tools"))
    try:
        return importlib.import_module("bench_trend")
    finally:
        sys.path.pop(0)


def _row(metric, value, backend="cpu", started_at=None, **kw):
    r = {"metric": metric, "value": value, "unit": "u",
         "vs_baseline": None, "backend": backend, **kw}
    if started_at is not None:
        r["run"] = {"git_sha": "abc", "started_at": started_at,
                    "backend": backend, "host": "h", "pid": 1}
    return r


def _write(path, rows):
    with open(path, "w") as f:
        for r in rows:
            f.write(json.dumps(r) + "\n")


HIST_SPS = [11000.0, 10500.0, 10800.0, 11200.0, 10900.0]


def test_regression_flagged_and_in_band_passes():
    bt = _trend()
    history = [_row("ctr_dnn_samples_per_sec", v) for v in HIST_SPS]
    # 20% below the 10900 median: far outside max(10% rel band, 3*MAD)
    bad = bt.compare([_row("ctr_dnn_samples_per_sec", 8720.0)], history)
    assert [v["status"] for v in bad] == ["regression"]
    assert bad[0]["direction"] == "higher"
    # 2% below: inside the 10% floor
    ok = bt.compare([_row("ctr_dnn_samples_per_sec", 10682.0)], history)
    assert [v["status"] for v in ok] == ["ok"]
    # and a 20% IMPROVEMENT never gates
    up = bt.compare([_row("ctr_dnn_samples_per_sec", 13080.0)], history)
    assert [v["status"] for v in up] == ["ok"]


def test_lower_is_better_direction():
    bt = _trend()
    history = [_row("pass_boundary_gap_ms", v)
               for v in [50.0, 52.0, 48.0, 51.0]]
    worse = bt.compare([_row("pass_boundary_gap_ms", 70.0)], history)
    assert [v["status"] for v in worse] == ["regression"]
    better = bt.compare([_row("pass_boundary_gap_ms", 40.0)], history)
    assert [v["status"] for v in better] == ["ok"]


def test_backends_never_cross_and_min_history():
    bt = _trend()
    history = [_row("m_samples_per_sec", v, backend="tpu")
               for v in HIST_SPS]
    # cpu candidate vs tpu-only history: no baseline, never a regression
    v = bt.compare([_row("m_samples_per_sec", 10.0, backend="cpu")],
                   history)
    assert [x["status"] for x in v] == ["no_baseline"]
    v = bt.compare([_row("m_samples_per_sec", 10.0, backend="tpu")],
                   history[:2])
    assert [x["status"] for x in v] == ["no_baseline"]


def test_unavailable_rows_tolerated_both_sides():
    bt = _trend()
    history = ([_row("m_samples_per_sec", v) for v in HIST_SPS]
               + [_row("m_samples_per_sec", None, backend="unavailable",
                       error_kind="backend_init_hang")] * 3)
    # unavailable rows poison neither the baseline...
    v = bt.compare([_row("m_samples_per_sec", 10900.0)], history)
    assert [x["status"] for x in v] == ["ok"]
    assert v[0]["n_history"] == 5
    # ...nor the verdict when the CANDIDATE is an outage row
    v = bt.compare(
        [_row("m_samples_per_sec", None, backend="unavailable")], history)
    assert [x["status"] for x in v] == ["unavailable"]


def test_mad_band_absorbs_noisy_history():
    bt = _trend()
    # noisy group: MAD ~ 1000, so 3*MAD dominates the 10% floor
    history = [_row("noisy_samples_per_sec", v)
               for v in [10000.0, 12000.0, 9000.0, 11000.0, 13000.0]]
    v = bt.compare([_row("noisy_samples_per_sec", 8200.0)], history)
    assert [x["status"] for x in v] == ["ok"]  # inside 3*MAD


def test_split_last_run_and_cli_exit_codes(tmp_path):
    bt = _trend()
    hist = tmp_path / "BENCH_HISTORY.jsonl"
    rows = [_row("ctr_dnn_samples_per_sec", v, started_at=float(i))
            for i, v in enumerate(HIST_SPS)]
    # the newest run regressed 20%
    rows.append(_row("ctr_dnn_samples_per_sec", 8720.0, started_at=99.0))
    _write(hist, rows)
    history, current = bt.split_last_run(bt.load_rows(str(hist)))
    assert len(current) == 1 and current[0]["value"] == 8720.0
    assert len(history) == 5
    assert bt.main(["--history", str(hist)]) == 1
    # replace the regressed row with an in-band one: gate passes
    rows[-1] = _row("ctr_dnn_samples_per_sec", 10682.0, started_at=99.0)
    _write(hist, rows)
    assert bt.main(["--history", str(hist)]) == 0
    # --list and empty-history paths exit 0
    assert bt.main(["--history", str(hist), "--list"]) == 0
    assert bt.main(["--history", str(tmp_path / "missing.jsonl")]) == 0


def test_malformed_and_unstamped_lines_skipped(tmp_path):
    bt = _trend()
    p = tmp_path / "h.jsonl"
    with open(p, "w") as f:
        f.write(json.dumps(_row("m_samples_per_sec", 1.0)) + "\n")
        f.write("{truncated\n")
        f.write("\n")
        f.write(json.dumps({"no_metric": 1}) + "\n")
    assert len(bt.load_rows(str(p))) == 1
    # a history of only unstamped rows has no "last run" to judge
    history, current = bt.split_last_run(bt.load_rows(str(p)))
    assert current == [] and len(history) == 1


def test_direction_heuristics():
    bt = _trend()
    assert bt.metric_direction("ctr_dnn_samples_per_sec") == "higher"
    assert bt.metric_direction("serving_qps_sweep_curve") == "higher"
    assert bt.metric_direction("hbm_cache_hit_rate") == "higher"
    assert bt.metric_direction("fleet_router_p99_ms") == "lower"
    assert bt.metric_direction("pass_boundary_gap_ms") == "lower"
    assert bt.metric_direction("storage_bytes_per_pass") == "lower"
    assert bt.metric_direction("quantized_auc_delta") == "higher"
