"""Data core tests: parser, record blocks, batching, dataset lifecycle.

Modeled on the reference's data tests (framework/data_feed_test.cc writes temp
slot files and exercises feeds; test_paddlebox_datafeed.py:71-87 fixture)."""

import numpy as np
import pytest

from paddlebox_tpu.config import DataFeedConfig, SlotConfig
from paddlebox_tpu.data import BatchBuilder, DatasetFactory, PadBoxSlotDataset, RecordBlock, SlotParser
from paddlebox_tpu.data.data_generator import format_instance


def make_conf(**kw):
    slots = [
        SlotConfig("click", type="float", is_dense=True, shape=(1,)),
        SlotConfig("slot_a", type="uint64"),
        SlotConfig("slot_b", type="uint64"),
        SlotConfig("dense_x", type="float", is_dense=True, shape=(3,)),
    ]
    return DataFeedConfig(slots=slots, batch_size=4, **kw)


def write_sample(path, conf, n=10, seed=0):
    rng = np.random.default_rng(seed)
    lines = []
    for i in range(n):
        a = list(rng.integers(1, 1000, size=rng.integers(1, 5)))
        b = list(rng.integers(1000, 2000, size=rng.integers(0, 3)))
        ins = [
            ("click", [float(i % 2)]),
            ("slot_a", a),
            ("slot_b", b),
            ("dense_x", [0.1 * i, 0.2, 0.3]),
        ]
        lines.append(format_instance(conf, ins))
    path.write_text("\n".join(lines) + "\n")
    return lines


def test_parse_roundtrip(tmp_path):
    conf = make_conf()
    f = tmp_path / "part-0"
    write_sample(f, conf, n=7)
    block = SlotParser(conf).parse_file(str(f))
    assert block.n_ins == 7
    assert block.n_sparse_slots == 2
    assert block.labels.tolist() == [float(i % 2) for i in range(7)]
    assert block.dense.shape == (7, 3)
    np.testing.assert_allclose(block.dense[3], [0.3, 0.2, 0.3], rtol=1e-6)
    # every instance has >=1 slot_a key, slot_b may be empty
    for i in range(7):
        assert block.slot_slice(i, 0).shape[0] >= 1


def test_block_concat_and_select():
    conf = make_conf()
    p = SlotParser(conf)
    b1 = p.parse_lines(["1 1 2 11 12 1 21 3 0.1 0.2 0.3"])
    b2 = p.parse_lines(["1 0 1 13 0 3 0.4 0.5 0.6", "1 1 3 14 15 16 2 22 23 3 0.7 0.8 0.9"])
    blk = RecordBlock.concat([b1, b2])
    assert blk.n_ins == 3
    assert blk.slot_slice(0, 0).tolist() == [11, 12]
    assert blk.slot_slice(1, 0).tolist() == [13]
    assert blk.slot_slice(1, 1).tolist() == []
    assert blk.slot_slice(2, 1).tolist() == [22, 23]
    sel = blk.select(np.array([2, 0]))
    assert sel.n_ins == 2
    assert sel.slot_slice(0, 0).tolist() == [14, 15, 16]
    assert sel.slot_slice(1, 0).tolist() == [11, 12]
    np.testing.assert_allclose(sel.labels, [1.0, 1.0])


def test_batch_builder_shapes_and_segments():
    conf = make_conf()
    p = SlotParser(conf)
    blk = p.parse_lines(
        ["1 1 2 11 12 1 21 3 0.1 0.2 0.3", "1 0 1 13 0 3 0.4 0.5 0.6"]
    )
    bb = BatchBuilder(conf)
    hb = bb.build(blk, np.array([0, 1]))
    B, S = conf.batch_size, 2
    assert hb.keys.shape == (conf.batch_size * conf.max_feasigns_per_ins,)
    assert hb.n_keys == 4
    assert hb.keys[:4].tolist() == [11, 12, 21, 13]
    # segments: ins0 slot0 ->0, slot1 ->1; ins1 slot0 ->2
    assert hb.key_segments[:4].tolist() == [0, 0, 1, 2]
    assert (hb.key_segments[4:] == B * S).all()
    assert hb.ins_mask.tolist() == [1, 1, 0, 0]


def test_batch_key_overflow_clips():
    conf = make_conf(batch_key_capacity=3)
    p = SlotParser(conf)
    blk = p.parse_lines(["1 1 2 11 12 1 21 3 0.1 0.2 0.3", "1 0 1 13 0 3 0.4 0.5 0.6"])
    bb = BatchBuilder(conf)
    hb = bb.build(blk, np.array([0, 1]))
    assert hb.n_keys == 3
    assert bb.dropped_keys == 1


def test_dataset_lifecycle(tmp_path):
    conf = make_conf()
    files = []
    for j in range(3):
        f = tmp_path / f"part-{j}"
        write_sample(f, conf, n=5, seed=j)
        files.append(str(f))
    ds = DatasetFactory().create_dataset("BoxPSDataset", conf)
    ds.set_filelist(files)
    ds.set_date("20260729")
    ds.load_into_memory()
    assert ds.get_memory_data_size() == 15
    keys = ds.unique_keys()
    assert keys.dtype == np.uint64 and keys.shape[0] > 0
    assert (np.diff(keys.astype(np.int64)) > 0).all()
    batches = list(ds.batches())
    assert len(batches) == 4  # 15 ins / bs 4
    assert sum(b.n_real_ins for b in batches) == 15
    ds.local_shuffle(seed=1)
    b2 = list(ds.batches(drop_last=True))
    assert len(b2) == 3
    ds.release_memory()
    assert ds.get_memory_data_size() == 0


def test_dataset_preload_overlap(tmp_path):
    conf = make_conf()
    f = tmp_path / "part-0"
    write_sample(f, conf, n=6)
    ds = PadBoxSlotDataset(conf)
    ds.set_filelist([str(f)])
    ds.preload_into_memory()
    ds.wait_preload_done()
    assert ds.get_memory_data_size() == 6
    with pytest.raises(RuntimeError):
        ds.wait_preload_done()


def test_slots_shuffle_preserves_other_slots(tmp_path):
    conf = make_conf()
    f = tmp_path / "part-0"
    write_sample(f, conf, n=8, seed=3)
    ds = PadBoxSlotDataset(conf)
    ds.set_filelist([str(f)])
    ds.load_into_memory()
    before_a = [ds._block.slot_slice(i, 0).tolist() for i in range(8)]
    before_b = sorted(tuple(ds._block.slot_slice(i, 1).tolist()) for i in range(8))
    ds.slots_shuffle(["slot_b"], seed=7)
    after_a = [ds._block.slot_slice(i, 0).tolist() for i in range(8)]
    after_b = sorted(tuple(ds._block.slot_slice(i, 1).tolist()) for i in range(8))
    assert before_a == after_a  # untouched slot identical per instance
    assert before_b == after_b  # shuffled slot is a permutation across instances
