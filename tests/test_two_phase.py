"""Join/update two-phase training (train/two_phase.py).

Reference semantics under test: phase flip between two programs per pass
(box_wrapper.h:627-630), phase-keyed metric streams (box_wrapper.cc:
1196-1270, boxps_worker.cc:530-540), and per-phase slot participation.
"""

import numpy as np
import pytest

from paddlebox_tpu.config import SparseTableConfig, TrainerConfig
from paddlebox_tpu.data.dataset import PadBoxSlotDataset
from paddlebox_tpu.data.synth import make_synth_config, write_synth_files
from paddlebox_tpu.models import CtrDnn
from paddlebox_tpu.sparse.table import SparseTable
from paddlebox_tpu.train import PhaseSpec, Trainer, TwoPhaseTrainer

N_SLOTS, DENSE, B, VOCAB = 4, 4, 64, 100


@pytest.fixture(scope="module")
def synth(tmp_path_factory):
    td = tmp_path_factory.mktemp("twophase")
    conf = make_synth_config(
        n_sparse_slots=N_SLOTS, dense_dim=DENSE, batch_size=B,
        batch_key_capacity=B * N_SLOTS * 4,
    )
    paths = write_synth_files(
        str(td), n_files=2, ins_per_file=4 * B, n_sparse_slots=N_SLOTS,
        vocab_per_slot=VOCAB, dense_dim=DENSE, seed=11,
    )
    return paths, conf


def _model():
    tconf = SparseTableConfig(embedding_dim=8)
    return tconf, lambda: CtrDnn(
        n_sparse_slots=N_SLOTS, emb_width=tconf.row_width, dense_dim=DENSE,
        hidden=(16,),
    )


def test_phase_state_api(synth):
    tconf, mk = _model()
    tp = TwoPhaseTrainer(
        [PhaseSpec("join", mk()), PhaseSpec("update", mk())], tconf,
        TrainerConfig(auc_buckets=1 << 10),
    )
    assert tp.phase == 0 and tp.phase_name == "join" and tp.phase_num == 2
    tp.flip_phase()
    assert tp.phase == 1 and tp.phase_name == "update"
    tp.flip_phase()
    assert tp.phase == 0
    tp.set_phase(1)
    assert tp.phase_name == "update"
    with pytest.raises(ValueError):
        tp.set_phase(2)
    with pytest.raises(ValueError):
        TwoPhaseTrainer([PhaseSpec("x", mk()), PhaseSpec("x", mk())], tconf)


def test_two_phase_pass_distinct_streams(synth):
    """A pass trains join then update over the same data; each phase keeps
    its own metric stream and both learn across passes."""
    paths, conf = synth
    tconf, mk = _model()
    tp = TwoPhaseTrainer(
        [
            PhaseSpec("join", mk(), slots=(0, 1)),
            PhaseSpec("update", mk(), slots=(2, 3)),
        ],
        tconf,
        TrainerConfig(auc_buckets=1 << 10, dense_lr=3e-3),
    )
    table = SparseTable(tconf)
    ds = PadBoxSlotDataset(conf)
    ds.set_filelist(paths)
    ds.load_into_memory()
    first, last = None, None
    for _ in range(4):
        table.begin_pass(ds.unique_keys())
        m = tp.train_pass(ds, table)
        table.end_pass()
        assert set(m) == {"join", "update"}
        # phase order: join trains first, then the flip; pass ends back at 0
        assert tp.phase == 0
        first = first or m
        last = m
    ds.close()
    for name in ("join", "update"):
        assert np.isfinite(last[name]["loss"])
        # each phase's stream accumulated all 4 passes of the same data
        assert last[name]["count"] == 4 * first[name]["count"]
        assert last[name]["loss"] < first[name]["loss"]  # both programs learn
    # the streams are genuinely distinct accumulators
    sj = tp.metrics("join")["join"]
    su = tp.metrics()["update"]
    assert sj is not su
    assert not np.array_equal(
        np.asarray(sj["auc"].pos), np.asarray(su["auc"].pos)
    )


def test_slot_participation_gates_grads_and_counters(synth):
    """Excluded slots must not train in a phase: their show counters stay
    zero and their embeddings keep the deterministic init (synth keys are
    slot-disjoint: slot s owns [s*VOCAB+1, (s+1)*VOCAB])."""
    paths, conf = synth
    tconf, mk = _model()
    trainer = Trainer(
        mk(), tconf, TrainerConfig(auc_buckets=1 << 10), slot_mask=(0, 1)
    )
    table = SparseTable(tconf)
    ds = PadBoxSlotDataset(conf)
    ds.set_filelist(paths)
    ds.load_into_memory()
    table.begin_pass(ds.unique_keys())
    trainer.train_from_dataset(ds, table)
    table.end_pass()
    ds.close()
    sd = table.state_dict()
    in_phase = sd["keys"] <= np.uint64(2 * VOCAB)
    # participating slots saw traffic
    assert sd["values"][in_phase, 0].sum() > 0
    # excluded slots: zero show AND zero clk
    np.testing.assert_array_equal(sd["values"][~in_phase, :2], 0.0)
    # excluded embeddings unchanged from the key-deterministic init
    from paddlebox_tpu.sparse.table import _key_uniform

    out_keys = sd["keys"][~in_phase]
    expect = _key_uniform(
        out_keys, seed=0, n_cols=tconf.row_width - tconf.cvm_offset,
        rng_range=tconf.initial_range,
    )
    np.testing.assert_allclose(
        sd["values"][~in_phase, tconf.cvm_offset : tconf.row_width], expect,
        rtol=1e-6,
    )
    # and their g2sum never moved
    np.testing.assert_array_equal(sd["values"][~in_phase, -1], 0.0)


def test_two_phase_multichip_matches_single_chip(synth):
    """Join/update over the 8-device mesh == the single-chip schedule on
    the same instances: per-phase slot participation gates identically
    through the sharded pull/push (reference: the phase flip applies in
    the production multi-GPU workers, box_wrapper.h:627-630)."""
    import jax

    from paddlebox_tpu.parallel import ShardedSparseTable, make_mesh

    paths, conf = synth
    tconf, mk = _model()
    trconf = TrainerConfig(auc_buckets=1 << 10, dense_lr=3e-3)
    phases = lambda: [
        PhaseSpec("join", mk(), slots=(0, 1)),
        PhaseSpec("update", mk(), slots=(2, 3)),
    ]

    # single-chip reference (2 passes: metric streams must carry)
    tp1 = TwoPhaseTrainer(phases(), tconf, trconf, seed=0)
    table1 = SparseTable(tconf, seed=0)
    ds1 = PadBoxSlotDataset(conf)
    ds1.set_filelist(paths)
    ds1.load_into_memory()
    for _ in range(2):
        table1.begin_pass(ds1.unique_keys())
        m1 = tp1.train_pass(ds1, table1)
        table1.end_pass()
    ds1.close()

    # multi-chip: same instances as 8 per-device batches of B/8
    n_dev = 8
    assert len(jax.devices()) >= n_dev, "conftest must force 8 CPU devices"
    mesh = make_mesh(n_dev)
    conf8 = make_synth_config(
        n_sparse_slots=N_SLOTS, dense_dim=DENSE, batch_size=B // n_dev,
        batch_key_capacity=B * N_SLOTS * 4 // n_dev,
    )
    tp8 = TwoPhaseTrainer(phases(), tconf, trconf, seed=0, mesh=mesh)
    table8 = ShardedSparseTable(tconf, mesh, seed=0,
                               bucket_slack=float(n_dev))
    ds8 = PadBoxSlotDataset(conf8)
    ds8.set_filelist(paths)
    ds8.load_into_memory()
    for _ in range(2):
        table8.begin_pass(ds8.unique_keys())
        m8 = tp8.train_pass(ds8, table8)
        table8.end_pass()
    ds8.close()

    for name in ("join", "update"):
        assert m8[name]["count"] == m1[name]["count"]
        assert abs(m1[name]["loss"] - m8[name]["loss"]) < 2e-4
    s1, s8 = table1.state_dict(), table8.state_dict()
    np.testing.assert_array_equal(s1["keys"], s8["keys"])
    np.testing.assert_allclose(s1["values"], s8["values"], atol=2e-4)
    # the phase gating itself is visible: join touched slots 0-1 only in
    # its program, update 2-3 — every slot shows traffic across the pass
    slot = (np.asarray(s8["keys"], np.int64) - 1) // VOCAB
    for s in range(N_SLOTS):
        assert s8["values"][slot == s, 0].sum() > 0


def test_two_phase_multichip_pv_join(tmp_path):
    """The canonical production schedule ON THE MESH: a PV-merged join
    phase (rank_offset model) then a flat update phase, per-phase PV
    gating intact (reference: per-phase PV channels, data_feed.cc:1663,
    in the multi-GPU workers)."""
    import jax

    from paddlebox_tpu.models import RankCtrDnn
    from paddlebox_tpu.parallel import ShardedSparseTable, make_mesh

    assert len(jax.devices()) >= 8, "conftest must force 8 CPU devices"
    conf = make_synth_config(
        n_sparse_slots=3, dense_dim=2, batch_size=8,
        max_feasigns_per_ins=16, parse_logkey=True, enable_pv_merge=True,
        pv_batch_size=4, rank_cmatch_filter=(222, 223),
    )
    files = write_synth_files(
        str(tmp_path), n_files=2, ins_per_file=96, n_sparse_slots=3,
        vocab_per_slot=50, dense_dim=2, seed=3, with_logkey=True,
        max_ads_per_pv=3,
    )
    ds = PadBoxSlotDataset(conf, read_threads=1)
    ds.set_filelist(files)
    ds.load_into_memory()
    ds.preprocess_instance()

    tconf = SparseTableConfig(embedding_dim=4)
    mesh = make_mesh(8)
    join_model = RankCtrDnn(3, tconf.row_width, dense_dim=2, hidden=(16,),
                            max_rank=conf.max_rank, att_out_dim=8)
    upd_model = CtrDnn(3, tconf.row_width, dense_dim=2, hidden=(16,))
    tp = TwoPhaseTrainer(
        [
            PhaseSpec("join", join_model, slots=(0, 1), use_pv=True),
            PhaseSpec("update", upd_model, slots=(2,)),
        ],
        tconf, TrainerConfig(auc_buckets=1 << 10), mesh=mesh,
    )
    table = ShardedSparseTable(tconf, mesh, seed=0)
    for _ in range(2):
        table.begin_pass(ds.unique_keys())
        m = tp.train_pass(ds, table)
        table.end_pass()
    assert ds.pv_mode  # the flat phase restored the PV grouping after
    ds.close()
    tp.close()
    assert np.isfinite(m["join"]["loss"]) and np.isfinite(m["update"]["loss"])
    assert m["join"]["count"] == m["update"]["count"] > 0


def test_single_phase_matches_plain_trainer(synth):
    """A one-phase TwoPhaseTrainer with no slot mask is exactly a Trainer
    (same seed -> identical loss/auc): the phase machinery adds nothing."""
    paths, conf = synth
    tconf, mk = _model()
    trconf = TrainerConfig(auc_buckets=1 << 10)

    def run_plain():
        t = Trainer(mk(), tconf, trconf, seed=0)
        table = SparseTable(tconf)
        ds = PadBoxSlotDataset(conf)
        ds.set_filelist(paths)
        ds.load_into_memory()
        table.begin_pass(ds.unique_keys())
        m = t.train_from_dataset(ds, table)
        table.end_pass()
        ds.close()
        return m

    def run_phased():
        tp = TwoPhaseTrainer([PhaseSpec("only", mk())], tconf, trconf, seed=0)
        table = SparseTable(tconf)
        ds = PadBoxSlotDataset(conf)
        ds.set_filelist(paths)
        ds.load_into_memory()
        table.begin_pass(ds.unique_keys())
        m = tp.train_pass(ds, table)["only"]
        table.end_pass()
        ds.close()
        tp.close()  # no-op on the single-chip path, must not raise
        return m

    a, b = run_plain(), run_phased()
    assert a["loss"] == pytest.approx(b["loss"], rel=1e-6)
    assert a["auc"] == pytest.approx(b["auc"], rel=1e-6)
