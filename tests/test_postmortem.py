"""Distributed tracing, the always-on flight recorder, and pbox_doctor
(telemetry/context.py, telemetry/flight.py, tools/pbox_doctor.py):
trace-ID continuity through router failover, crash-time flight dumps
(watchdog stall, stream.tail hang, replica SIGKILL), JSONL rotation, and
the cross-process e2e asserted on the doctor's parsed output."""

import http.client
import importlib
import json
import os
import signal
import sys
import threading
import time

import pytest

from paddlebox_tpu import telemetry
from paddlebox_tpu.config import DataFeedConfig, SlotConfig
from paddlebox_tpu.inference.server import ScoringServer
from paddlebox_tpu.parallel.watchdog import DistributedStallError, Watchdog
from paddlebox_tpu.serving_fleet import FleetRouter, ReplicaSupervisor
from paddlebox_tpu.telemetry import context as tctx
from paddlebox_tpu.telemetry import flight
from paddlebox_tpu.telemetry.events import EventLog
from paddlebox_tpu.utils.retry import RetryPolicy

HERE = os.path.dirname(os.path.abspath(__file__))
CHILD = os.path.join(HERE, "_replica_child.py")
BODY = b"line one\nline two\n"


def _doctor():
    sys.path.insert(0, os.path.join(os.path.dirname(HERE), "tools"))
    try:
        return importlib.import_module("pbox_doctor")
    finally:
        sys.path.pop(0)


def _wait_until(cond, timeout_s=30.0, interval_s=0.02):
    deadline = time.monotonic() + timeout_s
    while time.monotonic() < deadline:
        if cond():
            return True
        time.sleep(interval_s)
    return cond()


class _StubPredictor:
    meta = {"n_tasks": 1, "row_width": 4}
    bucket_shapes = [(8, 64)]
    n_features = 1


def _stub_server(tag=0.5):
    conf = DataFeedConfig(
        slots=(SlotConfig("click", type="float", is_dense=True),
               SlotConfig("s0")),
        batch_size=8,
    )
    srv = ScoringServer(max_queue=64, max_concurrency=1)
    srv.register_predictor("stub", _StubPredictor(), conf)
    srv.score_lines = lambda text, name=None: [float(tag)] * len(
        [ln for ln in text.decode().splitlines() if ln.strip()])
    return srv


def _post(port, body=BODY, path="/score", headers=None, timeout=30):
    conn = http.client.HTTPConnection("127.0.0.1", port, timeout=timeout)
    try:
        conn.request("POST", path, body=body, headers=headers or {})
        r = conn.getresponse()
        data = r.read()
        return r.status, (json.loads(data) if data else {}), {
            k.lower(): v for k, v in r.getheaders()}
    finally:
        conn.close()


# --------------------------------------------------------------------------- #
# trace context: W3C carriage + thread-local activation
# --------------------------------------------------------------------------- #
def test_traceparent_roundtrip():
    root = tctx.new_root()
    assert len(root.trace_id) == 32 and len(root.span_id) == 16
    hdr = root.to_traceparent()
    assert hdr.startswith("00-") and hdr.endswith("-01")
    parsed = tctx.parse_traceparent(hdr)
    # the parser CONTINUES the trace: same trace, new span, parented
    # under the caller's span
    assert parsed.trace_id == root.trace_id
    assert parsed.span_id != root.span_id
    assert parsed.parent_span_id == root.span_id


@pytest.mark.parametrize("bad", [
    None, "", "garbage", "00-short-span-01",
    "00-" + "0" * 32 + "-" + "1" * 16 + "-01",  # all-zero trace reserved
    "00-" + "g" * 32 + "-" + "1" * 16 + "-01",  # non-hex
    "ff-" + "1" * 32 + "-" + "2" * 16 + "-01",  # reserved version
])
def test_malformed_traceparent_is_none(bad):
    assert tctx.parse_traceparent(bad) is None


def test_activation_is_scoped_and_child_keeps_trace():
    assert tctx.current() is None
    root = tctx.new_root()
    with tctx.activate(root):
        assert tctx.current() is root
        child = root.child()
        assert child.trace_id == root.trace_id
        assert child.parent_span_id == root.span_id
        with tctx.activate(child):
            assert tctx.current() is child
        assert tctx.current() is root
    assert tctx.current() is None


def test_spans_carry_trace_ids_into_flight_ring():
    fr = flight.reset_for_tests(capacity=16)
    root = tctx.new_root()
    with tctx.activate(root):
        with telemetry.span("server.score", model="m"):
            pass
    rec = fr.snapshot()[-1]
    assert rec["name"] == "server.score" and rec["kind"] == "span"
    assert rec["trace_id"] == root.trace_id
    assert rec["parent_span_id"] == root.span_id  # child of the root
    assert rec["dur_s"] >= 0


# --------------------------------------------------------------------------- #
# flight recorder: ring bound, dump schema, triggers
# --------------------------------------------------------------------------- #
def test_ring_is_bounded_and_counts_evictions():
    fr = flight.reset_for_tests(capacity=4)
    base = telemetry.registry.get("trace.dropped_spans").value()
    for i in range(7):
        fr.record("event", f"e{i}")
    ring = fr.snapshot()
    assert len(ring) == 4
    assert [r["name"] for r in ring] == ["e3", "e4", "e5", "e6"]
    assert telemetry.registry.get("trace.dropped_spans").value() - base == 3


def test_dump_schema_and_metrics_snapshot(tmp_path):
    fr = flight.reset_for_tests(capacity=8)
    fr.name = "unittest"
    fr.record("event", "hello", x=1)
    path = fr.dump("testreason", {"why": "unit"}, dump_dir=str(tmp_path))
    assert path and os.path.exists(path)
    d = json.loads(open(path).read())
    assert d["schema"] == "pbox-flight-1"
    assert d["reason"] == "testreason"
    assert d["proc"] == "unittest"
    assert d["detail"] == {"why": "unit"}
    assert d["ring"][-1]["name"] == "hello"
    assert "counters" in d["metrics"]  # full registry snapshot attached
    # no dir configured anywhere -> None, never a raise
    assert flight.FlightRecorder(4).dump("nowhere") is None \
        or os.environ.get("PBOX_FLIGHT_DIR") or os.environ.get(
            "PBOX_EVENTS_PATH")


def test_watchdog_abort_dumps_flight(tmp_path, monkeypatch):
    monkeypatch.setenv("PBOX_FLIGHT_DIR", str(tmp_path))
    flight.reset_for_tests(capacity=32)
    wd = Watchdog(rank=2, world=4, install_current=False)
    try:
        wd.report("hostplane:grads")
        err = DistributedStallError(
            culprit=3, stage="feed", kind="peer", age_s=12.5,
            progress=7, detected_by=2,
        )
        wd.abort(err, poison=False)
    finally:
        wd.close()
    dumps = [f for f in os.listdir(tmp_path) if f.startswith("flight-")]
    assert len(dumps) == 1 and "-stall-" in dumps[0]
    d = json.loads(open(tmp_path / dumps[0]).read())
    assert d["reason"] == "stall"
    assert d["detail"]["culprit"] == 3
    assert d["detail"]["stage"] == "feed"
    assert d["detail"]["detected_by"] == 2


def test_sigterm_handler_dump_and_chain(tmp_path, monkeypatch):
    """install_signal_dump dumps the ring, then hands SIGTERM to the
    prior handler (here: a recorder we install first)."""
    monkeypatch.setenv("PBOX_FLIGHT_DIR", str(tmp_path))
    flight.reset_for_tests(capacity=8)
    flight.record("event", "before-term")
    got = []
    prev = signal.signal(signal.SIGTERM, lambda s, f: got.append(s))
    # force reinstallation in this process
    monkeypatch.setattr(flight, "_sigterm_installed", False)
    monkeypatch.setattr(flight, "_prev_sigterm", None)
    try:
        assert flight.install_signal_dump()
        os.kill(os.getpid(), signal.SIGTERM)
        assert _wait_until(lambda: got, timeout_s=5)
        dumps = [f for f in os.listdir(tmp_path) if "-sigterm-" in f]
        assert len(dumps) == 1
        d = json.loads(open(tmp_path / dumps[0]).read())
        assert any(r["name"] == "before-term" for r in d["ring"])
    finally:
        signal.signal(signal.SIGTERM, prev)


# --------------------------------------------------------------------------- #
# JSONL event-file rotation
# --------------------------------------------------------------------------- #
def test_event_log_rotates_by_size_keeping_last_k(tmp_path):
    path = str(tmp_path / "events.jsonl")
    # ~100-byte records against a 1KB bound: rotation every ~10 records
    el = EventLog(path, rank=0, max_mb=0.001, keep_files=3)
    try:
        for i in range(100):
            el.log("tick", i=i, pad="x" * 80)
    finally:
        el.close()
    files = sorted(os.listdir(tmp_path))
    assert "events.jsonl" in files
    rotated = [f for f in files if f.startswith("events.jsonl.")]
    assert rotated and len(rotated) <= 3  # keep-last-K bound holds
    # every surviving file is whole JSONL (rotation never tears a line)
    total = 0
    for f in files:
        for line in open(tmp_path / f):
            if line.strip():
                json.loads(line)
                total += 1
    # the newest records survive in the live file
    last = [json.loads(ln) for ln in open(tmp_path / "events.jsonl")
            if ln.strip()]
    assert last[-1]["i"] == 99
    assert total <= 100  # older generations beyond K were dropped


def test_event_log_rotation_off_by_default(tmp_path):
    path = str(tmp_path / "ev.jsonl")
    el = EventLog(path, rank=0, max_mb=0)  # 0 = never rotate
    try:
        for i in range(50):
            el.log("tick", i=i, pad="y" * 200)
    finally:
        el.close()
    assert os.listdir(tmp_path) == ["ev.jsonl"]


# --------------------------------------------------------------------------- #
# server + router debug headers and trace continuity
# --------------------------------------------------------------------------- #
def test_bare_server_echoes_minted_and_forwarded_trace_ids():
    srv = _stub_server()
    port = srv.start(port=0)
    try:
        # no header: the server mints a trace and echoes it
        st, _, hdrs = _post(port)
        assert st == 200
        assert len(hdrs.get("x-pbox-trace-id", "")) == 32
        # client traceparent: the SAME trace id comes back
        root = tctx.new_root()
        st, _, hdrs = _post(
            port, headers={"traceparent": root.to_traceparent()})
        assert st == 200
        assert hdrs["x-pbox-trace-id"] == root.trace_id
    finally:
        srv.stop()


def test_router_failover_keeps_one_trace_id_and_doctor_rebuilds_path(
        tmp_path):
    """A replica dies; the retry lands elsewhere; every span of the
    request shares the client's trace ID; X-PBox-Replica names the
    actual server; pbox_doctor reconstructs the hop from the dump."""
    flight.reset_for_tests(capacity=256)
    srv_a, srv_b = _stub_server(tag=1.0), _stub_server(tag=2.0)
    port_a, port_b = srv_a.start(port=0), srv_b.start(port=0)
    router = FleetRouter([f"127.0.0.1:{port_a}", f"127.0.0.1:{port_b}"],
                         probe_interval_s=3600)  # manual probing only
    router.probe_once()
    rport = router.start(port=0)
    try:
        srv_a.stop()  # replica A dies AFTER being probed healthy
        root = tctx.new_root()
        st, out, hdrs = _post(
            rport, headers={"traceparent": root.to_traceparent()})
        assert st == 200 and out["scores"][0] == 2.0  # B served
        assert hdrs["x-pbox-trace-id"] == root.trace_id
        assert hdrs["x-pbox-replica"] == f"127.0.0.1:{port_b}"
    finally:
        router.stop()
        srv_b.stop()
    flight.dump_flight("run_end", dump_dir=str(tmp_path))
    report = _doctor().analyze(str(tmp_path))
    recs = report["traces"][root.trace_id]
    names = [r["name"] for r in recs]
    assert "fleet.request" in names
    assert "fleet.failover" in names  # the dead-replica hop is explicit
    attempts = [r for r in recs if r["name"] == "fleet.attempt"]
    tried = {r["detail"].get("replica") for r in attempts}
    assert tried == {f"127.0.0.1:{port_a}", f"127.0.0.1:{port_b}"}
    # the replica-side span rides the same trace (in-process replicas
    # here share the ring; the subprocess e2e below proves cross-process)
    assert "server.request" in names
    # formatting never crashes on a real report
    assert root.trace_id in _doctor().format_trace(report, root.trace_id)


# --------------------------------------------------------------------------- #
# doctor units on fabricated artifacts
# --------------------------------------------------------------------------- #
def _write_dump(d, name, **payload):
    base = {
        "schema": "pbox-flight-1", "t": payload.pop("t", 100.0),
        "proc": payload.pop("proc", "pbox"),
        "rank": payload.pop("rank", 0), "pid": payload.pop("pid", 1),
        "reason": payload.pop("reason", "stall"),
        "detail": payload.pop("detail", {}),
        "ring": payload.pop("ring", []), "metrics": {},
    }
    with open(os.path.join(d, name), "w") as fh:
        json.dump(base, fh)


def test_doctor_names_who_stalled_first(tmp_path):
    # rank 1 froze at t=90 (local verdict dumped at 100); rank 0 noticed
    # later via the poison key — the LOCAL verdict must win
    _write_dump(tmp_path, "flight-pbox-r1-pid11-stall-1.json",
                t=100.0, rank=1, pid=11, detail={
                    "culprit": 1, "stage": "shuffle", "kind": "local",
                    "age_s": 10.0, "detected_by": 1})
    _write_dump(tmp_path, "flight-pbox-r0-pid10-stall-2.json",
                t=101.0, rank=0, pid=10, detail={
                    "culprit": 1, "stage": "shuffle", "kind": "poison",
                    "age_s": 0.0, "detected_by": 1})
    report = _doctor().analyze(str(tmp_path))
    first = report["stalls"]["first"]
    assert first["culprit"] == 1 and first["stage"] == "shuffle"
    assert first["kind"] == "local"
    assert first["t_stall_start"] == pytest.approx(90.0)
    assert len(report["stalls"]["stalls"]) == 2
    assert "STALLED FIRST" in _doctor().format_summary(report)


def _coll(rank, channel, seq, op="allgather"):
    return {"t": 10.0 + seq, "kind": "collective",
            "name": "hostplane.allgather", "channel": channel,
            "seq": seq, "op": op, "rank": rank}


def test_doctor_names_first_collective_divergence(tmp_path):
    # rank 1 skipped seq 2 on channel plan-7 (it has seq 3): the exact
    # hang spmd-rank-divergence catches statically, reconstructed from
    # production dumps
    _write_dump(tmp_path, "flight-trainer-r0-pid10-stall-1.json",
                rank=0, pid=10,
                ring=[_coll(0, "plan-7", s) for s in range(4)])
    _write_dump(tmp_path, "flight-trainer-r1-pid11-stall-2.json",
                rank=1, pid=11,
                ring=[_coll(1, "plan-7", s) for s in (0, 1, 3)])
    report = _doctor().analyze(str(tmp_path))
    first = report["collectives"]["first"]
    assert first["rank"] == 1
    assert first["channel"] == "plan-7"
    assert first["seq"] == 2
    assert first["kind"] == "skipped"
    summary = _doctor().format_summary(report)
    assert "COLLECTIVE DIVERGENCE" in summary
    assert "rank 1" in summary and "'plan-7'" in summary and "seq 2" in summary


def test_doctor_collective_op_mismatch_and_laggard(tmp_path):
    # channel a: rank 2 issued a DIFFERENT op at seq 1; channel b: rank 0
    # simply stopped at seq 0 while peers reached 2 (the wedged rank)
    _write_dump(tmp_path, "flight-t-r0-pid20-stall-1.json", rank=0, pid=20,
                ring=[_coll(0, "a", 0), _coll(0, "a", 1),
                      _coll(0, "b", 0, op="exchange")])
    _write_dump(tmp_path, "flight-t-r1-pid21-stall-2.json", rank=1, pid=21,
                ring=[_coll(1, "a", 0), _coll(1, "a", 1)]
                + [_coll(1, "b", s, op="exchange") for s in range(3)])
    _write_dump(tmp_path, "flight-t-r2-pid22-stall-3.json", rank=2, pid=22,
                ring=[_coll(2, "a", 0), _coll(2, "a", 1, op="exchange")]
                + [_coll(2, "b", s, op="exchange") for s in range(3)])
    report = _doctor().analyze(str(tmp_path))
    divs = {d["channel"]: d for d in report["collectives"]["divergences"]}
    assert divs["a"]["kind"] == "op-mismatch" and divs["a"]["rank"] == 2
    assert divs["a"]["seq"] == 1
    assert divs["b"]["kind"] == "behind" and divs["b"]["rank"] == 0
    # first divergence overall: lowest seq wins
    assert report["collectives"]["first"]["channel"] == "a"


def test_doctor_collectives_clean_and_ring_truncation(tmp_path):
    # matching digests -> no divergence; rank 1's ring evicted seq 0
    # (history lost, not a skip) -> still no divergence
    _write_dump(tmp_path, "flight-t-r0-pid30-stall-1.json", rank=0, pid=30,
                ring=[_coll(0, "c", s) for s in range(3)])
    _write_dump(tmp_path, "flight-t-r1-pid31-stall-2.json", rank=1, pid=31,
                ring=[_coll(1, "c", s) for s in (1, 2)])
    report = _doctor().analyze(str(tmp_path))
    assert report["collectives"]["divergences"] == []
    assert report["collectives"]["first"] is None
    assert "COLLECTIVE DIVERGENCE" not in _doctor().format_summary(report)


def test_doctor_lineage_lag_from_donefile_and_events(tmp_path):
    os.makedirs(tmp_path / "pub")
    with open(tmp_path / "pub" / "donefile.txt", "w") as fh:
        fh.write(json.dumps({
            "seq": 0, "kind": "base", "tag": "b0", "dir": "base-b0",
            "base_tag": "b0", "prev_tag": None, "published_at": 50.0,
            "lineage": "pass0"}) + "\n")
        fh.write(json.dumps({
            "seq": 1, "kind": "delta", "tag": "d1", "dir": "delta-d1",
            "base_tag": "b0", "prev_tag": "b0", "published_at": 60.0,
            "lineage": "w1"}) + "\n")
    with open(tmp_path / "replica.jsonl", "w") as fh:
        fh.write(json.dumps({
            "t": 61.5, "rank": 0, "event": "sync_applied", "model": "live",
            "seq": 1, "tag": "d1", "lineage": "w1",
            "published_at": 60.0}) + "\n")
    report = _doctor().analyze(str(tmp_path))
    lin = report["lineage"]
    assert set(lin) == {"pass0", "w1"}
    assert lin["w1"]["published_at"] == 60.0
    assert lin["w1"]["n_applies"] == 1
    assert lin["w1"]["first_apply_lag_s"] == pytest.approx(1.5)
    assert lin["pass0"]["n_applies"] == 0  # never applied -> visible
    out = _doctor().format_lineage(report)
    assert "w1" in out and "NEVER APPLIED" in out


def test_doctor_merges_trace_files_on_wall_clock(tmp_path):
    with open(tmp_path / "host-trace-r0-pass0.json", "w") as fh:
        json.dump({
            "traceEvents": [
                {"name": "pass", "ph": "X", "ts": 2_000_000.0,
                 "dur": 1000.0, "pid": 0, "tid": 1},
            ],
            "pboxWallT0": 1000.0, "pboxRank": 0, "pboxProcess": "trainer",
        }, fh)
    report = _doctor().analyze(str(tmp_path))
    rows = [r for r in report["timeline"] if r["src"] == "trace"]
    assert rows and rows[0]["t"] == pytest.approx(1002.0)
    assert rows[0]["proc"] == "trainer/r0"


def test_doctor_survives_torn_and_junk_files(tmp_path):
    (tmp_path / "torn.jsonl").write_text(
        '{"t": 1, "rank": 0, "event": "ok"}\n{"t": 2, "ran')
    (tmp_path / "flight-junk.json").write_text("{not json")
    report = _doctor().analyze(str(tmp_path))
    assert report["sources"]["events"] == 1  # the whole line survived
    assert report["sources"]["dumps"] == 0


# --------------------------------------------------------------------------- #
# flight dump on a wedged stream source caught by the watchdog
# --------------------------------------------------------------------------- #
def test_stream_tail_hang_dumps_flight_with_feed_stage(tmp_path,
                                                       monkeypatch):
    """The satellite chaos pin: an injected ``stream.tail`` hang wedges
    the feed; the watchdog catches it AND the abort dumps a flight file
    whose verdict names the ``feed`` stage — the postmortem exists the
    moment the structured error is raised, not after log archaeology."""
    from paddlebox_tpu.config import (
        LivenessConfig, SparseTableConfig, TrainerConfig,
    )
    from paddlebox_tpu.data.synth import make_synth_config
    from paddlebox_tpu.models import CtrDnn
    from paddlebox_tpu.sparse.table import SparseTable
    from paddlebox_tpu.streaming import MiniPassScheduler, StreamingTrainer
    from paddlebox_tpu.streaming.source import TailingFileSource
    from paddlebox_tpu.train.trainer import Trainer
    from paddlebox_tpu.utils.faults import fault_plan

    flight_dir = tmp_path / "postmortem"
    monkeypatch.setenv("PBOX_FLIGHT_DIR", str(flight_dir))
    flight.reset_for_tests(capacity=64)
    conf = make_synth_config(n_sparse_slots=2, dense_dim=2, batch_size=8,
                             max_feasigns_per_ins=8)
    tconf = SparseTableConfig(embedding_dim=4, store_buckets=4,
                              plan_scratch_rows=32)
    model = CtrDnn(2, tconf.row_width, dense_dim=2, hidden=(4,))
    table = SparseTable(tconf, seed=0)
    trainer = Trainer(
        model, tconf,
        TrainerConfig(
            auc_buckets=1 << 10,
            liveness=LivenessConfig(deadline_s=1.0,
                                    heartbeat_interval_s=0.2,
                                    poll_interval_s=0.05),
        ),
        seed=0,
    )
    stream_dir = tmp_path / "stream"
    stream_dir.mkdir()
    with fault_plan({"stream.tail": "hang:first:1"}):
        src = TailingFileSource(str(stream_dir), poll_interval_s=0.02)
        src.start()
        sched = MiniPassScheduler(src, conf, window_records=8).start()
        runner = StreamingTrainer(trainer, table, sched)
        with pytest.raises(DistributedStallError) as ei:
            runner.run(max_seconds=30.0)
        assert ei.value.stage == "feed"
    dumps = [f for f in os.listdir(flight_dir) if "-stall-" in f]
    assert len(dumps) == 1
    d = json.loads(open(flight_dir / dumps[0]).read())
    assert d["reason"] == "stall"
    assert d["detail"]["stage"] == "feed"
    assert d["detail"]["kind"] == "local"
    # and the doctor reads the same verdict from the run dir
    report = _doctor().analyze(str(tmp_path))
    first = report["stalls"]["first"]
    assert first["stage"] == "feed" and first["kind"] == "local"


# --------------------------------------------------------------------------- #
# the headline e2e: fleet + SIGKILL + lineage, judged by the doctor
# --------------------------------------------------------------------------- #
def test_e2e_sigkill_fleet_postmortem_via_doctor(tmp_path, monkeypatch):
    """A bench --fleet-style run: 3 real replica server PROCESSES behind
    the router, one SIGKILLed mid-stream; a real Publisher→Syncer chain
    shipping lineage-stamped model units in parallel.  Everything is
    asserted on ``pbox_doctor.analyze``'s parsed output: the killed
    replica is NAMED, a failover hop is reconstructed under ONE trace ID
    spanning router and replica processes, and publish→apply lag is
    reported per lineage ID."""
    from paddlebox_tpu.config import SparseTableConfig, TrainerConfig
    from paddlebox_tpu.data.dataset import PadBoxSlotDataset
    from paddlebox_tpu.data.synth import make_synth_config, \
        write_synth_files
    from paddlebox_tpu.models import CtrDnn
    from paddlebox_tpu.serving_sync import Publisher, Syncer
    from paddlebox_tpu.sparse.table import SparseTable
    from paddlebox_tpu.train.trainer import Trainer

    run = tmp_path
    monkeypatch.setenv("PBOX_FLIGHT_DIR", str(run / "postmortem"))
    # parent ring must hold the whole ~120-request stream (≈2-4 records
    # per routed request) so the early failover survives to the dump
    flight.reset_for_tests(capacity=2048)
    telemetry.set_process_name("router")
    telemetry.close_event_log()
    telemetry.ensure_event_log(str(run / "trainer-events.jsonl"))
    try:
        # -- delivery half: train -> publish (lineage-stamped) -> sync --- #
        S, DENSE, B = 2, 2, 8
        conf = make_synth_config(n_sparse_slots=S, dense_dim=DENSE,
                                 batch_size=B, max_feasigns_per_ins=8)
        tconf = SparseTableConfig(embedding_dim=4)
        model = CtrDnn(S, tconf.row_width, dense_dim=DENSE, hidden=(4,))
        table = SparseTable(tconf, seed=0)
        trainer = Trainer(model, tconf, TrainerConfig(auc_buckets=1 << 10),
                          seed=0)

        def train_pass(i):
            files = write_synth_files(
                str(run / f"d{i}"), n_files=1, ins_per_file=16,
                n_sparse_slots=S, vocab_per_slot=40, dense_dim=DENSE,
                seed=100 + i)
            ds = PadBoxSlotDataset(conf, read_threads=1)
            ds.set_filelist(files)
            ds.load_into_memory()
            table.begin_pass(ds.unique_keys())
            trainer.train_from_dataset(ds, table)
            table.end_pass()
            ds.close()

        root = str(run / "pub")
        pub = Publisher(root, staging_dir=str(run / "stage"))
        train_pass(0)
        t_pub0 = time.time()
        pub.publish_base(
            "b0", model, trainer.params, table, batch_size=B,
            key_capacity=B * 8, dense_dim=DENSE, feed_conf=conf,
            lineage="pass0")
        train_pass(1)
        pub.publish_delta("d1", table, lineage="w1")

        sync_srv = ScoringServer()
        syncer = Syncer(root, sync_srv, "live",
                        cache_dir=str(run / "cache"),
                        poll_interval_s=3600)
        syncer.poll_once()
        assert syncer.applied_seq == 1
        # the applied lineage is visible on the serving surface
        assert sync_srv.model_version("live")["lineage"] == "w1"

        # -- fleet half: 3 replica processes, SIGKILL mid-stream --------- #
        def argv_for(rid, port):
            return [sys.executable, CHILD, "--port", str(port),
                    "--service-ms", "10", "--max-queue", "64"]

        sup = ReplicaSupervisor(
            3, argv_for, poll_interval_s=0.05,
            restart_policy=RetryPolicy(max_attempts=1_000_000,
                                       base_delay_s=0.05, max_delay_s=0.5),
            stable_after_s=0.5, log_dir=str(run / "logs"))
        sup.start()
        router = FleetRouter(sup.endpoints(), probe_interval_s=0.1,
                             eject_after=2)
        results, res_lock = [], threading.Lock()
        killed = {}
        try:
            assert _wait_until(lambda: (router.probe_once() or all(
                r.state == "healthy" for r in router.replicas)),
                timeout_s=120)
            rport = router.start(port=0)

            n_per_thread = 50

            def hammer():
                for _ in range(n_per_thread):
                    try:
                        st, _, hdrs = _post(rport, timeout=30)
                        with res_lock:
                            results.append(
                                (st, hdrs.get("x-pbox-trace-id"),
                                 hdrs.get("x-pbox-replica")))
                    except Exception as e:  # pragma: no cover
                        with res_lock:
                            results.append((repr(e), None, None))

            threads = [threading.Thread(target=hammer) for _ in range(4)]
            for t in threads:
                t.start()
            # kill while the stream is provably mid-flight, not by sleep
            # guesswork: wait until some responses landed but well under
            # half the stream remains to absorb the failover
            assert _wait_until(lambda: len(results) >= 20, timeout_s=60)
            killed["pid"] = sup.kill_replica(0, signal.SIGKILL)
            for t in threads:
                t.join(timeout=120)
            # a SIGKILLed replica is never client-visible
            assert all(st == 200 for st, _, _ in results), results[:5]
            assert len(results) == 4 * n_per_thread
            # the supervisor noticed the crash (and dumped)
            assert _wait_until(lambda: sup.restart_count() >= 1)
        finally:
            router.stop()
            sup.stop()  # SIGTERM -> surviving replicas dump their rings
        telemetry.dump_flight("fleet_run_end", {"requests": len(results)})
    finally:
        telemetry.close_event_log()

    # -- the doctor's verdict, parsed --------------------------------- #
    doctor = _doctor()
    report = doctor.analyze(str(run))
    assert report["sources"]["dumps"] >= 3  # crash + sigterms + run_end
    assert "replica_crash" in report["dump_reasons"]
    assert "sigterm" in report["dump_reasons"]

    # 1. the killed replica is NAMED (id + pid)
    crashes = report["crashes"]
    assert any(c["replica_id"] == 0 and c["pid"] == killed["pid"]
               for c in crashes), crashes

    # 2. the failover hop lives under ONE trace ID, across processes
    failover_traces = {
        tid: recs for tid, recs in report["traces"].items()
        if any(r["name"] == "fleet.failover" for r in recs)
    }
    assert failover_traces, "SIGKILL mid-stream left no failover trace"
    tid, recs = next(iter(failover_traces.items()))
    # the client saw this exact trace id on a 200 response
    assert any(t == tid and st == 200 for st, t, _ in results)
    attempts = {r["detail"].get("replica") for r in recs
                if r["name"] == "fleet.attempt"}
    assert len(attempts) >= 2, recs  # the hop: dead replica + the server
    procs = {r["proc"].split("/")[0] for r in recs}
    assert "router" in procs
    assert "replica" in procs, (
        f"no replica-side record joined trace {tid}: {procs}")

    # 3. publish→apply lag per lineage ID
    lin = report["lineage"]
    assert set(lin) >= {"pass0", "w1"}
    for lid in ("pass0", "w1"):
        assert lin[lid]["n_applies"] >= 1, lin[lid]
        assert lin[lid]["first_apply_lag_s"] is not None
        assert 0 <= lin[lid]["first_apply_lag_s"] < 600
    assert lin["pass0"]["publish_seq"] == 0
    assert lin["w1"]["publish_seq"] == 1
    assert lin["w1"]["published_at"] >= t_pub0

    # and the human-facing renderings hold together
    assert "REPLICA CRASH" in doctor.format_summary(report)
    assert "lineage w1" in doctor.format_lineage(report)
    assert doctor.format_timeline(report, limit=20)
