"""Continuous micro-batching at the admission gate
(inference/admission.BatchCoalescer + the ScoringServer HTTP wiring):
batched-vs-sequential bit-exactness over mixed-shape requests under
concurrency, deadline shedding mid-linger (429, never scored), hot-swap
atomicity (one predictor per batch), per-request clipped-instance
attribution through a coalesced batch, and overload behavior of the
widened admission gate."""

import http.client
import json
import threading
import time
import urllib.request

import numpy as np
import pytest

from paddlebox_tpu import telemetry
from paddlebox_tpu.config import (
    DataFeedConfig,
    SlotConfig,
    SparseTableConfig,
    TrainerConfig,
)
from paddlebox_tpu.data.dataset import PadBoxSlotDataset
from paddlebox_tpu.data.synth import make_synth_config, write_synth_files
from paddlebox_tpu.inference import ScoringServer, export_model
from paddlebox_tpu.models import CtrDnn
from paddlebox_tpu.sparse.table import SparseTable
from paddlebox_tpu.train.trainer import Trainer

S, DENSE, B = 3, 2, 16


def _train_and_export(tmp_path, tag="m", seed=1):
    conf = make_synth_config(n_sparse_slots=S, dense_dim=DENSE, batch_size=B,
                             max_feasigns_per_ins=8)
    files = write_synth_files(str(tmp_path / f"d{tag}"), n_files=1,
                              ins_per_file=64, n_sparse_slots=S,
                              vocab_per_slot=40, dense_dim=DENSE, seed=seed)
    ds = PadBoxSlotDataset(conf, read_threads=1)
    ds.set_filelist(files)
    ds.load_into_memory()
    tconf = SparseTableConfig(embedding_dim=4)
    model = CtrDnn(S, tconf.row_width, dense_dim=DENSE, hidden=(8,))
    table = SparseTable(tconf, seed=seed)
    trainer = Trainer(model, tconf, TrainerConfig(auc_buckets=1 << 10),
                      seed=seed)
    table.begin_pass(ds.unique_keys())
    trainer.train_from_dataset(ds, table)
    table.end_pass()
    ds.close()
    kcap = conf.batch_key_capacity or (B * conf.max_feasigns_per_ins)
    art = str(tmp_path / f"art{tag}")
    export_model(model, trainer.params, table, art,
                 batch_size=B, key_capacity=kcap, dense_dim=DENSE)
    return conf, art


def _lines(n, seed=5, max_keys=5):
    rng = np.random.default_rng(seed)
    out = []
    for _ in range(n):
        parts = ["1 0"]
        for _s in range(S):
            ks = rng.integers(0, 40, int(rng.integers(1, max_keys)))
            parts.append(f"{len(ks)} " + " ".join(map(str, ks)))
        parts.append(f"{DENSE} " + " ".join(
            f"{v:.3f}" for v in rng.random(DENSE)))
        out.append(" ".join(parts))
    return ("\n".join(out) + "\n").encode()


def _post(port, body, path="/score", headers=None, timeout=60):
    conn = http.client.HTTPConnection("127.0.0.1", port, timeout=timeout)
    try:
        conn.request("POST", path, body=body, headers=headers or {})
        r = conn.getresponse()
        data = r.read()
        return r.status, (json.loads(data) if data else {}), dict(
            (k.lower(), v) for k, v in r.getheaders())
    finally:
        conn.close()


class _StubPredictor:
    meta = {"n_tasks": 1, "row_width": 4}
    bucket_shapes = [(8, 64)]
    n_features = 1


def _stub_conf():
    return DataFeedConfig(
        slots=(SlotConfig("click", type="float", is_dense=True),
               SlotConfig("s0")),
        batch_size=8,
    )


# --------------------------------------------------------------------------- #
# the tentpole pin: batched scores are BIT-EXACT vs sequential
# --------------------------------------------------------------------------- #
def test_batched_bitexact_vs_sequential_mixed_shapes(tmp_path):
    """The acceptance pin: mixed-shape concurrent requests coalesced into
    shared padded-bucket device calls demultiplex to EXACTLY the scores
    each request gets when scored alone, FIFO attribution intact —
    scoring is per-instance row-independent by the padding/segment rules,
    so the combined batch changes dispatch count, never a single bit of
    any score."""
    conf, art = _train_and_export(tmp_path)
    srv = ScoringServer(max_batch=8, batch_linger_ms=20)
    srv.register("m", art, conf)
    sizes = [1, 3, 7, 2, 5, 4, 1, 6, 3, 2, 8, 5, 2, 1, 4, 6]
    bodies = [_lines(n, seed=100 + i) for i, n in enumerate(sizes)]
    # sequential oracle through the DIRECT path (never coalesced)
    want = [srv.score_lines(b, "m") for b in bodies]

    port = srv.start(port=0)
    try:
        _post(port, bodies[0])  # compile warmup outside the hammer
        got = [None] * len(bodies)
        errors = []

        def post(i):
            try:
                st, out, _ = _post(port, bodies[i])
                assert st == 200, (st, out)
                got[i] = out["scores"]
            except Exception as e:  # surfaced below, not swallowed
                errors.append((i, repr(e)))

        for _round in range(3):  # several rounds -> varied batch mixes
            threads = [threading.Thread(target=post, args=(i,))
                       for i in range(len(bodies))]
            for t in threads:
                t.start()
            for t in threads:
                t.join(timeout=120)
            assert not errors, errors
            for i in range(len(bodies)):
                assert got[i] == want[i], f"request {i} diverged"
        # and batching actually happened: at least one multi-request batch
        hist = telemetry.histogram("serve.batch_size")
        assert (hist.summary() or {}).get("max", 0) > 1
    finally:
        srv.stop()


# --------------------------------------------------------------------------- #
# deadline mid-linger: shed with 429, never scored
# --------------------------------------------------------------------------- #
def test_deadline_expires_while_queued_behind_batch_never_scored():
    """A queued request whose deadline dies while the previous batch
    occupies the scorer (the mid-linger/mid-queue window) is shed with
    429 at batch cut — its payload NEVER reaches the scoring path."""
    srv = ScoringServer(max_batch=4, batch_linger_ms=50, max_queue=16)
    srv.register_predictor("stub", _StubPredictor(), _stub_conf())
    release = threading.Event()
    entered = threading.Event()
    scored = []

    def score_lines(text, name=None):
        scored.append(bytes(text))
        entered.set()
        assert release.wait(20), "test never released the scorer"
        return [0.5 for ln in text.decode().splitlines() if ln.strip()]

    srv.score_lines = score_lines
    port = srv.start(port=0)
    try:
        res_a = {}

        def post_a():
            res_a["r"] = _post(port, b"request-A\n")

        ta = threading.Thread(target=post_a)
        ta.start()
        assert entered.wait(10)  # A's batch is on the (blocked) scorer
        t0 = time.monotonic()
        # B carries a 200ms deadline; the scorer stays blocked past it
        res_b = {}

        def post_b():
            res_b["r"] = _post(
                port, b"request-B\n",
                headers={"X-Request-Deadline-Ms": "200"})

        tb = threading.Thread(target=post_b)
        tb.start()
        while time.monotonic() - t0 < 0.35:
            time.sleep(0.01)
        release.set()
        ta.join(timeout=20)
        tb.join(timeout=20)
        st_a, out_a, _ = res_a["r"]
        st_b, out_b, hdrs_b = res_b["r"]
        assert st_a == 200 and out_a["scores"] == [0.5]
        assert st_b == 429 and "deadline" in out_b["error"]
        assert "retry-after" in hdrs_b
        # the shed request's payload never reached the scorer
        assert all(b"request-B" not in s for s in scored)
    finally:
        release.set()
        srv.stop()


# --------------------------------------------------------------------------- #
# hot swap mid-coalesce: one predictor per batch
# --------------------------------------------------------------------------- #
def test_hot_swap_mid_coalesce_never_mixes_predictors(tmp_path):
    """swap_model racing batch formation: every HTTP response must be
    EXACTLY the old model's scores or the new one's — a batch split
    across two predictors (or one request's chunks scored on both) would
    produce a third sequence."""
    conf_a, art_a = _train_and_export(tmp_path, "a", seed=1)
    conf_b, art_b = _train_and_export(tmp_path, "b", seed=2)
    from paddlebox_tpu.inference import Predictor

    pred_a, pred_b = Predictor.load(art_a), Predictor.load(art_b)
    srv = ScoringServer(max_batch=8, batch_linger_ms=5)
    srv.register("m", art_a, conf_a)
    body = _lines(23)  # several chunks per request
    want_a = srv.score_lines(body, "m")
    srv.swap_model("m", pred_b)
    want_b = srv.score_lines(body, "m")
    assert want_a != want_b
    srv.swap_model("m", pred_a)

    port = srv.start(port=0)
    bad, stop = [], threading.Event()

    def hammer():
        while not stop.is_set():
            st, out, _ = _post(port, body)
            if st != 200:
                bad.append(("status", st, out))
            elif out["scores"] != want_a and out["scores"] != want_b:
                bad.append(("mixed", out["scores"][:3]))

    threads = [threading.Thread(target=hammer) for _ in range(4)]
    for t in threads:
        t.start()
    try:
        for i in range(30):
            srv.swap_model("m", pred_b if i % 2 == 0 else pred_a)
            time.sleep(0.005)
    finally:
        stop.set()
        for t in threads:
            t.join(timeout=60)
        srv.stop()
    assert not bad, bad[:3]


# --------------------------------------------------------------------------- #
# per-request clipped attribution through one coalesced batch
# --------------------------------------------------------------------------- #
def test_clipped_attribution_per_request_in_shared_batch(tmp_path):
    """A key-dense request and a normal one coalesced into ONE batch:
    clipped_instances lands on the fat request's response only (the
    combined call's clipped instance ids demultiplex by request range)."""
    conf, art = _train_and_export(tmp_path, "clip", seed=9)
    kcap = conf.batch_key_capacity or (B * conf.max_feasigns_per_ins)
    srv = ScoringServer(max_batch=8, batch_linger_ms=50)
    srv.register("clip", art, conf)
    calls = []
    orig = srv.score_lines

    def recording(text, name=None):
        out = orig(text, name)
        calls.append(len(out))
        return out

    srv.score_lines = recording

    rng = np.random.default_rng(3)
    parts = ["1 0"]
    per_slot = kcap // S + 8  # one instance over the whole batch capacity
    for _s in range(S):
        ks = rng.integers(0, 40, per_slot)
        parts.append(f"{len(ks)} " + " ".join(map(str, ks)))
    parts.append(f"{DENSE} " + " ".join(
        f"{v:.3f}" for v in rng.random(DENSE)))
    fat = (" ".join(parts) + "\n").encode()
    normal = _lines(3, seed=4)

    port = srv.start(port=0)
    try:
        # sacrificial request occupies the scorer so fat+normal pend
        # together and cut as ONE batch when it finishes
        with srv._lock:
            ts = threading.Thread(target=_post, args=(port, _lines(1)))
            ts.start()
            time.sleep(0.15)  # its batch is parsed and blocked at _lock
            res = {}

            def post(name, body):
                res[name] = _post(port, body)

            tf = threading.Thread(target=post, args=("fat", fat))
            tn = threading.Thread(target=post, args=("normal", normal))
            tf.start()
            tn.start()
            time.sleep(0.15)  # both pending in the forming batch
        ts.join(timeout=30)
        tf.join(timeout=30)
        tn.join(timeout=30)
        st_f, out_f, _ = res["fat"]
        st_n, out_n, _ = res["normal"]
        assert st_f == 200 and len(out_f["scores"]) == 1
        assert out_f["clipped_instances"] == 1
        assert st_n == 200 and len(out_n["scores"]) == 3
        assert "clipped_instances" not in out_n
        # fat + normal really shared one combined scoring call (4 scores)
        assert 4 in calls, calls
    finally:
        srv.stop()


# --------------------------------------------------------------------------- #
# overload under batching: shed loudly, never 5xx, queue drains
# --------------------------------------------------------------------------- #
def test_batched_overload_sheds_cleanly():
    srv = ScoringServer(max_batch=4, batch_linger_ms=2, max_queue=2)
    srv.register_predictor("stub", _StubPredictor(), _stub_conf())

    def score_lines(text, name=None):
        with srv._lock:
            time.sleep(0.03)  # one simulated device call per BATCH
        return [0.5 for ln in text.decode().splitlines() if ln.strip()]

    srv.score_lines = score_lines
    port = srv.start(port=0)
    statuses = []
    lock = threading.Lock()

    def client():
        for _ in range(5):
            st, out, hdrs = _post(port, b"a\nb\n")
            with lock:
                statuses.append(st)
            if st == 429:
                assert int(hdrs["retry-after"]) >= 1

    threads = [threading.Thread(target=client) for _ in range(12)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=120)
    srv.stop()
    assert set(statuses) <= {200, 429}
    assert statuses.count(200) > 0
    assert srv.gate.queue_depth() == 0  # no ghost tickets after the storm


def test_error_isolation_in_shared_batch(tmp_path):
    """One request's malformed payload 400s THAT request only: its batch
    mates score normally through the individual-fallback path."""
    conf, art = _train_and_export(tmp_path, "err", seed=7)
    srv = ScoringServer(max_batch=8, batch_linger_ms=50)
    srv.register("m", art, conf)
    good = _lines(2, seed=8)
    want = srv.score_lines(good, "m")
    port = srv.start(port=0)
    try:
        with srv._lock:
            ts = threading.Thread(target=_post, args=(port, _lines(1)))
            ts.start()
            time.sleep(0.15)
            res = {}

            def post(name, body):
                res[name] = _post(port, body)

            tg = threading.Thread(target=post, args=("good", good))
            tb = threading.Thread(
                target=post, args=("bad", b"not a slot line\n"))
            tg.start()
            tb.start()
            time.sleep(0.15)
        for t in (ts, tg, tb):
            t.join(timeout=30)
        st_g, out_g, _ = res["good"]
        st_b, out_b, _ = res["bad"]
        assert st_g == 200 and out_g["scores"] == want
        assert st_b == 400
    finally:
        srv.stop()


# --------------------------------------------------------------------------- #
# bench sweep smoke: the qps-sweep path cannot rot
# --------------------------------------------------------------------------- #
def test_bench_qps_sweep_smoke():
    """One tiny open-loop point through bench.py's sweep driver: both the
    batched and the max_batch=1 baseline curves come back with zero
    failed requests (the non-slow guard for `bench.py --serving
    --qps-sweep`)."""
    from bench import bench_serving_sweep

    res = bench_serving_sweep([8.0], duration_s=1.2, n_slots=3, dense=2,
                              req_lines=4, ins_per_file=48, hidden=(8,))
    for curve in ("batched_curve", "unbatched_curve"):
        pts = res[curve]
        assert len(pts) == 1
        assert pts[0]["failed"] == 0
        assert pts[0]["ok"] > 0
        assert pts[0]["p99_ms"] is not None
    assert res["max_batch"] > 1
