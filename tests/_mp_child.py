"""Rank program for the multi-process parity test (not a pytest module).

Launched by ``paddlebox_tpu.launch`` with N ranks x K virtual CPU devices;
each rank trains the SAME global batch stream but feeds only its own slice
of every device group — so the N-process run must reproduce the
single-process n-device run exactly (the reference's localhost-subprocess
distributed tier, test_dist_base.py:642 "dist loss == local loss").

argv: data_dir out_json [lrmap]
"""

import glob
import itertools
import json
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

from paddlebox_tpu.parallel.mesh import initialize_distributed  # noqa: E402

initialize_distributed()  # applies PBOX_FORCE_CPU + joins the coordinator

import jax  # noqa: E402
import numpy as np  # noqa: E402


def main() -> None:
    data_dir, out_path = sys.argv[1], sys.argv[2]
    from paddlebox_tpu.config import SparseTableConfig, TrainerConfig
    from paddlebox_tpu.data.dataset import PadBoxSlotDataset
    from paddlebox_tpu.data.feed import empty_like
    from paddlebox_tpu.data.synth import make_synth_config
    from paddlebox_tpu.models import CtrDnn
    from paddlebox_tpu.parallel import (
        MultiChipTrainer,
        ShardedSparseTable,
        make_mesh,
    )
    from paddlebox_tpu.parallel.multiprocess import host_allgather

    S, DENSE, B = 3, 2, 8
    conf = make_synth_config(
        n_sparse_slots=S, dense_dim=DENSE, batch_size=B, max_feasigns_per_ins=16
    )
    ds = PadBoxSlotDataset(conf, read_threads=1)
    ds.set_filelist(sorted(glob.glob(os.path.join(data_dir, "*"))))
    ds.load_into_memory()

    mesh = make_mesh()
    # "lrmap=<json>" arm: per-slot LR map over the sharded path — its slot
    # lrs ride the packed want-matrix allgather on the host-plane KV
    # channel.  The map itself comes from the test via argv so the
    # reference run and this child can never drift.
    lr_map = ()
    for a in sys.argv[3:]:
        if a.startswith("lrmap="):
            lr_map = tuple(tuple(p) for p in json.loads(a[6:]))
    tconf = SparseTableConfig(embedding_dim=8, slot_learning_rates=lr_map)
    trconf = TrainerConfig(auc_buckets=1 << 10)
    model = CtrDnn(S, tconf.row_width, dense_dim=DENSE, hidden=(32, 16))
    trainer = MultiChipTrainer(model, tconf, mesh, trconf, seed=0)
    table = ShardedSparseTable(tconf, mesh, seed=0)
    table.begin_pass(ds.unique_keys())

    pid, n_local, n_dev = jax.process_index(), trainer.n_local, trainer.n_dev

    def local_groups():
        """Global groups of n_dev batches, sliced to this rank's devices —
        same padding discipline as the single-process _group_batches."""
        it = iter(ds.batches(drop_last=False))
        while True:
            group = list(itertools.islice(it, n_dev))
            if not group:
                return
            if len(group) < n_dev:
                group += [empty_like(group[0])] * (n_dev - len(group))
            yield group[pid * n_local : (pid + 1) * n_local]

    metrics = trainer.train_groups(table, local_groups())
    table.end_pass()
    ds.close()

    params, _ = trainer.dense_state()
    param_abs_sum = float(
        sum(np.abs(np.asarray(l)).sum() for l in jax.tree.leaves(params))
    )
    total_features = int(
        host_allgather(np.asarray([table.n_features], np.int64)).sum()
    )
    if pid == 0:
        with open(out_path, "w") as f:
            json.dump(
                {
                    "loss": metrics["loss"],
                    "auc": metrics["auc"],
                    "count": metrics["count"],
                    "steps": metrics["steps"],
                    "param_abs_sum": param_abs_sum,
                    "total_features": total_features,
                },
                f,
            )


if __name__ == "__main__":
    main()
