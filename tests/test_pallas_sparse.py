"""Pallas sparse-kernel parity (interpret mode on CPU) + flag wiring.

Covers VERDICT r2 weak #4: ``flags.use_pallas_sparse`` now routes
``gather_rows``/``scatter_add_rows`` (the pull/push hot ops, single-chip AND
sharded) through the Pallas kernels; these tests pin exact parity with the
XLA gather/scatter they replace — including duplicate scatter indices, the
case CUDA needs atomics for (reference: box_wrapper.cu PushMergeCopy).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from paddlebox_tpu.config import flags
from paddlebox_tpu.ops.pallas_sparse import pallas_pull_rows, pallas_scatter_add


@pytest.fixture
def pallas_flag():
    flags.set("use_pallas_sparse", True)
    yield
    flags.set("use_pallas_sparse", False)


def test_pallas_pull_rows_matches_take():
    rng = np.random.default_rng(0)
    values = jnp.asarray(rng.normal(size=(64, 12)).astype(np.float32))
    idx = jnp.asarray(
        rng.integers(0, 64, size=32).astype(np.int32)
    )  # 32 % 8 == 0
    got = pallas_pull_rows(values, idx, interpret=True)
    want = jnp.take(values, idx, axis=0)
    np.testing.assert_array_equal(np.asarray(got), np.asarray(want))


def test_pallas_scatter_add_matches_at_add_with_duplicates():
    rng = np.random.default_rng(1)
    values = rng.normal(size=(32, 8)).astype(np.float32)
    # heavy duplication incl. the dead row, as real plans produce
    idx = np.array([3, 7, 3, 3, 31, 31, 0, 7], dtype=np.int32)
    delta = rng.normal(size=(8, 8)).astype(np.float32)
    got = pallas_scatter_add(
        jnp.asarray(values), jnp.asarray(idx), jnp.asarray(delta),
        interpret=True,
    )
    want = jnp.asarray(values).at[jnp.asarray(idx)].add(jnp.asarray(delta))
    np.testing.assert_allclose(
        np.asarray(got), np.asarray(want), rtol=1e-6, atol=1e-6
    )


def test_gather_scatter_route_through_flag(pallas_flag):
    """The flag must actually flip the implementation (dead-flag guard)."""
    from paddlebox_tpu.sparse import table as table_mod

    calls = {"pull": 0, "scatter": 0}
    orig_pull, orig_scat = pallas_pull_rows, pallas_scatter_add

    import paddlebox_tpu.ops.pallas_sparse as ps

    def spy_pull(values, idx, **kw):
        calls["pull"] += 1
        return orig_pull(values, idx, **kw)

    def spy_scat(values, idx, delta, **kw):
        calls["scatter"] += 1
        return orig_scat(values, idx, delta, **kw)

    ps.pallas_pull_rows = spy_pull
    ps.pallas_scatter_add = spy_scat
    try:
        values = jnp.zeros((16, 4))
        idx = jnp.zeros(8, dtype=jnp.int32)
        table_mod.gather_rows(values, idx)
        table_mod.scatter_add_rows(values, idx, jnp.ones((8, 4)))
    finally:
        ps.pallas_pull_rows = orig_pull
        ps.pallas_scatter_add = orig_scat
    assert calls == {"pull": 1, "scatter": 1}


def test_e2e_train_step_with_pallas_enabled(pallas_flag, tmp_path):
    """One full single-chip pass with the Pallas path on (interpret mode off
    TPU) must produce the same loss/AUC as the XLA path."""
    from paddlebox_tpu.config import SparseTableConfig, TrainerConfig
    from paddlebox_tpu.data.dataset import PadBoxSlotDataset
    from paddlebox_tpu.data.synth import make_synth_config, write_synth_files
    from paddlebox_tpu.models import CtrDnn
    from paddlebox_tpu.sparse.table import SparseTable
    from paddlebox_tpu.train.trainer import Trainer

    def run(enabled):
        flags.set("use_pallas_sparse", enabled)
        conf = make_synth_config(
            n_sparse_slots=3, dense_dim=2, batch_size=16,
            max_feasigns_per_ins=8,
        )
        files = write_synth_files(
            str(tmp_path / f"p{enabled}"), n_files=1, ins_per_file=64,
            n_sparse_slots=3, vocab_per_slot=40, dense_dim=2, seed=5,
        )
        ds = PadBoxSlotDataset(conf, read_threads=1)
        ds.set_filelist(files)
        ds.load_into_memory()
        tconf = SparseTableConfig(embedding_dim=4)
        model = CtrDnn(3, tconf.row_width, dense_dim=2, hidden=(8,))
        table = SparseTable(tconf, seed=0)
        trainer = Trainer(
            model, tconf, TrainerConfig(auc_buckets=1 << 10), seed=0
        )
        table.begin_pass(ds.unique_keys())
        m = trainer.train_from_dataset(ds, table)
        table.end_pass()
        state = table.state_dict()
        ds.close()
        return m, state

    m_pallas, s_pallas = run(True)
    m_xla, s_xla = run(False)
    assert np.isfinite(m_pallas["loss"])
    np.testing.assert_allclose(m_pallas["loss"], m_xla["loss"], rtol=1e-5)
    np.testing.assert_allclose(
        s_pallas["values"], s_xla["values"], rtol=1e-5, atol=1e-6
    )


def test_pallas_scatter_add_duplicates_across_tiles():
    """Duplicates spanning tile boundaries must accumulate sequentially —
    the cross-tile ordering guarantee (loads of tile g+1 start only after
    tile g's stores completed)."""
    rng = np.random.default_rng(2)
    values = rng.normal(size=(16, 8)).astype(np.float32)
    # 64 indices (tile 32 -> 2 tiles), every index duplicated in both tiles
    idx = np.concatenate([np.arange(16), np.arange(16)] * 2).astype(np.int32)
    delta = rng.normal(size=(64, 8)).astype(np.float32)
    got = pallas_scatter_add(
        jnp.asarray(values), jnp.asarray(idx), jnp.asarray(delta),
        interpret=True,
    )
    want = jnp.asarray(values).at[jnp.asarray(idx)].add(jnp.asarray(delta))
    np.testing.assert_allclose(
        np.asarray(got), np.asarray(want), rtol=1e-5, atol=1e-5
    )


def test_pallas_kernels_odd_and_large_shapes():
    """Tile size adapts to any length (odd -> tile 1; pow2 -> full tile)."""
    rng = np.random.default_rng(3)
    values = rng.normal(size=(128, 12)).astype(np.float32)
    for k in (1, 3, 40, 1024):
        idx = rng.integers(0, 128, size=k).astype(np.int32)
        got = pallas_pull_rows(
            jnp.asarray(values), jnp.asarray(idx), interpret=True
        )
        np.testing.assert_array_equal(
            np.asarray(got), values[idx]
        )
    for u in (3, 40, 256):
        idx = rng.integers(0, 128, size=u).astype(np.int32)
        delta = rng.normal(size=(u, 12)).astype(np.float32)
        got = pallas_scatter_add(
            jnp.asarray(values), jnp.asarray(idx), jnp.asarray(delta),
            interpret=True,
        )
        want = jnp.asarray(values).at[jnp.asarray(idx)].add(jnp.asarray(delta))
        np.testing.assert_allclose(
            np.asarray(got), np.asarray(want), rtol=1e-5, atol=1e-5
        )
