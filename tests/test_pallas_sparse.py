"""Pallas sparse-kernel parity (interpret mode on CPU) + flag wiring.

Covers VERDICT r2 weak #4: ``flags.use_pallas_sparse`` now routes
``gather_rows``/``scatter_add_rows`` (the pull/push hot ops, single-chip AND
sharded) through the Pallas kernels; these tests pin exact parity with the
XLA gather/scatter they replace — including duplicate scatter indices, the
case CUDA needs atomics for (reference: box_wrapper.cu PushMergeCopy).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from paddlebox_tpu.config import flags
from paddlebox_tpu.ops.pallas_sparse import (
    pallas_gather_slots,
    pallas_pull_rows,
    pallas_scatter_add,
    pallas_scatter_rows,
    pallas_sorted_search,
    split_u64,
)


@pytest.fixture
def pallas_flag():
    flags.set("use_pallas_sparse", True)
    yield
    flags.set("use_pallas_sparse", False)


def test_pallas_pull_rows_matches_take():
    rng = np.random.default_rng(0)
    values = jnp.asarray(rng.normal(size=(64, 12)).astype(np.float32))
    idx = jnp.asarray(
        rng.integers(0, 64, size=32).astype(np.int32)
    )  # 32 % 8 == 0
    got = pallas_pull_rows(values, idx, interpret=True)
    want = jnp.take(values, idx, axis=0)
    np.testing.assert_array_equal(np.asarray(got), np.asarray(want))


def test_pallas_scatter_add_matches_at_add_with_duplicates():
    rng = np.random.default_rng(1)
    values = rng.normal(size=(32, 8)).astype(np.float32)
    # heavy duplication incl. the dead row, as real plans produce
    idx = np.array([3, 7, 3, 3, 31, 31, 0, 7], dtype=np.int32)
    delta = rng.normal(size=(8, 8)).astype(np.float32)
    got = pallas_scatter_add(
        jnp.asarray(values), jnp.asarray(idx), jnp.asarray(delta),
        interpret=True,
    )
    want = jnp.asarray(values).at[jnp.asarray(idx)].add(jnp.asarray(delta))
    np.testing.assert_allclose(
        np.asarray(got), np.asarray(want), rtol=1e-6, atol=1e-6
    )


def test_gather_scatter_route_through_flag(pallas_flag):
    """The flag must actually flip the implementation (dead-flag guard)."""
    from paddlebox_tpu.sparse import table as table_mod

    calls = {"pull": 0, "scatter": 0}
    orig_pull, orig_scat = pallas_pull_rows, pallas_scatter_add

    import paddlebox_tpu.ops.pallas_sparse as ps

    def spy_pull(values, idx, **kw):
        calls["pull"] += 1
        return orig_pull(values, idx, **kw)

    def spy_scat(values, idx, delta, **kw):
        calls["scatter"] += 1
        return orig_scat(values, idx, delta, **kw)

    ps.pallas_pull_rows = spy_pull
    ps.pallas_scatter_add = spy_scat
    try:
        values = jnp.zeros((16, 4))
        idx = jnp.zeros(8, dtype=jnp.int32)
        table_mod.gather_rows(values, idx)
        table_mod.scatter_add_rows(values, idx, jnp.ones((8, 4)))
    finally:
        ps.pallas_pull_rows = orig_pull
        ps.pallas_scatter_add = orig_scat
    assert calls == {"pull": 1, "scatter": 1}


def test_e2e_train_step_with_pallas_enabled(pallas_flag, tmp_path):
    """One full single-chip pass with the Pallas path on (interpret mode off
    TPU) must produce the same loss/AUC as the XLA path."""
    from paddlebox_tpu.config import SparseTableConfig, TrainerConfig
    from paddlebox_tpu.data.dataset import PadBoxSlotDataset
    from paddlebox_tpu.data.synth import make_synth_config, write_synth_files
    from paddlebox_tpu.models import CtrDnn
    from paddlebox_tpu.sparse.table import SparseTable
    from paddlebox_tpu.train.trainer import Trainer

    def run(enabled):
        flags.set("use_pallas_sparse", enabled)
        conf = make_synth_config(
            n_sparse_slots=3, dense_dim=2, batch_size=16,
            max_feasigns_per_ins=8,
        )
        files = write_synth_files(
            str(tmp_path / f"p{enabled}"), n_files=1, ins_per_file=64,
            n_sparse_slots=3, vocab_per_slot=40, dense_dim=2, seed=5,
        )
        ds = PadBoxSlotDataset(conf, read_threads=1)
        ds.set_filelist(files)
        ds.load_into_memory()
        tconf = SparseTableConfig(embedding_dim=4)
        model = CtrDnn(3, tconf.row_width, dense_dim=2, hidden=(8,))
        table = SparseTable(tconf, seed=0)
        trainer = Trainer(
            model, tconf, TrainerConfig(auc_buckets=1 << 10), seed=0
        )
        table.begin_pass(ds.unique_keys())
        m = trainer.train_from_dataset(ds, table)
        table.end_pass()
        state = table.state_dict()
        ds.close()
        return m, state

    m_pallas, s_pallas = run(True)
    m_xla, s_xla = run(False)
    assert np.isfinite(m_pallas["loss"])
    np.testing.assert_allclose(m_pallas["loss"], m_xla["loss"], rtol=1e-5)
    np.testing.assert_allclose(
        s_pallas["values"], s_xla["values"], rtol=1e-5, atol=1e-6
    )


def test_pallas_scatter_add_duplicates_across_tiles():
    """Duplicates spanning tile boundaries must accumulate sequentially —
    the cross-tile ordering guarantee (loads of tile g+1 start only after
    tile g's stores completed)."""
    rng = np.random.default_rng(2)
    values = rng.normal(size=(16, 8)).astype(np.float32)
    # 64 indices (tile 32 -> 2 tiles), every index duplicated in both tiles
    idx = np.concatenate([np.arange(16), np.arange(16)] * 2).astype(np.int32)
    delta = rng.normal(size=(64, 8)).astype(np.float32)
    got = pallas_scatter_add(
        jnp.asarray(values), jnp.asarray(idx), jnp.asarray(delta),
        interpret=True,
    )
    want = jnp.asarray(values).at[jnp.asarray(idx)].add(jnp.asarray(delta))
    np.testing.assert_allclose(
        np.asarray(got), np.asarray(want), rtol=1e-5, atol=1e-5
    )


# --------------------------------------------------------------------------- #
# Cache-tier kernels (sparse/engine): numpy-reference parity in interpret mode
# --------------------------------------------------------------------------- #
def _np_gather_slots(table: np.ndarray, slots: np.ndarray) -> np.ndarray:
    """Reference: table[slot] per slot, the zero row where slot < 0."""
    return np.where(
        slots[:, None] >= 0, table[np.maximum(slots, 0)], 0.0
    ).astype(table.dtype)


def _np_scatter_rows(table, slots, rows) -> np.ndarray:
    """Reference: sequential replace — negative dropped, later wins."""
    out = table.copy()
    for i, s in enumerate(slots):
        if s >= 0:
            out[s] = rows[i]
    return out


def _np_sorted_search(keys: np.ndarray, q: np.ndarray) -> np.ndarray:
    """Reference: position of each query in sorted unique ``keys``, -1
    when absent (what HbmCache's numpy resolve computes)."""
    if keys.shape[0] == 0:
        return np.full(q.shape[0], -1, np.int32)
    pos = np.searchsorted(keys, q)
    pos_c = np.minimum(pos, keys.shape[0] - 1)
    return np.where(keys[pos_c] == q, pos_c, -1).astype(np.int32)


def _hay(keys: np.ndarray) -> jnp.ndarray:
    """pow2-padded (hi, lo) haystack for pallas_sorted_search."""
    n = keys.shape[0]
    cpad = 1 << max(0, (n - 1).bit_length()) if n else 0
    hay = np.full((cpad, 2), 0xFFFFFFFF, np.uint32)
    if n:
        hay[:n] = np.asarray(split_u64(keys))
    return jnp.asarray(hay)


class TestCacheKernels:
    def test_gather_slots_matches_reference_with_misses(self):
        rng = np.random.default_rng(4)
        table = rng.normal(size=(64, 12)).astype(np.float32)
        for k in (1, 8, 40):
            slots = rng.integers(-1, 64, size=k).astype(np.int32)
            got = pallas_gather_slots(
                jnp.asarray(table), jnp.asarray(slots), interpret=True
            )
            np.testing.assert_array_equal(
                np.asarray(got), _np_gather_slots(table, slots)
            )

    def test_gather_slots_all_miss_and_empty(self):
        table = np.arange(32, dtype=np.float32).reshape(8, 4)
        all_miss = np.full(8, -1, np.int32)
        got = pallas_gather_slots(
            jnp.asarray(table), jnp.asarray(all_miss), interpret=True
        )
        assert np.asarray(got).sum() == 0.0
        empty = pallas_gather_slots(
            jnp.asarray(table), jnp.zeros(0, jnp.int32), interpret=True
        )
        assert empty.shape == (0, 4)

    def test_scatter_rows_replace_drops_negatives_last_wins(self):
        rng = np.random.default_rng(5)
        table = rng.normal(size=(32, 8)).astype(np.float32)
        # duplicates within AND across tiles (size 8 -> tile 8; also try 16)
        for k in (8, 16):
            slots = rng.integers(-1, 32, size=k).astype(np.int32)
            slots[k // 2] = slots[0]  # force a duplicate
            rows = rng.normal(size=(k, 8)).astype(np.float32)
            got = pallas_scatter_rows(
                jnp.asarray(table), jnp.asarray(slots), jnp.asarray(rows),
                interpret=True,
            )
            np.testing.assert_array_equal(
                np.asarray(got), _np_scatter_rows(table, slots, rows)
            )

    def test_sorted_search_matches_reference(self):
        rng = np.random.default_rng(6)
        keys = np.unique(rng.integers(0, 2**63, size=100).astype(np.uint64))
        n = keys.shape[0]
        q = np.concatenate([
            keys[::3],
            np.asarray([12345, 2**63 + 17, 0], np.uint64),
        ])
        got = pallas_sorted_search(
            _hay(keys), jnp.asarray([n], jnp.int32), split_u64(q),
            interpret=True,
        )
        np.testing.assert_array_equal(
            np.asarray(got), _np_sorted_search(keys, q)
        )

    def test_sorted_search_empty_miss_and_all_miss(self):
        keys = np.asarray([5, 9, 11, 40], np.uint64)
        nr = jnp.asarray([4], jnp.int32)
        # empty-miss: every query present
        got = pallas_sorted_search(_hay(keys), nr, split_u64(keys),
                                   interpret=True)
        np.testing.assert_array_equal(np.asarray(got), [0, 1, 2, 3])
        # all-miss: none present (incl. a key colliding with the sentinel
        # low bits and one past the end)
        q = np.asarray([1, 6, 41, 2**64 - 1], np.uint64)
        got = pallas_sorted_search(_hay(keys), nr, split_u64(q),
                                   interpret=True)
        np.testing.assert_array_equal(np.asarray(got), [-1, -1, -1, -1])
        # empty haystack / empty queries
        got = pallas_sorted_search(
            _hay(np.empty(0, np.uint64)), jnp.asarray([0], jnp.int32),
            split_u64(keys), interpret=True,
        )
        np.testing.assert_array_equal(np.asarray(got), [-1] * 4)
        assert pallas_sorted_search(
            _hay(keys), nr, split_u64(np.empty(0, np.uint64)),
            interpret=True,
        ).shape == (0,)

    def test_sorted_search_max_key_vs_sentinel_padding(self):
        """A real all-ones key must match itself and a missing all-ones
        query must NOT false-positive against the 0xFFFFFFFF padding."""
        keys = np.asarray([3, 2**64 - 1], np.uint64)
        got = pallas_sorted_search(
            _hay(keys), jnp.asarray([2], jnp.int32), split_u64(keys),
            interpret=True,
        )
        np.testing.assert_array_equal(np.asarray(got), [0, 1])
        keys2 = np.asarray([3, 9, 11], np.uint64)  # padded to 4 slots
        got = pallas_sorted_search(
            _hay(keys2), jnp.asarray([3], jnp.int32),
            split_u64(np.asarray([2**64 - 1], np.uint64)), interpret=True,
        )
        np.testing.assert_array_equal(np.asarray(got), [-1])

    def test_hbm_cache_lookup_pallas_parity(self):
        """HbmCache.lookup must produce the identical plan through the
        Pallas sorted-search path and the numpy searchsorted path."""
        from paddlebox_tpu.sparse.engine import HbmCache

        rng = np.random.default_rng(7)
        c = HbmCache(64, 5)
        keys = np.unique(rng.integers(1, 500, size=48).astype(np.uint64))
        c.keys[: keys.shape[0]] = keys
        c.used[: keys.shape[0]] = True
        c._rebuild_index()
        q = np.unique(rng.integers(1, 600, size=80).astype(np.uint64))
        plan_np = c.lookup(q)
        flags.set("use_pallas_sparse", True)
        try:
            plan_pl = c.lookup(q)
        finally:
            flags.set("use_pallas_sparse", False)
        np.testing.assert_array_equal(plan_np.hit_mask, plan_pl.hit_mask)
        np.testing.assert_array_equal(plan_np.hit_pos, plan_pl.hit_pos)
        np.testing.assert_array_equal(plan_np.hit_slots, plan_pl.hit_slots)


def test_pallas_kernels_odd_and_large_shapes():
    """Tile size adapts to any length (odd -> tile 1; pow2 -> full tile)."""
    rng = np.random.default_rng(3)
    values = rng.normal(size=(128, 12)).astype(np.float32)
    for k in (1, 3, 40, 1024):
        idx = rng.integers(0, 128, size=k).astype(np.int32)
        got = pallas_pull_rows(
            jnp.asarray(values), jnp.asarray(idx), interpret=True
        )
        np.testing.assert_array_equal(
            np.asarray(got), values[idx]
        )
    for u in (3, 40, 256):
        idx = rng.integers(0, 128, size=u).astype(np.int32)
        delta = rng.normal(size=(u, 12)).astype(np.float32)
        got = pallas_scatter_add(
            jnp.asarray(values), jnp.asarray(idx), jnp.asarray(delta),
            interpret=True,
        )
        want = jnp.asarray(values).at[jnp.asarray(idx)].add(jnp.asarray(delta))
        np.testing.assert_allclose(
            np.asarray(got), np.asarray(want), rtol=1e-5, atol=1e-5
        )
