"""Stub serving replica for fleet chaos tests: a REAL ScoringServer
(real HTTP stack, real admission gate, real drain/degraded machinery)
whose scoring path is a stub — no artifact, no device work — so a
3-replica fleet spawns in seconds and SIGKILL chaos exercises the
router/supervisor, not XLA.

    python tests/_replica_child.py --port N [--service-ms M]
        [--max-queue Q] [--max-concurrency C] [--deadline-ms D]
        [--degraded REASON] [--crash-after S]
"""

import argparse
import os
import sys
import threading
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


class StubPredictor:
    """Just enough Predictor surface for ModelEntry + /healthz."""

    meta = {"n_tasks": 1, "row_width": 4}
    bucket_shapes = [(8, 64)]
    n_features = 1


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--port", type=int, required=True)
    ap.add_argument("--service-ms", type=float, default=1.0,
                    help="simulated per-request scoring time")
    ap.add_argument("--max-queue", type=int, default=64)
    ap.add_argument("--max-concurrency", type=int, default=1)
    ap.add_argument("--deadline-ms", type=float, default=0.0)
    ap.add_argument("--degraded", default=None,
                    help="advertise this degraded reason from startup")
    ap.add_argument("--crash-after", type=float, default=0.0,
                    help="os._exit(1) this many seconds after startup "
                         "(crash-loop simulation; 0 = never)")
    args = ap.parse_args()

    from paddlebox_tpu import telemetry
    from paddlebox_tpu.config import DataFeedConfig, SlotConfig
    from paddlebox_tpu.inference.server import ScoringServer

    # full postmortem participation: labeled flight dumps (PBOX_FLIGHT_DIR
    # inherited from the spawning test) + SIGTERM ring capture, exactly
    # like a real serve.py replica
    telemetry.set_process_name("replica")
    telemetry.install_signal_dump()

    conf = DataFeedConfig(
        slots=(
            SlotConfig("click", type="float", is_dense=True),
            SlotConfig("s0"),
        ),
        batch_size=8,
    )
    srv = ScoringServer(
        max_queue=args.max_queue,
        max_concurrency=args.max_concurrency,
        request_deadline_ms=args.deadline_ms or None,
    )
    srv.register_predictor("stub", StubPredictor(), conf)
    if args.degraded:
        srv.set_degraded(args.degraded, "stub replica flag")

    pid = os.getpid()
    service_s = args.service_ms / 1e3

    def score_lines(text: bytes, name=None) -> list:
        # the stub "model": one score per line, tagged with OUR pid so a
        # test can prove which replica answered — behind the server's
        # REAL scoring lock, so admission/concurrency behave exactly as
        # in production
        lines = [ln for ln in text.decode().splitlines() if ln.strip()]
        with srv._lock:
            if service_s > 0:
                time.sleep(service_s)
        return [float(pid)] * len(lines)

    srv.score_lines = score_lines

    if args.crash_after > 0:
        def crash():
            time.sleep(args.crash_after)
            os._exit(1)

        threading.Thread(target=crash, daemon=True).start()

    port = srv.start(port=args.port)
    print(f"stub replica pid={pid} port={port}", flush=True)
    srv.wait()


if __name__ == "__main__":
    main()
