"""ANN retrieval serving (inference/ann.py + POST /retrieve): artifact
roundtrip, exact/int8 search with the recall@10 pin, delta merge
semantics, and the full train -> publish -> sync -> /retrieve-through-
the-router e2e with failover chaos on the retrieve path."""

import json
import os
import urllib.error
import urllib.request

import numpy as np
import pytest

from paddlebox_tpu.config import SparseTableConfig, TrainerConfig
from paddlebox_tpu.data.dataset import PadBoxSlotDataset
from paddlebox_tpu.data.synth import make_synth_config, write_synth_files
from paddlebox_tpu.inference import ScoringServer
from paddlebox_tpu.inference.ann import (
    AnnIndex,
    export_ann_index,
    rows_to_item_embeddings,
)
from paddlebox_tpu.models import TwoTower
from paddlebox_tpu.scenarios import MultiScenarioTrainer, ScenarioSpec
from paddlebox_tpu.serving_fleet import FleetRouter
from paddlebox_tpu.serving_sync import Publisher, Syncer
from paddlebox_tpu.sparse.table import SparseTable
from paddlebox_tpu.utils.faults import fault_plan
from paddlebox_tpu.utils.monitor import stats

S, DENSE, B, VOCAB = 4, 4, 32, 50
ITEM_SLOT = S - 1
LO, HI = ITEM_SLOT * VOCAB + 1, (ITEM_SLOT + 1) * VOCAB


def _unit_rows(n, d, seed=0):
    rng = np.random.default_rng(seed)
    e = rng.normal(size=(n, d)).astype(np.float32)
    return e / np.linalg.norm(e, axis=1, keepdims=True)


def _index(n=64, d=8, seed=0, **meta):
    keys = np.arange(1, n + 1, dtype=np.uint64)
    return AnnIndex(keys, _unit_rows(n, d, seed),
                    {"embed_dim": d, "row_width": d + 2, "cvm_offset": 2,
                     "item_key_lo": 1, "item_key_hi": n,
                     "create_threshold": 0.0, **meta})


# --------------------------------------------------------------------------- #
# embeddings + search
# --------------------------------------------------------------------------- #
def test_rows_to_item_embeddings_normalizes():
    values = np.random.default_rng(0).normal(size=(5, 11)).astype(np.float32)
    emb = rows_to_item_embeddings(values, cvm_offset=2, row_width=10)
    assert emb.shape == (5, 8)
    np.testing.assert_allclose(np.linalg.norm(emb, axis=1), 1.0, rtol=1e-5)


def test_exact_search_matches_brute_force():
    idx = _index(n=40, d=8)
    q = _unit_rows(6, 8, seed=1)
    keys, scores = idx.search(q, k=5, tier="exact")
    ref = q @ idx.emb.T
    for i in range(len(q)):
        want = np.argsort(-ref[i])[:5]
        np.testing.assert_array_equal(keys[i], idx.keys[want])
        np.testing.assert_allclose(scores[i], ref[i][want], rtol=1e-5)
    # scores are sorted descending
    assert all((np.diff(s) <= 1e-6).all() for s in scores)


def test_search_validation():
    idx = _index()
    q = _unit_rows(2, 8)
    with pytest.raises(ValueError, match="tier"):
        idx.search(q, k=3, tier="fp64")
    with pytest.raises(ValueError, match="k"):
        idx.search(q, k=0)
    with pytest.raises(ValueError):
        idx.search(_unit_rows(2, 5), k=3)  # dim mismatch


def test_int8_recall_at_10_pin():
    """The acceptance pin: the int8 coarse tier's top-10 agrees with the
    exact scorer at >= 0.95 recall on unit-norm queries."""
    idx = _index(n=300, d=16, seed=2)
    q = _unit_rows(64, 16, seed=3)
    ek, _ = idx.search(q, k=10, tier="exact")
    qk, _ = idx.search(q, k=10, tier="int8")
    recall = np.mean([
        len(set(ek[i]) & set(qk[i])) / 10.0 for i in range(len(q))
    ])
    assert recall >= 0.95, f"int8 recall@10 {recall:.3f} < 0.95"


# --------------------------------------------------------------------------- #
# artifact roundtrip + delta merge
# --------------------------------------------------------------------------- #
def test_save_load_roundtrip(tmp_path):
    idx = _index(n=20, d=8)
    idx.save(str(tmp_path / "a"))
    back = AnnIndex.load(str(tmp_path / "a"))
    np.testing.assert_array_equal(back.keys, idx.keys)
    np.testing.assert_array_equal(back.emb, idx.emb)
    assert back.meta["artifact_kind"] == "ann"
    assert back.n_features == idx.n_features
    # predict() is not this artifact's surface
    with pytest.raises(ValueError, match="retrieve"):
        back.predict({})


def test_with_delta_replaces_inserts_and_range_filters():
    idx = _index(n=10, d=8, item_key_hi=20)  # range [1, 20], keys 1..10
    co, w = idx.meta["cvm_offset"], idx.meta["row_width"]
    rng = np.random.default_rng(4)

    def rows(n, show=10.0):
        v = rng.normal(size=(n, w)).astype(np.float32)
        v[:, 0] = show  # show counter clears admission
        return v

    # key 3 replaced, key 25 outside [1, 20] dropped, key 15 inserted
    # twice (last write wins)
    keys = np.array([3, 25, 15, 15], np.uint64)
    vals = rows(4)
    new = idx.with_delta(
        keys, vals, program_dir=None, bucket_meta=None)
    assert new.n_items == 11  # +15 only
    np.testing.assert_array_equal(
        new.keys, np.sort(np.concatenate([idx.keys, [np.uint64(15)]])))
    i3 = int(np.searchsorted(new.keys, 3))
    want3 = vals[0, co:w] / np.linalg.norm(vals[0, co:w])
    np.testing.assert_allclose(new.emb[i3], want3, rtol=1e-5)
    i15 = int(np.searchsorted(new.keys, 15))
    want15 = vals[3, co:w] / np.linalg.norm(vals[3, co:w])  # LAST dup wins
    np.testing.assert_allclose(new.emb[i15], want15, rtol=1e-5)
    # the source index is untouched (hot-swap semantics)
    assert idx.n_items == 10


def test_with_delta_admits_below_threshold_when_configured():
    idx = _index(n=4, d=8, create_threshold=5.0)
    w = idx.meta["row_width"]
    v = np.ones((1, w), np.float32)
    v[0, 0] = 2.0  # show 2 < threshold 5
    new = idx.with_delta(np.array([2], np.uint64), v,
                         program_dir=None, bucket_meta=None)
    assert new.n_items == 4  # rejected, not merged


# --------------------------------------------------------------------------- #
# export from a trained table
# --------------------------------------------------------------------------- #
@pytest.fixture(scope="module")
def trained(tmp_path_factory):
    d = tmp_path_factory.mktemp("ann_synth")
    paths = write_synth_files(
        str(d), n_files=2, ins_per_file=256, n_sparse_slots=S,
        vocab_per_slot=VOCAB, dense_dim=DENSE, seed=5,
    )
    conf = make_synth_config(n_sparse_slots=S, dense_dim=DENSE,
                             batch_size=B, max_feasigns_per_ins=12)
    tconf = SparseTableConfig(embedding_dim=8, learning_rate=0.5,
                              initial_range=0.05)
    table = SparseTable(tconf, seed=0)
    model = TwoTower(S, tconf.row_width, item_slots=(ITEM_SLOT,),
                     dense_dim=DENSE, hidden=(16, 8), temperature=0.05)
    mst = MultiScenarioTrainer(tconf, [ScenarioSpec(
        "retr", model, kind="retrieval",
        trainer_conf=TrainerConfig(dense_lr=3e-3, auc_buckets=1 << 10),
        seed=3)])
    ds = PadBoxSlotDataset(conf, read_threads=1)
    ds.set_filelist(paths)
    ds.load_into_memory()
    yield table, mst, ds, tconf
    ds.close()


def test_export_filters_to_item_key_range(trained, tmp_path):
    table, mst, ds, tconf = trained
    mst.train_pass({"retr": ds}, table)
    idx = export_ann_index(str(tmp_path / "ann"), table,
                           item_key_lo=LO, item_key_hi=HI)
    assert idx.n_items > 0
    assert idx.keys.min() >= LO and idx.keys.max() <= HI
    assert idx.meta["embed_dim"] == tconf.embedding_dim
    np.testing.assert_allclose(
        np.linalg.norm(idx.emb, axis=1), 1.0, rtol=1e-5)


# --------------------------------------------------------------------------- #
# e2e: publish -> sync -> /retrieve through the live router
# --------------------------------------------------------------------------- #
def _post(url, body, deadline_ms=None):
    headers = {"Content-Type": "application/json"}
    if deadline_ms:
        headers["X-Request-Deadline-Ms"] = str(deadline_ms)
    req = urllib.request.Request(url, data=json.dumps(body).encode(),
                                 headers=headers)
    try:
        with urllib.request.urlopen(req, timeout=30) as r:
            return r.status, json.loads(r.read())
    except urllib.error.HTTPError as exc:
        return exc.code, exc.read().decode()


def test_retrieve_e2e_through_router(trained, tmp_path):
    """train -> publish_ann_base -> more training -> publish_delta ->
    Syncer hot-apply -> POST /retrieve through the live fleet router;
    the delta MOVES the candidates.  Plus: chaos failover on
    retrieve.query, the 404 split for unknown POST paths, and the clean
    /score refusal on a feed-less retrieval model."""
    table, mst, ds, tconf = trained
    mst.train_pass({"retr": ds}, table)
    root = str(tmp_path / "pub")
    pub = Publisher(root, staging_dir=str(tmp_path / "stage"))
    pub.publish_ann_base("a0", table, item_key_lo=LO, item_key_hi=HI,
                         meta={"scenario": "retr"})

    srv = ScoringServer()
    syncer = Syncer(root, srv, "retr", cache_dir=str(tmp_path / "cache"),
                    poll_interval_s=0.05)
    assert syncer.poll_once() == 1
    port = srv.start(port=0, host="127.0.0.1")
    router = FleetRouter([f"127.0.0.1:{port}"])
    rport = router.start(port=0, host="127.0.0.1")
    try:
        base = f"http://127.0.0.1:{rport}"
        q = _unit_rows(3, tconf.embedding_dim, seed=9)
        st, out = _post(f"{base}/retrieve/retr",
                        {"queries": q.tolist(), "k": 5})
        assert st == 200
        assert len(out["results"]) == 3
        assert all(len(r["keys"]) == 5 for r in out["results"])
        assert all(LO <= k <= HI for r in out["results"] for k in r["keys"])
        before = out["results"]

        # int8 tier serves through the same endpoint
        st, out8 = _post(f"{base}/retrieve/retr",
                         {"queries": q.tolist(), "k": 5, "tier": "int8"})
        assert st == 200 and out8["tier"] == "int8"

        # train more, ship a DELTA, hot-apply: candidates move
        mst.train_pass({"retr": ds}, table)
        pub.publish_delta("a1", table)
        assert syncer.poll_once() == 1
        assert syncer.applied_seq == 1
        st, after = _post(f"{base}/retrieve/retr",
                          {"queries": q.tolist(), "k": 5})
        assert st == 200
        moved = any(
            a["keys"] != b["keys"] or not np.allclose(
                a["scores"], b["scores"])
            for a, b in zip(after["results"], before)
        )
        assert moved, "delta applied but top-k candidates did not move"

        # chaos: one injected fault on the retrieve path -> the router's
        # verbatim-body failover retries the OTHER replica and the
        # CLIENT still sees 200.  Second replica = its own synced server
        # over the same publish root.
        srv2 = ScoringServer()
        syncer2 = Syncer(root, srv2, "retr",
                         cache_dir=str(tmp_path / "cache2"),
                         poll_interval_s=0.05)
        assert syncer2.poll_once() == 2  # base + delta
        port2 = srv2.start(port=0, host="127.0.0.1")
        router2 = FleetRouter([f"127.0.0.1:{port}", f"127.0.0.1:{port2}"])
        rport2 = router2.start(port=0, host="127.0.0.1")
        try:
            n0 = stats.get("faults.injected.retrieve.query")
            with fault_plan({"retrieve.query": "first:1"}):
                st, _ = _post(f"http://127.0.0.1:{rport2}/retrieve/retr",
                              {"queries": q.tolist(), "k": 5})
            assert st == 200
            assert stats.get("faults.injected.retrieve.query") == n0 + 1
        finally:
            router2.stop()
            srv2.stop()

        # unknown POST path: clean 404 on server AND router
        st, _ = _post(f"{base}/bogus", {"x": 1})
        assert st == 404
        st, _ = _post(f"http://127.0.0.1:{port}/bogus", {"x": 1})
        assert st == 404
        # a retrieval model refuses /score with a clean 400
        st, msg = _post(f"{base}/score/retr", {"x": 1})
        assert st in (400, 404)

        # unknown model name on /retrieve -> 404
        st, _ = _post(f"{base}/retrieve/nope", {"queries": q.tolist()})
        assert st == 404
        # malformed body -> 400
        st, _ = _post(f"http://127.0.0.1:{port}/retrieve/retr",
                      {"queries": []})
        assert st == 400
    finally:
        router.stop()
        srv.stop()


def test_unknown_post_path_hits_request_counter(trained, tmp_path):
    """The 404 split satellite: an unknown POST path lands in
    server.requests under the default-model label with status 4xx."""
    from paddlebox_tpu import telemetry

    table, mst, ds, tconf = trained
    idx_dir = str(tmp_path / "ann")
    mst.train_pass({"retr": ds}, table)
    export_ann_index(idx_dir, table, item_key_lo=LO, item_key_hi=HI)
    srv = ScoringServer()
    srv.register_predictor("retr", AnnIndex.load(idx_dir), None)
    port = srv.start(port=0, host="127.0.0.1")
    try:
        before = telemetry.registry.snapshot()["counters"]
        st, _ = _post(f"http://127.0.0.1:{port}/nope", {"x": 1})
        assert st == 404
        after = telemetry.registry.snapshot()["counters"]
        key = "server.requests{model=-,status=4xx}"
        assert after.get(key, 0) == before.get(key, 0) + 1
    finally:
        srv.stop()


def test_feedless_register_requires_search():
    srv = ScoringServer()

    class _NotRetrieval:
        meta = {"n_tasks": 1}

    with pytest.raises(ValueError, match="feed schema"):
        srv.register_predictor("m", _NotRetrieval(), None)
