"""Run-health plane (telemetry/health.py + tools/pbox_doctor.py):
EWMA z-score math, window flattening, alert plumbing (counter + JSONL
event + critical flight dump with the run-identity stamp), the seeded
fault -> specific-alert pins, the clean-run false-positive pin, the
health-rule-drift guard, and the doctor's first-bad-pass verdict
reconstructed from dump files alone."""

import importlib
import json
import math
import os
import sys

import numpy as np
import pytest

from paddlebox_tpu import telemetry
from paddlebox_tpu.config import SparseTableConfig, TrainerConfig
from paddlebox_tpu.data.synth import make_synth_config, write_synth_files
from paddlebox_tpu.models import CtrDnn
from paddlebox_tpu.sparse.table import SparseTable
from paddlebox_tpu.telemetry import flight, health
from paddlebox_tpu.telemetry.events import EventLog
from paddlebox_tpu.telemetry.health import (
    HealthMonitor,
    HealthRule,
    _Ewma,
    flatten_window,
    rule_names,
)
from paddlebox_tpu.telemetry.metrics import registry
from paddlebox_tpu.train.trainer import Trainer
from paddlebox_tpu.utils import faults

HERE = os.path.dirname(os.path.abspath(__file__))

N_SLOTS = 3
DENSE = 2


def _tool(mod: str):
    sys.path.insert(0, os.path.join(os.path.dirname(HERE), "tools"))
    try:
        return importlib.import_module(mod)
    finally:
        sys.path.pop(0)


@pytest.fixture(autouse=True)
def _fresh_monitor():
    """Every test gets a clean singleton (EWMA state and alert rings are
    per-run state, not per-process state)."""
    health.reset_for_tests()
    faults.clear()
    yield
    health.reset_for_tests()
    faults.clear()


def _rule(**kw) -> HealthRule:
    base = dict(name="t.rule", family="training", signal="metrics.x",
                kind="zscore", direction="above", threshold=4.0)
    base.update(kw)
    return HealthRule(**base)


# --------------------------------------------------------------------------- #
# unit: EWMA + rule evaluation
# --------------------------------------------------------------------------- #
def test_ewma_update_math():
    e = _Ewma()
    e.update(10.0, 0.5)
    assert (e.mean, e.var) == (10.0, 0.0)  # first sample seeds the mean
    e.update(14.0, 0.5)
    # d=4: mean 10+2=12, var = 0.5*(0 + 0.5*16) = 4
    assert e.mean == pytest.approx(12.0)
    assert e.var == pytest.approx(4.0)


def test_zscore_fires_after_warmup_only():
    m = HealthMonitor(rules=[_rule(min_delta=0.5)], ewma_alpha=0.5,
                      warmup=3, enabled=True)
    fired = []
    for i, x in enumerate([1.0, 1.0, 1.0, 1.0, 50.0]):
        fired.append(m.observe(i, metrics={"x": x}))
    assert [len(a) for a in fired] == [0, 0, 0, 0, 1]
    a = fired[-1][0]
    assert a.rule == "t.rule" and a.window == 4
    assert a.observed == 50.0 and a.baseline == pytest.approx(1.0)


def test_zscore_noise_floor_suppresses_small_deviation():
    # zero-variance baseline makes z infinite — only the min_delta floor
    # keeps a 0.1 wiggle from alerting
    m = HealthMonitor(rules=[_rule(min_delta=0.5)], ewma_alpha=0.5,
                      warmup=2, enabled=True)
    for i, x in enumerate([1.0, 1.0, 1.0, 1.1]):
        assert m.observe(i, metrics={"x": x}) == []


def test_zscore_direction_below_and_min_rel():
    m = HealthMonitor(
        rules=[_rule(direction="below", min_rel=0.3)],
        ewma_alpha=0.5, warmup=2, enabled=True)
    assert m.observe(0, metrics={"x": 10.0}) == []
    assert m.observe(1, metrics={"x": 10.0}) == []
    # floor = 0.3*10 = 3: an 8.0 reading (dev 2) stays quiet...
    assert m.observe(2, metrics={"x": 8.0}) == []
    # ...a collapse to 1.0 does not
    alerts = m.observe(3, metrics={"x": 1.0})
    assert [a.rule for a in alerts] == ["t.rule"]


def test_nonfinite_observation_fires_even_during_warmup():
    m = HealthMonitor(rules=[_rule()], ewma_alpha=0.5, warmup=10,
                      enabled=True)
    alerts = m.observe(0, metrics={"x": float("nan")})
    assert len(alerts) == 1 and alerts[0].detail == "non-finite observation"
    # and the dict form survives JSON round-tripping
    d = json.loads(json.dumps(alerts[0].to_dict()))
    assert d["observed"] == "nan"


def test_abs_max_and_nonzero_kinds():
    rules = [
        _rule(name="t.abs", kind="abs_max", threshold=2.0),
        _rule(name="t.zero", kind="nonzero", signal="counter.jit.compiles"),
    ]
    m = HealthMonitor(rules=rules, ewma_alpha=0.5, warmup=1, enabled=True)
    # window 0: inside warmup — nonzero must NOT fire (warmup = compiles
    # are expected); abs_max has no warmup and fires immediately
    a0 = m.observe(0, metrics={"x": 3.0},
                   telemetry={"counters": {"jit.compiles{stage=s}": 2}})
    assert [a.rule for a in a0] == ["t.abs"]
    # window 1: past warmup, a compile is an incident; absent counter = 0
    a1 = m.observe(1, metrics={"x": 0.0},
                   telemetry={"counters": {"jit.compiles{stage=s}": 1}})
    assert [a.rule for a in a1] == ["t.zero"]
    assert m.observe(2, metrics={"x": 0.0}) == []


def test_disabled_monitor_is_inert():
    m = HealthMonitor(rules=[_rule()], enabled=False)
    assert m.observe(0, metrics={"x": float("nan")}) == []
    assert m.snapshot()["enabled"] is False


# --------------------------------------------------------------------------- #
# unit: window flattening
# --------------------------------------------------------------------------- #
def test_flatten_window_namespace_and_derived_rates():
    sig = flatten_window(
        metrics={"loss": 0.5, "steps": 90, "samples": 1000.0,
                 "duration_s": 2.0, "path": "scan8"},
        telemetry={
            "counters": {"train.nan_skipped_steps": 10,
                         "data.quarantined_lines": 20,
                         "x.y{a=1}": 3, "x.y{a=2}": 4},
            "gauges": {"g.z{a=1}": 5.0, "g.z{a=2}": 9.0},
            "histograms": {
                "h.s{a=1}": {"boundaries": [1.0], "counts": [2, 0],
                             "sum": 1.0, "count": 2, "min": 0.4,
                             "max": 0.6},
                "h.s{a=2}": {"boundaries": [1.0], "counts": [0, 2],
                             "sum": 8.0, "count": 2, "min": 3.0,
                             "max": 5.0},
            },
        },
        table_stats={"cache_hit_rate": 0.75, "note": "str ignored"},
    )
    assert sig["metrics.loss"] == 0.5
    assert "metrics.path" not in sig  # non-numeric fields dropped
    assert sig["counter.x.y"] == 7.0  # label variants sum
    assert sig["gauge.g.z"] == 9.0  # gauges take the max
    assert sig["hist.h.s.count"] == 4.0
    assert sig["hist.h.s.mean"] == pytest.approx(9.0 / 4)
    assert sig["hist.h.s.p99"] == pytest.approx(5.0, abs=0.2)
    assert sig["table.cache_hit_rate"] == 0.75
    assert sig["derived.nan_skip_rate"] == pytest.approx(10 / 100)
    assert sig["derived.quarantine_rate"] == pytest.approx(20 / 1000)
    assert sig["derived.samples_per_s"] == pytest.approx(500.0)


# --------------------------------------------------------------------------- #
# plumbing: counter + event + critical flight dump (+ run identity stamp)
# --------------------------------------------------------------------------- #
def test_alert_plumbing_counter_event_and_critical_dump(tmp_path,
                                                        monkeypatch):
    monkeypatch.setenv("PBOX_FLIGHT_DIR", str(tmp_path))
    events_path = tmp_path / "events.jsonl"
    el = EventLog(str(events_path))
    monkeypatch.setattr("paddlebox_tpu.telemetry.events._event_log", el)
    m = health.reset_for_tests(warmup=0)
    before = registry.snapshot()["counters"].get(
        "health.alerts{rule=train.loss_spike,severity=critical}", 0)
    alerts = m.observe(7, metrics={"loss": float("nan")})
    assert [a.rule for a in alerts] == ["train.loss_spike"]
    after = registry.snapshot()["counters"][
        "health.alerts{rule=train.loss_spike,severity=critical}"]
    assert after == before + 1
    el.close()
    # the JSONL event
    recs = [json.loads(ln) for ln in events_path.read_text().splitlines()]
    evs = [r for r in recs if r["event"] == "health_alert"]
    assert evs and evs[0]["rule"] == "train.loss_spike"
    assert evs[0]["window"] == 7
    # the critical dump, carrying the alert as detail AND the run identity
    dumps = [f for f in os.listdir(tmp_path) if "-health-" in f]
    assert len(dumps) == 1
    d = json.loads((tmp_path / dumps[0]).read_text())
    assert d["reason"] == "health"
    assert d["detail"]["rule"] == "train.loss_spike"
    assert d["detail"]["window"] == 7
    run = d["run"]
    assert run["git_sha"] and run["host"] and run["pid"] == os.getpid()
    assert "jax_version" in run and "backend" in run
    # snapshot view (what /healthz and the router fleet view expose)
    view = telemetry.health_view()
    assert view["alerts_total"] == 1 and view["critical_total"] == 1
    assert view["recent"][0]["rule"] == "train.loss_spike"


def test_doctor_health_report_from_dumps_alone(tmp_path, monkeypatch):
    """pbox_doctor must name the first bad pass with ONLY flight dump
    files on disk — no JSONL event log survived the crash."""
    monkeypatch.setenv("PBOX_FLIGHT_DIR", str(tmp_path))
    flight.reset_for_tests()  # drop health_alert ring records of prior tests
    m = health.reset_for_tests(warmup=0)
    m.observe(7, metrics={"loss": float("nan")})
    m.observe(5, metrics={"auc": float("nan")})
    assert len([f for f in os.listdir(tmp_path) if "-health-" in f]) == 2
    doctor = _tool("pbox_doctor")
    report = doctor.analyze(str(tmp_path))
    hr = report["health"]
    assert hr["by_severity"] == {"critical": 2}
    assert hr["first_bad_window"] == 5  # smallest window, not earliest t
    assert hr["first_bad"]["rule"] == "train.auc_drop"
    assert "FIRST BAD PASS/WINDOW: 5" in doctor.format_summary(report)


# --------------------------------------------------------------------------- #
# e2e pins: seeded fault -> its specific alert within 2 passes
# --------------------------------------------------------------------------- #
@pytest.fixture(scope="module")
def synth(tmp_path_factory):
    d = tmp_path_factory.mktemp("health_synth")
    paths = write_synth_files(
        str(d), n_files=2, ins_per_file=256, n_sparse_slots=N_SLOTS,
        vocab_per_slot=40, dense_dim=DENSE, seed=3,
    )
    conf = make_synth_config(
        n_sparse_slots=N_SLOTS, dense_dim=DENSE, batch_size=32,
        max_feasigns_per_ins=8,
    )
    return paths, conf


def _world(conf, nan_policy="raise", seed=0):
    tconf = SparseTableConfig(embedding_dim=4, learning_rate=0.4,
                              initial_range=0.05)
    table = SparseTable(tconf, seed=seed)
    model = CtrDnn(N_SLOTS, tconf.row_width, dense_dim=DENSE, hidden=(16, 8))
    trainer = Trainer(
        model, tconf,
        TrainerConfig(auc_buckets=1 << 10, nan_policy=nan_policy,
                      check_nan_inf=True),
        seed=seed,
    )
    return table, trainer


def _load(paths, conf):
    from paddlebox_tpu.data.dataset import DatasetFactory

    ds = DatasetFactory().create_dataset("BoxPSDataset", conf)
    ds.set_filelist(paths)
    ds.load_into_memory()
    return ds


def test_clean_run_fires_zero_alerts(synth):
    """The false-positive pin: five ordinary passes (loss moving, AUC
    improving, weights growing — normal early-training drift) must not
    trip any rule."""
    paths, conf = synth
    monitor = health.reset_for_tests()
    ds = _load(paths, conf)
    table, trainer = _world(conf)
    try:
        for p in range(5):
            table.begin_pass(ds.unique_keys())
            metrics = trainer.train_from_dataset(ds, table, drop_last=True)
            table.end_pass()
    finally:
        ds.close()
    snap = monitor.snapshot()
    assert snap["alerts_total"] == 0, snap["recent"]
    assert snap["windows"] == 5
    # satellite: pass metrics now carry wall-clock + sample count
    assert metrics["duration_s"] > 0
    assert metrics["samples"] == 512.0
    assert metrics["grad_norm"] > 0 and metrics["weight_norm"] > 0


def test_nan_fault_fires_training_alert_within_two_passes(synth):
    paths, conf = synth
    monitor = health.reset_for_tests()
    ds = _load(paths, conf)
    table, trainer = _world(conf, nan_policy="skip_batch")
    bad_pass = 3
    try:
        for p in range(5):
            table.begin_pass(ds.unique_keys())
            if p == bad_pass:
                # poison 8 of the 16 batches of this pass
                faults.install(faults.FaultPlan({"train.nan": "first:8"}))
            try:
                trainer.train_from_dataset(ds, table, drop_last=True)
            finally:
                faults.clear()
            table.end_pass()
    finally:
        ds.close()
    fired = {(a["rule"], a["window"]) for a in monitor.snapshot()["recent"]}
    windows = {w for r, w in fired if r == "train.nan_rate"}
    assert windows, f"train.nan_rate never fired: {fired}"
    assert min(windows) <= bad_pass + 1  # within 2 passes of the fault
    # the clean passes around it stayed quiet on this rule
    assert all(w >= bad_pass for w in windows)


def test_cache_starvation_fires_hit_rate_collapse():
    """Mid-run HBM-cache starvation: swap the warm cache for a tiny one
    (the operational shape: capacity reconfigured way under the working
    set) and the collapse rule must fire within 2 passes."""
    from paddlebox_tpu.sparse.engine import HbmCache

    tconf = SparseTableConfig(
        embedding_dim=4, store_buckets=16, plan_scratch_rows=64,
        hbm_cache_rows=512,
    )
    table = SparseTable(tconf, seed=0)
    keys = np.arange(1, 300, dtype=np.uint64)
    monitor = HealthMonitor(ewma_alpha=0.5, warmup=2, enabled=True)
    fired = {}
    starve_at = 10
    for p in range(starve_at + 2):
        if p == starve_at:
            table._drain_cache()
            table._cache = HbmCache(8, tconf.row_width + 1)
        table.begin_pass(keys)
        table.end_pass()
        for a in monitor.observe(
                p, metrics={"steps": 1},
                telemetry=registry.delta_snapshot(), table=table):
            fired.setdefault(a.rule, a)
    assert "table.hit_rate_collapse" in fired, fired
    a = fired["table.hit_rate_collapse"]
    assert a.window >= starve_at and a.observed < 0.2
    assert a.severity == "critical"


def test_steady_state_recompile_alert():
    from paddlebox_tpu.telemetry.compiles import (
        counted_jit,
        install_compile_listener,
    )

    if not install_compile_listener():
        pytest.skip("no compile-event listener on this jax")
    monitor = HealthMonitor(ewma_alpha=0.5, warmup=0, enabled=True)
    registry.delta_snapshot()  # reset the delta baseline
    assert monitor.observe(0, telemetry=registry.delta_snapshot()) == []
    import jax.numpy as jnp

    fn = counted_jit(lambda x: x * 2 + 1, stage="health_test")
    fn(jnp.ones((4,), jnp.float32))  # fresh wrapper -> a compile
    alerts = monitor.observe(1, telemetry=registry.delta_snapshot())
    assert "pipeline.steady_state_recompile" in [a.rule for a in alerts]


# --------------------------------------------------------------------------- #
# shared window: log_pass returns the snapshot the monitor must see
# --------------------------------------------------------------------------- #
def test_log_pass_returns_the_logged_snapshot(tmp_path):
    registry.counter("health_test.c", help="t").inc(3)
    el = EventLog(str(tmp_path / "ev.jsonl"))
    registry.delta_snapshot()
    registry.counter("health_test.c").inc(2)
    snap = el.log_pass({"loss": 0.1}, pass_idx=0)
    el.close()
    assert snap["counters"]["health_test.c"] == 2
    rec = [json.loads(ln) for ln in
           (tmp_path / "ev.jsonl").read_text().splitlines()
           if json.loads(ln)["event"] == "pass_end"][0]
    assert rec["telemetry"]["counters"]["health_test.c"] == 2


# --------------------------------------------------------------------------- #
# drift guard: catalog <-> ARCHITECTURE.md "## Run health", both ways
# --------------------------------------------------------------------------- #
def test_health_rule_drift_guard_clean():
    rd = _tool("pbox_analyze.rules_drift")
    names = rd.health_rule_names()
    assert set(names) == set(rule_names())
    missing, stale = rd.health_check()
    assert missing == [] and stale == []


def test_health_rule_drift_guard_detects_both_directions(monkeypatch):
    rd = _tool("pbox_analyze.rules_drift")
    real = rd.health_rule_names()
    extra = dict(real)
    extra["train.made_up_rule"] = "health.py:1"
    monkeypatch.setattr(rd, "health_rule_names", lambda: extra)
    missing, stale = rd.health_check()
    assert [n for n, _ in missing] == ["train.made_up_rule"]
    shrunk = dict(real)
    shrunk.pop("train.loss_spike")
    monkeypatch.setattr(rd, "health_rule_names", lambda: shrunk)
    missing, stale = rd.health_check()
    assert missing == []
    assert any("train.loss_spike" in pat for pat, _ in stale)


def test_rule_catalog_is_well_formed():
    rules = health.default_rules()
    assert len(rules) == len({r.name for r in rules})  # unique names
    fams = {r.family for r in rules}
    assert fams == {"training", "table", "pipeline"}
    with pytest.raises(ValueError):
        HealthRule(name="x", family="training", signal="s", kind="bogus")
    with pytest.raises(ValueError):
        HealthRule(name="x", family="training", signal="s", kind="zscore",
                   severity="loud")
