"""Rank program for the frozen-worker liveness chaos test (not pytest).

Launched by ``paddlebox_tpu.launch``: every rank joins the JAX
coordination service and drives lockstep KV-channel allgathers (the
host-planning plane a real multi-host pass rides) under a liveness
watchdog with KV heartbeats.  One rank — argv ``stall_rank`` — activates
a hang-injection fault plan through the PBOX_FAULT_PLAN env path,
freezing itself mid-gather; the whole fleet must then abort with a
DistributedStallError naming that rank instead of hanging forever.

Device collectives are deliberately absent: this jaxlib's CPU backend has
no cross-process computations, and the liveness plane is host-side by
design (the same reason the planning plane is).

argv: n_steps stall_rank site spec deadline_s
exit codes: 7 = aborted with DistributedStallError (expected),
3 = completed (the test treats that as failure), anything else = crash.
"""

import os
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

n_steps = int(sys.argv[1])
stall_rank = int(sys.argv[2])
site, spec = sys.argv[3], sys.argv[4]
deadline_s = float(sys.argv[5])

rank = int(os.environ.get("PBOX_PROCESS_ID", "0"))
if rank == stall_rank:
    # the env-activation path: the plan is read lazily on first inject()
    os.environ["PBOX_FAULT_PLAN"] = f"{site}={spec}"

from paddlebox_tpu.parallel.mesh import initialize_distributed  # noqa: E402

initialize_distributed()  # applies PBOX_FORCE_CPU + joins the coordinator


def main() -> int:
    import numpy as np

    from paddlebox_tpu.config import LivenessConfig
    from paddlebox_tpu.parallel import watchdog as wmod
    from paddlebox_tpu.parallel.host_plane import KvChannel

    liveness = LivenessConfig(
        deadline_s=deadline_s,
        heartbeat_interval_s=deadline_s / 6,
        poll_interval_s=min(0.2, deadline_s / 10),
        hard_exit_grace_s=15.0,
    )
    wd = wmod.for_trainer(liveness, namespace="fleet")
    assert wd is not None and wd.kv is not None, "expected a KV-backed watchdog"
    wd.start()
    ch = KvChannel("fleet-work", timeout_s=120.0)
    try:
        for i in range(n_steps):
            wd.report("step")
            out = ch.allgather(np.asarray([rank * 1000 + i], np.int64))
            assert out.shape[0] == wd.world, out.shape
            time.sleep(0.05)
    except wmod.DistributedStallError as e:
        print(f"STALL-ABORT rank={rank}: {e}", flush=True)
        return 7
    finally:
        wd.close()
    print("COMPLETED-UNEXPECTEDLY", flush=True)
    return 3


if __name__ == "__main__":
    sys.exit(main())
