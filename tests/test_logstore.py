"""Durable cold tier component tests: segment framing, crash windows,
manifest commit discipline, bloom rejects, compaction barrier.

The SIGKILL end-to-end versions (a real child process frozen at each
fault site and killed) live in test_durable_store.py (`-m chaos`); this
file proves the same crash windows in-process by byte surgery and
raising fault plans, so tier-1 covers every recovery rule fast.
"""

import os
import zlib

import numpy as np
import pytest

from paddlebox_tpu.sparse.logstore import (
    BloomFilter,
    LogStore,
    LogStoreCorrupt,
    SegmentWriter,
    read_segment,
)
from paddlebox_tpu.utils import faults
from paddlebox_tpu.utils.faults import fault_plan
from paddlebox_tpu.utils.monitor import stats


def _rows(keys, n_cols=3, salt=0.0):
    keys = np.asarray(keys, dtype=np.uint64)
    base = keys.astype(np.float64)[:, None] * np.arange(1, n_cols + 1)
    return (base * 0.001 + salt).astype(np.float32)


def _store(root, **kw):
    kw.setdefault("n_cols", 3)
    kw.setdefault("n_buckets", 2)
    return LogStore(str(root), **kw)


# --------------------------------------------------------------------------- #
# segment files
# --------------------------------------------------------------------------- #
class TestSegment:
    def test_roundtrip_and_typestate(self, tmp_path):
        k = np.array([3, 9, 11], dtype=np.uint64)
        v = _rows(k)
        w = SegmentWriter(str(tmp_path), 0, 1)
        with pytest.raises(RuntimeError, match="sealed"):
            w.info()  # unsealed segments must never be read
        w.append(k, v)
        info = w.seal()
        with pytest.raises(RuntimeError):
            w.append(k, v)  # sealed files never grow
        blocks = read_segment(os.path.join(str(tmp_path), info.name),
                              expect_bytes=info.n_bytes, expect_crc=info.crc)
        assert len(blocks) == 1
        np.testing.assert_array_equal(blocks[0][0], k)
        np.testing.assert_array_equal(blocks[0][1], v)
        assert (info.min_key, info.max_key) == (3, 11)

    def test_unsorted_keys_loud(self, tmp_path):
        w = SegmentWriter(str(tmp_path), 0, 1)
        try:
            with pytest.raises(Exception):
                w.append(np.array([9, 3], dtype=np.uint64),
                         _rows([9, 3]))
        finally:
            w.abort()

    def test_torn_tail_byte_sweep(self, tmp_path):
        """Truncate the file at EVERY byte: orphan decode never raises and
        always yields a prefix of the committed blocks; strict decode
        always raises."""
        k1 = np.array([1, 5], dtype=np.uint64)
        k2 = np.array([2, 8, 12], dtype=np.uint64)
        w = SegmentWriter(str(tmp_path), 0, 1)
        w.append(k1, _rows(k1))
        w.append(k2, _rows(k2))
        info = w.seal()
        path = os.path.join(str(tmp_path), info.name)
        data = open(path, "rb").read()
        torn = os.path.join(str(tmp_path), "torn.seg")
        for cut in range(len(data)):
            with open(torn, "wb") as fh:
                fh.write(data[:cut])
            blocks = read_segment(torn)  # orphan mode: recoverable prefix
            assert len(blocks) <= 2
            for got, want in zip(blocks, [k1, k2]):
                np.testing.assert_array_equal(got[0], want)
            with pytest.raises(LogStoreCorrupt):
                read_segment(torn, expect_bytes=info.n_bytes,
                             expect_crc=info.crc)
        # flipping one payload byte (size intact) still fails strict
        flipped = bytearray(data)
        flipped[-1] ^= 0xFF
        with open(torn, "wb") as fh:
            fh.write(bytes(flipped))
        with pytest.raises(LogStoreCorrupt):
            read_segment(torn, expect_bytes=info.n_bytes,
                         expect_crc=info.crc)

    def test_bloom_rejects_absent_keys(self):
        present = np.arange(0, 4000, 2, dtype=np.uint64)
        absent = np.arange(1, 4001, 2, dtype=np.uint64)
        bf = BloomFilter.build(present)
        assert bf.might_contain(present).all()
        fp = bf.might_contain(absent).mean()
        assert fp < 0.05
        # hex round-trip (the manifest wire form)
        bf2 = BloomFilter.from_hex(bf.to_hex())
        np.testing.assert_array_equal(
            bf2.might_contain(absent), bf.might_contain(absent))


# --------------------------------------------------------------------------- #
# the store: commit visibility, newest-wins, recovery
# --------------------------------------------------------------------------- #
class TestLogStore:
    def test_uncommitted_is_invisible(self, tmp_path):
        ls = _store(tmp_path)
        k = np.array([1, 2, 3], dtype=np.uint64)
        ls.append(k, _rows(k))  # staged, never committed
        ls.close()
        again = _store(tmp_path)
        assert again.gen == 0
        mk, _ = again.materialize()
        assert mk.shape[0] == 0
        again.close()

    def test_commit_newest_wins_and_reopen(self, tmp_path):
        ls = _store(tmp_path)
        k = np.arange(1, 40, dtype=np.uint64)
        ls.append(k, _rows(k))
        ls.commit()
        ls.append(k[:10], _rows(k[:10], salt=9.0))
        ls.commit()
        ls.close()
        again = _store(tmp_path)
        mk, mv = again.materialize()
        np.testing.assert_array_equal(mk, k)
        np.testing.assert_array_equal(mv[:10], _rows(k[:10], salt=9.0))
        np.testing.assert_array_equal(mv[10:], _rows(k[10:]))
        vals, found = again.lookup(np.array([5, 999], dtype=np.uint64))
        assert found.tolist() == [True, False]
        np.testing.assert_array_equal(vals[0], _rows([5], salt=9.0)[0])
        again.close()

    def test_single_bucket_store(self, tmp_path):
        """n_buckets=1 makes the bucket shift 64 — undefined for numpy
        uint64; every key must land in bucket 0 (r17 review finding)."""
        ls = _store(tmp_path, n_buckets=1)
        k = np.array([1, 2**63, 2**64 - 1], dtype=np.uint64)
        ls.append(k, _rows(k))
        ls.commit()
        ls.close()
        again = _store(tmp_path, n_buckets=1)
        mk, mv = again.materialize()
        np.testing.assert_array_equal(mk, k)
        np.testing.assert_array_equal(mv, _rows(k))
        _, found = again.lookup(k)
        assert found.all()
        again.close()

    def test_no_history_manifest_files_bounded(self, tmp_path):
        """keep_history=False: per-merge-batch commit()s must not
        accumulate manifest-<gen>.json files — only the committed
        generation's manifest survives (r17 review finding)."""
        ls = _store(tmp_path / "flat")
        for i in range(12):
            k = np.arange(1 + i, 20 + i, dtype=np.uint64)
            ls.append(k, _rows(k, salt=float(i)))
            ls.commit()
        manifests = sorted(
            n for n in os.listdir(str(tmp_path / "flat"))
            if n.startswith("manifest-")
        )
        assert manifests == [f"manifest-{ls.gen:08d}.json"]
        ls.close()
        # keep_history stores keep every generation materializable
        hs = _store(tmp_path / "hist", keep_history=True)
        for i in range(3):
            k = np.arange(1, 5, dtype=np.uint64)
            hs.append(k, _rows(k, salt=float(i)))
            hs.commit()
        hist = [n for n in os.listdir(str(tmp_path / "hist"))
                if n.startswith("manifest-")]
        assert len(hist) == 3
        hs.close()

    def test_lookup_skips_segments_without_disk(self, tmp_path):
        ls = _store(tmp_path)
        lo = np.arange(1, 50, dtype=np.uint64)
        hi = np.arange(10_000, 10_050, dtype=np.uint64)
        ls.append(lo, _rows(lo))
        ls.commit()
        ls.append(hi, _rows(hi))
        ls.commit()
        before = stats.get("store.log_seg_skips")
        # an old key: the newer (hi-range) segment is consulted first and
        # skipped via its min-max sidecar, never read
        vals, found = ls.lookup(np.array([5], dtype=np.uint64))
        assert found.all()
        np.testing.assert_array_equal(vals[0], _rows([5])[0])
        assert stats.get("store.log_seg_skips") > before
        assert not ls.might_contain(
            np.array([777_777], dtype=np.uint64)).any()
        ls.close()

    def test_compaction_is_content_preserving(self, tmp_path):
        ls = _store(tmp_path, compact_threshold=2)
        k = np.arange(1, 60, dtype=np.uint64)
        for p in range(4):
            ls.append(k, _rows(k, salt=float(p)))
            ls.commit()
        pre_k, pre_v = ls.materialize()
        assert ls.buckets_over_threshold()
        n = ls.compact()
        assert n > 0 and not ls.buckets_over_threshold()
        post_k, post_v = ls.materialize()
        np.testing.assert_array_equal(pre_k, post_k)
        np.testing.assert_array_equal(pre_v, post_v)
        ls.close()
        # and the compacted root recovers identically
        again = _store(tmp_path)
        rk, rv = again.materialize()
        np.testing.assert_array_equal(rk, pre_k)
        np.testing.assert_array_equal(rv, pre_v)
        again.close()

    def test_verify_gen_detects_damage(self, tmp_path):
        ls = _store(tmp_path, keep_history=True)
        k = np.arange(1, 30, dtype=np.uint64)
        ls.append(k, _rows(k))
        gen = ls.commit()
        ok, _ = ls.verify_gen(gen)
        assert ok
        seg = [n for n in os.listdir(str(tmp_path)) if n.endswith(".seg")][0]
        with open(os.path.join(str(tmp_path), seg), "r+b") as fh:
            fh.seek(-3, os.SEEK_END)
            fh.write(b"\x00\x00\x00")
        ok, reason = ls.verify_gen(gen)
        assert not ok and "crc" in reason
        ls.close()

    def test_materialize_at_time_travel(self, tmp_path):
        ls = _store(tmp_path, keep_history=True)
        k = np.arange(1, 20, dtype=np.uint64)
        gens = []
        for p in range(3):
            ls.append(k, _rows(k, salt=float(p)))
            gens.append(ls.commit())
        for p, g in enumerate(gens):
            gk, gv = ls.materialize_at(g)
            np.testing.assert_array_equal(gk, k)
            np.testing.assert_array_equal(gv, _rows(k, salt=float(p)))
        ls.close()


# --------------------------------------------------------------------------- #
# crash windows, in-process: every fault site aborts clean and retries
# to commit
# --------------------------------------------------------------------------- #
class TestFaultSites:
    def _committed_state(self, root):
        probe = _store(root)
        try:
            return probe.gen, probe.materialize()
        finally:
            probe.close()

    @pytest.mark.parametrize("site", [
        "store.segment_write", "store.manifest_commit",
    ])
    def test_append_commit_abort_then_retry(self, tmp_path, site):
        ls = _store(tmp_path)
        k0 = np.arange(1, 25, dtype=np.uint64)
        ls.append(k0, _rows(k0))
        ls.commit()
        gen0, (mk0, mv0) = self._committed_state(tmp_path)
        k1 = np.arange(100, 125, dtype=np.uint64)
        with fault_plan({site: "first:1"}):
            with pytest.raises(faults.FaultInjected):
                ls.append(k1, _rows(k1))
                ls.commit()
            # clean abort: committed state unchanged on disk
            gen, (mk, mv) = self._committed_state(tmp_path)
            assert gen == gen0
            np.testing.assert_array_equal(mk, mk0)
            np.testing.assert_array_equal(mv, mv0)
            # retry-to-commit under the same (exhausted) plan
            ls.discard_pending()
            ls.append(k1, _rows(k1))
            ls.commit()
        gen, (mk, _) = self._committed_state(tmp_path)
        assert gen > gen0
        np.testing.assert_array_equal(mk, np.concatenate([k0, k1]))
        ls.close()

    def test_compact_abort_keeps_old_segments(self, tmp_path):
        ls = _store(tmp_path, compact_threshold=2)
        k = np.arange(1, 60, dtype=np.uint64)
        for p in range(3):
            ls.append(k, _rows(k, salt=float(p)))
            ls.commit()
        pre_gen, (mk, mv) = self._committed_state(tmp_path)
        n_live = ls.n_live_segments
        with fault_plan({"store.compact": "first:1"}):
            with pytest.raises(faults.FaultInjected):
                ls.compact()
            assert ls.n_live_segments == n_live  # nothing swapped
            gen, (ak, av) = self._committed_state(tmp_path)
            assert gen == pre_gen
            np.testing.assert_array_equal(ak, mk)
            np.testing.assert_array_equal(av, mv)
            # the staged orphan was dropped, retry compacts for real
            assert ls.compact() > 0
        gen, (ak, av) = self._committed_state(tmp_path)
        np.testing.assert_array_equal(ak, mk)
        np.testing.assert_array_equal(av, mv)
        ls.close()

    def test_kill_between_manifest_and_current(self, tmp_path):
        """The CURRENT-last window, by byte surgery: a manifest that landed
        without its CURRENT swing is an orphan the reopen ignores."""
        ls = _store(tmp_path)
        k = np.arange(1, 30, dtype=np.uint64)
        ls.append(k, _rows(k))
        gen1 = ls.commit()
        # forge the crash: newer manifest exists, CURRENT still points back
        man = open(os.path.join(str(tmp_path),
                                f"manifest-{gen1:08d}.json")).read()
        forged = man.replace(f'"gen": {gen1}', f'"gen": {gen1 + 1}')
        with open(os.path.join(str(tmp_path),
                               f"manifest-{gen1 + 1:08d}.json"), "w") as fh:
            fh.write(forged)
        ls.close()
        again = _store(tmp_path)
        assert again.gen == gen1  # CURRENT rules, the orphan never existed
        again.close()


def test_known_sites_cover_the_new_surface():
    for site in ("store.segment_write", "store.compact",
                 "store.manifest_commit", "ckpt.delta_save"):
        assert site in faults.KNOWN_SITES
