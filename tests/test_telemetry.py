"""Telemetry layer (paddlebox_tpu/telemetry/): typed metrics + quantile
math, Prometheus exposition, span tracing, JSONL events, /metrics on the
scoring server, and cross-rank snapshot aggregation."""

import json
import os
import time
import urllib.error
import urllib.request

import numpy as np
import pytest

from paddlebox_tpu import telemetry
from paddlebox_tpu.telemetry.metrics import (
    MetricRegistry,
    quantile_from_buckets,
)
from paddlebox_tpu.utils.monitor import stats


@pytest.fixture(autouse=True)
def _clean_registry():
    telemetry.registry.reset()
    yield
    telemetry.registry.reset()
    telemetry.disable_tracing()


# --------------------------------------------------------------------------- #
# histogram bucket / quantile math
# --------------------------------------------------------------------------- #
def test_histogram_empty_quantiles_are_none():
    reg = MetricRegistry()
    h = reg.histogram("h", buckets=(1.0, 2.0))
    assert h.quantile(0.5) is None
    assert h.summary()["count"] == 0
    assert h.summary()["p99"] is None
    assert h.summary()["mean"] is None


def test_histogram_single_sample_reports_that_sample():
    reg = MetricRegistry()
    h = reg.histogram("h", buckets=(0.01, 0.1, 1.0))
    h.observe(0.05)
    for q in (0.0, 0.5, 0.99, 1.0):
        assert h.quantile(q) == pytest.approx(0.05)
    s = h.summary()
    assert s["count"] == 1 and s["min"] == s["max"] == 0.05


def test_histogram_quantiles_bracket_the_distribution():
    reg = MetricRegistry()
    h = reg.histogram("h", buckets=(0.01, 0.1, 1.0, 10.0))
    rng = np.random.default_rng(0)
    vals = rng.uniform(0.02, 0.09, size=1000)  # all inside one bucket
    for v in vals:
        h.observe(float(v))
    p50 = h.quantile(0.5)
    # interpolation inside the (0.01, 0.1] bucket: must stay in range
    assert 0.02 <= p50 <= 0.09
    # p99 >= p50 and both clamp to observed extremes
    assert p50 <= h.quantile(0.99) <= vals.max()
    assert h.quantile(0.0) >= vals.min()


def test_histogram_overflow_bucket_uses_observed_max():
    reg = MetricRegistry()
    h = reg.histogram("h", buckets=(1.0,))
    h.observe(5.0)
    h.observe(7.0)
    assert h.quantile(0.99) <= 7.0
    assert h.quantile(0.99) > 1.0


def test_quantile_from_buckets_validates_q():
    with pytest.raises(ValueError):
        quantile_from_buckets((1.0,), [1, 0], 1, 0.5, 0.5, 1.5)


def test_histogram_labels_split_and_merge():
    reg = MetricRegistry()
    h = reg.histogram("h", buckets=(0.01, 1.0))
    h.observe(0.005, stage="a")
    h.observe(0.5, stage="b")
    assert h.summary(stage="a")["count"] == 1
    assert h.summary(stage="b")["count"] == 1
    assert h.summary()["count"] == 2  # no labels = merged across series


def test_counter_gauge_basics_and_kind_conflict():
    reg = MetricRegistry()
    c = reg.counter("c")
    c.inc()
    c.inc(2, rank="1")
    assert c.value() == 1 and c.value(rank="1") == 2
    with pytest.raises(ValueError):
        c.inc(-1)
    g = reg.gauge("g")
    g.set(3.5)
    g.set(1.0, rank="0")
    g.remove(rank="0")
    assert g.value(rank="0") == 0.0 and g.value() == 3.5
    with pytest.raises(TypeError):
        reg.gauge("c")  # same name, different kind


def test_delta_snapshot_reports_per_interval_values():
    reg = MetricRegistry()
    reg.counter("c").inc(5)
    reg.histogram("h", buckets=(1.0,)).observe(0.5)
    first = reg.delta_snapshot()
    assert first["counters"]["c"] == 5
    assert first["histograms"]["h"]["count"] == 1
    reg.counter("c").inc(2)
    second = reg.delta_snapshot()
    assert second["counters"]["c"] == 2  # only the new increments
    assert second["histograms"]["h"]["count"] == 0


# --------------------------------------------------------------------------- #
# legacy stats facade
# --------------------------------------------------------------------------- #
def test_stats_facade_forwards_to_typed_registry():
    stats.add("x.count", 2)
    stats.add("x.count")
    stats.set("x.gauge", 7.5)
    assert stats.get("x.count") == 3
    assert stats.get("x.gauge") == 7.5
    snap = stats.snapshot()
    assert snap["x.count"] == 3 and snap["x.gauge"] == 7.5
    # the satellite: snapshot carries a monotonic timestamp taken under
    # the registry lock
    assert snap.monotonic_ts > 0
    snap2 = stats.snapshot()
    assert snap2.monotonic_ts >= snap.monotonic_ts
    # legacy counters land in the shared typed registry
    assert telemetry.registry.get("x.count") is not None


def test_stats_reset_keeps_cached_metric_handles_registered():
    c = telemetry.counter("cached.handle")
    c.inc(3)
    stats.reset()
    assert stats.get("cached.handle") == 0
    c.inc()  # the old handle still feeds the registry after reset
    assert stats.get("cached.handle") == 1
    assert "cached_handle_total" in telemetry.render_prometheus()


# --------------------------------------------------------------------------- #
# profiler: auto-created stages + counts (satellites 1-2)
# --------------------------------------------------------------------------- #
def test_step_profiler_auto_creates_stages():
    from paddlebox_tpu.utils.profiler import StepProfiler

    p = StepProfiler()
    with p.stage("brand_new_stage"):  # KeyError before this PR
        pass
    with p.stage("plan"):
        pass
    with p.stage("plan"):
        pass
    p.step_done()
    r = p.report()
    assert r["brand_new_stage_count"] == 1
    assert r["plan_count"] == 2  # resume/pause cycles now reported
    assert "brand_new_stage_sec" in r
    assert "plan" in p.log_line()
    q = p.quantiles()
    assert q["plan"]["count"] == 2 and q["plan"]["p99_ms"] >= 0


def test_stats_profiler_records_histograms_without_enabling():
    from paddlebox_tpu.utils.profiler import StatsProfiler

    p = StatsProfiler()
    assert p.enabled is False
    with p.stage("plan"):
        pass
    h = telemetry.registry.get("trainer.stage_seconds")
    assert h.summary(stage="plan")["count"] == 1


# --------------------------------------------------------------------------- #
# Prometheus exposition
# --------------------------------------------------------------------------- #
def test_prometheus_golden_output():
    reg = MetricRegistry()
    reg.counter("train.nan_rollback", help="rollbacks").inc(2)
    reg.gauge("watchdog.staleness_s").set(1.5, rank="0")
    h = reg.histogram("req.seconds", buckets=(0.1, 1.0))
    h.observe(0.05, model="m")
    h.observe(0.5, model="m")
    h.observe(5.0, model="m")
    golden = "\n".join([
        "# TYPE req_seconds histogram",
        'req_seconds_bucket{model="m",le="0.1"} 1',
        'req_seconds_bucket{model="m",le="1"} 2',
        'req_seconds_bucket{model="m",le="+Inf"} 3',
        'req_seconds_sum{model="m"} 5.55',
        'req_seconds_count{model="m"} 3',
        "# HELP train_nan_rollback_total rollbacks",
        "# TYPE train_nan_rollback_total counter",
        "train_nan_rollback_total 2",
        "# TYPE watchdog_staleness_s gauge",
        'watchdog_staleness_s{rank="0"} 1.5',
        "",
    ])
    assert telemetry.render_prometheus(reg) == golden


# --------------------------------------------------------------------------- #
# span tracing: Chrome-trace JSON nesting
# --------------------------------------------------------------------------- #
def test_span_trace_nesting_and_json_validity(tmp_path):
    tr = telemetry.enable_tracing(pid=3)
    with telemetry.span("outer", pass_idx=1):
        with telemetry.span("inner"):
            pass
        with telemetry.span("inner2"):
            pass
    telemetry.instant("marker", note="x")
    path = telemetry.flush_trace(str(tmp_path / "t.json"))
    doc = json.load(open(path))  # valid JSON by construction
    evs = {e["name"]: e for e in doc["traceEvents"] if e.get("ph") == "X"}
    assert set(evs) == {"outer", "inner", "inner2"}
    assert evs["inner"]["args"]["parent"] == "outer"
    assert evs["inner2"]["args"]["parent"] == "outer"
    assert "parent" not in evs["outer"].get("args", {})
    # time containment: children inside the parent window (Perfetto nests
    # same-tid X events by exactly this)
    out = evs["outer"]
    for child in ("inner", "inner2"):
        c = evs[child]
        assert c["ts"] >= out["ts"]
        assert c["ts"] + c["dur"] <= out["ts"] + out["dur"] + 1e-3
    assert any(e.get("ph") == "i" for e in doc["traceEvents"])
    assert tr.pid == 3
    # flush drained the buffer: a second flush writes no X events
    doc2 = json.loads(json.dumps(tr.to_dict()))
    assert not [e for e in doc2["traceEvents"] if e.get("ph") == "X"]


def test_span_is_noop_when_disabled():
    telemetry.disable_tracing()
    with telemetry.span("nothing"):
        pass
    assert telemetry.flush_trace("/nonexistent/never-written.json") is None


# --------------------------------------------------------------------------- #
# JSONL events
# --------------------------------------------------------------------------- #
def test_event_log_rank_tagged_jsonl(tmp_path):
    path = str(tmp_path / "ev.jsonl")
    el = telemetry.EventLog(path, rank=2)
    telemetry.counter("ev.c").inc(4)
    el.log("custom", foo=1)
    el.log_pass({"auc": 0.5, "steps": 3}, pass_idx=0)
    el.close()
    recs = [json.loads(ln) for ln in open(path)]
    assert [r["event"] for r in recs] == ["custom", "pass_end"]
    assert all(r["rank"] == 2 and r["t"] > 0 for r in recs)
    assert recs[1]["metrics"]["auc"] == 0.5
    assert recs[1]["telemetry"]["counters"]["ev.c"] == 4


# --------------------------------------------------------------------------- #
# /metrics on ScoringServer (round-trip, no artifact needed)
# --------------------------------------------------------------------------- #
class _StubPredictor:
    """Predictor stand-in: the HTTP/parse/batch path is real, only the
    device program is faked (export is unavailable on legacy-jax images)."""

    meta = {"n_tasks": 1}
    n_features = 3

    def __init__(self, conf):
        b = conf.batch_size
        kcap = conf.batch_key_capacity or b * conf.max_feasigns_per_ins
        self.bucket_shapes = [(b, kcap)]

    def predict(self, batch):
        return np.zeros(int(batch.ins_mask.sum()), np.float32)


@pytest.fixture
def stub_server(tmp_path):
    from paddlebox_tpu.data.slot_parser import SlotParser
    from paddlebox_tpu.data.synth import make_synth_config, write_synth_files
    from paddlebox_tpu.inference.server import ModelEntry, ScoringServer

    conf = make_synth_config(
        n_sparse_slots=3, dense_dim=2, batch_size=8, max_feasigns_per_ins=8
    )
    files = write_synth_files(
        str(tmp_path / "d"), n_files=1, ins_per_file=4, n_sparse_slots=3,
        vocab_per_slot=10, dense_dim=2, seed=1,
    )
    srv = ScoringServer()
    entry = ModelEntry.__new__(ModelEntry)
    entry.name, entry.predictor, entry.feed_conf = (
        "m", _StubPredictor(conf), conf
    )
    entry.parser = SlotParser(conf)
    entry.requests = entry.instances = 0
    srv._models["m"] = entry
    srv._default = "m"
    port = srv.start()
    body = open(files[0], "rb").read()
    try:
        yield srv, port, body
    finally:
        srv.stop()


def _get(port, path):
    with urllib.request.urlopen(
        f"http://127.0.0.1:{port}{path}", timeout=10
    ) as r:
        return r.status, r.headers, r.read().decode()


def _wait_for(cond, timeout=5.0):
    """The handler thread records telemetry AFTER writing the response, so
    a client-side assertion must allow that handoff to land."""
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if cond():
            return True
        time.sleep(0.01)
    return cond()


def test_metrics_endpoint_round_trip(stub_server):
    srv, port, body = stub_server
    # one 2xx, one 4xx (unknown model), one 4xx (garbage body)
    req = urllib.request.Request(
        f"http://127.0.0.1:{port}/score", data=body, method="POST"
    )
    with urllib.request.urlopen(req, timeout=10) as r:
        assert r.status == 200
    for path, data in (("/score/ghost", b"x"), ("/score", b"garbage")):
        with pytest.raises(urllib.error.HTTPError):
            urllib.request.urlopen(
                urllib.request.Request(
                    f"http://127.0.0.1:{port}{path}", data=data,
                    method="POST",
                ),
                timeout=10,
            )
    c = telemetry.registry.get("server.requests")
    assert _wait_for(
        lambda: sum(cell[0] for cell in c.series().values()) >= 3
    )
    st, headers, text = _get(port, "/metrics")
    assert st == 200
    # the satellite: exposition content type, version pinned
    assert headers["Content-Type"] == "text/plain; version=0.0.4"
    # request-latency histogram buckets, split by model and status class
    assert 'server_request_seconds_bucket{model="m",status="2xx"' in text
    assert 'server_request_seconds_bucket{model="m",status="4xx"' in text
    assert 'server_request_seconds_bucket{model="ghost",status="4xx"' in text
    assert 'server_requests_total{model="m",status="2xx"} 1' in text
    # valid exposition shape: every _bucket line ends with an int, and the
    # +Inf bucket equals _count for each series
    inf = {
        ln.split("le=\"+Inf\"}")[0] for ln in text.splitlines()
        if 'le="+Inf"' in ln
    }
    assert inf  # at least one histogram rendered


def test_metrics_endpoint_counts_error_latency(stub_server):
    srv, port, body = stub_server
    with pytest.raises(urllib.error.HTTPError):
        urllib.request.urlopen(
            urllib.request.Request(
                f"http://127.0.0.1:{port}/score/ghost", data=b"x",
                method="POST",
            ),
            timeout=10,
        )
    h = telemetry.registry.get("server.request_seconds")
    assert _wait_for(
        lambda: h.summary(model="ghost", status="4xx")["count"] == 1
    )


# --------------------------------------------------------------------------- #
# cross-rank aggregation (2-rank simulated fleet on the in-memory KV)
# --------------------------------------------------------------------------- #
def test_gather_fleet_snapshot_two_ranks_merge():
    from paddlebox_tpu.parallel.watchdog import InMemoryKv
    from paddlebox_tpu.utils.profiler import STAGE_BUCKETS

    kv = InMemoryKv()
    regs = [MetricRegistry() for _ in range(2)]
    # per-rank stage timings: rank 1 is the slow one
    for rank, reg in enumerate(regs):
        h = reg.histogram("trainer.stage_seconds", buckets=STAGE_BUCKETS)
        for _ in range(10):
            h.observe(0.001 if rank == 0 else 0.2, stage="step")
        reg.counter("train.steps").inc(10)
        reg.gauge("watchdog.staleness_s").set(0.5 * (rank + 1), rank=str(rank))
    merged = [None, None]
    import threading

    def run(rank):
        merged[rank] = telemetry.gather_fleet_snapshot(
            kv, rank=rank, world=2, seq=7, registry=regs[rank],
            timeout_s=10.0,
        )

    ts = [threading.Thread(target=run, args=(r,)) for r in range(2)]
    for t in ts:
        t.start()
    for t in ts:
        t.join()
    # both ranks converge on the SAME merged view
    assert merged[0] == merged[1]
    m = merged[0]
    assert m["world"] == 2
    assert m["counters"]["train.steps"]["sum"] == 20
    assert m["counters"]["train.steps"]["per_rank"] == [10.0, 10.0]
    h = m["histograms"]["trainer.stage_seconds{stage=step}"]
    assert h["count"] == 20
    # fleet p99 reflects the slow rank; per-rank p99 names it
    assert h["p99"] > 0.05
    assert h["per_rank_p99"][1] > h["per_rank_p99"][0]
    # the rank-0 pass log line carries merged per-rank stage timings
    line = telemetry.format_fleet_view(m)
    assert "world=2" in line
    assert "trainer.stage_seconds{stage=step}" in line
    assert "per_rank_p99_ms=" in line
    assert "train.steps=20" in line


def test_gather_fleet_snapshot_timeout_names_missing_rank():
    from paddlebox_tpu.parallel.watchdog import InMemoryKv

    kv = InMemoryKv()
    with pytest.raises(telemetry.FleetGatherTimeout) as ei:
        telemetry.gather_fleet_snapshot(
            kv, rank=0, world=2, seq=0, registry=MetricRegistry(),
            timeout_s=0.2, poll_s=0.01,
        )
    assert ei.value.missing == [1]
    assert "rank(s) [1]" in str(ei.value)


# --------------------------------------------------------------------------- #
# standalone exporter
# --------------------------------------------------------------------------- #
def test_metrics_exporter_serves_registry(tmp_path):
    telemetry.counter("exp.hits").inc(3)
    exp = telemetry.MetricsExporter()
    port = exp.start(port=0)
    try:
        st, headers, text = _get(port, "/metrics")
        assert st == 200
        assert headers["Content-Type"] == "text/plain; version=0.0.4"
        assert "exp_hits_total 3" in text
        st, _, _ = _get(port, "/healthz")
        assert st == 200
    finally:
        exp.stop()


# --------------------------------------------------------------------------- #
# acceptance: a traced single-pass training run
# --------------------------------------------------------------------------- #
def test_traced_training_pass_writes_nested_chrome_trace(tmp_path):
    from paddlebox_tpu.config import (
        SparseTableConfig,
        TelemetryConfig,
        TrainerConfig,
    )
    from paddlebox_tpu.data.dataset import PadBoxSlotDataset
    from paddlebox_tpu.data.synth import make_synth_config, write_synth_files
    from paddlebox_tpu.models import CtrDnn
    from paddlebox_tpu.sparse.table import SparseTable
    from paddlebox_tpu.train.trainer import Trainer

    conf = make_synth_config(
        n_sparse_slots=3, dense_dim=2, batch_size=16, max_feasigns_per_ins=8
    )
    files = write_synth_files(
        str(tmp_path / "d"), n_files=1, ins_per_file=64, n_sparse_slots=3,
        vocab_per_slot=40, dense_dim=2, seed=3,
    )
    ds = PadBoxSlotDataset(conf, read_threads=1)
    ds.set_filelist(files)
    ds.load_into_memory()
    tconf = SparseTableConfig(embedding_dim=4)
    trace_dir = str(tmp_path / "traces")
    events = str(tmp_path / "events.jsonl")
    trconf = TrainerConfig(
        auc_buckets=1 << 10,
        telemetry=TelemetryConfig(trace_dir=trace_dir, events_path=events),
        need_dump_field=True,
        dump_fields_path=str(tmp_path / "dump"),
    )
    model = CtrDnn(3, tconf.row_width, dense_dim=2, hidden=(8,))
    table = SparseTable(tconf, seed=0)
    trainer = Trainer(model, tconf, trconf, seed=0)
    table.begin_pass(ds.unique_keys())
    metrics = trainer.train_from_dataset(ds, table)
    table.end_pass()
    ds.close()
    telemetry.close_event_log()

    # Chrome-trace JSON with nested plan/feed/step/dump spans
    tf = [f for f in os.listdir(trace_dir) if f.endswith(".json")]
    assert tf == ["host-trace-r0-pass0.json"]
    doc = json.load(open(os.path.join(trace_dir, tf[0])))
    spans = [e for e in doc["traceEvents"] if e.get("ph") == "X"]
    names = {e["name"] for e in spans}
    assert {"pass", "plan", "feed", "step", "dump"} <= names
    for e in spans:
        if e["name"] in ("plan", "feed", "step", "dump"):
            assert e["args"]["parent"] == "pass"
    # existing stats.add call-sites unmodified + per-stage distributions
    assert metrics["profile"]["stage_quantiles"]["step"]["count"] > 0
    # JSONL pass record, rank-tagged
    recs = [json.loads(ln) for ln in open(events)]
    assert recs and recs[-1]["event"] == "pass_end"
    assert "trainer.stage_seconds{stage=step}" in (
        recs[-1]["telemetry"]["histograms"]
    )
