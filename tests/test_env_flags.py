"""Env-flag drift check (tools/check_env_flags.py): every PBOX_* var the
package reads must be documented in ARCHITECTURE.md/README.md and vice
versa — the tier-1 guard that keeps the ops contract honest, exactly
like the metric-name and fault-site guards."""

import os
import subprocess
import sys

import pytest

TOOL = os.path.join(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
    "tools", "check_env_flags.py",
)


def _tool():
    sys.path.insert(0, os.path.dirname(TOOL))
    try:
        import importlib

        return importlib.import_module("check_env_flags")
    finally:
        sys.path.pop(0)


def test_tree_has_no_drift():
    mod = _tool()
    undocumented, stale = mod.check()
    assert undocumented == [] and stale == []
    assert mod.main([]) == 0


def test_flag_shim_entries_are_derived():
    """Every _Flags._DEFAULTS key becomes a PBOX_<NAME> var even when the
    literal string never appears anywhere (the dynamic-read hazard this
    tool exists for)."""
    mod = _tool()
    fv = mod.flag_vars()
    assert "PBOX_RETRY_MAX_ATTEMPTS" in fv
    assert "PBOX_HBM_CACHE" in fv
    # the streaming flags this PR adds are caught from day one
    assert "PBOX_STREAM_ROOT" in fv
    assert "PBOX_MAX_STALENESS_S" in fv
    assert "PBOX_STREAM_WINDOW_RECORDS" in fv


def test_scanner_finds_literal_reads():
    """Direct os.environ reads (no flag-shim entry) are collected from
    source literals."""
    mod = _tool()
    refs = mod.referenced_vars()
    assert "PBOX_COORDINATOR_ADDRESS" in refs  # launch.py env injection
    assert "PBOX_HADOOP_BIN" in refs  # utils/fs.py direct read
    assert "PBOX_BENCH_CPU" in refs  # bench.py escape hatch


def test_docs_cover_referenced_vars():
    mod = _tool()
    documented = mod.documented_vars()
    for var in ("PBOX_STREAM_ROOT", "PBOX_MAX_STALENESS_S",
                "PBOX_STREAM_WINDOW_RECORDS", "PBOX_FAULT_PLAN"):
        assert var in documented, f"{var} missing from the docs catalog"


def test_undocumented_var_fails(monkeypatch):
    mod = _tool()
    real = mod.referenced_vars()

    def fake():
        return {**real, "PBOX_TOTALLY_NEW_KNOB": "nowhere.py:1"}

    monkeypatch.setattr(mod, "referenced_vars", fake)
    undocumented, stale = mod.check()
    assert any(v == "PBOX_TOTALLY_NEW_KNOB" for v, _ in undocumented)
    assert stale == []


def test_stale_doc_fails(monkeypatch):
    mod = _tool()
    real = mod.documented_vars()

    def fake():
        return {**real, "PBOX_REMOVED_KNOB": "ARCHITECTURE.md:1"}

    monkeypatch.setattr(mod, "documented_vars", fake)
    undocumented, stale = mod.check()
    assert undocumented == []
    assert any(v == "PBOX_REMOVED_KNOB" for v, _ in stale)


@pytest.mark.parametrize("args,rc", [([], 0), (["--list"], 0)])
def test_cli_exit_codes(args, rc):
    r = subprocess.run(
        [sys.executable, TOOL] + args,
        capture_output=True, text=True, timeout=60,
    )
    assert r.returncode == rc, r.stderr
