"""Multi-scenario training plane (scenarios/): N heterogeneous towers
over ONE shared SparseTable — interleave determinism (bit-exact rerun),
union census, per-scenario slot/admission policy, per-scenario telemetry
attribution, and pass-protocol discipline under mid-pass failure."""

import numpy as np
import pytest

from paddlebox_tpu import telemetry
from paddlebox_tpu.config import SparseTableConfig, TrainerConfig
from paddlebox_tpu.data.dataset import PadBoxSlotDataset
from paddlebox_tpu.data.synth import make_synth_config, write_synth_files
from paddlebox_tpu.models import CtrDnn, TwoTower, WideDeep
from paddlebox_tpu.scenarios import (
    MultiScenarioTrainer,
    RetrievalTrainer,
    ScenarioSpec,
)
from paddlebox_tpu.sparse.table import SparseTable

S, DENSE, B, VOCAB = 4, 4, 32, 40

@pytest.fixture(scope="module")
def synth(tmp_path_factory):
    d = tmp_path_factory.mktemp("scen_synth")
    paths = write_synth_files(
        str(d), n_files=2, ins_per_file=256, n_sparse_slots=S,
        vocab_per_slot=VOCAB, dense_dim=DENSE, seed=11,
    )
    conf = make_synth_config(
        n_sparse_slots=S, dense_dim=DENSE, batch_size=B,
        max_feasigns_per_ins=12,
    )
    return paths, conf

def _specs(tconf):
    return [
        ScenarioSpec(
            "feed", CtrDnn(S, tconf.row_width, dense_dim=DENSE, hidden=(16,)),
            trainer_conf=TrainerConfig(dense_lr=3e-3, auc_buckets=1 << 10),
            seed=1,
        ),
        ScenarioSpec(
            "cvr", WideDeep(S, tconf.row_width, dense_dim=DENSE, hidden=(8,)),
            slot_mask=(0, 1, 2), create_threshold=0.0,
            trainer_conf=TrainerConfig(dense_lr=3e-3, auc_buckets=1 << 10),
            seed=2,
        ),
        ScenarioSpec(
            "retr",
            TwoTower(S, tconf.row_width, item_slots=(3,), dense_dim=DENSE,
                     hidden=(16, 8), temperature=0.05),
            kind="retrieval",
            trainer_conf=TrainerConfig(dense_lr=3e-3, auc_buckets=1 << 10),
            seed=3,
        ),
    ]

def _world(conf, paths, seed=0):
    tconf = SparseTableConfig(embedding_dim=8, learning_rate=0.5,
                              initial_range=0.05)
    table = SparseTable(tconf, seed=seed)
    mst = MultiScenarioTrainer(tconf, _specs(tconf))
    datasets = {}
    for name in mst.scenario_names():
        ds = PadBoxSlotDataset(conf, read_threads=1)
        ds.set_filelist(paths)
        ds.load_into_memory()
        datasets[name] = ds
    return table, mst, datasets

def _close(datasets):
    for ds in datasets.values():
        ds.close()

def _run(conf, paths, passes=2):
    table, mst, datasets = _world(conf, paths)
    try:
        results = [mst.train_pass(datasets, table) for _ in range(passes)]
    finally:
        _close(datasets)
    return table, mst, results

# --------------------------------------------------------------------------- #
# determinism pin
# --------------------------------------------------------------------------- #
def test_interleaved_pass_is_bit_deterministic(synth):
    """The pin the ISSUE demands: two independent worlds with the same
    seeds and datasets produce BIT-EXACT shared-table state (keys, values
    including counters and g2sum) and identical per-scenario AUC."""
    paths, conf = synth
    t1, _, r1 = _run(conf, paths)
    t2, _, r2 = _run(conf, paths)
    s1, s2 = t1.state_dict(), t2.state_dict()
    np.testing.assert_array_equal(s1["keys"], s2["keys"])
    np.testing.assert_array_equal(s1["values"], s2["values"])  # incl. g2sum
    for a, b in zip(r1, r2):
        assert set(a) == set(b) == {"feed", "cvr", "retr"}
        for name in a:
            assert a[name]["auc"] == b[name]["auc"], name
            assert a[name]["loss"] == b[name]["loss"], name

def test_scenarios_learn_and_share_one_table(synth):
    paths, conf = synth
    table, mst, results = _run(conf, paths, passes=3)
    # every scenario's loss moves down against pass 0 on shared rows
    for name in ("feed", "retr"):
        assert results[-1][name]["loss"] < results[0][name]["loss"], name
    assert table.n_features > 0
    assert table.missing_key_count == 0  # union census covered everyone
    # the retrieval trainer is the specialized subclass
    assert isinstance(mst.trainers["retr"], RetrievalTrainer)

# --------------------------------------------------------------------------- #
# slot / admission policy
# --------------------------------------------------------------------------- #
def test_union_census_is_union_of_scenario_keys(synth):
    paths, conf = synth
    table, mst, datasets = _world(conf, paths)
    try:
        union = mst.union_census(datasets)
        every = np.unique(np.concatenate([
            np.asarray(ds.unique_keys(), np.uint64)
            for ds in datasets.values()
        ]))
        np.testing.assert_array_equal(union, every)
    finally:
        _close(datasets)

def test_per_scenario_create_threshold_resolves_on_trainer(synth):
    paths, conf = synth
    tconf = SparseTableConfig(embedding_dim=8, create_threshold=5.0)
    mst = MultiScenarioTrainer(tconf, _specs(tconf))
    # cvr overrides to 0.0; the others inherit the table's 5.0
    assert mst.trainers["cvr"].table_conf.create_threshold == 0.0
    assert mst.trainers["feed"].table_conf.create_threshold == 5.0
    # the override must not fork the physical row layout
    assert (mst.trainers["cvr"].table_conf.row_width
            == mst.trainers["feed"].table_conf.row_width)

def test_slot_mask_rides_each_scenario(synth):
    paths, conf = synth
    tconf = SparseTableConfig(embedding_dim=8)
    mst = MultiScenarioTrainer(tconf, _specs(tconf))
    assert mst.trainers["cvr"].slot_mask == (0, 1, 2)
    assert mst.trainers["feed"].slot_mask is None

# --------------------------------------------------------------------------- #
# validation + pass protocol
# --------------------------------------------------------------------------- #
def test_spec_validation():
    tconf = SparseTableConfig(embedding_dim=8)
    model = CtrDnn(S, tconf.row_width, dense_dim=DENSE, hidden=(8,))
    with pytest.raises(ValueError, match="at least one"):
        MultiScenarioTrainer(tconf, [])
    with pytest.raises(ValueError, match="duplicate"):
        MultiScenarioTrainer(tconf, [
            ScenarioSpec("a", model), ScenarioSpec("a", model)])
    with pytest.raises(ValueError, match="unknown kind"):
        MultiScenarioTrainer(tconf, [ScenarioSpec("a", model, kind="nope")])
    # retrieval kind needs a two-tower model
    with pytest.raises(ValueError, match="apply_towers"):
        MultiScenarioTrainer(tconf, [
            ScenarioSpec("a", model, kind="retrieval")])

def test_missing_dataset_refused_before_begin_pass(synth):
    paths, conf = synth
    table, mst, datasets = _world(conf, paths)
    try:
        del datasets["cvr"]
        with pytest.raises(ValueError, match="cvr"):
            mst.train_pass(datasets, table)
        # the refusal happened BEFORE begin_pass: the table is still idle
        table.begin_pass(np.array([1], np.uint64))
        table.abort_pass()
    finally:
        _close(datasets)

def test_mid_pass_failure_aborts_pass(synth):
    """A scenario step blowing up mid-pass must abort_pass (not leave the
    table wedged in-pass) and re-raise."""
    paths, conf = synth
    table, mst, datasets = _world(conf, paths)
    try:
        boom = RuntimeError("boom")
        real_feed = datasets["feed"]

        class _Exploder:
            def batches(self, drop_last=False):
                raise boom

            def unique_keys(self):
                return real_feed.unique_keys()

        datasets["feed"] = _Exploder()
        with pytest.raises(RuntimeError, match="boom"):
            mst.train_pass(datasets, table)
        # abort_pass ran: a fresh pass can begin
        table.begin_pass(np.array([1], np.uint64))
        table.abort_pass()
    finally:
        datasets["feed"] = real_feed
        _close(datasets)

# --------------------------------------------------------------------------- #
# telemetry attribution
# --------------------------------------------------------------------------- #
def test_three_scenarios_separately_attributable(synth):
    """Scenario is a first-class telemetry label: after one interleaved
    pass, per-scenario step/sample counters and AUC/loss gauges exist for
    EVERY scenario under its own label."""
    paths, conf = synth
    before = telemetry.registry.snapshot()
    _run(conf, paths, passes=1)
    snap = telemetry.registry.snapshot()

    def delta(kind, key):
        return snap[kind].get(key, 0) - before[kind].get(key, 0)

    for name in ("feed", "cvr", "retr"):
        assert delta("counters", f"scenario.steps{{scenario={name}}}") > 0
        assert delta("counters", f"scenario.samples{{scenario={name}}}") > 0
        assert f"scenario.auc{{scenario={name}}}" in snap["gauges"]
        assert f"scenario.loss{{scenario={name}}}" in snap["gauges"]
