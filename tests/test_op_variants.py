"""Numpy-oracle parity for the seqpool-CVM variant family + fused_concat +
rank_attention2 + quant pull descale + conv counter push.

Oracles transcribe the reference CUDA kernel semantics directly
(fused_seqpool_cvm_with_conv_op.cu:63-83, _with_diff_thres_op.cu:100-127,
_with_pcoc_op.cu:120-155, fused_concat_op.cu:34-50, box_wrapper.cu quant
pull) — SURVEY.md §4 tier 1, same pattern as the reference's OpTest files.
"""

import jax
import jax.numpy as jnp
import numpy as np

from paddlebox_tpu.config import SparseTableConfig
from paddlebox_tpu.ops import (
    fused_concat,
    fused_seqpool_cvm,
    fused_seqpool_cvm_with_conv,
    fused_seqpool_cvm_with_diff_thres,
    fused_seqpool_cvm_with_pcoc,
    rank_attention,
    rank_attention2,
)
from paddlebox_tpu.sparse.table import pull_rows


def _mk(rng, B, S, W, max_len=4, cvm_cols=2):
    lens = rng.integers(0, max_len, size=(B, S))
    K_real = int(lens.sum())
    K = B * S * max_len
    rows = rng.normal(size=(K, W)).astype(np.float32)
    rows[:, :cvm_cols] = rng.integers(0, 8, size=(K, cvm_cols))
    segs = np.full(K, B * S, dtype=np.int32)
    segs[:K_real] = np.repeat(np.arange(B * S), lens.reshape(-1))
    rows[K_real:] = 0.0
    return rows, segs


def _pool(rows, segs, B, S, W):
    out = np.zeros((B, S, W), dtype=np.float64)
    for k in range(rows.shape[0]):
        if segs[k] < B * S:
            out[segs[k] // S, segs[k] % S] += rows[k]
    return out


def test_conv_variant_cvm_columns():
    rng = np.random.default_rng(0)
    B, S, W = 3, 2, 7  # [show, clk, conv, 4 embeds]
    rows, segs = _mk(rng, B, S, W, cvm_cols=3)
    got = np.asarray(
        fused_seqpool_cvm_with_conv(
            jnp.asarray(rows), jnp.asarray(segs), B, S, cvm_offset=3
        )
    ).reshape(B, S, W)
    p = _pool(rows, segs, B, S, W)
    exp = p.copy()
    exp[..., 0] = np.log(p[..., 0] + 1)
    exp[..., 1] = np.log(p[..., 1] + 1)  # conv layout: log click, NOT ctr
    exp[..., 2] = np.log(p[..., 2] + 1) - np.log(p[..., 1] + 1)
    np.testing.assert_allclose(got, exp, rtol=1e-4, atol=1e-5)


def test_conv_variant_show_filter_drops_show_col():
    rng = np.random.default_rng(1)
    B, S, W = 2, 2, 6
    rows, segs = _mk(rng, B, S, W, cvm_cols=3)
    got = np.asarray(
        fused_seqpool_cvm_with_conv(
            jnp.asarray(rows), jnp.asarray(segs), B, S, cvm_offset=3,
            show_filter=True,
        )
    )
    assert got.shape == (B, S * (W - 1))
    p = _pool(rows, segs, B, S, W)
    exp = np.concatenate(
        [
            np.log(p[..., 1:2] + 1),
            np.log(p[..., 2:3] + 1) - np.log(p[..., 1:2] + 1),
            p[..., 3:],
        ],
        axis=-1,
    ).reshape(B, -1)
    np.testing.assert_allclose(got, exp, rtol=1e-4, atol=1e-5)


def test_diff_thres_per_slot_thresholds():
    """Slot 0 threshold filters its occurrence; slot 1's lower threshold
    keeps an identical occurrence (the xbox_diff_thres_filter path)."""
    B, S, W = 1, 2, 4
    rows = np.zeros((4, W), dtype=np.float32)
    rows[0] = [5, 1, 3.0, 3.0]  # score (5-1)*0.2+1 = 1.8
    rows[1] = [5, 1, 7.0, 7.0]  # same score, slot 1
    segs = np.array([0, 1, B * S, B * S], dtype=np.int32)
    got = np.asarray(
        fused_seqpool_cvm_with_diff_thres(
            jnp.asarray(rows), jnp.asarray(segs), B, S,
            threshold_vec=[2.0, 1.0],  # slot0 filters (1.8 < 2), slot1 keeps
            use_cvm=False, show_coeff=0.2, clk_coeff=1.0,
        )
    ).reshape(S, W - 2)
    np.testing.assert_allclose(got[0], [0.0, 0.0])
    np.testing.assert_allclose(got[1], [7.0, 7.0])


def test_quant_ratio_rounds_embeds_before_pooling():
    B, S, W = 1, 1, 4
    rows = np.array(
        [[2, 1, 0.1234, -0.077], [1, 0, 0.5061, 0.25]], dtype=np.float32
    )
    segs = np.array([0, 0], dtype=np.int32)
    ratio = 128
    got = np.asarray(
        fused_seqpool_cvm(
            jnp.asarray(rows), jnp.asarray(segs), B, S, use_cvm=False,
            quant_ratio=ratio,
        )
    )[0]
    # reference rounding: int(v * ratio + 0.5) / ratio (C trunc toward zero)
    q = np.trunc(rows[:, 2:] * ratio + 0.5) / ratio
    np.testing.assert_allclose(got, q.sum(axis=0), rtol=1e-6)


def test_pcoc_variant_cvm_columns():
    rng = np.random.default_rng(2)
    p_num = 3
    mco = 4 + p_num  # [show, clk, d0, d1, q0..q2]
    B, S, W = 2, 2, mco + 4
    rows, segs = _mk(rng, B, S, W, cvm_cols=mco)
    got = np.asarray(
        fused_seqpool_cvm_with_pcoc(
            jnp.asarray(rows), jnp.asarray(segs), B, S, pclk_num=p_num
        )
    ).reshape(B, S, -1)
    p = _pool(rows, segs, B, S, W)
    show, clk = p[..., 0], p[..., 1]
    d0, d1 = p[..., 2], p[..., 3]
    q = p[..., 4 : 4 + p_num]
    exp = np.concatenate(
        [
            np.log(show + 1)[..., None],
            (np.log(clk + 1) - np.log(show + 1))[..., None],
            np.log(q + 1) - np.log(d0 + 1)[..., None],
            np.log(q + 1) - np.log(d1 + 1)[..., None],
            p[..., mco:],
        ],
        axis=-1,
    )
    assert got.shape == exp.shape  # 2 + 2*pclk_num + embeds
    np.testing.assert_allclose(got, exp, rtol=1e-4, atol=1e-5)


def test_fused_concat_column_spec():
    rng = np.random.default_rng(3)
    B = 4
    x1 = [jnp.asarray(rng.normal(size=(B, 5)).astype(np.float32)) for _ in range(2)]
    x2 = [jnp.asarray(rng.normal(size=(B, 3)).astype(np.float32)) for _ in range(2)]
    spec = [(0, 0), (0, 4), (1, 2), (1, 0)]
    outs = fused_concat(x1, x2, spec)
    assert len(outs) == 2
    for s in range(2):
        exp = np.stack(
            [
                np.asarray(x1[s])[:, 0],
                np.asarray(x1[s])[:, 4],
                np.asarray(x2[s])[:, 2],
                np.asarray(x2[s])[:, 0],
            ],
            axis=1,
        )
        np.testing.assert_array_equal(np.asarray(outs[s]), exp)


def test_fused_concat_differentiable():
    x1 = [jnp.ones((2, 3))]
    x2 = [jnp.ones((2, 2))]

    def f(a):
        return fused_concat([a], x2, [(0, 1), (1, 0)])[0].sum()

    g = jax.grad(f)(x1[0])
    np.testing.assert_array_equal(np.asarray(g), [[0, 1, 0], [0, 1, 0]])


def test_rank_attention2_is_rank_attention():
    """The two reference ops compute the same contraction (v1 via scratch +
    batched GEMM, v2 directly); here one einsum serves both names."""
    assert rank_attention2 is rank_attention


def test_quant_pull_descale():
    """Descale hits embedx only: [show, click, embed_w, embedx...] keeps
    embed_w unscaled (the reference stores it unquantized)."""
    values = jnp.asarray(
        np.array(
            [[3, 1, 10.0, 20.0, 12.0], [5, 2, -4.0, 8.0, 0.5]],
            dtype=np.float32,
        )
    )
    idx = jnp.asarray([1, 0, 1], dtype=jnp.int32)
    rows = np.asarray(pull_rows(values, idx, pull_embedx_scale=0.25))
    exp = np.asarray(values)[np.asarray(idx)]
    exp[:, 3:] *= 0.25  # counters + embed_w untouched, embedx descaled
    np.testing.assert_allclose(rows, exp, rtol=1e-6)


def test_conv_counter_push_end_to_end(tmp_path):
    """cvm_offset=3 table + counter_label_tasks: the third (conv) counter
    accumulates the conversion task label of each key's instance
    (parser -> push counter update -> CVM, VERDICT r3 item #5)."""
    from paddlebox_tpu.config import (
        DataFeedConfig,
        SlotConfig,
        TrainerConfig,
    )
    from paddlebox_tpu.data.data_generator import format_instance
    from paddlebox_tpu.data.dataset import PadBoxSlotDataset
    from paddlebox_tpu.models import CtrDnn
    from paddlebox_tpu.sparse.table import SparseTable
    from paddlebox_tpu.train.trainer import Trainer

    rng = np.random.default_rng(4)
    slots = [
        SlotConfig("click", "float", is_dense=True, shape=(1,)),
        SlotConfig("conv", "float", is_dense=True, shape=(1,)),
        SlotConfig("d0", "float", is_dense=True, shape=(2,)),
        SlotConfig("s0"),
        SlotConfig("s1"),
    ]
    conf = DataFeedConfig(
        slots=slots, batch_size=8, max_feasigns_per_ins=4,
        task_label_slots=("conv",),
    )
    path = str(tmp_path / "part-0")
    n_conv = 0
    with open(path, "w") as fh:
        for i in range(64):
            click = int(rng.integers(0, 2))
            convl = int(click and rng.integers(0, 2))
            n_conv += convl
            ins = [
                ("click", [float(click)]),
                ("conv", [float(convl)]),
                ("d0", rng.normal(size=2).round(3).tolist()),
                ("s0", rng.integers(0, 30, size=2).tolist()),
                ("s1", rng.integers(30, 50, size=1).tolist()),
            ]
            fh.write(format_instance(conf, ins) + "\n")
    ds = PadBoxSlotDataset(conf, read_threads=1)
    ds.set_filelist([path])
    ds.load_into_memory()
    tconf = SparseTableConfig(embedding_dim=4, cvm_offset=3)
    # task_labels col 0 = primary label (click); col 1 = the "conv" slot
    trconf = TrainerConfig(
        auc_buckets=1 << 10, counter_label_tasks=(1,)
    )
    model = CtrDnn(
        2, tconf.row_width, dense_dim=2, hidden=(8,), layout="conv",
        cvm_offset=3,
    )
    table = SparseTable(tconf, seed=0)
    trainer = Trainer(model, tconf, trconf, seed=0)
    table.begin_pass(ds.unique_keys())
    m = trainer.train_from_dataset(ds, table)
    table.end_pass()
    ds.close()
    assert np.isfinite(m["loss"])
    state = table.state_dict()
    # each instance contributes 3 key occurrences (2 in s0, 1 in s1):
    # conv counter total = 3 * n_conv, show total = 3 * 64
    np.testing.assert_allclose(state["values"][:, 0].sum(), 3 * 64, rtol=1e-5)
    np.testing.assert_allclose(
        state["values"][:, 2].sum(), 3 * n_conv, rtol=1e-5
    )
