"""End-to-end single-chip training (VERDICT item 3; SURVEY §7 stage 3 gate).

Mirrors the reference e2e template (python/paddle/fluid/tests/unittests/
test_paddlebox_datafeed.py:22-120): write slot files, run the full pass
lifecycle — load -> key census -> begin_pass -> train -> end_pass — and
assert the model actually learns (loss drops, AUC beats chance).
"""

import numpy as np
import pytest

from paddlebox_tpu.config import SparseTableConfig, TrainerConfig
from paddlebox_tpu.data.dataset import DatasetFactory
from paddlebox_tpu.data.synth import make_synth_config, write_synth_files
from paddlebox_tpu.models import CtrDnn
from paddlebox_tpu.sparse import SparseTable
from paddlebox_tpu.train import Trainer

N_SLOTS = 3
DENSE = 4


@pytest.fixture(scope="module")
def synth(tmp_path_factory):
    d = tmp_path_factory.mktemp("synth")
    paths = write_synth_files(
        str(d), n_files=2, ins_per_file=512, n_sparse_slots=N_SLOTS,
        vocab_per_slot=50, dense_dim=DENSE, seed=7,
    )
    conf = make_synth_config(
        n_sparse_slots=N_SLOTS, dense_dim=DENSE, batch_size=64,
        max_feasigns_per_ins=16,
    )
    return paths, conf


def _make_world(conf, seed=0):
    tconf = SparseTableConfig(embedding_dim=8, learning_rate=0.5, initial_range=0.05)
    table = SparseTable(tconf, seed=seed)
    model = CtrDnn(
        n_sparse_slots=N_SLOTS, emb_width=tconf.row_width, dense_dim=DENSE,
        hidden=(32, 16),
    )
    trainer = Trainer(
        model, tconf, TrainerConfig(dense_lr=3e-3, auc_buckets=1 << 12), seed=seed
    )
    return table, trainer


def test_e2e_loss_decreases_and_auc_beats_chance(synth):
    paths, conf = synth
    ds = DatasetFactory().create_dataset("BoxPSDataset", conf)
    ds.set_filelist(paths)
    ds.load_into_memory()
    assert ds.get_memory_data_size() == 1024

    table, trainer = _make_world(conf)
    per_pass = []
    for p in range(4):
        ds.local_shuffle(seed=p)
        table.begin_pass(ds.unique_keys())
        metrics = trainer.train_from_dataset(ds, table)
        table.end_pass()
        per_pass.append(metrics)
    ds.close()

    losses = [m["loss"] for m in per_pass]
    assert losses[-1] < losses[0] * 0.9, f"loss did not decrease: {losses}"
    assert per_pass[-1]["auc"] > 0.65, f"AUC barely above chance: {per_pass[-1]}"
    # table persisted features across passes
    assert table.n_features > 0
    assert table.missing_key_count == 0  # census covered every batch key


def test_e2e_preload_overlap_lifecycle(synth):
    """The double-buffered day pipeline: preload pass N+1 while training N
    (reference: BoxHelper::PreLoadIntoMemory / WaitFeedPassDone)."""
    paths, conf = synth
    with DatasetFactory().create_dataset("BoxPSDataset", conf) as ds:
        ds.set_filelist(paths)
        ds.preload_into_memory()
        table, trainer = _make_world(conf, seed=1)
        ds.wait_preload_done()
        table.begin_pass(ds.unique_keys())
        ds.preload_into_memory()  # next pass reads while we train
        m1 = trainer.train_from_dataset(ds, table)
        table.end_pass()
        ds.wait_preload_done()
        table.begin_pass(ds.unique_keys())
        m2 = trainer.train_from_dataset(ds, table)
        table.end_pass()
    assert m1["steps"] == m2["steps"] == 16
    assert m2["loss"] < m1["loss"]


def test_scan_nan_short_circuits_remaining_ticks():
    """With check_nan_inf under scan_steps=k, ticks after the first
    non-finite one must pass state through untouched: blast radius is one
    corrupted update, same as scan_steps=1 (advisor r3).  Uses a counting
    stub body so 'how many updates applied' is directly observable."""
    import jax.numpy as jnp

    from paddlebox_tpu.train.trainer import Trainer

    def fake_body(p, o, v, g, m, feed):
        return (p + 1, o, v, g, m, (p + 1).astype(jnp.float32),
                feed["ok"] > 0, p)

    tr = Trainer.__new__(Trainer)
    tr.conf = TrainerConfig(check_nan_inf=True, scan_steps=3)
    tr._step_body = fake_body
    scan_fn = tr._build_scan_step()

    def zs():  # distinct buffers: the scan donates each argument
        return [jnp.zeros(()) for _ in range(5)]

    feeds = {"ok": jnp.array([1.0, 0.0, 1.0])}  # tick 1 goes non-finite
    p, _, _, _, _, losses, finites = scan_fn(*zs(), feeds)
    # tick 0 applies, tick 1 applies (the one corrupted update), tick 2 skips
    assert float(p) == 2.0
    assert not bool(finites.all())  # per-tick flags (nan_policy accounting)
    assert losses.shape == (3,) and finites.shape == (3,)
    assert bool(jnp.isnan(losses[2]))  # skipped tick reports nan loss

    # all-finite group still applies every tick
    tr2 = Trainer.__new__(Trainer)
    tr2.conf = TrainerConfig(check_nan_inf=True, scan_steps=3)
    tr2._step_body = fake_body
    p, _, _, _, _, losses, finites = tr2._build_scan_step()(
        *zs(), {"ok": jnp.ones(3)}
    )
    assert float(p) == 3.0 and bool(finites.all())


def test_check_nan_inf_catches_poisoned_lr(synth):
    """FLAGS_check_nan_inf analog actually fires (VERDICT weak #27)."""
    paths, conf = synth
    with DatasetFactory().create_dataset("BoxPSDataset", conf) as ds:
        ds.set_filelist(paths)
        ds.load_into_memory()
        tconf = SparseTableConfig(embedding_dim=8)
        table = SparseTable(tconf)
        model = CtrDnn(
            n_sparse_slots=N_SLOTS, emb_width=tconf.row_width, dense_dim=DENSE,
            hidden=(16,),
        )
        trainer = Trainer(
            model, tconf,
            TrainerConfig(dense_lr=1e30, auc_buckets=1 << 10, check_nan_inf=True),
        )
        table.begin_pass(ds.unique_keys())
        with pytest.raises(FloatingPointError):
            trainer.train_from_dataset(ds, table)
