"""tools/check_publish_dir.py: publish-root donefile/manifest lint."""

import json
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", "tools"))

from check_publish_dir import check_publish_root, main  # noqa: E402

from paddlebox_tpu.serving_sync import DONEFILE_NAME, PublishEntry  # noqa: E402


def _write_unit(root, entry, payload=b"payload"):
    """A minimal publish unit: one data file + a valid recursive manifest."""
    from paddlebox_tpu.checkpoint import write_manifest

    d = os.path.join(root, entry.dir)
    os.makedirs(os.path.join(d, "sparse"), exist_ok=True)
    with open(os.path.join(d, "sparse", "rows.npy"), "wb") as fh:
        fh.write(payload)
    write_manifest(d, "manifest.json", recursive=True)


def _write_root(tmp_path, entries):
    root = str(tmp_path / "pub")
    os.makedirs(root, exist_ok=True)
    for e in entries:
        _write_unit(root, e)
    with open(os.path.join(root, DONEFILE_NAME), "w") as fh:
        for e in entries:
            fh.write(e.to_json() + "\n")
    return root


def _entries():
    return [
        PublishEntry(seq=0, kind="base", tag="t0", dir="base-t0",
                     base_tag="t0", prev_tag=None, published_at=1.0),
        PublishEntry(seq=1, kind="delta", tag="t1", dir="delta-t1",
                     base_tag="t0", prev_tag="t0", published_at=2.0),
        PublishEntry(seq=2, kind="delta", tag="t2", dir="delta-t2",
                     base_tag="t0", prev_tag="t1", published_at=3.0),
    ]


def test_clean_root_passes(tmp_path, capsys):
    root = _write_root(tmp_path, _entries())
    errors, warnings = check_publish_root(root)
    assert errors == [] and warnings == []
    assert main([root]) == 0
    assert "OK" in capsys.readouterr().out


def test_missing_manifest_and_dir(tmp_path):
    root = _write_root(tmp_path, _entries())
    os.remove(os.path.join(root, "delta-t1", "manifest.json"))
    import shutil

    shutil.rmtree(os.path.join(root, "delta-t2"))
    errors, _ = check_publish_root(root)
    assert any("no integrity manifest" in e for e in errors)
    assert any("missing from the root" in e for e in errors)
    assert main([root]) == 1


def test_corrupt_payload_fails_manifest(tmp_path):
    root = _write_root(tmp_path, _entries())
    with open(os.path.join(root, "delta-t1", "sparse", "rows.npy"),
              "wb") as fh:
        fh.write(b"corrupted!!")
    errors, _ = check_publish_root(root)
    assert any("delta-t1" in e for e in errors)


def test_out_of_order_seq_and_broken_chain(tmp_path):
    e0, e1, e2 = _entries()
    import dataclasses

    # seq jumps 0 -> 2 and t2 claims prev t1 which is absent
    root = _write_root(tmp_path, [e0, dataclasses.replace(e2, seq=2)])
    errors, _ = check_publish_root(root)
    assert any("out-of-order sequence" in e for e in errors)
    assert any("broken chain" in e for e in errors)


def test_delta_anchoring_unknown_base(tmp_path):
    e0, e1, _ = _entries()
    import dataclasses

    bad = dataclasses.replace(e1, base_tag="never-published")
    root = _write_root(tmp_path, [e0, bad])
    errors, _ = check_publish_root(root)
    assert any("no earlier donefile entry published" in e for e in errors)


def test_orphan_dir_warns_and_strict_fails(tmp_path):
    root = _write_root(tmp_path, _entries())
    _write_unit(root, PublishEntry(
        seq=9, kind="delta", tag="t9", dir="delta-t9", base_tag="t0",
        prev_tag="t2", published_at=9.0))  # uploaded, never donefiled
    errors, warnings = check_publish_root(root)
    assert errors == []
    assert any("orphan" in w for w in warnings)
    assert main([root]) == 0
    assert main([root, "--strict"]) == 1


def test_torn_tail_warns_corruption_fails(tmp_path):
    root = _write_root(tmp_path, _entries())
    done = os.path.join(root, DONEFILE_NAME)
    with open(done, "a") as fh:
        fh.write('{"seq": 3, "kind": "del')  # torn append
    errors, warnings = check_publish_root(root)
    assert errors == [] and any("torn" in w for w in warnings)
    # garbage mid-file is corruption
    with open(done) as fh:
        lines = fh.read().splitlines()
    lines[1] = "garbage line"
    with open(done, "w") as fh:
        fh.write("\n".join(lines) + "\n")
    errors, _ = check_publish_root(root)
    assert errors and "unparsable" in errors[0]


def test_no_donefile_is_an_error(tmp_path):
    root = str(tmp_path / "empty")
    os.makedirs(root)
    errors, _ = check_publish_root(root)
    assert errors and DONEFILE_NAME in errors[0]
