"""Distributed-liveness unit tier: heartbeat staleness math, poison-key
convergence, hang-injection interruption, deadline-bounded KV-channel waits
with named missing keys — all tier-1-safe (no multi-process JAX; simulated
workers are Watchdog instances sharing an InMemoryKv, driven either
synchronously through tick(now) with a fake clock or on their real monitor
threads with sub-second deadlines)."""

import threading
import time

import numpy as np
import pytest

from paddlebox_tpu.config import LivenessConfig, flags
from paddlebox_tpu.parallel import host_plane
from paddlebox_tpu.parallel.watchdog import (
    DistributedStallError,
    InMemoryKv,
    PeerTracker,
    Watchdog,
    beat,
    current,
)
from paddlebox_tpu.utils import faults
from paddlebox_tpu.utils.faults import FaultPlan, FaultSpec
from paddlebox_tpu.utils.monitor import stats

pytestmark = pytest.mark.distributed

FAST = LivenessConfig(
    deadline_s=0.5, heartbeat_interval_s=0.1, poll_interval_s=0.05
)


def _sim_fleet(n, kv, conf=FAST, t0=100.0):
    """n simulated workers' watchdogs over one shared KV store, driven
    synchronously (install_current=False keeps them out of the process-wide
    registry so they can coexist)."""
    wds = [
        Watchdog(conf, rank=r, world=n, kv=kv, namespace="sim",
                 install_current=False)
        for r in range(n)
    ]
    for wd in wds:  # pin the staleness origin to the fake clock
        wd._tracker = PeerTracker()
        wd._tracker.observe(wd.rank, 0, "start", t0)
    return wds


# --------------------------------------------------------------------------- #
# staleness math
# --------------------------------------------------------------------------- #
def test_peer_tracker_staleness_math():
    tr = PeerTracker()
    tr.observe(1, 0, "feed", 10.0)
    assert tr.age(1, 12.0) == pytest.approx(2.0)
    # progress change resets the clock
    tr.observe(1, 5, "step", 12.0)
    assert tr.age(1, 12.5) == pytest.approx(0.5)
    # frozen progress does NOT reset it, even when heartbeats keep arriving
    tr.observe(1, 5, "step", 14.0)
    tr.observe(1, 5, "shuffle", 15.0)
    assert tr.age(1, 15.0) == pytest.approx(3.0)
    assert tr.last(1) == (5, "shuffle")  # stage label stays fresh
    assert tr.age(2, 15.0) is None  # never observed
    stale = tr.stale(15.5, deadline_s=3.0)
    assert stale == [(1, pytest.approx(3.5), 5, "shuffle")]
    assert tr.stale(15.5, deadline_s=10.0) == []


def test_staleness_is_observer_clocked_not_heartbeat_clocked():
    """The protocol must be clock-skew immune: a peer's heartbeat carries
    no timestamp the detector trusts — only progress counters, aged by the
    observer's own clock."""
    tr = PeerTracker()
    # the same progress observed repeatedly: age grows with OUR clock
    for t in (0.0, 1.0, 2.0, 3.0):
        tr.observe(7, 42, "step", t)
    assert tr.age(7, 3.0) == pytest.approx(3.0)


def test_local_stall_detection_and_error_fields():
    wd = Watchdog(FAST, rank=3, world=1, install_current=False)
    wd._tracker = PeerTracker()
    wd._tracker.observe(3, 0, "start", 0.0)
    wd.report("feed")
    assert not wd.tick(now=0.2)
    # frozen past the deadline
    assert wd.tick(now=1.0)
    err = wd.error
    assert isinstance(err, DistributedStallError)
    assert err.culprit == 3
    assert err.stage == "feed"
    assert err.kind == "local"
    assert err.age_s > FAST.deadline_s
    assert err.detected_by == 3
    assert "process 3" in str(err) and "'feed'" in str(err)
    with pytest.raises(DistributedStallError):
        wd.check()


def test_progress_keeps_watchdog_quiet():
    wd = Watchdog(FAST, rank=0, world=1, install_current=False)
    wd._tracker = PeerTracker()
    wd._tracker.observe(0, 0, "start", 0.0)
    for i in range(40):  # 4 simulated seconds, reporting every 0.1
        wd.report("step")
        assert not wd.tick(now=i * 0.1)
    assert not wd.aborted


# --------------------------------------------------------------------------- #
# poison-key convergence
# --------------------------------------------------------------------------- #
def test_poison_key_convergence_names_the_frozen_worker():
    kv = InMemoryKv()
    wds = _sim_fleet(3, kv)
    t0 = 100.0
    # everyone heartbeats and progresses except rank 1
    for step in range(4):
        t = t0 + step * 0.1
        for wd in wds:
            if wd.rank != 1:
                wd.report("step")
            assert not wd.tick(now=t)
    # push rank 1 past the deadline (healthy ranks keep reporting, so
    # only the frozen worker's progress counter is stale): every watchdog
    # must converge on culprit 1
    t = t0 + 0.65
    for wd in wds:
        if wd.rank != 1:
            wd.report("step")
        wd.tick(now=t)
    for wd in wds:
        assert wd.aborted
        assert wd.error.culprit == 1
        # the detector sees it as a peer stall; everyone else via poison
        assert wd.error.kind in ("peer", "poison")
    assert kv.get(wds[0].poison_key) is not None
    # convergence reconstructs the same structured story everywhere
    stages = {wd.error.stage for wd in wds}
    assert len(stages) == 1


def test_poison_payload_roundtrip_and_corruption():
    err = DistributedStallError(
        culprit=2, stage="hostplane:plan-4", kind="peer", age_s=12.5,
        progress=77, detected_by=0,
    )
    back = DistributedStallError.from_payload(err.to_payload(), reader_rank=1)
    assert back.culprit == 2
    assert back.stage == "hostplane:plan-4"
    assert back.kind == "poison"
    assert back.progress == 77
    # a corrupt payload still converges (culprit unknown)
    bad = DistributedStallError.from_payload("not json{", reader_rank=1)
    assert bad.kind == "poison" and bad.culprit == -1


# --------------------------------------------------------------------------- #
# deliberate membership shrink (PR 16: elastic fleet)
# --------------------------------------------------------------------------- #
def test_retired_rank_never_named_stall_culprit():
    """A drained-and-retired rank's frozen heartbeat is EXPECTED: after
    retire_peer, pushing its staleness arbitrarily past the deadline must
    not trip anyone's abort latch."""
    kv = InMemoryKv()
    wds = _sim_fleet(3, kv)
    t0 = 100.0
    for step in range(4):  # everyone healthy first
        t = t0 + step * 0.1
        for wd in wds:
            wd.report("step")
            assert not wd.tick(now=t)
    # rank 1 drains out of the fleet on purpose
    for wd in wds:
        if wd.rank != 1:
            wd.retire_peer(1)
    assert kv.get(wds[0]._hb_key(1)) is None  # heartbeat key pruned
    # rank 1 frozen forever; survivors keep working far past the deadline
    for step in range(30):
        t = t0 + 0.4 + step * 0.1
        for wd in wds:
            if wd.rank == 1:
                continue
            wd.report("step")
            assert not wd.tick(now=t)
    for wd in wds:
        if wd.rank != 1:
            assert not wd.aborted
    assert kv.get(wds[0].poison_key) is None


def test_poison_naming_retired_rank_is_ignored_and_cleared():
    """A racing detector that poisoned the fleet naming a rank that was
    deliberately retired (it saw the drain, not a stall): readers must
    drop the stale poison, clear the key, and NOT abort."""
    kv = InMemoryKv()
    wds = _sim_fleet(3, kv)
    wds[0].retire_peer(1)
    err = DistributedStallError(
        culprit=1, stage="step", kind="peer", age_s=9.9, progress=3,
        detected_by=2,
    )
    kv.set(wds[0].poison_key, err.to_payload())
    base = stats.get("watchdog.poison_retired_ignored")
    wds[0].report("step")
    assert not wds[0].tick(now=100.1)
    assert not wds[0].aborted
    assert kv.get(wds[0].poison_key) is None  # cleared for everyone
    assert stats.get("watchdog.poison_retired_ignored") == base + 1
    # a poison naming a NON-retired rank still aborts as before
    err2 = DistributedStallError(
        culprit=2, stage="step", kind="peer", age_s=9.9, progress=3,
        detected_by=0,
    )
    kv.set(wds[0].poison_key, err2.to_payload())
    assert wds[0].tick(now=100.2)
    assert wds[0].aborted and wds[0].error.culprit == 2


def test_retire_peer_is_idempotent_and_guards_own_rank():
    kv = InMemoryKv()
    wds = _sim_fleet(2, kv)
    tr = PeerTracker()
    tr.observe(1, 0, "step", 0.0)
    tr.deregister(1)
    assert tr.age(1, 5.0) is None
    tr.deregister(1)  # deregistering an unknown rank is a no-op
    wds[0].retire_peer(1)
    wds[0].retire_peer(1)  # idempotent
    assert wds[0]._is_retired(1)
    with pytest.raises(ValueError):
        wds[0].retire_peer(0)


def test_threaded_fleet_aborts_within_deadline():
    """Real monitor threads + heartbeats: freeze one of two workers and the
    whole simulated fleet aborts within ~2x the deadline, naming it."""
    kv = InMemoryKv()
    conf = LivenessConfig(
        deadline_s=0.4, heartbeat_interval_s=0.08, poll_interval_s=0.04
    )
    wd0 = Watchdog(conf, rank=0, world=2, kv=kv, namespace="thr",
                   install_current=False).start()
    wd1 = Watchdog(conf, rank=1, world=2, kv=kv, namespace="thr",
                   install_current=False).start()
    try:
        t0 = time.monotonic()
        # rank 0 keeps working; rank 1 never reports (frozen from birth)
        while not (wd0.aborted and wd1.aborted):
            wd0.report("step")
            if time.monotonic() - t0 > 2 * conf.deadline_s + 1.0:
                pytest.fail("fleet did not abort within 2x deadline")
            time.sleep(0.02)
        assert wd0.error.culprit == 1
        assert wd1.error.culprit == 1
    finally:
        wd0.close()
        wd1.close()


def test_heartbeat_fault_site():
    kv = InMemoryKv()
    wd = Watchdog(FAST, rank=0, world=2, kv=kv, namespace="hb",
                  install_current=False)
    wd._tracker = PeerTracker()
    wd._tracker.observe(0, 0, "start", 0.0)
    base = stats.get("watchdog.heartbeat_faults")
    with faults.fault_plan({"watchdog.heartbeat": "first:1"}):
        wd.tick(now=0.0)  # first publish attempt: injected failure
        assert kv.get(wd._hb_key(0)) is None
        assert stats.get("watchdog.heartbeat_faults") == base + 1
        wd.tick(now=0.2)  # past the heartbeat interval: publishes fine
        assert kv.get(wd._hb_key(0)) is not None


# --------------------------------------------------------------------------- #
# hang injection
# --------------------------------------------------------------------------- #
def test_hang_spec_parse():
    spec = FaultSpec.parse("hang:first:2")
    assert spec.hang and spec.fail_first == 2
    spec = FaultSpec.parse("hang:at:3,5")
    assert spec.hang and spec.at == (3, 5)
    with pytest.raises(ValueError):
        FaultSpec.parse("freeze:1")


def test_hang_interrupted_by_watchdog():
    conf = LivenessConfig(
        deadline_s=0.3, heartbeat_interval_s=0.05, poll_interval_s=0.03
    )
    wd = Watchdog(conf, rank=0, world=1).start()
    try:
        with faults.fault_plan({"train.step": "hang:first:1"}):
            t0 = time.monotonic()
            with pytest.raises(DistributedStallError) as ei:
                faults.inject("train.step")
            assert time.monotonic() - t0 < 2 * conf.deadline_s + 0.5
            assert ei.value.culprit == 0
        assert stats.get("faults.hung.train.step") >= 1
    finally:
        wd.close()
        faults.clear()


def test_hang_released_without_watchdog():
    with faults.fault_plan({"x.y": "hang:first:1"}):
        done = threading.Event()

        def run():
            faults.inject("x.y")  # hangs until released
            done.set()

        t = threading.Thread(target=run, daemon=True)
        t.start()
        assert not done.wait(0.2)
        faults.release_hangs()
        assert done.wait(2.0)


def test_prefetcher_get_interrupted_by_abort():
    """A consumer blocked on a stalled producer's queue unblocks with the
    structured error within one poll slice."""
    from paddlebox_tpu.train.trainer import _FeedPrefetcher

    hold = threading.Event()

    def gen():
        hold.wait(10.0)  # the "stalled" producer
        yield "never"

    wd = Watchdog(FAST, rank=0, world=1).start()
    pf = _FeedPrefetcher(gen(), depth=1)
    try:
        wd.abort(
            DistributedStallError(
                culprit=0, stage="feed", kind="local", age_s=9.9,
                progress=0, detected_by=0,
            )
        )
        with pytest.raises(DistributedStallError):
            next(pf)
    finally:
        hold.set()
        wd.close()
        pf.close()


# --------------------------------------------------------------------------- #
# current-watchdog registry / beats
# --------------------------------------------------------------------------- #
def test_current_registry_and_beat():
    assert current() is None
    beat("feed")  # no-op without a watchdog
    wd = Watchdog(FAST, rank=0, world=1).start()
    try:
        assert current() is wd
        _, p0 = wd.state()
        beat("shuffle")
        stage, p1 = wd.state()
        assert stage == "shuffle" and p1 == p0 + 1
    finally:
        wd.close()
    assert current() is None


# --------------------------------------------------------------------------- #
# KvChannel: deadline-bounded waits, rich timeout, config resolution
# --------------------------------------------------------------------------- #
class _FakeCoordClient:
    """Coordination-service client double: blocking gets poll a dict and
    time out with the DEADLINE_EXCEEDED status string the real one uses."""

    def __init__(self):
        self.store = {}

    def key_value_set(self, k, v):
        self.store[k] = v

    def blocking_key_value_get(self, k, timeout_ms):
        end = time.monotonic() + timeout_ms / 1000.0
        while time.monotonic() < end:
            if k in self.store:
                return self.store[k]
            time.sleep(0.005)
        raise RuntimeError(f"DEADLINE_EXCEEDED: key {k}")

    def key_value_delete(self, k):
        self.store.pop(k, None)


@pytest.fixture
def fake_world(monkeypatch):
    """3-process world with a fake coordination client (rank 0's view)."""
    import jax

    client = _FakeCoordClient()
    monkeypatch.setattr(host_plane, "_client", lambda: client)
    monkeypatch.setattr(jax, "process_index", lambda: 0)
    monkeypatch.setattr(jax, "process_count", lambda: 3)
    return client


def _peer_payload(x: np.ndarray, codec: str = "varint") -> str:
    """What a same-version peer would post for ``x`` (codec-framed,
    base64'd — the KvChannel wire format)."""
    import base64

    return base64.b64encode(
        host_plane._encode_array(np.ascontiguousarray(x), codec)
    ).decode("ascii")


def test_kvchannel_timeout_names_missing_keys(fake_world):
    ch = host_plane.KvChannel("plan-7", timeout_s=0.4)
    ch.POLL_S = 0.05
    # peer 1 answers, peer 2 never does
    x = np.asarray([5], dtype=np.int64)
    fake_world.store["pbox_hp/plan-7/0/1"] = _peer_payload(
        np.asarray([6], np.int64), ch.codec
    )
    with pytest.raises(host_plane.HostPlaneTimeout) as ei:
        ch.allgather(x)
    err = ei.value
    assert err.channel == "plan-7" and err.seq == 0
    assert [r for r, _ in err.missing] == [2]
    assert "pbox_hp/plan-7/0/2" in str(err)
    assert "process(es) [2]" in str(err)


def test_kvchannel_completes_when_peers_answer(fake_world):
    ch = host_plane.KvChannel("plan-8", timeout_s=2.0)
    ch.POLL_S = 0.05
    for r in (1, 2):
        fake_world.store[f"pbox_hp/plan-8/0/{r}"] = _peer_payload(
            np.asarray([r], np.int64), ch.codec
        )
    out = ch.allgather(np.asarray([0], dtype=np.int64))
    np.testing.assert_array_equal(out, np.asarray([[0], [1], [2]]))
    ch.close()


def test_kvchannel_gather_bytes_varlen(fake_world):
    """Opaque varlen byte payloads gather in rank order with no padding
    contract (the census wire's transport face)."""
    import base64

    ch = host_plane.KvChannel("plan-b", timeout_s=2.0)
    ch.POLL_S = 0.05
    fake_world.store["pbox_hp/plan-b/0/1"] = base64.b64encode(
        b"peer-one-longer-payload"
    ).decode()
    fake_world.store["pbox_hp/plan-b/0/2"] = base64.b64encode(b"p2").decode()
    out = ch.gather_bytes(b"mine")
    assert out == [b"mine", b"peer-one-longer-payload", b"p2"]
    ch.close()


def test_kvchannel_codec_mismatch_fails_loudly(fake_world):
    """A legacy (unframed) peer payload on a codec-enabled channel raises
    the structured codec error naming the peer — never a silent
    frombuffer of garbage."""
    ch = host_plane.KvChannel("plan-m", timeout_s=2.0, codec="varint")
    ch.POLL_S = 0.05
    # peer 1 speaks the old bare-bytes wire; peer 2 is well-formed
    fake_world.store["pbox_hp/plan-m/0/1"] = (
        __import__("base64").b64encode(
            np.asarray([6], np.int64).tobytes()
        ).decode()
    )
    fake_world.store["pbox_hp/plan-m/0/2"] = _peer_payload(
        np.asarray([7], np.int64), "varint"
    )
    with pytest.raises(host_plane.HostPlaneCodecError) as ei:
        ch.allgather(np.asarray([0], dtype=np.int64))
    assert ei.value.rank == 1 and ei.value.channel == "plan-m"
    # and the mirror case: a framed payload hitting a legacy rank
    ch2 = host_plane.KvChannel("plan-m2", timeout_s=2.0, codec="legacy")
    ch2.POLL_S = 0.05
    fake_world.store["pbox_hp/plan-m2/0/1"] = _peer_payload(
        np.asarray([6], np.int64), "varint"
    )
    fake_world.store["pbox_hp/plan-m2/0/2"] = (
        __import__("base64").b64encode(
            np.asarray([7], np.int64).tobytes()
        ).decode()
    )
    with pytest.raises(host_plane.HostPlaneCodecError):
        ch2.allgather(np.asarray([0], dtype=np.int64))


def test_kvchannel_codec_roundtrip_all_modes(fake_world):
    """Every codec mode round-trips int and float payloads exactly."""
    for codec in ("varint", "raw", "legacy"):
        for x in (
            np.asarray([[5, -3, 4095, 4095]], dtype=np.int32),
            np.asarray([1.5, -2.25], dtype=np.float32),
            np.asarray([0, (1 << 63)], dtype=np.uint64),
        ):
            name = f"plan-c-{codec}-{x.dtype}"
            ch = host_plane.KvChannel(name, timeout_s=2.0, codec=codec)
            ch.POLL_S = 0.05
            for r in (1, 2):
                fake_world.store[f"pbox_hp/{name}/0/{r}"] = _peer_payload(
                    x + x.dtype.type(r), codec
                )
            out = ch.allgather(x)
            assert out.dtype == x.dtype
            np.testing.assert_array_equal(out[0], x)
            np.testing.assert_array_equal(out[2], x + x.dtype.type(2))
            ch.close()


def test_kvchannel_records_collective_digest(fake_world):
    """Every allgather leaves a (channel, seq, op) digest in the flight
    ring — the runtime witness pbox_doctor's cross-rank check consumes."""
    from paddlebox_tpu.telemetry import flight

    rec = flight.reset_for_tests()
    ch = host_plane.KvChannel("plan-w", timeout_s=2.0)
    ch.POLL_S = 0.05
    for s in range(2):
        for r in (1, 2):
            fake_world.store[f"pbox_hp/plan-w/{s}/{r}"] = _peer_payload(
                np.asarray([r], np.int64), ch.codec
            )
        ch.allgather(np.asarray([0], dtype=np.int64))
    digests = [
        r for r in rec.snapshot()
        if r["kind"] == "collective" and r.get("channel") == "plan-w"
    ]
    assert [(d["seq"], d["op"], d["rank"]) for d in digests] == [
        (0, "allgather", 0), (1, "allgather", 0),
    ]
    ch.close()
    flight.reset_for_tests()


def test_kvchannel_wait_interrupted_by_watchdog_abort(fake_world):
    wd = Watchdog(FAST, rank=0, world=1).start()
    ch = host_plane.KvChannel("plan-9", timeout_s=30.0)
    ch.POLL_S = 0.05
    try:
        wd.abort(
            DistributedStallError(
                culprit=2, stage="step", kind="peer", age_s=9.0,
                progress=4, detected_by=0,
            )
        )
        t0 = time.monotonic()
        with pytest.raises(DistributedStallError):
            ch.allgather(np.asarray([1], dtype=np.int64))
        assert time.monotonic() - t0 < 5.0  # nowhere near the 30s timeout
    finally:
        wd.close()


def test_kvchannel_default_timeout_resolution(fake_world, monkeypatch):
    # flags default
    assert host_plane.KvChannel("a").timeout_s == flags.hostplane_timeout_s
    # env flag override
    monkeypatch.setenv("PBOX_HOSTPLANE_TIMEOUT_S", "123.0")
    assert host_plane.KvChannel("b").timeout_s == 123.0
    # the active watchdog's LivenessConfig outranks the flag
    conf = LivenessConfig(
        deadline_s=5.0, heartbeat_interval_s=1.0, poll_interval_s=0.5,
        hostplane_timeout_s=42.0,
    )
    wd = Watchdog(conf, rank=0, world=1).start()
    try:
        assert host_plane.KvChannel("c").timeout_s == 42.0
    finally:
        wd.close()


def test_kvchannel_allgather_fault_site(fake_world):
    with faults.fault_plan({"hostplane.allgather": "first:1"}):
        ch = host_plane.KvChannel("plan-f", timeout_s=1.0)
        with pytest.raises(faults.FaultInjected):
            ch.allgather(np.asarray([1], dtype=np.int64))


# --------------------------------------------------------------------------- #
# LivenessConfig
# --------------------------------------------------------------------------- #
def test_liveness_config_validation():
    with pytest.raises(ValueError):
        LivenessConfig(deadline_s=0.0)
    with pytest.raises(ValueError):
        LivenessConfig(deadline_s=10.0, heartbeat_interval_s=10.0)
    with pytest.raises(ValueError):
        LivenessConfig(poll_interval_s=0.0)


def test_liveness_config_from_flags(monkeypatch):
    monkeypatch.setenv("PBOX_LIVENESS_DEADLINE_S", "77.0")
    monkeypatch.setenv("PBOX_LIVENESS_HEARTBEAT_S", "7.0")
    conf = LivenessConfig.from_flags()
    assert conf.deadline_s == 77.0
    assert conf.heartbeat_interval_s == 7.0


def test_for_trainer_disabled_and_single_process():
    from paddlebox_tpu.parallel import watchdog as wmod

    assert wmod.for_trainer(None, "x") is None
    conf = LivenessConfig(
        deadline_s=5.0, heartbeat_interval_s=1.0, poll_interval_s=0.5,
        enabled=False,
    )
    assert wmod.for_trainer(conf, "x") is None
    wd = wmod.for_trainer(FAST, "x")
    assert wd is not None and wd.kv is None and wd.world == 1
    # single-process watchdogs must never arm the hard-exit reaper
    assert wd._hard_exit_grace_s is None
