"""Composed 2-D mesh training: data x expert in ONE jitted step.

The sparse table + batch shard over 'data' exactly as on a 1-D mesh while
MMoE's expert bank shards over the inner 'expert' axis
(expert_mesh="inherit": the model's shard_map binds the inner axis inside
MultiChipTrainer's outer data-axis shard_map — nested shard_map over
disjoint axes of one mesh).  Parity oracle: the SAME run on a plain
4-device data mesh, which must produce identical metrics — the expert
axis splits compute, never math."""

import jax
import numpy as np
import pytest

from paddlebox_tpu.config import SparseTableConfig, TrainerConfig
from paddlebox_tpu.data.dataset import PadBoxSlotDataset
from paddlebox_tpu.data.synth import make_synth_config, write_synth_files
from paddlebox_tpu.models import MMoE
from paddlebox_tpu.parallel import make_mesh
from paddlebox_tpu.parallel.expert import EXPERT_AXIS
from paddlebox_tpu.parallel.mesh import data_axis_size, make_composed_mesh
from paddlebox_tpu.parallel.sharded_table import ShardedSparseTable
from paddlebox_tpu.parallel.trainer import MultiChipTrainer

S, DENSE, B, E = 3, 2, 16, 4

# the inner 'inherit' shard_map needs the context-mesh mode of modern
# jax.shard_map; legacy builds (jax.experimental.shard_map only) have no
# equivalent (utils/jax_compat raises NotImplementedError naming the
# version) — the composed tests are a platform gap there, not a failure
needs_context_mesh = pytest.mark.skipif(
    not hasattr(jax, "shard_map"),
    reason="composed (context-mesh) shard_map needs modern jax.shard_map",
)


def _data(tmp_path, n_ins=256):
    conf = make_synth_config(
        n_sparse_slots=S, dense_dim=DENSE, batch_size=B,
        max_feasigns_per_ins=8, n_task_labels=1,
    )
    files = write_synth_files(
        str(tmp_path), n_files=1, ins_per_file=n_ins, n_sparse_slots=S,
        vocab_per_slot=50, dense_dim=DENSE, seed=9, n_task_labels=1,
    )
    ds = PadBoxSlotDataset(conf, read_threads=1)
    ds.set_filelist(files)
    ds.load_into_memory()
    return conf, ds


def _run(mesh, model, tmp_path, passes=2):
    conf, ds = _data(tmp_path)
    tconf = SparseTableConfig(embedding_dim=4)
    table = ShardedSparseTable(tconf, mesh, seed=0)
    trainer = MultiChipTrainer(
        model, tconf, mesh, TrainerConfig(auc_buckets=1 << 10), seed=0
    )
    out = None
    for p in range(passes):
        table.begin_pass(ds.unique_keys())
        out = trainer.train_from_dataset(ds, table)
        table.end_pass()
    state = table.state_dict()
    ds.close()
    return out, state


def test_mesh_helpers():
    mesh = make_composed_mesh(4, 2, EXPERT_AXIS)
    assert mesh.axis_names == ("data", EXPERT_AXIS)
    assert data_axis_size(mesh) == 4
    assert data_axis_size(make_mesh(8)) == 8
    with pytest.raises(ValueError, match="need"):
        make_composed_mesh(8, 2, EXPERT_AXIS)
    # a 1-sized data axis is an explicit config error (VERDICT r4 next #6:
    # formerly an XLA RET_CHECK at jit time / a silent dryrun skip), and the
    # message must point at the supported alternative
    with pytest.raises(ValueError, match="single-chip Trainer"):
        make_composed_mesh(1, 2, EXPERT_AXIS)


@needs_context_mesh
def test_composed_mesh_odd_device_total(tmp_path):
    """Odd device totals compose: 3x2 uses 6 of the 8 virtual devices (the
    remainder stays out of the mesh) and trains to the same kind of state
    as any other composed run — no even-count restriction (the reference's
    section-based pipeline imposes no analogous shape limit,
    pipeline_trainer.cc)."""
    kw = dict(dense_dim=DENSE, n_tasks=2, n_experts=E, expert_hidden=(16,),
              expert_dim=8, tower_hidden=(8,))
    mesh = make_composed_mesh(3, 2, EXPERT_AXIS)
    m, s = _run(mesh, MMoE(S, 6, expert_mesh="inherit", **kw),
                tmp_path / "odd", passes=1)
    assert m["steps"] > 0 and np.isfinite(m["loss"])
    # data-side counters are exact sums over the instances seen
    assert s["values"][:, 0].sum() > 0  # show counters accumulated


@needs_context_mesh
def test_composed_data_expert_matches_data_only(tmp_path):
    kw = dict(dense_dim=DENSE, n_tasks=2, n_experts=E, expert_hidden=(16,),
              expert_dim=8, tower_hidden=(8,))
    mesh1 = make_mesh(4)
    m1, s1 = _run(mesh1, MMoE(S, 6, **kw), tmp_path / "a")

    mesh2 = make_composed_mesh(4, 2, EXPERT_AXIS)
    m2, s2 = _run(
        mesh2, MMoE(S, 6, expert_mesh="inherit", **kw), tmp_path / "b"
    )

    assert m1["steps"] == m2["steps"] > 0
    # What must be EXACT: the data path.  show/clk counters are pure
    # data-side sums — any composed-mesh plumbing error (wrong batch
    # routing, double counting over the inner axis) breaks them first.
    np.testing.assert_array_equal(s1["keys"], s2["keys"])
    np.testing.assert_array_equal(s1["values"][:, :2], s2["values"][:, :2])
    # What is close but NOT bitwise: gradients.  The auto expert axis lets
    # the partitioner regroup float reductions (~1e-7/apply), and a ReLU
    # pre-activation sitting within that of a boundary flips its unit's
    # gradient path discretely — isolated O(lr*grad) embedding diffs that
    # training dynamics then amplify.  Single-apply EP parity at 2e-5 is
    # pinned in test_moe_ep; here the claim is structural equivalence.
    assert m2["loss"] == pytest.approx(m1["loss"], rel=5e-3)
    assert m2["auc"] == pytest.approx(m1["auc"], abs=2e-2)
    assert m2["task1/auc"] == pytest.approx(m1["task1/auc"], abs=2e-2)
    np.testing.assert_allclose(s1["values"], s2["values"], atol=2e-2)


@needs_context_mesh
def test_composed_data_seq_matches_data_only(tmp_path):
    """data x seq composition: LongSeqCtrDnn's ring attention (positions
    riding the ring — no axis_index) nested inside MultiChipTrainer's
    data-axis shard_map."""
    from paddlebox_tpu.models import LongSeqCtrDnn
    from paddlebox_tpu.parallel.sequence import SEQ_AXIS

    T = 8

    def data(tmp_path):
        conf = make_synth_config(
            n_sparse_slots=S, dense_dim=DENSE, batch_size=B,
            max_feasigns_per_ins=12, sequence_slot="slot0", max_seq_len=T,
        )
        files = write_synth_files(
            str(tmp_path), n_files=1, ins_per_file=256, n_sparse_slots=S,
            vocab_per_slot=50, dense_dim=DENSE, seed=9, max_keys_per_slot=9,
        )
        ds = PadBoxSlotDataset(conf, read_threads=1)
        ds.set_filelist(files)
        ds.load_into_memory()
        return conf, ds

    def run(mesh, model, tp):
        conf, ds = data(tp)
        tconf = SparseTableConfig(embedding_dim=4)
        table = ShardedSparseTable(tconf, mesh, seed=0)
        trainer = MultiChipTrainer(
            model, tconf, mesh, TrainerConfig(auc_buckets=1 << 10), seed=0
        )
        table.begin_pass(ds.unique_keys())
        m = trainer.train_from_dataset(ds, table)
        table.end_pass()
        state = table.state_dict()
        ds.close()
        return m, state

    kw = dict(dense_dim=DENSE, hidden=(16,), max_seq_len=T, n_heads=2,
              head_dim=4)
    m1, s1 = run(make_mesh(4), LongSeqCtrDnn(S, 6, **kw), tmp_path / "a")
    m2, s2 = run(
        make_composed_mesh(4, 2, SEQ_AXIS),
        LongSeqCtrDnn(S, 6, seq_mesh="inherit", seq_impl="ring", **kw),
        tmp_path / "b",
    )
    assert m1["steps"] == m2["steps"] > 0
    np.testing.assert_array_equal(s1["keys"], s2["keys"])
    np.testing.assert_array_equal(s1["values"][:, :2], s2["values"][:, :2])
    assert m2["loss"] == pytest.approx(m1["loss"], rel=5e-3)
    assert m2["auc"] == pytest.approx(m1["auc"], abs=2e-2)
    np.testing.assert_allclose(s1["values"], s2["values"], atol=2e-2)
