"""Elastic fleet (PR 16): supervisor spawn/retire membership, router
add/remove, and the FleetAutoscaler's hysteresis/cooldown decision loop
— driven synchronously with a fake clock and stub supervisor/router so
every decision is deterministic.  Chaos coverage: the ``fleet.scale``
site aborts a scale-up cleanly, and a ``hang:``-wedged drain is bounded
by the watchdog with the retirement (and the rest of a rolling restart)
proceeding past it."""

import signal
import socket
import sys
import time

import pytest

from paddlebox_tpu import telemetry
from paddlebox_tpu.config import LivenessConfig
from paddlebox_tpu.parallel.watchdog import Watchdog
from paddlebox_tpu.serving_fleet import (
    EJECTED,
    AutoscalerConfig,
    FleetAutoscaler,
    FleetRouter,
    ReplicaProc,
    ReplicaSupervisor,
)
from paddlebox_tpu.utils import faults
from paddlebox_tpu.utils.faults import FaultInjected, fault_plan
from paddlebox_tpu.utils.retry import RetryPolicy

_SLEEPER = [sys.executable, "-c", "import time; time.sleep(300)"]


def _fast_policy():
    return RetryPolicy(max_attempts=1_000_000, base_delay_s=0.05,
                       max_delay_s=0.2)


def _supervisor(n=1):
    return ReplicaSupervisor(
        n, lambda rid, port: _SLEEPER, poll_interval_s=0.05,
        restart_policy=_fast_policy(), stable_after_s=0.5,
    )


def _wait_until(cond, timeout_s=15.0, interval_s=0.02):
    deadline = time.monotonic() + timeout_s
    while time.monotonic() < deadline:
        if cond():
            return True
        time.sleep(interval_s)
    return cond()


# --------------------------------------------------------------------------- #
# supervisor elastic membership
# --------------------------------------------------------------------------- #
def test_spawn_replica_grows_fleet_with_fresh_port():
    sup = _supervisor(1)
    sup.start()
    try:
        spawns = telemetry.counter("fleet.spawns")
        base = spawns.value()
        addr = sup.spawn_replica()
        assert len(sup.replicas) == 2
        assert sup.replicas[1].alive()
        assert addr == f"{sup.host}:{sup.replicas[1].port}"
        assert sup.endpoints() == [f"{sup.host}:{r.port}"
                                   for r in sup.replicas]
        # the port is bind-probed fresh, never a static offset collision
        assert sup.replicas[1].port != sup.replicas[0].port
        assert spawns.value() == base + 1
        assert sup.live_replica_ids() == [0, 1]
    finally:
        sup.stop()


def test_retired_replica_never_resurrected():
    """The babysitter must treat a deliberate retirement as membership,
    not as a crash: across many poll ticks the retired replica stays
    down, keeps restarts == 0, and leaves the endpoint list."""
    sup = _supervisor(2)
    sup.start()
    try:
        sup.retire_replica(1)
        assert not sup.replicas[1].alive()
        assert sup.endpoints() == [f"{sup.host}:{sup.replicas[0].port}"]
        assert sup.live_replica_ids() == [0]
        # give the babysitter many chances to wrongly respawn it
        for _ in range(6):
            sup.poll_once()
            time.sleep(0.05)
        assert not sup.replicas[1].alive()
        assert sup.replicas[1].restarts == 0
        # a retired replica is no longer a chaos target either
        with pytest.raises(RuntimeError):
            sup.kill_replica(1)
        sup.retire_replica(1)  # idempotent
    finally:
        sup.stop()


def test_retired_port_returns_to_the_os_pool():
    sup = _supervisor(2)
    sup.start()
    try:
        port = sup.replicas[1].port
        sup.retire_replica(1)
        s = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        s.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        try:
            s.bind(("127.0.0.1", port))  # freed: a later spawn may take it
        finally:
            s.close()
    finally:
        sup.stop()


def test_scale_fault_site_aborts_spawn_cleanly():
    """Chaos at fleet.scale: the scale-up fails BEFORE anything joins the
    fleet — membership unchanged, and the next attempt succeeds."""
    sup = _supervisor(1)
    sup.start()
    try:
        with fault_plan({"fleet.scale": "first:1"}):
            with pytest.raises(FaultInjected):
                sup.spawn_replica()
            assert len(sup.replicas) == 1
            assert len(sup.endpoints()) == 1
            sup.spawn_replica()  # first:1 spent: recovery is clean
            assert len(sup.replicas) == 2 and sup.replicas[1].alive()
    finally:
        sup.stop()


# --------------------------------------------------------------------------- #
# router dynamic membership
# --------------------------------------------------------------------------- #
def test_router_add_remove_replica():
    router = FleetRouter(["127.0.0.1:1"], recover_after=2)
    h = router.add_replica("127.0.0.1:2")
    assert [r["addr"] for r in router.fleet_view()["replicas"]] == \
        ["127.0.0.1:1", "127.0.0.1:2"]
    # unproven: it starts ejected, one clean probe from admission
    assert h.state == EJECTED
    assert h.consecutive_ok == 1
    assert router.add_replica("127.0.0.1:2") is h  # idempotent on addr
    assert len(router.replicas) == 2
    router.remove_replica("127.0.0.1:2")
    assert [r["addr"] for r in router.fleet_view()["replicas"]] == \
        ["127.0.0.1:1"]
    router.remove_replica("127.0.0.1:2")  # idempotent too
    # bare port normalizes like the constructor's endpoints do
    router.add_replica("7777")
    assert router.replicas[-1].addr == "127.0.0.1:7777"


# --------------------------------------------------------------------------- #
# autoscaler decisions (fake clock + stub supervisor/router)
# --------------------------------------------------------------------------- #
class _StubSupervisor:
    """Membership bookkeeping without processes: ports are fake (nothing
    listens, so _await_drain's probe sees OSError == already drained)."""

    def __init__(self, n=1):
        self.host = "127.0.0.1"
        self.replicas = [ReplicaProc(replica_id=i, port=40000 + i)
                         for i in range(n)]
        self.killed = []

    def endpoints(self):
        return [f"{self.host}:{r.port}"
                for r in self.replicas if not r.retired]

    def live_replica_ids(self):
        return [r.replica_id for r in self.replicas if not r.retired]

    def spawn_replica(self):
        faults.inject("fleet.scale")
        r = ReplicaProc(replica_id=len(self.replicas),
                        port=40000 + len(self.replicas))
        self.replicas.append(r)
        return f"{self.host}:{r.port}"

    def retire_replica(self, replica_id, timeout_s=10.0):
        self.replicas[replica_id].retired = True

    def kill_replica(self, replica_id, sig=signal.SIGKILL):
        if self.replicas[replica_id].retired:
            raise RuntimeError(f"replica {replica_id} is retired")
        self.killed.append((replica_id, sig))
        return 1000 + replica_id


class _StubRouter:
    """Canned fleet_view + membership recording."""

    def __init__(self):
        self.rows = []
        self.added = []
        self.removed = []

    def set_pressure(self, addrs, queue_depth=0.0, wait_s=0.0,
                     age_seconds=1.0):
        self.rows = [
            {"addr": a, "state": "healthy", "queue_depth": queue_depth,
             "estimated_wait_s": wait_s,
             "models": {"live": {"seq": 7, "age_seconds": age_seconds}}}
            for a in addrs
        ]

    def fleet_view(self):
        return {"replicas": list(self.rows)}

    def add_replica(self, addr):
        self.added.append(addr)

    def remove_replica(self, addr):
        self.removed.append(addr)
        self.rows = [r for r in self.rows if r["addr"] != addr]


def _scaler(sup, router, **over):
    opts = dict(min_replicas=1, max_replicas=4, cooldown_s=30.0,
                up_after=3, down_after=5, drain_timeout_s=0.2)
    opts.update(over)
    conf = AutoscalerConfig(**opts)
    clock = [1000.0]

    def _clock():
        # every read advances a little, so the autoscaler's internal
        # deadline-bounded waits always terminate under the fake clock
        # (tick() itself is driven by the explicit now= below)
        clock[0] += 0.05
        return clock[0]

    a = FleetAutoscaler(sup, router, conf, clock=_clock)
    return a, clock


def test_autoscaler_needs_a_streak_not_one_spike():
    sup, router = _StubSupervisor(1), _StubRouter()
    a, clock = _scaler(sup, router)
    router.set_pressure(sup.endpoints(), queue_depth=10.0)
    assert a.tick(now=clock[0]) is None  # tick 1: pressured, no action
    clock[0] += 1
    assert a.tick(now=clock[0]) is None  # tick 2
    clock[0] += 1
    # one calm tick in between resets the streak entirely
    router.set_pressure(sup.endpoints(), queue_depth=2.0)  # dead band
    assert a.tick(now=clock[0]) is None
    router.set_pressure(sup.endpoints(), queue_depth=10.0)
    for _ in range(2):
        clock[0] += 1
        assert a.tick(now=clock[0]) is None
    clock[0] += 1
    assert a.tick(now=clock[0]) == "up"  # 3rd consecutive pressured tick
    assert len(sup.replicas) == 2
    assert router.added == [sup.endpoints()[-1]]


def test_autoscaler_cooldown_blocks_back_to_back_actions():
    sup, router = _StubSupervisor(1), _StubRouter()
    a, clock = _scaler(sup, router)
    router.set_pressure(sup.endpoints(), queue_depth=10.0)
    for _ in range(3):
        clock[0] += 1
        last = a.tick(now=clock[0])
    assert last == "up"
    t_up = clock[0]
    # keep the pressure on: nothing may fire inside the cooldown window
    for _ in range(20):
        clock[0] += 1
        router.set_pressure(sup.endpoints(), queue_depth=10.0)
        assert a.tick(now=clock[0]) is None
    assert len(sup.replicas) == 2
    # past cooldown the still-standing pressure streak acts again
    clock[0] = t_up + 31.0
    results = []
    for _ in range(3):
        clock[0] += 1
        router.set_pressure(sup.endpoints(), queue_depth=10.0)
        results.append(a.tick(now=clock[0]))
    assert "up" in results and len(sup.replicas) == 3


def test_autoscaler_scale_down_is_drain_then_retire_lifo():
    sup, router = _StubSupervisor(3), _StubRouter()
    a, clock = _scaler(sup, router)
    victim_addr = sup.endpoints()[-1]
    for _ in range(5):
        clock[0] += 1
        router.set_pressure(sup.endpoints(), queue_depth=0.0)
        last = a.tick(now=clock[0])
    assert last == "down"
    # newest replica drained out: unrouted FIRST, then retired
    assert router.removed == [victim_addr]
    assert sup.live_replica_ids() == [0, 1]
    assert sup.replicas[2].retired


def test_autoscaler_respects_min_and_max():
    sup, router = _StubSupervisor(1), _StubRouter()
    a, clock = _scaler(sup, router, max_replicas=1)
    for _ in range(10):  # pressured at the ceiling: hold
        clock[0] += 1
        router.set_pressure(sup.endpoints(), queue_depth=10.0)
        assert a.tick(now=clock[0]) is None
    assert len(sup.replicas) == 1
    for _ in range(10):  # idle at the floor: hold
        clock[0] += 1
        router.set_pressure(sup.endpoints(), queue_depth=0.0)
        assert a.tick(now=clock[0]) is None
    assert sup.live_replica_ids() == [0]
    with pytest.raises(ValueError):
        FleetAutoscaler(sup, router, AutoscalerConfig(min_replicas=0))
    with pytest.raises(ValueError):
        FleetAutoscaler(sup, router,
                        AutoscalerConfig(min_replicas=3, max_replicas=2))


def test_autoscaler_shed_rate_pressures_and_spike_scales_up():
    from paddlebox_tpu.serving_fleet.router import _REQUESTS

    sup, router = _StubSupervisor(1), _StubRouter()
    a, clock = _scaler(sup, router)
    router.set_pressure(sup.endpoints(), queue_depth=0.0)
    a.tick(now=clock[0])  # prime the shed-rate baseline
    for _ in range(3):
        clock[0] += 1
        _REQUESTS.inc(2, outcome="shed")  # 2 sheds/s > up_shed_rate
        last = a.tick(now=clock[0])
    assert last == "up"


def test_injected_scale_failure_leaves_fleet_unchanged():
    """Chaos at fleet.scale THROUGH the autoscaler: the action fails, the
    decision loop logs + holds (cooldown applies), membership intact."""
    sup, router = _StubSupervisor(1), _StubRouter()
    a, clock = _scaler(sup, router)
    with fault_plan({"fleet.scale": "first:1"}):
        router.set_pressure(sup.endpoints(), queue_depth=10.0)
        for _ in range(3):
            clock[0] += 1
            last = a.tick(now=clock[0])
    assert last is None  # the failed action reports no scale event
    assert len(sup.replicas) == 1
    assert router.added == []


def test_drain_fault_abandons_but_still_retires():
    sup, router = _StubSupervisor(2), _StubRouter()
    a, clock = _scaler(sup, router)
    with fault_plan({"fleet.drain": "first:1"}):
        a.drain_replica(1)
    # the drain chaos-failed, but the replica was already unrouted — the
    # retirement must proceed (abandoning can only drop already-lost work)
    assert router.removed == [f"{sup.host}:{sup.replicas[1].port}"]
    assert sup.replicas[1].retired


# --------------------------------------------------------------------------- #
# rolling restart
# --------------------------------------------------------------------------- #
def test_rolling_restart_recycles_one_at_a_time():
    sup, router = _StubSupervisor(3), _StubRouter()
    a, clock = _scaler(sup, router)
    addrs = sup.endpoints()

    orig_remove = router.remove_replica

    def remove_and_restore(addr):
        orig_remove(addr)
        # the babysitter "respawns at the same port": the stub router's
        # next view shows every addr serving again (same membership)
        router.set_pressure(addrs)

    router.remove_replica = remove_and_restore
    router.set_pressure(addrs)
    rolled = a.rolling_restart(freshness_max_age_s=60.0,
                               replica_timeout_s=1.0)
    assert rolled == [0, 1, 2]
    # each victim left the routing set exactly once, SIGTERM'd (graceful
    # stop), and re-admitted before the next was touched
    assert router.removed == addrs
    assert router.added == addrs
    assert sup.killed == [(0, signal.SIGTERM), (1, signal.SIGTERM),
                          (2, signal.SIGTERM)]
    rolls = telemetry.counter("fleet.rolls")
    assert rolls.value(outcome="ok") >= 3


def test_rolling_restart_skips_when_rest_of_fleet_is_stale():
    """Freshness gate: if taking the victim down would leave the fleet's
    min-freshness past the deadline, the roll must NOT touch it."""
    sup, router = _StubSupervisor(2), _StubRouter()
    a, clock = _scaler(sup, router)
    # every replica's model is 500s old: no remainder can hold the floor
    router.set_pressure(sup.endpoints(), age_seconds=500.0)
    rolled = a.rolling_restart(freshness_max_age_s=60.0,
                               replica_timeout_s=0.3)
    assert rolled == []
    assert sup.killed == []
    assert router.removed == []


def test_rolling_restart_skips_replica_retired_out_from_under_it():
    """A concurrent scale-down may retire a replica between the roll's
    snapshot and its turn: the roll must skip it (it is gone for good —
    the babysitter will not respawn it) and keep recycling the rest."""
    sup, router = _StubSupervisor(3), _StubRouter()
    a, clock = _scaler(sup, router)
    addrs = sup.endpoints()

    orig_remove = router.remove_replica

    def remove_and_restore(addr):
        orig_remove(addr)
        if addr == addrs[0]:
            # the race: a scale-down retires replica 1 while the roll is
            # still busy recycling replica 0
            sup.retire_replica(1)
        router.set_pressure(sup.endpoints())

    router.remove_replica = remove_and_restore
    router.set_pressure(addrs)
    rolled = a.rolling_restart(freshness_max_age_s=60.0,
                               replica_timeout_s=1.0)
    assert rolled == [0, 2]
    assert [rid for rid, _ in sup.killed] == [0, 2]
    assert addrs[1] not in router.added  # never touched, never re-admitted


def test_rolling_restart_survives_victim_retired_mid_drain():
    """Tighter race: the victim itself retires AFTER the roll unroutes it
    but before the SIGTERM.  kill_replica refuses (retired replicas are
    not chaos/restart targets); the roll counts it skipped, leaves it
    unrouted, and moves on instead of crashing."""
    sup, router = _StubSupervisor(3), _StubRouter()
    a, clock = _scaler(sup, router)
    addrs = sup.endpoints()

    orig_remove = router.remove_replica

    def remove_and_restore(addr):
        orig_remove(addr)
        if addr == addrs[0]:
            sup.retire_replica(0)  # retired right after its unroute
        router.set_pressure(sup.endpoints())

    router.remove_replica = remove_and_restore
    router.set_pressure(addrs)
    rolled = a.rolling_restart(replica_timeout_s=1.0)
    assert rolled == [1, 2]
    assert [rid for rid, _ in sup.killed] == [1, 2]
    assert addrs[0] not in router.added  # gone for good: stays unrouted


@pytest.mark.distributed
def test_drain_hang_bounded_by_watchdog_and_roll_continues():
    """Chaos: a ``hang:`` spec wedges the drain wait.  The watchdog's
    hang interrupt bounds it (no unbounded stall), the drain is
    abandoned, and the rolling restart still recycles EVERY replica —
    one wedged drain must not stop the roll."""
    sup, router = _StubSupervisor(2), _StubRouter()
    a, clock = _scaler(sup, router)
    addrs = sup.endpoints()

    orig_remove = router.remove_replica

    def remove_and_restore(addr):
        orig_remove(addr)
        router.set_pressure(addrs)

    router.remove_replica = remove_and_restore
    router.set_pressure(addrs)
    conf = LivenessConfig(
        deadline_s=0.3, heartbeat_interval_s=0.05, poll_interval_s=0.03)
    wd = Watchdog(conf, rank=0, world=1).start()
    try:
        with fault_plan({"fleet.drain": "hang:first:1"}):
            t0 = time.monotonic()
            rolled = a.rolling_restart(replica_timeout_s=1.0)
            assert time.monotonic() - t0 < 10.0  # bounded, not wedged
        assert rolled == [0, 1]
        assert sup.killed == [(0, signal.SIGTERM), (1, signal.SIGTERM)]
        from paddlebox_tpu.utils.monitor import stats

        assert stats.get("faults.hung.fleet.drain") >= 1
    finally:
        wd.close()
        faults.clear()
