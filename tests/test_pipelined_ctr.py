"""Pipeline parallelism over the REAL CTR tower (models/pipelined_ctr.py).

VERDICT r3 next #7: "one model from models/ trains pipelined to parity" —
PipelinedCtrDnn is CtrDnn's tower as GPipe stages, driven by the
unmodified Trainer with stage 0 consuming pooled sparse features.
"""

import jax
import numpy as np
import pytest
from jax.sharding import Mesh

from paddlebox_tpu.config import SparseTableConfig, TrainerConfig
from paddlebox_tpu.data.dataset import PadBoxSlotDataset
from paddlebox_tpu.data.synth import make_synth_config, write_synth_files
from paddlebox_tpu.models import CtrDnn
from paddlebox_tpu.models.pipelined_ctr import PipelinedCtrDnn, _split_stages
from paddlebox_tpu.parallel.pipeline import PIPE_AXIS

N_SLOTS, DENSE, B = 3, 2, 64
HIDDEN = (48, 32, 16)
P_STAGES = 4


def _mesh():
    return Mesh(np.array(jax.devices()[:P_STAGES]), (PIPE_AXIS,))


def _models(tconf, microbatches=8):
    plain = CtrDnn(n_sparse_slots=N_SLOTS, emb_width=tconf.row_width,
                   dense_dim=DENSE, hidden=HIDDEN)
    piped = PipelinedCtrDnn(
        _mesh(), n_sparse_slots=N_SLOTS, emb_width=tconf.row_width,
        dense_dim=DENSE, hidden=HIDDEN, microbatches=microbatches,
    )
    return plain, piped


def test_split_stages():
    assert _split_stages(4, 4) == [[0], [1], [2], [3]]
    assert _split_stages(6, 4) == [[0, 1], [2, 3], [4], [5]]
    with pytest.raises(ValueError):
        _split_stages(3, 4)


def test_forward_parity_with_ctr_dnn():
    """Same init key -> pipelined logits == plain CtrDnn logits (padding
    and the schedule are exact, not approximate)."""
    tconf = SparseTableConfig(embedding_dim=8)
    plain, piped = _models(tconf)
    key = jax.random.PRNGKey(7)
    p_plain = plain.init(key)
    p_piped = piped.init(key)

    rng = np.random.default_rng(0)
    K = B * N_SLOTS
    rows = rng.normal(size=(K, tconf.row_width)).astype(np.float32)
    segs = np.repeat(np.arange(B) * N_SLOTS, N_SLOTS) + np.tile(
        np.arange(N_SLOTS), B
    )
    dense = rng.normal(size=(B, DENSE)).astype(np.float32)

    l_plain = np.asarray(plain.apply(p_plain, rows, segs, dense, B))
    l_piped = np.asarray(piped.apply(p_piped, rows, segs, dense, B))
    np.testing.assert_allclose(l_piped, l_plain, rtol=2e-5, atol=2e-5)


def test_pack_unpack_roundtrip():
    tconf = SparseTableConfig(embedding_dim=8)
    _, piped = _models(tconf)
    layers = [
        {"w": np.full((a, b), i + 1, np.float32), "b": np.arange(b, dtype=np.float32)}
        for i, (a, b) in enumerate(zip(piped.dims[:-1], piped.dims[1:]))
    ]
    packed = {"stages": piped.pack_tower(layers)}
    back = piped.unpack_tower(packed)
    for l0, l1 in zip(layers, back):
        np.testing.assert_array_equal(l0["w"], l1["w"])
        np.testing.assert_array_equal(l0["b"], l1["b"])


def test_trains_pipelined_to_parity(tmp_path):
    """The full gate: the same dataset trains CtrDnn and PipelinedCtrDnn
    (same seeds) to matching loss/AUC through the unmodified Trainer —
    sparse pull/push, metrics, prefetch included."""
    from paddlebox_tpu.sparse.table import SparseTable
    from paddlebox_tpu.train import Trainer

    conf = make_synth_config(
        n_sparse_slots=N_SLOTS, dense_dim=DENSE, batch_size=B,
        batch_key_capacity=B * N_SLOTS * 4,
    )
    paths = write_synth_files(
        str(tmp_path), n_files=2, ins_per_file=2 * B, n_sparse_slots=N_SLOTS,
        vocab_per_slot=60, dense_dim=DENSE, seed=13,
    )
    tconf = SparseTableConfig(embedding_dim=8)

    def run(model):
        trainer = Trainer(model, tconf, TrainerConfig(auc_buckets=1 << 10),
                          seed=0)
        table = SparseTable(tconf, seed=0)
        ds = PadBoxSlotDataset(conf)
        ds.set_filelist(paths)
        ds.load_into_memory()
        m = None
        for _ in range(2):
            table.begin_pass(ds.unique_keys())
            m = trainer.train_from_dataset(
                ds, table, auc_state=trainer.last_metric_state)
            table.end_pass()
        ds.close()
        return m, table.state_dict()

    plain, piped = _models(tconf)
    m1, sd1 = run(plain)
    m2, sd2 = run(piped)
    assert m2["loss"] == pytest.approx(m1["loss"], rel=1e-4)
    assert m2["auc"] == pytest.approx(m1["auc"], abs=1e-4)
    # the sparse tables saw identical gradients through both towers
    np.testing.assert_array_equal(sd1["keys"], sd2["keys"])
    np.testing.assert_allclose(sd1["values"], sd2["values"], rtol=1e-4,
                               atol=1e-6)


def test_batch_not_divisible_rejected():
    tconf = SparseTableConfig(embedding_dim=8)
    _, piped = _models(tconf, microbatches=7)
    rows = np.zeros((B * N_SLOTS, tconf.row_width), np.float32)
    segs = np.zeros(B * N_SLOTS, np.int32)
    dense = np.zeros((B, DENSE), np.float32)
    with pytest.raises(ValueError):
        piped.apply(piped.init(jax.random.PRNGKey(0)), rows, segs, dense, B)


def test_bf16_compute_dtype_honored():
    """TrainerConfig.compute_dtype must actually flip the pipelined tower
    to bf16 (not be silently dropped with a warning), and stay close to the
    bf16 CtrDnn, which shares the cast policy."""
    import warnings

    import jax.numpy as jnp

    from paddlebox_tpu.models.layers import apply_compute_dtype_override

    tconf = SparseTableConfig(embedding_dim=8)
    plain, piped = _models(tconf)
    with warnings.catch_warnings():
        warnings.simplefilter("error")  # the no-attribute path warns
        apply_compute_dtype_override(plain, "bfloat16")
        apply_compute_dtype_override(piped, "bfloat16")
    assert piped.compute_dtype == jnp.bfloat16

    key = jax.random.PRNGKey(7)
    p_plain, p_piped = plain.init(key), piped.init(key)
    rng = np.random.default_rng(0)
    K = B * N_SLOTS
    rows = rng.normal(size=(K, tconf.row_width)).astype(np.float32)
    rows[:, :2] = np.abs(rows[:, :2])  # sane show/clk counters
    segs = np.repeat(np.arange(B) * N_SLOTS, N_SLOTS) + np.tile(
        np.arange(N_SLOTS), B
    )
    dense = rng.normal(size=(B, DENSE)).astype(np.float32)
    lp = np.asarray(plain.apply(p_plain, rows, segs, dense, B))
    lq = np.asarray(piped.apply(p_piped, rows, segs, dense, B))
    assert lq.dtype == np.float32
    np.testing.assert_allclose(lq, lp, rtol=2e-2, atol=2e-2)  # bf16 noise
