"""Fault-site drift check (tools/check_fault_sites.py): the KNOWN_SITES
catalog and the inject()/fire()/retry_call(site=) call sites must agree
in both directions — the tier-1 guard that keeps chaos plans typo-proof."""

import os
import subprocess
import sys

import pytest

TOOL = os.path.join(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
    "tools", "check_fault_sites.py",
)


def _tool():
    sys.path.insert(0, os.path.dirname(TOOL))
    try:
        import importlib

        return importlib.import_module("check_fault_sites")
    finally:
        sys.path.pop(0)


def test_tree_has_no_drift():
    mod = _tool()
    unknown, orphaned = mod.check()
    assert unknown == [] and orphaned == []
    assert mod.main([]) == 0


def test_scanner_finds_known_shapes():
    mod = _tool()
    used, prefixes, _registered = mod.scan_sources()
    # inject() literals, retry_call(site=) literals, fire() literals
    assert "sync.poll" in used
    assert "publish.donefile" in used
    assert "train.nan" in used
    # the new fleet sites are instrumented from day one
    assert "fleet.probe" in used
    assert "fleet.route" in used
    assert "fleet.restart" in used
    # fs.py's "fs." + cmd construction is a dynamic prefix, covering the
    # hadoop-command sites that never appear as full literals
    assert "fs." in prefixes


def test_known_sites_parse_matches_runtime():
    mod = _tool()
    from paddlebox_tpu.utils.faults import KNOWN_SITES

    assert mod.known_sites() == set(KNOWN_SITES)


def test_unknown_site_fixture_fails(tmp_path):
    fixture = tmp_path / "bad_site.py"
    fixture.write_text('faults.inject("nope.unknown_site")\n')
    mod = _tool()
    unknown, _ = mod.check(extra=[str(fixture)])
    assert ("nope.unknown_site", f"../{fixture.relative_to('/')}") \
        in unknown or any(s == "nope.unknown_site" for s, _ in unknown)
    assert mod.main(["--also", str(fixture)]) == 1


def test_orphaned_site_fixture_fails(tmp_path, monkeypatch):
    """A KNOWN_SITES entry nothing references must fail the check: fake
    one by parsing a doctored faults.py copy."""
    mod = _tool()
    real = mod.known_sites()
    monkeypatch.setattr(mod, "known_sites",
                        lambda: real | {"ghost.site"})
    unknown, orphaned = mod.check()
    assert unknown == []
    assert any(s == "ghost.site" for s, _ in orphaned)


@pytest.mark.parametrize("args,rc", [([], 0), (["--list"], 0)])
def test_cli_exit_codes(args, rc):
    r = subprocess.run(
        [sys.executable, TOOL] + args,
        capture_output=True, text=True, timeout=60,
    )
    assert r.returncode == rc, r.stderr
