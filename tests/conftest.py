"""Test env: force an 8-device virtual CPU mesh before any backend init.

This is the TPU analog of the reference's localhost-subprocess distributed
tests (SURVEY.md §4): multi-chip sharding is exercised on a fake CPU mesh.

Note: this image's sitecustomize registers an ``axon`` PJRT backend (the
real-TPU tunnel) in every Python process and forces
``jax_platforms="axon,cpu"`` via ``jax.config.update`` — which outranks the
``JAX_PLATFORMS`` env var.  Unit tests must never touch the tunnel (it is a
single-client resource reserved for bench.py), so the *config* is overridden
here, before any test initializes a backend.
"""

import os

flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()
os.environ["JAX_PLATFORMS"] = "cpu"
os.environ.setdefault("JAX_ENABLE_X64", "0")

# Tests that exercise bench.py stages go through its emit() path, which
# appends every row to the bench-trend history file.  Test rows must
# never pollute the checked-in BENCH_HISTORY at the repo root.
os.environ.setdefault(
    "PBOX_BENCH_HISTORY", os.path.join("/tmp", f"pbox-test-bench-{os.getpid()}.jsonl")
)

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

# On jax builds without the top-level ``jax.shard_map`` alias, importing
# ``paddlebox_tpu.parallel`` raises AttributeError — but the failed attempt
# caches the parallel leaf modules (sequence, pipeline, expert) in
# sys.modules, after which models/train/inference import fine.  The full
# suite always hit that ordering by accident (the first collected test
# module that touches parallel fails and warms sys.modules for everyone
# after it); do it explicitly so single-file runs collect the same set the
# full suite does.
try:
    import paddlebox_tpu.parallel  # noqa: F401
except AttributeError:
    pass


def pytest_configure(config):
    config.addinivalue_line("markers", "slow: long-running test")
    config.addinivalue_line(
        "markers",
        "chaos: fault-injection end-to-end test (also marked slow so "
        "tier-1 stays fast; run with -m chaos)",
    )
    config.addinivalue_line(
        "markers",
        "distributed: exercises the multi-process plane (localhost ranks "
        "via paddlebox_tpu.launch); heavy ones are also marked slow — "
        "tier-1 (-m 'not slow') still collects everything here without "
        "needing multi-process JAX",
    )
