"""Test env: force an 8-device virtual CPU mesh before jax import.

This is the TPU analog of the reference's localhost-subprocess distributed
tests (SURVEY.md §4): multi-chip sharding is exercised on a fake CPU mesh."""

import os

os.environ["JAX_PLATFORMS"] = "cpu"
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()
os.environ.setdefault("JAX_ENABLE_X64", "0")
