"""Quantized embedding artifacts (inference/quant.py + embedding_dtype
through export/predictor/publisher/syncer): per-row-scale int8/fp8
codecs, dequant-on-gather scoring quality (AUC delta vs fp32), the
quantized delta-publish round trip, and the chain-mixing guard (fp32
delta onto an int8 base is a structured refusal -> full-reload
fallback, never a corrupt merge)."""

import os

import numpy as np
import pytest

from paddlebox_tpu import telemetry
from paddlebox_tpu.config import SparseTableConfig, TrainerConfig
from paddlebox_tpu.data.dataset import PadBoxSlotDataset
from paddlebox_tpu.data.synth import make_synth_config, write_synth_files
from paddlebox_tpu.inference import Predictor, ScoringServer, export_model
from paddlebox_tpu.inference import quant
from paddlebox_tpu.inference.predictor import EmbeddingDtypeMismatch
from paddlebox_tpu.models import CtrDnn
from paddlebox_tpu.serving_sync import Publisher, Syncer
from paddlebox_tpu.sparse.table import SparseTable
from paddlebox_tpu.train.trainer import Trainer

S, DENSE, B = 3, 2, 8
KCAP = B * 8


# --------------------------------------------------------------------------- #
# codec units: determinism, zero rows, disk round trip
# --------------------------------------------------------------------------- #
@pytest.mark.parametrize("dtype", ["int8", "fp8"])
def test_quantize_rows_roundtrip_and_determinism(dtype):
    rng = np.random.default_rng(3)
    vals = rng.normal(scale=0.2, size=(50, 2 + 1 + 8)).astype(np.float32)
    vals[7] = 0.0  # an all-zero row must quantize/dequantize cleanly
    head, q, scales = quant.quantize_rows(vals, 2, dtype)
    assert head.shape == (50, 3) and q.shape == (50, 8)
    assert scales.shape == (50,)
    np.testing.assert_array_equal(head, vals[:, :3])
    # zero row: scale 1.0, zero codes, zero dequant
    assert scales[7] == 1.0 and not q[7].any()
    # row-wise deterministic: the same row quantizes to the same bytes
    # whatever export it rides in (the delta round-trip foundation)
    h2, q2, s2 = quant.quantize_rows(vals.copy(), 2, dtype)
    np.testing.assert_array_equal(np.asarray(q), np.asarray(q2))
    np.testing.assert_array_equal(scales, s2)
    # disk form round-trips bit-exactly
    restored = quant.load_q(quant.store_q(q).copy(), dtype)
    np.testing.assert_array_equal(np.asarray(restored), np.asarray(q))
    # dequant error bounded by one quantization step per element
    deq = quant.dequantize_rows(head, q, scales)
    step = scales[:, None] * (1.0 if dtype == "int8" else 32.0)
    assert np.all(np.abs(deq[:, 3:] - vals[:, 3:]) <= step + 1e-7)


def test_quantize_rows_refuses_headonly_rows():
    with pytest.raises(ValueError, match="nothing to quantize"):
        quant.quantize_rows(np.zeros((4, 3), np.float32), 2, "int8")
    with pytest.raises(ValueError, match="embedding_dtype"):
        quant.validate_dtype("int4")


# --------------------------------------------------------------------------- #
# export/predict: dequant-on-gather quality + payload bytes + reporting
# --------------------------------------------------------------------------- #
def _train_small(td, embedding_dim=16, create_threshold=0.0):
    conf = make_synth_config(
        n_sparse_slots=S, dense_dim=DENSE, batch_size=B,
        max_feasigns_per_ins=8,
    )
    files = write_synth_files(
        str(td), n_files=1, ins_per_file=128, n_sparse_slots=S,
        vocab_per_slot=60, dense_dim=DENSE, seed=11,
    )
    ds = PadBoxSlotDataset(conf, read_threads=1)
    ds.set_filelist(files)
    ds.load_into_memory()
    tconf = SparseTableConfig(embedding_dim=embedding_dim,
                              create_threshold=create_threshold)
    model = CtrDnn(S, tconf.row_width, dense_dim=DENSE, hidden=(16, 8))
    table = SparseTable(tconf, seed=0)
    trainer = Trainer(model, tconf, TrainerConfig(auc_buckets=1 << 10),
                      seed=0)
    table.begin_pass(ds.unique_keys())
    trainer.train_from_dataset(ds, table)
    table.end_pass()
    return conf, ds, model, table, trainer


def _sparse_payload_bytes(art):
    sp = os.path.join(art, "sparse")
    return sum(os.path.getsize(os.path.join(sp, f))
               for f in os.listdir(sp) if not f.startswith("keys"))


def test_quantized_auc_delta_and_bytes(tmp_path):
    """int8 AND fp8 artifacts score the synthetic CTR eval within
    0.005 AUC of the fp32 artifact, at a fraction of its payload bytes
    (the acceptance criterion's quality gate; the ~30%-of-fp32 bytes
    figure at production embedding widths is bench.py --quantized's)."""
    from bench import _rank_auc

    conf, ds, model, table, trainer = _train_small(tmp_path / "d")
    kcap = conf.batch_key_capacity or KCAP
    labels = []
    for batch in ds.batches(drop_last=False):
        labels.extend(batch.labels[: batch.n_real_ins].tolist())
    auc, payload = {}, {}
    for dt in ("fp32", "int8", "fp8"):
        art = str(tmp_path / f"art-{dt}")
        export_model(model, trainer.params, table, art, batch_size=B,
                     key_capacity=kcap, dense_dim=DENSE, embedding_dtype=dt)
        pred = Predictor.load(art)
        assert pred.embedding_dtype == dt
        scores = np.concatenate(list(pred.predict_dataset(ds)))
        auc[dt] = _rank_auc(scores, labels)
        payload[dt] = _sparse_payload_bytes(art)
        if dt != "fp32":
            assert pred._quantized and pred.artifact_bytes > 0
    ds.close()
    assert abs(auc["int8"] - auc["fp32"]) < 0.005
    assert abs(auc["fp8"] - auc["fp32"]) < 0.005
    # emb 16: head 3*4 + q 16 + scale 4 = 32 B/row vs 76 B/row fp32
    assert payload["int8"] < 0.55 * payload["fp32"]
    assert payload["fp8"] < 0.55 * payload["fp32"]


def test_quantized_respects_create_threshold(tmp_path):
    """Feature admission is fused INTO the quantized program: with an
    impossible create_threshold every score must equal the zero-embedding
    forward, exactly as the fp32 host resolve produces it."""
    conf, ds, model, table, trainer = _train_small(
        tmp_path / "d", create_threshold=1e9)
    kcap = conf.batch_key_capacity or KCAP
    outs = {}
    for dt in ("fp32", "int8"):
        art = str(tmp_path / f"art-{dt}")
        export_model(model, trainer.params, table, art, batch_size=B,
                     key_capacity=kcap, dense_dim=DENSE, embedding_dtype=dt)
        pred = Predictor.load(art)
        outs[dt] = pred.predict(next(ds.batches(drop_last=False)))
    ds.close()
    # all embeddings hidden on both paths -> identical forward
    np.testing.assert_allclose(outs["int8"], outs["fp32"], rtol=1e-6,
                               atol=1e-7)


def test_models_endpoint_reports_bytes_and_dtype(tmp_path):
    import json
    import urllib.request

    conf, ds, model, table, trainer = _train_small(tmp_path / "d")
    ds.close()
    kcap = conf.batch_key_capacity or KCAP
    art = str(tmp_path / "art")
    export_model(model, trainer.params, table, art, batch_size=B,
                 key_capacity=kcap, dense_dim=DENSE, embedding_dtype="int8",
                 feed_conf=conf)
    srv = ScoringServer()
    srv.register("q", art)
    port = srv.start(port=0)
    try:
        with urllib.request.urlopen(
                f"http://127.0.0.1:{port}/models", timeout=30) as r:
            m = json.loads(r.read())["models"]["q"]
        assert m["embedding_dtype"] == "int8"
        assert m["artifact_bytes"] > 0
        with urllib.request.urlopen(
                f"http://127.0.0.1:{port}/healthz", timeout=30) as r:
            h = json.loads(r.read())["models"]["q"]
        assert h["embedding_dtype"] == "int8"
        assert h["artifact_bytes"] == m["artifact_bytes"]
    finally:
        srv.stop()


# --------------------------------------------------------------------------- #
# delta plane: quantized round trip + chain-mixing guard
# --------------------------------------------------------------------------- #
class _Job:
    """Trainable CTR job mirroring test_serving_sync's, publishing at a
    configurable embedding dtype."""

    def __init__(self, workdir, seed=0):
        self.workdir = str(workdir)
        self.conf = make_synth_config(
            n_sparse_slots=S, dense_dim=DENSE, batch_size=B,
            max_feasigns_per_ins=8,
        )
        self.tconf = SparseTableConfig(embedding_dim=4)
        self.model = CtrDnn(S, self.tconf.row_width, dense_dim=DENSE,
                            hidden=(8,))
        self.table = SparseTable(self.tconf, seed=seed)
        self.trainer = Trainer(self.model, self.tconf,
                               TrainerConfig(auc_buckets=1 << 10), seed=seed)

    def train_pass(self, i):
        files = write_synth_files(
            os.path.join(self.workdir, f"d{i}"), n_files=1, ins_per_file=32,
            n_sparse_slots=S, vocab_per_slot=60, dense_dim=DENSE,
            seed=100 + i,
        )
        ds = PadBoxSlotDataset(self.conf, read_threads=1)
        ds.set_filelist(files)
        ds.load_into_memory()
        self.table.begin_pass(ds.unique_keys())
        self.trainer.train_from_dataset(ds, self.table)
        self.table.end_pass()
        ds.close()

    def publisher(self, root):
        return Publisher(
            root, staging_dir=os.path.join(self.workdir, "stage"))

    def publish_base(self, pub, tag, dtype):
        return pub.publish_base(
            tag, self.model, self.trainer.params, self.table,
            batch_size=B, key_capacity=KCAP, dense_dim=DENSE,
            feed_conf=self.conf, embedding_dtype=dtype,
        )

    def fresh_artifact(self, out, dtype):
        export_model(
            self.model, self.trainer.params, self.table, out,
            batch_size=B, key_capacity=KCAP, dense_dim=DENSE,
            feed_conf=self.conf, embedding_dtype=dtype,
        )
        return out


def _lines(n, seed=5, vocab=60):
    rng = np.random.default_rng(seed)
    out = []
    for _ in range(n):
        parts = ["1 0"]
        for _s in range(S):
            ks = rng.integers(0, vocab, 2)
            parts.append(f"{len(ks)} " + " ".join(map(str, ks)))
        parts.append(f"{DENSE} " + " ".join(
            f"{v:.3f}" for v in rng.random(DENSE)))
        out.append(" ".join(parts))
    return ("\n".join(out) + "\n").encode()


@pytest.mark.parametrize("dtype", ["int8", "fp8"])
def test_quantized_delta_chain_roundtrip(tmp_path, dtype):
    """Quantized base + 3 quantized deltas == a quantized fresh full
    export at the same pass: bit-equal keys, head, embedx codes, scales
    AND scores — the delta-publish path ships ~4x fewer bytes with zero
    drift (row-wise deterministic quantization, inference/quant.py)."""
    job = _Job(tmp_path)
    root = str(tmp_path / "pub")
    pub = job.publisher(root)
    job.train_pass(0)
    entry = job.publish_base(pub, "p0", dtype)
    assert entry.embedding_dtype == dtype and entry.n_bytes > 0
    for i in range(1, 4):
        job.train_pass(i)
        d = pub.publish_delta(f"p{i}", job.table, job.model,
                              job.trainer.params)
        assert d.embedding_dtype == dtype

    srv = ScoringServer()
    sync = Syncer(root, srv, "live", cache_dir=str(tmp_path / "cache"),
                  poll_interval_s=0.05)
    assert sync.poll_once() == 4
    version = sync.registry.current_version("live")
    assert version.embedding_dtype == dtype

    fresh = Predictor.load(
        job.fresh_artifact(str(tmp_path / "full"), dtype))
    live = srv._models["live"].predictor
    np.testing.assert_array_equal(live._keys, fresh._keys)
    np.testing.assert_array_equal(live._head, fresh._head)
    np.testing.assert_array_equal(np.asarray(live._q),
                                  np.asarray(fresh._q))
    np.testing.assert_array_equal(live._scales, fresh._scales)

    body = _lines(23)
    srv2 = ScoringServer()
    srv2.register("fresh", str(tmp_path / "full"))
    assert srv.score_lines(body, "live") == srv2.score_lines(body, "fresh")


def test_fp32_delta_onto_quantized_base_full_reloads(tmp_path):
    """The chain-mixing guard: an fp32 delta arriving on an int8 chain is
    a STRUCTURED refusal (EmbeddingDtypeMismatch) that triggers the
    Syncer's full-reload fallback — the live table is never corrupted by
    a dtype-mixed merge, and serving continues."""
    job = _Job(tmp_path)
    root = str(tmp_path / "pub")
    pub = job.publisher(root)
    job.train_pass(0)
    job.publish_base(pub, "p0", "int8")
    srv = ScoringServer()
    sync = Syncer(root, srv, "live", cache_dir=str(tmp_path / "cache"),
                  poll_interval_s=0.05)
    assert sync.poll_once() == 1
    body = _lines(9)
    assert srv.score_lines(body, "live")

    # unit guard first: the predictor itself refuses the mixed merge
    live = srv._models["live"].predictor
    with pytest.raises(EmbeddingDtypeMismatch):
        live.with_delta(np.array([1], np.uint64),
                        np.zeros((1, job.tconf.row_width), np.float32),
                        embedding_dtype="fp32")

    # now ship a mismatched delta for real (a misconfigured trainer
    # overriding the chain dtype) and let the fallback ladder handle it
    job.train_pass(1)
    d = pub.publish_delta("p1", job.table, job.model, job.trainer.params,
                          embedding_dtype="fp32")
    assert d.embedding_dtype == "fp32"
    fails = telemetry.counter("sync.apply_failures")
    reloads = telemetry.counter("sync.full_reload_fallback")
    f0, r0 = fails.value(kind="delta"), reloads.value()
    sync.poll_once()
    assert fails.value(kind="delta") == f0 + 1
    assert reloads.value() == r0 + 1
    # the full reload re-applied the base; the server keeps serving and
    # the live artifact is still the quantized base, not a corrupt mix
    live = srv._models["live"].predictor
    assert live.embedding_dtype == "int8" and live._quantized
    assert srv.score_lines(body, "live")


def test_resumed_publisher_keeps_chain_dtype(tmp_path):
    """A publisher restarted against an existing root publishes deltas in
    the CHAIN's dtype (read off the donefile base entry), not the flag
    default — restart must not silently flip a chain to fp32."""
    job = _Job(tmp_path)
    root = str(tmp_path / "pub")
    pub = job.publisher(root)
    job.train_pass(0)
    job.publish_base(pub, "p0", "int8")
    job.train_pass(1)
    pub2 = Publisher(root, staging_dir=os.path.join(job.workdir, "stage2"))
    d = pub2.publish_delta("p1", job.table)  # sparse-only, resumed
    assert d.embedding_dtype == "int8"
    srv = ScoringServer()
    sync = Syncer(root, srv, "live", cache_dir=str(tmp_path / "cache"),
                  poll_interval_s=0.05)
    assert sync.poll_once() == 2  # base + delta, no fallback needed
    assert srv._models["live"].predictor.embedding_dtype == "int8"


def test_legacy_quantize_flag_still_loads(tmp_path):
    """The pre-existing quantize=True format (global per-shard scale,
    dequant at load) keeps working unchanged next to the new path."""
    conf, ds, model, table, trainer = _train_small(tmp_path / "d",
                                                   embedding_dim=8)
    kcap = conf.batch_key_capacity or KCAP
    art = str(tmp_path / "legacy")
    export_model(model, trainer.params, table, art, batch_size=B,
                 key_capacity=kcap, dense_dim=DENSE, quantize=True)
    pred = Predictor.load(art)
    assert pred.embedding_dtype == "fp32"  # in-memory form IS f32
    assert not pred._quantized
    out = pred.predict(next(ds.batches(drop_last=False)))
    assert np.all(np.isfinite(out))
    ds.close()
