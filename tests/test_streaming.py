"""Streaming online learning plane (ARCHITECTURE.md "Streaming online
learning"): sources (tailing/socket, torn-tail discipline), the
mini-pass scheduler, the deadline publish policy, the watchdog guard
over a wedged feed, the mini-pass determinism pin on both trainer
paths, and the headline e2e — a label flip appended to the live stream
measurably moves the SERVED score (through a real Syncer'd
ScoringServer) within a bounded number of seconds, with
``stream.freshness_seconds`` recording the event→served latency."""

import json
import os
import threading
import time
import urllib.request

import jax
import numpy as np
import pytest

from paddlebox_tpu import telemetry
from paddlebox_tpu.config import (
    LivenessConfig,
    SparseTableConfig,
    StreamingConfig,
    TrainerConfig,
)
from paddlebox_tpu.data.dataset import PadBoxSlotDataset
from paddlebox_tpu.data.synth import make_synth_config, write_synth_files
from paddlebox_tpu.models import CtrDnn
from paddlebox_tpu.sparse.table import SparseTable
from paddlebox_tpu.streaming import (
    DeadlinePublishPolicy,
    IterableSource,
    MiniPassScheduler,
    SocketSource,
    StreamingTrainer,
    TailingFileSource,
)
from paddlebox_tpu.train.trainer import Trainer
from paddlebox_tpu.utils import faults
from paddlebox_tpu.utils.faults import fault_plan
from paddlebox_tpu.utils.monitor import stats


def _drain(source, n, timeout=5.0):
    """Collect up to n records from a source (test helper)."""
    out = []
    deadline = time.monotonic() + timeout
    while len(out) < n and time.monotonic() < deadline:
        rec = source.get(timeout=0.05)
        if rec is not None:
            out.append(rec)
    return out


# --------------------------------------------------------------------------- #
# sources
# --------------------------------------------------------------------------- #
class TestTailingSource:
    def test_follows_growth_and_new_shards(self, tmp_path):
        src = TailingFileSource(str(tmp_path), poll_interval_s=0.01).start()
        try:
            p0 = tmp_path / "part-000"
            p0.write_text("a 1\nb 2\n")
            got = _drain(src, 2)
            assert [r.line for r in got] == ["a 1", "b 2"]
            # growth of an existing file + a newly appearing shard
            with open(p0, "a") as fh:
                fh.write("c 3\n")
            (tmp_path / "part-001").write_text("d 4\ne 5\n")
            got = _drain(src, 3)
            assert sorted(r.line for r in got) == ["c 3", "d 4", "e 5"]
            assert src.watermark() is not None
        finally:
            src.close()
        assert src.drained

    def test_tmp_and_hidden_files_skipped(self, tmp_path):
        (tmp_path / "part-000.tmp").write_text("staging 1\n")
        (tmp_path / ".hidden").write_text("hidden 1\n")
        (tmp_path / "part-001").write_text("real 1\n")
        src = TailingFileSource(str(tmp_path), poll_interval_s=0.01).start()
        try:
            got = _drain(src, 1)
            assert [r.line for r in got] == ["real 1"]
            assert src.get(timeout=0.2) is None
        finally:
            src.close()

    def test_torn_tail_held_back_and_reread_whole(self, tmp_path):
        """The satellite pin: a partially written last line is NEVER
        emitted torn — it is held back and re-read whole once the writer
        finishes it — and parsing the stream quarantines nothing."""
        conf = make_synth_config(n_sparse_slots=2, dense_dim=2,
                                 batch_size=8, max_feasigns_per_ins=8)
        p = tmp_path / "part-000"
        full = "1 1 2 5 9 2 105 3 2 0.1 0.2"
        with open(p, "w") as fh:
            for _ in range(3):
                fh.write(full + "\n")
            fh.write("1 0 2 7 11 2 10")  # torn mid-append: no newline
        src = TailingFileSource(str(tmp_path), poll_interval_s=0.01).start()
        try:
            got = _drain(src, 3)
            assert len(got) == 3
            # the torn fragment is held, not emitted
            assert src.get(timeout=0.3) is None
            assert src.torn_tails_held > 0
            # writer finishes the line: it must arrive WHOLE
            with open(p, "a") as fh:
                fh.write("8 9 2 0.3 0.4\n")
            got2 = _drain(src, 1)
            assert [r.line for r in got2] == ["1 0 2 7 11 2 108 9 2 0.3 0.4"]
        finally:
            src.close()
        # the quarantine counter stays at zero: nothing ever parsed torn
        q0 = stats.get("data.quarantined_lines")
        from paddlebox_tpu.data.slot_parser import SlotParser

        block = SlotParser(conf).parse_lines(
            [r.line for r in got + got2]
        )
        assert block.n_ins == 4
        assert stats.get("data.quarantined_lines") == q0
        assert {7, 11, 108}.issubset(set(int(k) for k in block.keys))

    def test_backpressure_blocks_producer_without_loss(self, tmp_path):
        (tmp_path / "part-000").write_text(
            "".join(f"r {i}\n" for i in range(50))
        )
        src = TailingFileSource(str(tmp_path), poll_interval_s=0.01,
                                buffer_records=8).start()
        try:
            time.sleep(0.3)  # producer fills the bounded buffer and blocks
            assert src.depth() <= 8
            got = _drain(src, 50)
            assert [r.line for r in got] == [f"r {i}" for i in range(50)]
        finally:
            src.close()

    def test_stop_drains_writes_landed_after_last_poll(self, tmp_path):
        """The stop() contract: everything already written when stop()
        is called is picked up by the final drain sweep — even records
        the poll loop never saw because they landed while it slept."""
        src = TailingFileSource(str(tmp_path), poll_interval_s=30.0).start()
        try:
            time.sleep(0.2)  # first (empty) poll done; producer sleeping
            (tmp_path / "part-000").write_text("a 1\nb 2\nc 3\n")
            src.stop()
            got = _drain(src, 3)
            assert [r.line for r in got] == ["a 1", "b 2", "c 3"]
            deadline = time.monotonic() + 5.0
            while not src.drained and time.monotonic() < deadline:
                time.sleep(0.01)
            assert src.drained
        finally:
            src.close()

    def test_stop_under_backpressure_loses_nothing(self, tmp_path):
        """stop() while the producer is blocked mid-chunk on a full
        buffer: the aborted chunk's unemitted lines are re-read by the
        drain sweep — nothing skipped, nothing duplicated."""
        (tmp_path / "part-000").write_text(
            "".join(f"r {i}\n" for i in range(50))
        )
        src = TailingFileSource(str(tmp_path), poll_interval_s=0.01,
                                buffer_records=4).start()
        try:
            time.sleep(0.3)  # producer blocked mid-chunk on the buffer
            src.stop()
            got = _drain(src, 50)
            assert [r.line for r in got] == [f"r {i}" for i in range(50)]
            assert src.get(timeout=0.3) is None  # drain re-emitted nothing twice
        finally:
            src.close()


class TestSocketSource:
    def test_lines_across_sends_and_torn_final(self):
        import socket as socketlib

        src = SocketSource().start()
        try:
            c = socketlib.create_connection(("127.0.0.1", src.port))
            c.sendall(b"one 1\ntwo")
            time.sleep(0.1)
            c.sendall(b" 2\nthree 3\n")
            c.sendall(b"torn-fragment")  # no newline, then the sender dies
            c.close()
            got = _drain(src, 3)
            assert [r.line for r in got] == ["one 1", "two 2", "three 3"]
            assert src.get(timeout=0.3) is None  # fragment never emitted
        finally:
            src.close()


# --------------------------------------------------------------------------- #
# mini-pass scheduler
# --------------------------------------------------------------------------- #
def _lines(n, label=1, seed=0, n_slots=2, dense=2):
    rng = np.random.default_rng(seed)
    out = []
    for _ in range(n):
        parts = [f"1 {label}"]
        for s in range(n_slots):
            k = int(rng.integers(1, 40)) + s * 1000
            parts.append(f"2 {k} {k + 1}")
        parts.append(
            f"{dense} " + " ".join(f"{v:.3f}" for v in rng.normal(size=dense))
        )
        out.append(" ".join(parts))
    return out


class TestMiniPassScheduler:
    CONF = make_synth_config(n_sparse_slots=2, dense_dim=2, batch_size=8,
                             max_feasigns_per_ins=8)

    def test_cut_by_count_then_drain(self):
        src = IterableSource(_lines(25)).start()
        sched = MiniPassScheduler(src, self.CONF, window_records=10,
                                  window_seconds=0.0).start()
        try:
            wins = []
            while True:
                w = sched.next_window(timeout=1.0)
                if w is None and sched.done:
                    break
                if w is not None:
                    wins.append(w)
            assert [w.n_records for w in wins] == [10, 10, 5]
            assert [w.cut_reason for w in wins] == ["count", "count", "drain"]
            for w in wins:
                assert np.array_equal(w.census, np.unique(w.block.keys))
                assert w.first_event_ts <= w.last_event_ts
            assert [w.index for w in wins] == [0, 1, 2]
        finally:
            sched.close()
            src.close()

    def test_cut_by_wall_clock(self):
        src = IterableSource(_lines(3)).start()
        # huge record bound: only the age trigger (or drain) can cut
        sched = MiniPassScheduler(src, self.CONF, window_records=10_000,
                                  window_seconds=0.2).start()
        try:
            w = sched.next_window(timeout=3.0)
            assert w is not None and w.n_records == 3
            assert w.cut_reason in ("time", "drain")
        finally:
            sched.close()
            src.close()

    def test_window_dataset_batches(self):
        src = IterableSource(_lines(20)).start()
        sched = MiniPassScheduler(src, self.CONF, window_records=20).start()
        try:
            w = sched.next_window(timeout=3.0)
            ds = sched.dataset(w)
            assert ds.unique_keys() is w.census
            batches = list(ds.batches())
            assert [b.n_real_ins for b in batches] == [8, 8, 4]
        finally:
            sched.close()
            src.close()

    def test_injected_cut_fault_defers_never_drops(self):
        with fault_plan({"stream.cut": "first:1"}):
            src = IterableSource(_lines(10)).start()
            sched = MiniPassScheduler(src, self.CONF,
                                      window_records=5).start()
            try:
                wins = []
                while True:
                    w = sched.next_window(timeout=1.0)
                    if w is None and sched.done:
                        break
                    if w is not None:
                        wins.append(w)
                # the first cut was deferred: its records merged into the
                # next window — total preserved, nothing dropped
                assert sched.cut_deferrals >= 1
                assert sum(w.n_records for w in wins) == 10
            finally:
                sched.close()
                src.close()

    def test_wait_census_matches_next_window(self):
        src = IterableSource(_lines(16)).start()
        sched = MiniPassScheduler(src, self.CONF, window_records=8).start()
        try:
            census = sched.wait_census(timeout=3.0)
            w = sched.next_window(timeout=3.0)
            assert np.array_equal(census, w.census)
        finally:
            sched.close()
            src.close()


# --------------------------------------------------------------------------- #
# watchdog guard: a wedged tail source must be caught, not hung on
# --------------------------------------------------------------------------- #
def test_wedged_tail_source_caught_by_watchdog_feed_stage(tmp_path):
    """The satellite chaos pin: a hang injected at ``stream.tail`` wedges
    the feed; the runner's liveness watchdog names the ``feed`` stage in
    a structured DistributedStallError instead of stalling silently."""
    from paddlebox_tpu.parallel.watchdog import DistributedStallError

    conf = make_synth_config(n_sparse_slots=2, dense_dim=2, batch_size=8,
                             max_feasigns_per_ins=8)
    tconf = SparseTableConfig(embedding_dim=4, store_buckets=4,
                              plan_scratch_rows=32)
    model = CtrDnn(2, tconf.row_width, dense_dim=2, hidden=(4,))
    table = SparseTable(tconf, seed=0)
    trainer = Trainer(
        model, tconf,
        TrainerConfig(
            auc_buckets=1 << 10,
            liveness=LivenessConfig(
                deadline_s=1.0, heartbeat_interval_s=0.2,
                poll_interval_s=0.05,
            ),
        ),
        seed=0,
    )
    with fault_plan({"stream.tail": "hang:first:1"}):
        src = TailingFileSource(str(tmp_path), poll_interval_s=0.02).start()
        sched = MiniPassScheduler(src, conf, window_records=8).start()
        runner = StreamingTrainer(trainer, table, sched)
        t0 = time.monotonic()
        with pytest.raises(DistributedStallError) as ei:
            runner.run(max_seconds=30.0)
        assert ei.value.stage == "feed"
        assert ei.value.kind == "local"
        # caught promptly: ~deadline, nowhere near the 30s cap
        assert time.monotonic() - t0 < 15.0
    assert stats.get("faults.hung.stream.tail") >= 1


# --------------------------------------------------------------------------- #
# determinism pin: N mini-passes == one batch pass, both trainer paths
# --------------------------------------------------------------------------- #
N_SLOTS, DENSE, B = 3, 2, 16
N_INS = 384  # 3 windows of 128 = 8 batches of 16


def _det_tconf():
    return SparseTableConfig(
        embedding_dim=4, learning_rate=0.4, initial_range=0.05,
        store_buckets=16, plan_scratch_rows=64,
    )


@pytest.fixture(scope="module")
def det_records(tmp_path_factory):
    """A fixed record sequence, both as files (the batch baseline) and as
    the ordered line list (the stream replay)."""
    conf = make_synth_config(
        n_sparse_slots=N_SLOTS, dense_dim=DENSE, batch_size=B,
        max_feasigns_per_ins=16,
    )
    d = tmp_path_factory.mktemp("det")
    files = write_synth_files(
        str(d), n_files=2, ins_per_file=N_INS // 2, n_sparse_slots=N_SLOTS,
        vocab_per_slot=40, dense_dim=DENSE, seed=17,
    )
    lines = []
    for f in files:
        with open(f) as fh:
            lines += [ln for ln in fh.read().splitlines() if ln.strip()]
    assert len(lines) == N_INS
    return conf, files, lines


def _fresh_single(seed=3):
    tconf = _det_tconf()
    table = SparseTable(tconf, seed=seed)
    model = CtrDnn(N_SLOTS, tconf.row_width, dense_dim=DENSE, hidden=(16, 8))
    trainer = Trainer(
        model, tconf, TrainerConfig(dense_lr=3e-3, auc_buckets=1 << 12),
        seed=seed,
    )
    return table, trainer


def _assert_state_equal(a, b):
    assert np.array_equal(a["keys"], b["keys"])
    # values carry [show, clk, embed..., g2sum]: exact equality pins the
    # counters, the embeddings AND the optimizer state bit-for-bit
    assert np.array_equal(a["values"], b["values"])


class TestMiniPassDeterminism:
    def test_single_chip_minipasses_match_one_pass(self, det_records):
        conf, files, lines = det_records
        # batch baseline: ONE pass over the whole record set
        table, trainer = _fresh_single()
        ds = PadBoxSlotDataset(conf, read_threads=2)
        ds.set_filelist(files)
        ds.load_into_memory()
        table.begin_pass(ds.unique_keys())
        m_batch = trainer.train_from_dataset(ds, table)
        table.end_pass()
        sd_batch, delta_batch = table.state_dict(), table.pop_delta()
        ds.close()

        # streaming: the SAME records replayed through the mini-pass loop
        # (window = 8 batches, so batch boundaries are preserved)
        table2, trainer2 = _fresh_single()
        src = IterableSource(lines).start()
        sched = MiniPassScheduler(src, conf, window_records=8 * B,
                                  window_seconds=0.0).start()
        runner = StreamingTrainer(trainer2, table2, sched)
        summary = runner.run()
        assert summary["windows"] == 3
        assert summary["records"] == N_INS
        sd_stream, delta_stream = table2.state_dict(), table2.pop_delta()

        _assert_state_equal(sd_batch, sd_stream)
        _assert_state_equal(delta_batch, delta_stream)
        # the metric stream carried across windows equals the single pass
        assert summary["auc"] == m_batch["auc"]

    def test_multichip_minipasses_match_one_pass(self, det_records):
        if len(jax.devices()) < 8:
            pytest.skip("needs the conftest 8-device CPU mesh")
        from paddlebox_tpu.parallel import (
            MultiChipTrainer,
            ShardedSparseTable,
            make_mesh,
        )

        conf, files, lines = det_records

        def fresh():
            mesh = make_mesh(8)
            tconf = _det_tconf()
            table = ShardedSparseTable(tconf, mesh, seed=3)
            model = CtrDnn(N_SLOTS, tconf.row_width, dense_dim=DENSE,
                           hidden=(16, 8))
            trainer = MultiChipTrainer(
                model, tconf, mesh,
                TrainerConfig(dense_lr=3e-3, auc_buckets=1 << 12), seed=3,
            )
            return table, trainer

        table, trainer = fresh()
        ds = PadBoxSlotDataset(conf, read_threads=2)
        ds.set_filelist(files)
        ds.load_into_memory()
        table.begin_pass(ds.unique_keys())
        m_batch = trainer.train_from_dataset(ds, table)
        table.end_pass()
        sd_batch = table.state_dict()
        ds.close()

        # window = n_local * B records = exactly one device group, so the
        # group composition (which batch lands on which device) is
        # identical and cross-device update merge order is preserved
        table2, trainer2 = fresh()
        src = IterableSource(lines).start()
        sched = MiniPassScheduler(src, conf, window_records=8 * B,
                                  window_seconds=0.0).start()
        runner = StreamingTrainer(trainer2, table2, sched)
        summary = runner.run()
        assert summary["windows"] == 3
        _assert_state_equal(sd_batch, table2.state_dict())
        assert summary["auc"] == m_batch["auc"]


# --------------------------------------------------------------------------- #
# deadline publish policy
# --------------------------------------------------------------------------- #
class _StubEntry:
    def __init__(self, seq):
        self.seq = seq


class _StubPublisher:
    def __init__(self, fail=0):
        self.seqs = []
        self.fail = fail

    @property
    def next_seq(self):
        return len(self.seqs)

    def publish_delta(self, tag, table, model=None, params=None,
                      metrics=None, **kw):
        if self.fail > 0:
            self.fail -= 1
            raise RuntimeError("publish root down")
        e = _StubEntry(self.next_seq)
        self.seqs.append(tag)
        return e


class _StubWindow:
    def __init__(self, age_s):
        now = time.time()
        self.first_event_ts = now - age_s
        self.last_event_ts = now


class _StubScheduler:
    def __init__(self, window_records=100):
        self.window_records = window_records


class TestDeadlinePublishPolicy:
    def test_due_on_deadline_not_cadence(self):
        pol = DeadlinePublishPolicy(_StubPublisher(), max_staleness_s=10.0,
                                    trigger_fraction=0.5)
        assert not pol.due()  # nothing unpublished
        pol.observe_window(_StubWindow(age_s=1.0))
        assert not pol.due()  # fresh: 1s < 5s trigger
        pol2 = DeadlinePublishPolicy(_StubPublisher(), max_staleness_s=10.0,
                                     trigger_fraction=0.5)
        pol2.observe_window(_StubWindow(age_s=6.0))
        assert pol2.due()  # 6s >= 5s trigger

    def test_publish_resets_and_counts_misses(self):
        pub = _StubPublisher()
        pol = DeadlinePublishPolicy(pub, max_staleness_s=0.5)
        pol.observe_window(_StubWindow(age_s=2.0))  # already past budget
        entry = pol.maybe_publish(table=None)
        assert entry is not None and pub.seqs
        assert pol.deadline_misses == 1  # 2s > 0.5s budget at publish
        assert not pol.due()  # the unpublished-window tracker reset

    def test_failure_widens_and_retries_at_least_once(self):
        sched = _StubScheduler(window_records=100)
        pub = _StubPublisher(fail=1)
        pol = DeadlinePublishPolicy(pub, max_staleness_s=10.0,
                                    scheduler=sched, widen_factor=2.0)
        pol.observe_window(_StubWindow(age_s=20.0))
        assert pol.maybe_publish(table=None) is None  # first attempt dies
        assert pol.publish_failures == 1
        assert sched.window_records == 200  # backpressure widened
        assert pol.due()  # the window is STILL unpublished
        assert pol.maybe_publish(table=None) is not None  # retried ok
        assert pol.widenings == 1

    def test_injected_publish_deadline_fault(self):
        sched = _StubScheduler()
        pub = _StubPublisher()
        pol = DeadlinePublishPolicy(pub, max_staleness_s=10.0,
                                    scheduler=sched)
        pol.observe_window(_StubWindow(age_s=20.0))
        with fault_plan({"stream.publish_deadline": "first:1"}):
            assert pol.maybe_publish(table=None) is None
            assert pub.seqs == []  # the fault fired BEFORE the publisher
            assert pol.maybe_publish(table=None) is not None
        assert stats.get("faults.injected.stream.publish_deadline") >= 1

    def test_served_confirmation_records_freshness(self):
        pub = _StubPublisher()
        pol = DeadlinePublishPolicy(pub, max_staleness_s=1.0)
        pol.track_served()
        pol.observe_window(_StubWindow(age_s=0.2))
        pol.maybe_publish(table=None, force=True)
        assert pol.outstanding == 1
        assert pol.deadline_misses == 0  # judged at serve time now
        # serving confirms seq 0 late: freshness > budget => miss
        assert pol.confirm_served(0, now=time.time() + 2.0) == 1
        assert pol.outstanding == 0
        assert pol.deadline_misses == 1
        assert pol.last_freshness_s > 1.0


def test_streaming_config_from_flags(monkeypatch):
    monkeypatch.setenv("PBOX_STREAM_ROOT", "/tmp/sroot")
    monkeypatch.setenv("PBOX_MAX_STALENESS_S", "3.5")
    monkeypatch.setenv("PBOX_STREAM_WINDOW_RECORDS", "256")
    sc = StreamingConfig.from_flags()
    assert sc.stream_root == "/tmp/sroot"
    assert sc.max_staleness_s == 3.5
    assert sc.window_records == 256


def test_from_config_builds_and_trains(tmp_path, monkeypatch):
    """The flags→config→plane wiring: PBOX_STREAM_ROOT + friends (what
    ``launch.py --stream-root/--max-staleness-s`` export fleet-wide) are
    enough to build and run the whole plane via
    StreamingTrainer.from_config — no hand wiring."""
    stream = tmp_path / "stream"
    stream.mkdir()
    monkeypatch.setenv("PBOX_STREAM_ROOT", str(stream))
    monkeypatch.setenv("PBOX_MAX_STALENESS_S", "5.0")
    monkeypatch.setenv("PBOX_STREAM_WINDOW_RECORDS", "16")

    conf = make_synth_config(n_sparse_slots=2, dense_dim=2, batch_size=8,
                             max_feasigns_per_ins=8)
    tconf = SparseTableConfig(embedding_dim=4, store_buckets=4,
                              plan_scratch_rows=32)
    model = CtrDnn(2, tconf.row_width, dense_dim=2, hidden=(4,))
    table = SparseTable(tconf, seed=0)
    trainer = Trainer(model, tconf, TrainerConfig(auc_buckets=1 << 10),
                      seed=0)
    runner = StreamingTrainer.from_config(trainer, table, conf)
    assert runner.scheduler.window_records == 16
    (stream / "part-000").write_text("\n".join(_lines(32)) + "\n")

    def write_then_stop():
        time.sleep(0.6)
        runner.stop()

    threading.Thread(target=write_then_stop, daemon=True).start()
    summary = runner.run(max_seconds=20.0)
    assert summary["windows"] == 2
    assert summary["records"] == 32
    assert table.n_features > 0


def test_from_config_requires_a_root():
    conf = make_synth_config(n_sparse_slots=2, dense_dim=2, batch_size=8)
    with pytest.raises(ValueError, match="stream_root is empty"):
        StreamingTrainer.from_config(
            trainer=None, table=None, feed_conf=conf,
            stream_conf=StreamingConfig(stream_root=""),
        )


def test_launch_env_carries_stream_flags():
    from paddlebox_tpu.launch import rank_env

    env = rank_env(0, 1, "127.0.0.1:1234", stream_root="/data/stream",
                   max_staleness_s=2.0)
    assert env["PBOX_STREAM_ROOT"] == "/data/stream"
    assert env["PBOX_MAX_STALENESS_S"] == "2.0"


# --------------------------------------------------------------------------- #
# the headline e2e: label flip -> served score moves within seconds
# --------------------------------------------------------------------------- #
def _fresh_count(name="stream.freshness_seconds"):
    from paddlebox_tpu.telemetry.metrics import Histogram

    m = telemetry.registry.get(name)
    return m.summary()["count"] if isinstance(m, Histogram) else 0


def test_e2e_label_flip_moves_served_score(tmp_path):
    """The acceptance pin: a label flip appended to the LIVE stream moves
    the served score (through a real Publisher → donefile → Syncer →
    ScoringServer chain) within a bounded number of seconds on CPU, and
    ``stream.freshness_seconds`` records the event→served latency."""
    from paddlebox_tpu.data.feed import BatchBuilder
    from paddlebox_tpu.data.slot_parser import SlotParser
    from paddlebox_tpu.inference import ScoringServer
    from paddlebox_tpu.serving_sync import Publisher, Syncer
    from paddlebox_tpu.streaming.minipass import MiniPassWindow, WindowDataset

    S, D, Bsz = 2, 2, 16
    conf = make_synth_config(n_sparse_slots=S, dense_dim=D, batch_size=Bsz,
                             max_feasigns_per_ins=8)
    tconf = SparseTableConfig(embedding_dim=4, learning_rate=0.3,
                              store_buckets=8, plan_scratch_rows=64)
    model = CtrDnn(S, tconf.row_width, dense_dim=D, hidden=(8,))
    table = SparseTable(tconf, seed=0)
    trainer = Trainer(model, tconf, TrainerConfig(auc_buckets=1 << 12),
                      seed=0)
    rng = np.random.default_rng(0)

    from paddlebox_tpu.data.synth import stream_line

    def line(label):
        # every record carries the hot pair (5, 1005) + one noise key each
        return stream_line(rng, label, n_sparse_slots=S, dense_dim=D,
                           hot_keys=(5, 1005))

    # warm pass anchors the delta chain (and pays jit/export off-clock)
    warm = [line(1) for _ in range(4 * Bsz)]
    block = SlotParser(conf).parse_lines(warm)
    w0 = MiniPassWindow(0, block, np.unique(block.keys), len(warm),
                        time.time(), time.time(), "warm", time.time())
    table.begin_pass(w0.census)
    trainer.train_from_dataset(WindowDataset(w0, BatchBuilder(conf)), table)
    table.end_pass()

    root = str(tmp_path / "publish")
    stream = str(tmp_path / "stream")
    os.makedirs(stream)
    pub = Publisher(root, staging_dir=str(tmp_path / "staging"))
    pub.publish_base("base", model, trainer.params, table,
                     batch_size=Bsz,
                     key_capacity=Bsz * conf.max_feasigns_per_ins,
                     dense_dim=D, feed_conf=conf)

    server = ScoringServer()
    syncer = Syncer(root, server, "live", cache_dir=str(tmp_path / "cache"),
                    poll_interval_s=0.05)
    syncer.poll_once()
    syncer.start()
    port = server.start(port=0)
    probe = b"1 0 2 5 30 2 1005 1030 2 0.0 0.0\n"

    def score():
        req = urllib.request.Request(
            f"http://127.0.0.1:{port}/score/live", data=probe, method="POST")
        with urllib.request.urlopen(req, timeout=30) as r:
            return json.loads(r.read())["scores"][0]

    source = TailingFileSource(stream, poll_interval_s=0.02)
    sched = MiniPassScheduler(source, conf, window_records=2 * Bsz,
                              window_seconds=0.2)
    policy = DeadlinePublishPolicy(pub, max_staleness_s=1.0,
                                   scheduler=sched)
    runner = StreamingTrainer(
        trainer, table, sched, policy=policy, model=model,
        served_seq_fn=lambda: (server.model_version("live") or {}).get("seq"),
    )
    source.start()
    sched.start()
    fresh0 = _fresh_count()
    run_err = []

    def run():
        try:
            runner.run()
        except BaseException as e:  # surfaced after the join
            run_err.append(e)

    t = threading.Thread(target=run, daemon=True)
    t.start()
    feed = open(os.path.join(stream, "part-000"), "w", buffering=1)
    try:
        # phase 1: label-1 traffic until the served score has clearly
        # learned it (publish + sync happen continuously underneath)
        high = None
        deadline = time.monotonic() + 60.0
        while time.monotonic() < deadline:
            for _ in range(2 * Bsz):
                feed.write(line(1))
            time.sleep(0.4)
            s = score()
            if s > 0.55:
                high = s
                break
        assert high is not None, "served score never learned label=1"

        # phase 2: THE FLIP — the same hot keys now stream label=0
        flip_ts = time.monotonic()
        moved = None
        deadline = time.monotonic() + 60.0
        while time.monotonic() < deadline:
            for _ in range(2 * Bsz):
                feed.write(line(0))
            time.sleep(0.4)
            s = score()
            if s < high - 0.2:
                moved = time.monotonic() - flip_ts
                break
        assert moved is not None, "served score never moved after the flip"
        # bounded freshness: the flip reached the SERVED model in seconds
        assert moved < 45.0
    finally:
        feed.close()
        runner.stop()
        t.join(timeout=60.0)
        syncer.stop()
        server.stop()
    assert not run_err, f"streaming loop died: {run_err!r}"
    summary = runner.summary()
    assert summary["publishes"] >= 2
    # the syncer's public confirmation surface tracked the chain
    assert syncer.applied_seq >= 1
    # the event->served freshness histogram recorded the loop
    assert _fresh_count() > fresh0
    assert policy.last_freshness_s is not None
