"""Parity of the vectorized host hot paths vs straightforward loop oracles
(VERDICT r2 weak #9: _hash_ins_ids / _shuffle_slots / build_rank_offset are
per-record Python loops that die at pass scale; the reference keeps this
layer in C++ for the same reason, SURVEY.md §2.4)."""

import numpy as np

from paddlebox_tpu.config import DataFeedConfig
from paddlebox_tpu.data.feed import build_rank_offset
from paddlebox_tpu.data.record import RecordBlock
from paddlebox_tpu.data.shuffle import _FNV_OFFSET, _FNV_PRIME, _hash_ins_ids


def _fnv_oracle(s: str) -> int:
    h = int(_FNV_OFFSET)
    for b in s.encode():
        h = ((h ^ b) * int(_FNV_PRIME)) & 0xFFFFFFFFFFFFFFFF
    return h


def test_hash_ins_ids_matches_fnv_oracle():
    ids = ["", "a", "ins-000123", "αβγ", "x" * 100, "ins-000123"]
    got = _hash_ins_ids(ids)
    want = np.asarray([_fnv_oracle(s) for s in ids], dtype=np.uint64)
    np.testing.assert_array_equal(got, want)


def test_hash_native_and_numpy_agree():
    """Routing must not depend on whether the native lib built."""
    from paddlebox_tpu import _native
    from paddlebox_tpu.data import shuffle as sh

    ids = [f"ins-{i:08d}" for i in range(500)] + ["", "漢字", "a b c"]
    native = _native.hash_ids_native(ids)
    if native is None:
        import pytest

        pytest.skip("native lib unavailable")
    orig = _native.hash_ids_native
    try:
        _native.hash_ids_native = lambda _ids: None  # force numpy path
        pure = sh._hash_ins_ids(ids)
    finally:
        _native.hash_ids_native = orig
    np.testing.assert_array_equal(native, pure)


def _random_block(rng, n_ins, s, with_logkey=True):
    lens = rng.integers(0, 5, size=(n_ins, s))
    offsets = np.zeros(n_ins * s + 1, dtype=np.int64)
    np.cumsum(lens.reshape(-1), out=offsets[1:])
    keys = rng.integers(1, 1 << 40, size=int(offsets[-1])).astype(np.uint64)
    return RecordBlock(
        n_ins=n_ins,
        n_sparse_slots=s,
        keys=keys,
        key_offsets=offsets,
        dense=rng.normal(size=(n_ins, 2)).astype(np.float32),
        labels=rng.integers(0, 2, size=n_ins).astype(np.float32),
        ranks=rng.integers(0, 5, size=n_ins).astype(np.int32)
        if with_logkey else None,
        cmatches=rng.choice(
            np.array([222, 223, 111], dtype=np.int32), size=n_ins
        ) if with_logkey else None,
    )


def _shuffle_slots_oracle(block, slot_idxs, rng):
    """The pre-vectorization per-instance loop, kept as the oracle."""
    s = block.n_sparse_slots
    lens = np.diff(block.key_offsets).reshape(block.n_ins, s).copy()
    new_vals = {}
    for si in slot_idxs:
        perm = rng.permutation(block.n_ins)
        rows = np.arange(block.n_ins) * s + si
        starts = block.key_offsets[rows][perm]
        plens = lens[:, si][perm]
        new_vals[si] = (starts, plens)
        lens[:, si] = plens
    new_offsets = np.zeros(block.n_ins * s + 1, dtype=np.int64)
    np.cumsum(lens.reshape(-1), out=new_offsets[1:])
    keys = np.empty(int(new_offsets[-1]), dtype=np.uint64)
    for i in range(block.n_ins):
        for si in range(s):
            r = i * s + si
            lo, hi = new_offsets[r], new_offsets[r + 1]
            if si in new_vals:
                st, pl = new_vals[si]
                keys[lo:hi] = block.keys[st[i] : st[i] + pl[i]]
            else:
                olo = block.key_offsets[r]
                keys[lo:hi] = block.keys[olo : olo + (hi - lo)]
    return keys, new_offsets


def test_shuffle_slots_matches_loop_oracle():
    from paddlebox_tpu.data.dataset import _shuffle_slots

    rng = np.random.default_rng(0)
    block = _random_block(rng, 200, 4)
    got = _shuffle_slots(block, [1, 3], np.random.default_rng(42))
    want_keys, want_offs = _shuffle_slots_oracle(
        block, [1, 3], np.random.default_rng(42)
    )
    np.testing.assert_array_equal(got.key_offsets, want_offs)
    np.testing.assert_array_equal(got.keys, want_keys)


def _rank_offset_oracle(block, ids, pv_bounds, batch_size, max_rank,
                        cmatch_filter=None):
    """The pre-vectorization per-PV loop, kept as the oracle."""
    cols = 2 * max_rank + 1
    mat = np.full((batch_size, cols), -1, dtype=np.int32)
    if block.ranks is None:
        return mat
    ranks = block.ranks[ids]
    cmatches = (
        block.cmatches[ids] if block.cmatches is not None
        else np.zeros_like(ranks)
    )
    ok = (ranks > 0) & (ranks <= max_rank)
    if cmatch_filter is not None:
        ok &= np.isin(cmatches, np.asarray(list(cmatch_filter)))
    eff = np.where(ok, ranks, -1)
    for p in range(pv_bounds.shape[0] - 1):
        lo, hi = int(pv_bounds[p]), int(pv_bounds[p + 1])
        members = np.arange(lo, hi)
        mat[members, 0] = eff[lo:hi]
        ranked = members[eff[lo:hi] > 0]
        for j in members:
            if eff[j] <= 0:
                continue
            for k in ranked:
                m = eff[k] - 1
                mat[j, 2 * m + 1] = eff[k]
                mat[j, 2 * m + 2] = k
    return mat


def test_build_rank_offset_matches_loop_oracle():
    rng = np.random.default_rng(1)
    n = 64
    block = _random_block(rng, n, 2)
    ids = rng.permutation(n)
    # random PV partition of the 64 ids
    cuts = np.sort(rng.choice(np.arange(1, n), size=12, replace=False))
    pv_bounds = np.concatenate([[0], cuts, [n]]).astype(np.int64)
    for filt in (None, (222, 223)):
        got = build_rank_offset(block, ids, pv_bounds, 80, 3, filt)
        want = _rank_offset_oracle(block, ids, pv_bounds, 80, 3, filt)
        np.testing.assert_array_equal(got, want)


def test_build_rank_offset_no_ranked():
    rng = np.random.default_rng(2)
    block = _random_block(rng, 8, 2)
    block = RecordBlock(
        **{**block.__dict__, "ranks": np.zeros(8, dtype=np.int32)}
    )
    ids = np.arange(8)
    pv_bounds = np.asarray([0, 4, 8], dtype=np.int64)
    got = build_rank_offset(block, ids, pv_bounds, 8, 3)
    assert (got[:, 1:] == -1).all()


def test_vectorized_paths_scale(capsys):
    """Micro-bench at meaningful scale — results land in BASELINE.md.
    Fails only on gross (>60s) regression; prints throughput."""
    import time

    n = 200_000
    ids = [f"ins-{i:012d}" for i in range(n)]
    t0 = time.perf_counter()
    _hash_ins_ids(ids)
    t_hash = time.perf_counter() - t0

    from paddlebox_tpu.data.dataset import _shuffle_slots

    rng = np.random.default_rng(3)
    block = _random_block(rng, n, 4)
    t0 = time.perf_counter()
    _shuffle_slots(block, [0, 2], rng)
    t_shuf = time.perf_counter() - t0

    ids_arr = np.arange(n)
    pv_bounds = np.arange(0, n + 1, 4, dtype=np.int64)  # 4-ad PVs
    t0 = time.perf_counter()
    build_rank_offset(block, ids_arr, pv_bounds, n, 3, (222, 223))
    t_rank = time.perf_counter() - t0
    print(
        f"\n[host-bench n={n}] hash {n/t_hash:,.0f}/s  "
        f"slots_shuffle {n/t_shuf:,.0f} ins/s  rank_offset {n/t_rank:,.0f} ins/s"
    )
    assert t_hash < 60 and t_shuf < 60 and t_rank < 60
