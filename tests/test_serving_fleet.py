"""Serving-fleet resilience (serving_fleet/ + inference/admission.py):
admission control with load shedding, the router's replica state machine
(healthy -> degraded -> ejected, half-open recovery) with per-request
failover, supervisor crash restarts with backoff, and the SIGKILL chaos
e2e — a killed replica must never be client-visible."""

import http.client
import json
import os
import signal
import sys
import threading
import time

import pytest

from paddlebox_tpu import telemetry
from paddlebox_tpu.config import DataFeedConfig, SlotConfig
from paddlebox_tpu.inference.admission import AdmissionGate, ShedRequest
from paddlebox_tpu.inference.server import ScoringServer
from paddlebox_tpu.serving_fleet import (
    DEGRADED,
    EJECTED,
    HEALTHY,
    FleetRouter,
    ReplicaSupervisor,
)
from paddlebox_tpu.utils.faults import fault_plan
from paddlebox_tpu.utils.retry import RetryPolicy

CHILD = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                     "_replica_child.py")
BODY = b"line one\nline two\n"  # 2 "instances" for the stub scorer


def _wait_until(cond, timeout_s=15.0, interval_s=0.02):
    deadline = time.monotonic() + timeout_s
    while time.monotonic() < deadline:
        if cond():
            return True
        time.sleep(interval_s)
    return cond()


class _StubPredictor:
    meta = {"n_tasks": 1, "row_width": 4}
    bucket_shapes = [(8, 64)]
    n_features = 1


def _stub_server(service_ms=1.0, max_queue=64, max_concurrency=1,
                 deadline_ms=None, tag=0.5, max_batch=1):
    """A REAL ScoringServer (HTTP stack, admission gate, drain, degraded
    flags) whose scoring is a stub: `tag` per line after `service_ms` of
    simulated device time under the real scoring lock.  max_batch
    defaults to 1 (not the production flag default): the shed/deadline
    pins below were calibrated for the one-at-a-time admission math —
    micro-batched admission has its own coverage in
    tests/test_microbatch.py."""
    conf = DataFeedConfig(
        slots=(SlotConfig("click", type="float", is_dense=True),
               SlotConfig("s0")),
        batch_size=8,
    )
    srv = ScoringServer(max_queue=max_queue,
                        max_concurrency=max_concurrency,
                        request_deadline_ms=deadline_ms,
                        max_batch=max_batch)
    srv.register_predictor("stub", _StubPredictor(), conf)

    def score_lines(text, name=None):
        lines = [ln for ln in text.decode().splitlines() if ln.strip()]
        with srv._lock:
            if service_ms:
                time.sleep(service_ms / 1e3)
        return [float(tag)] * len(lines)

    srv.score_lines = score_lines
    return srv


def _post(port, body=BODY, path="/score", headers=None, timeout=30):
    conn = http.client.HTTPConnection("127.0.0.1", port, timeout=timeout)
    try:
        conn.request("POST", path, body=body, headers=headers or {})
        r = conn.getresponse()
        data = r.read()
        return r.status, (json.loads(data) if data else {}), dict(
            (k.lower(), v) for k, v in r.getheaders())
    finally:
        conn.close()


def _get(port, path):
    conn = http.client.HTTPConnection("127.0.0.1", port, timeout=30)
    try:
        conn.request("GET", path)
        r = conn.getresponse()
        return r.status, json.loads(r.read())
    finally:
        conn.close()


# --------------------------------------------------------------------------- #
# admission gate: bounded FIFO + deadline-aware shedding
# --------------------------------------------------------------------------- #
def test_gate_bounds_queue_and_stays_fifo():
    gate = AdmissionGate(max_concurrency=1, max_queue=2,
                         initial_service_s=0.01)
    gate.admit()  # occupy the only slot
    order = []

    def waiter(i):
        gate.admit()
        order.append(i)
        time.sleep(0.01)
        gate.release(0.01)

    t1 = threading.Thread(target=waiter, args=(1,))
    t1.start()
    assert _wait_until(lambda: gate.queue_depth() == 1)
    t2 = threading.Thread(target=waiter, args=(2,))
    t2.start()
    assert _wait_until(lambda: gate.queue_depth() == 2)
    # queue full: arrival #3 sheds immediately with a wait estimate
    with pytest.raises(ShedRequest) as ei:
        gate.admit()
    assert ei.value.reason == "queue_full"
    assert ei.value.retry_after_s > 0
    assert int(ei.value.retry_after_header) >= 1
    gate.release(0.01)  # free the held slot -> t1 then t2, FIFO
    t1.join(timeout=5)
    t2.join(timeout=5)
    assert order == [1, 2]
    assert gate.queue_depth() == 0 and gate.active() == 0


def test_gate_deadline_sheds_upfront_and_while_queued():
    # estimated wait (1 active x 50ms EWMA) already exceeds a 10ms
    # deadline: shed before queuing at all
    gate = AdmissionGate(max_concurrency=1, max_queue=8,
                         initial_service_s=0.05)
    gate.admit()
    with pytest.raises(ShedRequest) as ei:
        gate.admit(deadline_s=0.01)
    assert ei.value.reason == "deadline"
    # a cheap-looking estimate admits to the queue, but the deadline
    # expiring IN the queue sheds too (never waits past the deadline)
    gate2 = AdmissionGate(max_concurrency=1, max_queue=8,
                          initial_service_s=0.0001)
    gate2.admit()  # never released
    t0 = time.monotonic()
    with pytest.raises(ShedRequest) as ei:
        gate2.admit(deadline_s=0.05)
    assert ei.value.reason == "deadline"
    assert 0.03 < time.monotonic() - t0 < 2.0
    assert gate2.queue_depth() == 0  # the shed left no ghost ticket


def test_gate_release_updates_service_estimate():
    gate = AdmissionGate(initial_service_s=0.05, ewma_alpha=0.5)
    gate.admit()
    gate.release(0.15)
    assert abs(gate.service_estimate_s() - 0.10) < 1e-9


def test_gate_cleans_ticket_on_foreign_exception():
    """Regression: a NON-shed exception escaping cv.wait (e.g. a
    KeyboardInterrupt delivered to a worker thread) must still remove the
    waiter's ticket — a dead ticket reaching the head would starve every
    later request into permanent 429s."""
    gate = AdmissionGate(max_concurrency=1, max_queue=4,
                         initial_service_s=0.0001)
    gate.admit()  # hold the only slot so the next admit queues
    orig_wait = gate._cv.wait

    def interrupted_wait(timeout=None):
        gate._cv.wait = orig_wait  # only the first wait blows up
        raise KeyboardInterrupt

    gate._cv.wait = interrupted_wait
    with pytest.raises(KeyboardInterrupt):
        gate.admit()
    assert gate.queue_depth() == 0  # no ghost ticket left behind
    gate.release(0.001)
    gate.admit(deadline_s=1.0)  # a live waiter still admits
    gate.release(0.001)


# --------------------------------------------------------------------------- #
# HTTP overload: 2x capacity -> 429s rise, admitted p99 stays bounded
# --------------------------------------------------------------------------- #
def test_http_shed_under_overload():
    """The acceptance pin: a server at ~25 rps capacity (40ms service,
    1 in flight) hammered by 12 closed-loop clients (far above 2x) must
    shed with 429 + Retry-After — never 5xx, never queue collapse — and
    the p99 of ADMITTED requests stays bounded by the queue cap, not by
    the offered load."""
    srv = _stub_server(service_ms=40, max_queue=3)
    shed_counter = telemetry.counter("serve.shed_total")
    shed_base = shed_counter.value(reason="queue_full")
    port = srv.start(port=0)
    statuses, ok_lat = [], []
    lock = threading.Lock()

    def client():
        for _ in range(6):
            t0 = time.perf_counter()
            st, out, hdrs = _post(port)
            dt = (time.perf_counter() - t0) * 1e3
            with lock:
                statuses.append(st)
                if st == 200:
                    ok_lat.append(dt)
                elif st == 429:
                    # every shed carries the retry hint
                    assert int(hdrs["retry-after"]) >= 1
                    assert out["retry_after_s"] >= 0

    threads = [threading.Thread(target=client) for _ in range(12)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=60)
    srv.stop()
    assert set(statuses) <= {200, 429}  # shed loudly, never 5xx
    n_shed = statuses.count(429)
    assert n_shed > 0 and statuses.count(200) > 0
    assert shed_counter.value(reason="queue_full") >= shed_base + n_shed
    ok_lat.sort()
    # worst admitted wait = (1 active + 3 queued) x 40ms service; with
    # unbounded queuing the tail would be ~72 x 40ms ≈ 2.9s.  1s leaves
    # CI slack while still separating the two regimes decisively.
    assert ok_lat[-1] < 1000.0, f"admitted tail unbounded: {ok_lat[-3:]}"
    assert srv.gate.queue_depth() == 0  # no ghost tickets after the storm


def test_http_deadline_header_sheds():
    srv = _stub_server(service_ms=100, max_queue=8)
    port = srv.start(port=0)
    try:
        blocker = threading.Thread(target=lambda: _post(port))
        blocker.start()
        time.sleep(0.02)  # the blocker holds the only scoring slot
        st, out, hdrs = _post(
            port, headers={"X-Request-Deadline-Ms": "1"})
        assert st == 429 and "deadline" in out["error"]
        assert "retry-after" in hdrs
        blocker.join(timeout=10)
        # without the header the same request queues and serves
        st, out, _ = _post(port)
        assert st == 200 and len(out["scores"]) == 2
    finally:
        srv.stop()


# --------------------------------------------------------------------------- #
# router: state machine, failover, degraded deprioritization
# --------------------------------------------------------------------------- #
def test_router_failover_eject_and_half_open_recovery():
    srv_a = _stub_server(tag=1.0)
    srv_b = _stub_server(tag=2.0)
    pa, pb = srv_a.start(port=0), srv_b.start(port=0)
    router = FleetRouter([f"127.0.0.1:{pa}", f"127.0.0.1:{pb}"],
                         probe_interval_s=60, eject_after=2,
                         recover_after=2)
    try:
        router.probe_once()
        assert [r.state for r in router.replicas] == [HEALTHY, HEALTHY]
        st, data, _ = router.route_request("POST", "/score", BODY, {})
        assert st == 200

        # replica A dies hard: every request must still answer 200 via
        # failover onto B — the client never sees the death
        srv_a.stop()
        for _ in range(6):
            st, data, _ = router.route_request("POST", "/score", BODY, {})
            assert st == 200
            assert json.loads(data)["scores"] == [2.0, 2.0]
        # probes converge the membership view: A ejected
        router.probe_once()
        router.probe_once()
        ra = router.replicas[0]
        assert ra.state == EJECTED

        # half-open recovery: A comes back on the SAME port; one clean
        # probe is not enough (recover_after=2), two readmit it
        srv_a2 = _stub_server(tag=1.0)
        srv_a2.start(port=pa)
        try:
            router.probe_once()
            assert ra.state == EJECTED
            router.probe_once()
            assert ra.state == HEALTHY
            scores = set()
            for _ in range(8):
                st, data, _ = router.route_request(
                    "POST", "/score", BODY, {})
                assert st == 200
                scores.add(json.loads(data)["scores"][0])
            assert scores == {1.0, 2.0}  # round-robin spreads again
        finally:
            srv_a2.stop()
    finally:
        router.stop()
        srv_b.stop()


def test_router_degraded_deprioritized_but_kept():
    srv_a = _stub_server(tag=1.0)
    srv_b = _stub_server(tag=2.0)
    pa, pb = srv_a.start(port=0), srv_b.start(port=0)
    router = FleetRouter([f"127.0.0.1:{pa}", f"127.0.0.1:{pb}"],
                         probe_interval_s=60, eject_after=2)
    try:
        srv_b.set_degraded("sync:live", "3 entries behind")
        router.probe_once()
        assert router.replicas[0].state == HEALTHY
        assert router.replicas[1].state == DEGRADED
        view = router.fleet_view()
        assert view["n_serving"] == 2  # degraded still counts as serving
        assert view["replicas"][1]["degraded_reasons"] == {
            "sync:live": "3 entries behind"}
        # all traffic prefers the healthy replica
        for _ in range(5):
            st, data, _ = router.route_request("POST", "/score", BODY, {})
            assert json.loads(data)["scores"][0] == 1.0
        # healthy replica dies: the degraded one takes over — degrade,
        # don't fail
        srv_a.stop()
        for _ in range(4):
            st, data, _ = router.route_request("POST", "/score", BODY, {})
            assert st == 200
            assert json.loads(data)["scores"][0] == 2.0
        # and recovery of the flag restores HEALTHY on the next probe
        srv_b.clear_degraded("sync:live")
        router.probe_once()  # also ejects A (2nd failure from routing)
        assert router.replicas[1].state == HEALTHY
    finally:
        router.stop()
        srv_b.stop()


def test_router_probe_fault_site_ejects_and_recovers():
    """Chaos at the registered fleet.probe site: injected probe failures
    run the replica through eject + half-open recovery with no real
    network fault at all."""
    srv = _stub_server()
    p = srv.start(port=0)
    router = FleetRouter([f"127.0.0.1:{p}"], probe_interval_s=60,
                         eject_after=2, recover_after=1)
    try:
        router.probe_once()
        assert router.replicas[0].state == HEALTHY
        with fault_plan({"fleet.probe": "first:2"}):
            router.probe_once()
            router.probe_once()
            assert router.replicas[0].state == EJECTED
            router.probe_once()  # 3rd hit passes: half-open success
        assert router.replicas[0].state == HEALTHY
    finally:
        router.stop()
        srv.stop()


def test_router_http_front_door_and_fleet_view():
    srv_a = _stub_server(tag=1.0)
    srv_b = _stub_server(tag=2.0)
    pa, pb = srv_a.start(port=0), srv_b.start(port=0)
    router = FleetRouter([f"127.0.0.1:{pa}", f"127.0.0.1:{pb}"],
                         probe_interval_s=0.1)
    try:
        port = router.start(port=0)
        st, out, _ = _post(port)
        assert st == 200 and len(out["scores"]) == 2
        st, health = _get(port, "/healthz")
        assert st == 200 and health["ok"]
        assert health["n_serving"] == 2
        st, view = _get(port, "/fleet")
        assert {r["state"] for r in view["replicas"]} == {HEALTHY}
        assert all("stub" in r["models"] for r in view["replicas"])
        conn = http.client.HTTPConnection("127.0.0.1", port, timeout=30)
        conn.request("GET", "/metrics")
        r = conn.getresponse()
        text = r.read().decode()
        conn.close()
        assert r.status == 200 and "fleet_requests_total" in text
        # unroutable paths answer 404/400 at the router, not a replica
        st, out, _ = _post(port, path="/nope")
        assert st == 404
    finally:
        router.stop()
        srv_a.stop()
        srv_b.stop()


def test_router_deadline_aware_retry_math():
    """Deadline-aware failover: the forwarded X-Request-Deadline-Ms
    carries only the REMAINING client budget (a replica's admission gate
    must shed against what is actually left, not the original number),
    and once the budget is spent mid-failover the router stops retrying
    and answers 504 instead of burning more replicas."""
    seen = {}
    srv = _stub_server(tag=1.0)
    orig = srv.score_lines

    def recording_score(text, name=None):
        return orig(text, name)

    srv.score_lines = recording_score
    p = srv.start(port=0)
    router = FleetRouter([f"127.0.0.1:{p}"], probe_interval_s=60)
    try:
        router.probe_once()
        # capture what the replica actually receives: forward through a
        # recording proxy of router._forward
        orig_fwd = router._forward

        def capture_forward(r, method, path, body, headers):
            seen["deadline"] = headers.get("X-Request-Deadline-Ms")
            return orig_fwd(r, method, path, body, headers)

        router._forward = capture_forward
        st, data, _ = router.route_request(
            "POST", "/score", BODY, {"X-Request-Deadline-Ms": "30000"})
        assert st == 200
        fwd = float(seen["deadline"])
        # remaining budget, not the original: strictly less, same order
        assert 0 < fwd <= 30000
        router._forward = orig_fwd

        # an already-spent budget never reaches a replica: 504, zero
        # attempts (scoring is idempotent but not free)
        calls = {"n": 0}

        def counting_forward(r, method, path, body, headers):
            calls["n"] += 1
            return orig_fwd(r, method, path, body, headers)

        router._forward = counting_forward
        st, data, _ = router.route_request(
            "POST", "/score", BODY, {"X-Request-Deadline-Ms": "0.000001"})
        assert st == 504 and b"deadline" in data
        assert calls["n"] == 0
    finally:
        router.stop()
        srv.stop()


def test_router_caps_body_at_front_door():
    """Regression: the router buffers the full body for failover retries,
    so max_body_bytes must be enforced at the front door itself — an
    oversized payload answers 413 before any bytes are read or
    forwarded."""
    srv = _stub_server()
    p = srv.start(port=0)
    router = FleetRouter([f"127.0.0.1:{p}"], probe_interval_s=0.1,
                         max_body_bytes=64)
    oversized = telemetry.counter("fleet.oversized_body")
    base = oversized.value()
    try:
        port = router.start(port=0)
        st, out, _ = _post(port, body=b"x" * 65)
        assert st == 413 and "max_body_bytes" in out["error"]
        assert oversized.value() == base + 1
        st, out, _ = _post(port)  # within the cap: routed normally
        assert st == 200 and len(out["scores"]) == 2
    finally:
        router.stop()
        srv.stop()


def test_replica_argv_never_reenters_fleet_mode(monkeypatch):
    """Regression: replica children inherit the parent environment, so
    with fleet mode enabled via PBOX_SERVE_REPLICAS the child command
    line must pin --replicas 0 — otherwise every replica would re-enter
    fleet mode and recursively spawn its own supervisor + router."""
    from paddlebox_tpu import serve

    monkeypatch.setenv("PBOX_SERVE_REPLICAS", "3")
    ap = serve._build_parser()
    args = ap.parse_args(["--artifact", "m=/tmp/art"])
    assert args.replicas == 3  # the parent IS in fleet mode via env
    child = serve._replica_argv(args, replica_id=0, port=18080)
    # strip "python -m paddlebox_tpu.serve"; reparse under the same env
    child_args = ap.parse_args(child[3:])
    assert child_args.replicas == 0


def test_router_zero_failures_while_replica_dies_midstream():
    """Tier-1 kill test (in-process replicas; the subprocess SIGKILL
    variant is the chaos-marked e2e below): one of three replicas goes
    down mid-hammer and EVERY client response is still 200."""
    servers = [_stub_server(service_ms=2, tag=float(i + 1))
               for i in range(3)]
    ports = [s.start(port=0) for s in servers]
    router = FleetRouter([f"127.0.0.1:{p}" for p in ports],
                         probe_interval_s=0.05, eject_after=2)
    port = router.start(port=0)
    bad, seen_tags = [], set()
    stop = threading.Event()

    def hammer():
        while not stop.is_set():
            try:
                st, out, _ = _post(port)
                if st != 200:
                    bad.append(st)
                else:
                    seen_tags.add(out["scores"][0])
            except Exception as e:
                bad.append(repr(e))

    threads = [threading.Thread(target=hammer) for _ in range(4)]
    try:
        for t in threads:
            t.start()
        time.sleep(0.4)
        servers[1].stop()  # hard down, mid-stream
        time.sleep(0.8)
    finally:
        stop.set()
        for t in threads:
            t.join(timeout=30)
        router.stop()
        for i, s in enumerate(servers):
            if i != 1:
                s.stop()
    assert not bad, f"client-visible failures: {bad[:5]}"
    assert seen_tags >= {1.0, 3.0}  # the survivors carried the load
    assert _wait_until(
        lambda: router.replicas[1].state == EJECTED, timeout_s=1) \
        or router.replicas[1].consecutive_failures > 0


# --------------------------------------------------------------------------- #
# supervisor: crash restarts with backoff (cheap no-jax children)
# --------------------------------------------------------------------------- #
_SLEEPER = [sys.executable, "-c", "import time; time.sleep(300)"]


def _fast_policy():
    return RetryPolicy(max_attempts=1_000_000, base_delay_s=0.05,
                       max_delay_s=0.2)


def test_supervisor_restarts_sigkilled_replica():
    sup = ReplicaSupervisor(
        2, lambda rid, port: _SLEEPER, poll_interval_s=0.05,
        restart_policy=_fast_policy(), stable_after_s=0.5,
    )
    sup.start()
    try:
        assert all(r.alive() for r in sup.replicas)
        assert len(set(sup.endpoints())) == 2
        pid0 = sup.replicas[0].pid
        sup.kill_replica(0, signal.SIGKILL)
        assert _wait_until(
            lambda: sup.restart_count() >= 1 and sup.replicas[0].alive())
        assert sup.replicas[0].pid != pid0
        assert sup.replicas[1].restarts == 0  # only the dead one respawns
    finally:
        sup.stop()
    assert not any(r.alive() for r in sup.replicas)


def test_supervisor_backoff_deepens_on_crash_loop():
    """A replica that dies instantly must be respawned with a GROWING
    delay (crash_streak drives RetryPolicy.delay), not hot-looped."""
    crashy = [sys.executable, "-c", "raise SystemExit(1)"]
    sup = ReplicaSupervisor(
        1, lambda rid, port: crashy, poll_interval_s=0.02,
        restart_policy=RetryPolicy(max_attempts=1_000_000,
                                   base_delay_s=0.05, max_delay_s=10.0),
        stable_after_s=60.0,
    )
    sup.start()
    try:
        assert _wait_until(lambda: sup.replicas[0].crash_streak >= 3,
                           timeout_s=20)
        r = sup.replicas[0]
        # streak 3 => pending delay ~= 0.05 * 2**2 = 0.2s (jittered): the
        # scheduled respawn sits measurably in the future
        assert r.crash_streak >= 3
        assert sup.restart_count() >= 1
    finally:
        sup.stop()


def test_supervisor_reuses_log_handle_across_respawns(tmp_path):
    """Regression: a crash-looping replica must not open (and leak) a new
    log FD per respawn — one persistent append handle per replica,
    closed once at stop()."""
    crashy = [sys.executable, "-c", "print('boom'); raise SystemExit(1)"]
    sup = ReplicaSupervisor(
        1, lambda rid, port: crashy, poll_interval_s=0.02,
        restart_policy=RetryPolicy(max_attempts=1_000_000,
                                   base_delay_s=0.01, max_delay_s=0.05),
        stable_after_s=60.0, log_dir=str(tmp_path),
    )
    sup.start()
    try:
        assert _wait_until(lambda: sup.restart_count() >= 3, timeout_s=20)
        assert len(sup._logs) == 1  # one handle, however many respawns
    finally:
        sup.stop()
    assert not sup._logs


def test_supervisor_restart_fault_injected_then_recovers():
    """Chaos at the fleet.restart site: the respawn attempt itself fails
    once (counted), backs off deeper, and the NEXT attempt brings the
    replica back."""
    sup = ReplicaSupervisor(
        1, lambda rid, port: _SLEEPER, poll_interval_s=0.05,
        restart_policy=_fast_policy(), stable_after_s=0.5,
    )
    failures = telemetry.counter("fleet.restart_failures")
    base = failures.value()
    sup.start()
    try:
        with fault_plan({"fleet.restart": "first:1"}):
            sup.kill_replica(0, signal.SIGKILL)
            assert _wait_until(lambda: failures.value() >= base + 1)
            assert _wait_until(
                lambda: sup.restart_count() >= 1
                and sup.replicas[0].alive())
    finally:
        sup.stop()


# --------------------------------------------------------------------------- #
# the chaos e2e: real processes, real SIGKILL, zero client failures
# --------------------------------------------------------------------------- #
@pytest.mark.chaos
@pytest.mark.slow
def test_fleet_sigkill_chaos_zero_client_failures(tmp_path):
    """SIGKILL one of three replica PROCESSES under a concurrent request
    hammer: zero non-2xx client responses, the supervisor restart is
    observed, and the fleet freshness view converges back to 3 serving
    replicas."""
    def argv_for(rid, port):
        return [sys.executable, CHILD, "--port", str(port),
                "--service-ms", "2"]

    sup = ReplicaSupervisor(
        3, argv_for, log_dir=str(tmp_path / "logs"),
        poll_interval_s=0.1, restart_policy=_fast_policy(),
        stable_after_s=1.0,
    )
    sup.start()
    router = FleetRouter(sup.endpoints(), probe_interval_s=0.2,
                         eject_after=2, recover_after=2)
    bad, pids_seen = [], set()
    stop = threading.Event()
    try:
        # children pay a fresh interpreter + package import each
        assert _wait_until(
            lambda: (router.probe_once() or True)
            and all(r.state == HEALTHY for r in router.replicas),
            timeout_s=180, interval_s=0.5,
        ), f"fleet never healthy: {[r.last_error for r in router.replicas]}"
        port = router.start(port=0)

        def hammer():
            while not stop.is_set():
                try:
                    st, out, _ = _post(port)
                    if st != 200:
                        bad.append(st)
                    else:
                        pids_seen.add(int(out["scores"][0]))
                except Exception as e:
                    bad.append(repr(e))

        threads = [threading.Thread(target=hammer) for _ in range(6)]
        for t in threads:
            t.start()
        time.sleep(1.0)
        victim_pid = sup.kill_replica(0, signal.SIGKILL)
        time.sleep(2.5)
        stop.set()
        for t in threads:
            t.join(timeout=60)

        assert not bad, f"client-visible failures: {bad[:5]}"
        assert victim_pid in pids_seen  # the victim served before dying
        assert len(pids_seen) >= 3  # every replica took traffic
        # the supervisor restarts the victim and the fleet view
        # converges back to all-serving (the respawn pays a fresh
        # package import)
        assert _wait_until(lambda: sup.restart_count() >= 1, timeout_s=30)
        assert _wait_until(
            lambda: (router.probe_once() or True)
            and router.fleet_view()["n_serving"] == 3,
            timeout_s=180, interval_s=0.5,
        ), f"fleet never reconverged: {router.fleet_view()}"
        new_pid = sup.replicas[0].pid
        assert new_pid is not None and new_pid != victim_pid
    finally:
        stop.set()
        router.stop()
        sup.stop()
