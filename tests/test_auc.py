"""Streaming AUC vs sklearn oracle (VERDICT item 6).

Reference: BasicAucCalculator (fleet/box_wrapper.h:61-138, bucket kernels
box_wrapper.cu:1035-1060, final reduction box_wrapper.cc:321-400).
"""

import jax.numpy as jnp
import numpy as np

from paddlebox_tpu.metrics import (
    compute_metrics,
    init_auc_state,
    merge_auc_states,
    update_auc_state,
)

try:
    from sklearn.metrics import roc_auc_score

    HAVE_SKLEARN = True
except ImportError:  # fall back to a direct pairwise oracle
    HAVE_SKLEARN = False


def _oracle_auc(preds, labels):
    if HAVE_SKLEARN:
        return roc_auc_score(labels, preds)
    pos = preds[labels == 1][:, None]
    neg = preds[labels == 0][None, :]
    return float(((pos > neg).mean() + 0.5 * (pos == neg).mean()))


def test_auc_matches_oracle_exactly_on_bucket_centers():
    nb = 1 << 16
    rng = np.random.default_rng(0)
    n = 4000
    # quantize predictions to bucket centers so bucketing is exact
    preds = (rng.integers(0, nb, size=n) + 0.5) / nb
    labels = (rng.random(n) < preds).astype(np.float64)  # correlated
    state = init_auc_state(nb)
    # feed in chunks with masks, like training batches
    for lo in range(0, n, 512):
        chunk = slice(lo, lo + 512)
        p, l = preds[chunk], labels[chunk]
        pad = 512 - p.shape[0]
        mask = np.concatenate([np.ones_like(p), np.zeros(pad)])
        p = np.concatenate([p, np.full(pad, 0.99)])  # padding must not count
        l = np.concatenate([l, np.ones(pad)])
        state = update_auc_state(
            state, jnp.asarray(p), jnp.asarray(l), jnp.asarray(mask)
        )
    m = compute_metrics(state)
    assert abs(m["auc"] - _oracle_auc(preds, labels)) < 1e-6
    np.testing.assert_allclose(m["mae"], np.abs(preds - labels).mean(), rtol=1e-5)
    np.testing.assert_allclose(
        m["rmse"], np.sqrt(((preds - labels) ** 2).mean()), rtol=1e-5
    )
    np.testing.assert_allclose(m["actual_ctr"], labels.mean(), rtol=1e-5)
    np.testing.assert_allclose(m["predicted_ctr"], preds.mean(), rtol=1e-5)
    assert m["count"] == n


def test_auc_merge_states_equals_single_stream():
    nb = 1 << 12
    rng = np.random.default_rng(1)
    n = 1024
    preds = (rng.integers(0, nb, size=n) + 0.5) / nb
    labels = (rng.random(n) < 0.3).astype(np.float64)
    ones = jnp.ones(n // 2)
    s1 = update_auc_state(
        init_auc_state(nb), jnp.asarray(preds[: n // 2]),
        jnp.asarray(labels[: n // 2]), ones,
    )
    s2 = update_auc_state(
        init_auc_state(nb), jnp.asarray(preds[n // 2 :]),
        jnp.asarray(labels[n // 2 :]), ones,
    )
    merged = compute_metrics(merge_auc_states(s1, s2))
    full = compute_metrics(
        update_auc_state(
            init_auc_state(nb), jnp.asarray(preds), jnp.asarray(labels), jnp.ones(n)
        )
    )
    assert abs(merged["auc"] - full["auc"]) < 1e-12
    assert merged["count"] == full["count"]


def test_degenerate_single_class_auc():
    state = update_auc_state(
        init_auc_state(64), jnp.asarray([0.2, 0.7]), jnp.asarray([1.0, 1.0]),
        jnp.ones(2),
    )
    assert compute_metrics(state)["auc"] == 0.5  # no negatives -> undefined -> 0.5
