"""Streaming AUC vs sklearn oracle (VERDICT item 6).

Reference: BasicAucCalculator (fleet/box_wrapper.h:61-138, bucket kernels
box_wrapper.cu:1035-1060, final reduction box_wrapper.cc:321-400).
"""

import jax.numpy as jnp
import numpy as np

from paddlebox_tpu.metrics import (
    compute_metrics,
    init_auc_state,
    merge_auc_states,
    update_auc_state,
)

try:
    from sklearn.metrics import roc_auc_score

    HAVE_SKLEARN = True
except ImportError:  # fall back to a direct pairwise oracle
    HAVE_SKLEARN = False


def _oracle_auc(preds, labels):
    if HAVE_SKLEARN:
        return roc_auc_score(labels, preds)
    pos = preds[labels == 1][:, None]
    neg = preds[labels == 0][None, :]
    return float(((pos > neg).mean() + 0.5 * (pos == neg).mean()))


def test_auc_matches_oracle_exactly_on_bucket_centers():
    nb = 1 << 16
    rng = np.random.default_rng(0)
    n = 4000
    # quantize predictions to bucket centers so bucketing is exact
    preds = (rng.integers(0, nb, size=n) + 0.5) / nb
    labels = (rng.random(n) < preds).astype(np.float64)  # correlated
    state = init_auc_state(nb)
    # feed in chunks with masks, like training batches
    for lo in range(0, n, 512):
        chunk = slice(lo, lo + 512)
        p, l = preds[chunk], labels[chunk]
        pad = 512 - p.shape[0]
        mask = np.concatenate([np.ones_like(p), np.zeros(pad)])
        p = np.concatenate([p, np.full(pad, 0.99)])  # padding must not count
        l = np.concatenate([l, np.ones(pad)])
        state = update_auc_state(
            state, jnp.asarray(p), jnp.asarray(l), jnp.asarray(mask)
        )
    m = compute_metrics(state)
    assert abs(m["auc"] - _oracle_auc(preds, labels)) < 1e-6
    np.testing.assert_allclose(m["mae"], np.abs(preds - labels).mean(), rtol=1e-5)
    np.testing.assert_allclose(
        m["rmse"], np.sqrt(((preds - labels) ** 2).mean()), rtol=1e-5
    )
    np.testing.assert_allclose(m["actual_ctr"], labels.mean(), rtol=1e-5)
    np.testing.assert_allclose(m["predicted_ctr"], preds.mean(), rtol=1e-5)
    assert m["count"] == n


def test_auc_merge_states_equals_single_stream():
    nb = 1 << 12
    rng = np.random.default_rng(1)
    n = 1024
    preds = (rng.integers(0, nb, size=n) + 0.5) / nb
    labels = (rng.random(n) < 0.3).astype(np.float64)
    ones = jnp.ones(n // 2)
    s1 = update_auc_state(
        init_auc_state(nb), jnp.asarray(preds[: n // 2]),
        jnp.asarray(labels[: n // 2]), ones,
    )
    s2 = update_auc_state(
        init_auc_state(nb), jnp.asarray(preds[n // 2 :]),
        jnp.asarray(labels[n // 2 :]), ones,
    )
    merged = compute_metrics(merge_auc_states(s1, s2))
    full = compute_metrics(
        update_auc_state(
            init_auc_state(nb), jnp.asarray(preds), jnp.asarray(labels), jnp.ones(n)
        )
    )
    assert abs(merged["auc"] - full["auc"]) < 1e-12
    assert merged["count"] == full["count"]


def test_degenerate_single_class_auc():
    state = update_auc_state(
        init_auc_state(64), jnp.asarray([0.2, 0.7]), jnp.asarray([1.0, 1.0]),
        jnp.ones(2),
    )
    assert compute_metrics(state)["auc"] == 0.5  # no negatives -> undefined -> 0.5


def test_exact_accumulation_past_2pow24():
    """f32 saturates at 2^24 (x + 1.0 == x); uint32 buckets and Kahan moment
    sums must keep counting exactly (VERDICT r2 weak #10; reference uses
    double tables, box_wrapper.h:61)."""
    import jax
    from paddlebox_tpu.metrics.auc import kahan_value

    state = init_auc_state(64)
    big = np.uint32(1 << 24)
    # pre-seed the accumulators as if 2^24 positives already landed in one
    # bucket (walking there one batch at a time would take minutes)
    state = state._replace(
        pos=state.pos.at[32].set(big),
        count=jnp.asarray(big),
        label_sum=jnp.asarray(big),
        abserr=jnp.asarray([float(1 << 24), 0.0], dtype=jnp.float32),
    )

    # 1000 more single-positive updates, jit-rolled like the train step
    def body(_, s):
        return update_auc_state(
            s, jnp.asarray([32.5 / 64]), jnp.asarray([1.0]), jnp.ones(1)
        )

    state = jax.jit(
        lambda s: jax.lax.fori_loop(0, 1000, body, s)
    )(state)
    assert int(state.pos[32]) == (1 << 24) + 1000  # f32 would stay at 2^24
    assert int(state.count) == (1 << 24) + 1000
    assert int(state.label_sum) == (1 << 24) + 1000
    # Kahan: adding 1000 * |pred-label| ≈ 0.492 increments to a 2^24-sized
    # sum; a plain f32 sum would absorb every one of them (0.492 < ulp=2.0)
    got = kahan_value(state.abserr) - float(1 << 24)
    want = 1000 * (1.0 - 32.5 / 64)
    assert abs(got - want) < 0.05 * want
