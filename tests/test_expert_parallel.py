"""Expert parallelism: the sharded expert mix must equal the serial MMoE
expert computation, forward and backward."""

import functools

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from paddlebox_tpu.parallel.expert import (
    EXPERT_AXIS,
    expert_parallel_forward,
    serial_expert_forward,
)
from paddlebox_tpu.utils.jax_compat import shard_map

P_DEV, E, B, D_IN, D_HID = 4, 8, 16, 10, 12


def _mesh():
    return Mesh(np.array(jax.devices()[:P_DEV]), (EXPERT_AXIS,))


def _inputs(seed=0):
    rng = np.random.default_rng(seed)
    w = rng.normal(size=(E, D_IN, D_HID)).astype(np.float32) * 0.3
    b = rng.normal(size=(E, D_HID)).astype(np.float32) * 0.1
    x = rng.normal(size=(B, D_IN)).astype(np.float32)
    logits = rng.normal(size=(B, E)).astype(np.float32)
    gates = np.asarray(jax.nn.softmax(jnp.asarray(logits), axis=1))
    return w, b, x, gates


def _sharded_fn(mesh):
    return jax.jit(
        shard_map(
            expert_parallel_forward,
            mesh=mesh,
            in_specs=(P(EXPERT_AXIS), P(EXPERT_AXIS), P(),
                      P(None, EXPERT_AXIS)),
            out_specs=P(),
        )
    )


def test_forward_matches_serial():
    mesh = _mesh()
    w, b, x, gates = _inputs()
    want = np.asarray(serial_expert_forward(*map(jnp.asarray, (w, b, x, gates))))
    got = np.asarray(_sharded_fn(mesh)(w, b, x, gates))
    np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-6)


def test_gradients_match_serial():
    mesh = _mesh()
    w, b, x, gates = _inputs(1)
    tgt = np.random.default_rng(7).normal(size=(B, D_HID)).astype(np.float32)

    def loss_serial(w_, b_):
        return jnp.mean((serial_expert_forward(w_, b_, x, gates) - tgt) ** 2)

    want = jax.grad(loss_serial, argnums=(0, 1))(
        jnp.asarray(w), jnp.asarray(b)
    )

    def loss_sharded(w_, b_):
        body = shard_map(
            expert_parallel_forward,
            mesh=mesh,
            in_specs=(P(EXPERT_AXIS), P(EXPERT_AXIS), P(),
                      P(None, EXPERT_AXIS)),
            out_specs=P(),
        )
        return jnp.mean((body(w_, b_, x, gates) - tgt) ** 2)

    got = jax.jit(jax.grad(loss_sharded, argnums=(0, 1)))(w, b)
    for g, wref in zip(got, want):
        np.testing.assert_allclose(
            np.asarray(g), np.asarray(wref), rtol=1e-4, atol=1e-7
        )
