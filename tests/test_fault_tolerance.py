"""Integration tests for the fault-tolerance layer: bad-input quarantine,
checkpoint integrity + fallback resume, donefile-last publish discipline
under injected failures, and the trainer's NaN policies."""

import json
import os

import numpy as np
import pytest

from paddlebox_tpu.checkpoint import (
    CheckpointCorrupt,
    CheckpointManager,
    verify_checkpoint_dir,
)
from paddlebox_tpu.config import SparseTableConfig, TrainerConfig
from paddlebox_tpu.data.dataset import PadBoxSlotDataset
from paddlebox_tpu.data.synth import make_synth_config, write_synth_files
from paddlebox_tpu.models import CtrDnn
from paddlebox_tpu.sparse.table import SparseTable
from paddlebox_tpu.train import (
    AutoCheckpointer,
    PassRolledBack,
    Trainer,
)
from paddlebox_tpu.utils import faults
from paddlebox_tpu.utils.faults import fault_plan
from paddlebox_tpu.utils.fs import FsError, publish_checkpoint
from paddlebox_tpu.utils.monitor import stats

S, DENSE, B = 3, 2, 16


@pytest.fixture(autouse=True)
def _fast_and_clean(monkeypatch):
    """Fast retries, no leftover plans/stats between tests."""
    monkeypatch.setenv("PBOX_RETRY_BASE_DELAY_S", "0.001")
    monkeypatch.setenv("PBOX_RETRY_MAX_DELAY_S", "0.002")
    stats.reset()
    faults.clear()
    yield
    faults.clear()


def _world(tmp_path, seed=0, n_files=2, trainer_conf=None, sub="data"):
    conf = make_synth_config(
        n_sparse_slots=S, dense_dim=DENSE, batch_size=B,
        max_feasigns_per_ins=8,
    )
    files = write_synth_files(
        str(tmp_path / sub), n_files=n_files, ins_per_file=64,
        n_sparse_slots=S, vocab_per_slot=60, dense_dim=DENSE, seed=9,
    )
    ds = PadBoxSlotDataset(conf, read_threads=1)
    ds.set_filelist(files)
    ds.load_into_memory()
    tconf = SparseTableConfig(embedding_dim=4)
    model = CtrDnn(S, tconf.row_width, dense_dim=DENSE, hidden=(16, 8))
    table = SparseTable(tconf, seed=seed)
    trainer = Trainer(
        model, tconf,
        trainer_conf or TrainerConfig(auc_buckets=1 << 10),
        seed=seed,
    )
    return ds, table, trainer


def _run_pass(ds, table, trainer):
    table.begin_pass(ds.unique_keys())
    m = trainer.train_from_dataset(ds, table)
    table.end_pass()
    return m


# --------------------------------------------------------------------------- #
# bad-input quarantine
# --------------------------------------------------------------------------- #
class TestQuarantine:
    def _conf_files(self, tmp_path, policy, frac=0.5, n_bad=2):
        conf = make_synth_config(
            n_sparse_slots=S, dense_dim=DENSE, batch_size=B,
            malformed_policy=policy, quarantine_abort_frac=frac,
        )
        files = write_synth_files(
            str(tmp_path / "q"), n_files=2, ins_per_file=32,
            n_sparse_slots=S, dense_dim=DENSE, seed=4,
        )
        # corruption appended at the END of the last file: quarantining it
        # restores the clean instance stream byte-for-byte
        with open(files[-1], "a") as fh:
            for i in range(n_bad):
                fh.write("garbage line %d\n" % i if i % 2 else "1\n")
        return conf, files

    def test_skip_policy_restores_clean_stream(self, tmp_path):
        conf, files = self._conf_files(tmp_path, "skip")
        ds = PadBoxSlotDataset(conf, read_threads=1)
        ds.set_filelist(files)
        ds.load_into_memory()
        assert ds.get_memory_data_size() == 64  # the 2 bad lines are gone
        assert ds.parser.quarantined_lines == 2
        assert ds.parser.quarantined_files == 1
        snap = stats.snapshot()
        assert snap["data.quarantined_lines"] == 2
        assert snap["data.quarantined_files"] == 1
        # block content identical to a clean parse
        clean_conf = make_synth_config(
            n_sparse_slots=S, dense_dim=DENSE, batch_size=B,
        )
        clean = PadBoxSlotDataset(clean_conf, read_threads=1)
        clean_files = write_synth_files(
            str(tmp_path / "qc"), n_files=2, ins_per_file=32,
            n_sparse_slots=S, dense_dim=DENSE, seed=4,
        )
        clean.set_filelist(clean_files)
        clean.load_into_memory()
        np.testing.assert_array_equal(ds._block.keys, clean._block.keys)
        np.testing.assert_array_equal(ds._block.labels, clean._block.labels)
        ds.close()
        clean.close()

    def test_raise_policy_aborts(self, tmp_path):
        conf, files = self._conf_files(tmp_path, "raise")
        ds = PadBoxSlotDataset(conf, read_threads=1)
        ds.set_filelist(files)
        with pytest.raises(ValueError, match="malformed"):
            ds.load_into_memory()
        ds.close()

    def test_abort_threshold(self, tmp_path):
        # 8 bad lines over 64 good = 11% > 10% threshold -> the load fails
        conf, files = self._conf_files(tmp_path, "skip", frac=0.10, n_bad=8)
        ds = PadBoxSlotDataset(conf, read_threads=1)
        ds.set_filelist(files)
        with pytest.raises(RuntimeError, match="quarantined"):
            ds.load_into_memory()
        assert stats.get("data.quarantine_aborts") == 1
        ds.close()

    def test_mid_line_corruption_rolls_back_partial_appends(self, tmp_path):
        """A line that fails mid-instance (after appending some keys) must
        not leak its partial keys into the block."""
        conf, files = self._conf_files(tmp_path, "skip", n_bad=0)
        # valid label + first slot, then garbage where slot1's count should be
        with open(files[0], "a") as fh:
            fh.write("1 1 2 5 7 nope\n")
        ds = PadBoxSlotDataset(conf, read_threads=1)
        ds.set_filelist(files)
        ds.load_into_memory()
        assert ds.get_memory_data_size() == 64
        assert ds.parser.quarantined_lines == 1
        # offsets stay consistent: total keys == last offset
        assert ds._block.keys.shape[0] == ds._block.key_offsets[-1]
        ds.close()


# --------------------------------------------------------------------------- #
# data-read retry
# --------------------------------------------------------------------------- #
def test_transient_read_failure_is_retried(tmp_path):
    conf = make_synth_config(n_sparse_slots=S, dense_dim=DENSE, batch_size=B)
    files = write_synth_files(
        str(tmp_path / "d"), n_files=2, ins_per_file=32,
        n_sparse_slots=S, dense_dim=DENSE,
    )
    ds = PadBoxSlotDataset(conf, read_threads=1)
    ds.set_filelist(files)
    with fault_plan({"data.read": "first:1"}):
        ds.load_into_memory()  # first read fails, retry succeeds
    assert ds.get_memory_data_size() == 64
    assert stats.get("faults.injected.data.read") == 1
    assert stats.get("retry.data.read.retries") >= 1
    ds.close()


def test_parse_errors_never_retry(tmp_path):
    conf = make_synth_config(n_sparse_slots=S, dense_dim=DENSE, batch_size=B)
    bad = tmp_path / "bad.txt"
    bad.write_text("definitely not slot format\n")
    ds = PadBoxSlotDataset(conf, read_threads=1)
    ds.set_filelist([str(bad)])
    with pytest.raises(ValueError):
        ds.load_into_memory()
    assert stats.get("retry.data.read.retries") == 0
    ds.close()


# --------------------------------------------------------------------------- #
# checkpoint integrity
# --------------------------------------------------------------------------- #
def _saved_manager(tmp_path, n_passes=1):
    ds, table, trainer = _world(tmp_path)
    mgr = CheckpointManager(str(tmp_path / "ckpt"))
    for p in range(n_passes):
        _run_pass(ds, table, trainer)
        save = mgr.save_base if p == 0 else mgr.save_delta
        save(f"t{p}", table, *trainer.dense_state())
    ds.close()
    return mgr, table, trainer


class TestCheckpointIntegrity:
    def test_manifest_written_and_verifies(self, tmp_path):
        mgr, _, _ = _saved_manager(tmp_path)
        d = mgr.list_checkpoints()[0].dirname
        manifest = json.load(open(os.path.join(d, "manifest.json")))
        assert set(manifest["files"]) >= {"sparse.npz", "meta.json"}
        verify_checkpoint_dir(d)  # no raise

    def test_truncated_file_detected(self, tmp_path):
        mgr, _, _ = _saved_manager(tmp_path)
        d = mgr.list_checkpoints()[0].dirname
        path = os.path.join(d, "sparse.npz")
        data = open(path, "rb").read()
        open(path, "wb").write(data[: len(data) // 2])
        with pytest.raises(CheckpointCorrupt, match="size"):
            verify_checkpoint_dir(d)

    def test_bitflip_detected(self, tmp_path):
        mgr, _, _ = _saved_manager(tmp_path)
        d = mgr.list_checkpoints()[0].dirname
        path = os.path.join(d, "dense.npz")
        data = bytearray(open(path, "rb").read())
        data[len(data) // 2] ^= 0xFF
        open(path, "wb").write(bytes(data))
        with pytest.raises(CheckpointCorrupt, match="sha256"):
            verify_checkpoint_dir(d)

    def test_load_refuses_corrupt_chain(self, tmp_path):
        mgr, table, trainer = _saved_manager(tmp_path)
        d = mgr.list_checkpoints()[0].dirname
        os.remove(os.path.join(d, "sparse.npz"))
        t2 = SparseTable(SparseTableConfig(embedding_dim=4), seed=0)
        with pytest.raises(CheckpointCorrupt):
            mgr.load(t2)

    def test_find_valid_tag_walks_back(self, tmp_path):
        mgr, _, _ = _saved_manager(tmp_path, n_passes=3)
        assert mgr.find_valid_tag() == "t2"
        d2 = [c for c in mgr.list_checkpoints() if c.tag == "t2"][0].dirname
        path = os.path.join(d2, "sparse.npz")
        data = open(path, "rb").read()
        open(path, "wb").write(data[: len(data) // 2])
        assert mgr.find_valid_tag() == "t1"
        # corrupting the base kills every chain
        d0 = [c for c in mgr.list_checkpoints() if c.tag == "t0"][0].dirname
        os.remove(os.path.join(d0, "sparse.npz"))
        assert mgr.find_valid_tag() is None


# --------------------------------------------------------------------------- #
# publish: donefile-last discipline under injected failures (satellite)
# --------------------------------------------------------------------------- #
class TestPublish:
    def test_failed_upload_never_exposes_donefile(self, tmp_path, monkeypatch):
        monkeypatch.setenv("PBOX_RETRY_MAX_ATTEMPTS", "2")
        mgr, _, _ = _saved_manager(tmp_path)
        remote = str(tmp_path / "pub")
        with fault_plan({"publish.upload": "first:10"}):
            # retries exhaust and the last failure (the injected one)
            # propagates
            with pytest.raises((FsError, faults.FaultInjected)):
                publish_checkpoint(mgr, "t0", remote)
        # the remote donefile must not exist: consumers see NO tag rather
        # than a tag whose data may be partial
        assert not os.path.exists(os.path.join(remote, "donefile.txt"))

    def test_transient_failure_retries_to_completion(self, tmp_path):
        mgr, _, _ = _saved_manager(tmp_path)
        remote = str(tmp_path / "pub2")
        with fault_plan(
            {"publish.upload": "first:1", "publish.donefile": "first:1"}
        ):
            publish_checkpoint(mgr, "t0", remote)
        assert os.path.exists(os.path.join(remote, "donefile.txt"))
        lines = open(os.path.join(remote, "donefile.txt")).read()
        assert '"tag": "t0"' in lines
        # the published copy verifies against its manifest
        verify_checkpoint_dir(os.path.join(remote, "base-t0"))
        assert stats.get("faults.injected.publish.upload") == 1
        assert stats.get("retry.publish.upload.retries") >= 1

    def test_corrupt_remote_copy_fails_before_donefile(
        self, tmp_path, monkeypatch
    ):
        """Post-upload verification: if the remote bytes are wrong, publish
        fails BEFORE the donefile lands."""
        from paddlebox_tpu.utils.fs import LocalFS

        monkeypatch.setenv("PBOX_RETRY_MAX_ATTEMPTS", "1")
        mgr, _, _ = _saved_manager(tmp_path)
        remote = str(tmp_path / "pub3")

        class CorruptingFS(LocalFS):
            def upload(self, local, dest):
                super().upload(local, dest)
                if os.path.isdir(dest):  # truncate one uploaded file
                    p = os.path.join(dest, "sparse.npz")
                    data = open(p, "rb").read()
                    open(p, "wb").write(data[:10])

        with pytest.raises(CheckpointCorrupt):
            publish_checkpoint(mgr, "t0", remote, fs=CorruptingFS())
        assert not os.path.exists(os.path.join(remote, "donefile.txt"))


# --------------------------------------------------------------------------- #
# corrupt-checkpoint fallback resume (satellite)
# --------------------------------------------------------------------------- #
def test_resume_falls_back_to_previous_valid_tag(tmp_path):
    """Truncate the newest checkpoint; resume must recover from the
    previous tag and the replay must reproduce the uninterrupted run."""
    N = 4
    # uninterrupted reference
    ds, table, trainer = _world(tmp_path)
    for _ in range(N):
        ref = _run_pass(ds, table, trainer)
    ref_state = table.state_dict()
    ds.close()

    # run A: passes 0..2 checkpointed, then "die"
    ds2, table_a, trainer_a = _world(tmp_path)
    acp_a = AutoCheckpointer(str(tmp_path / "acp"), job_id="jf")
    for p in range(3):
        _run_pass(ds2, table_a, trainer_a)
        acp_a.after_pass(p, table_a, trainer_a)
    ds2.close()

    # truncate the newest checkpoint's sparse payload
    newest = acp_a.ckpt.list_checkpoints()[-1]
    assert newest.tag == "jf-p000002"
    path = os.path.join(newest.dirname, "sparse.npz")
    data = open(path, "rb").read()
    open(path, "wb").write(data[: len(data) // 2])

    # run B: fresh objects; resume falls back to pass 1's tag
    ds3, table_b, trainer_b = _world(tmp_path)
    acp_b = AutoCheckpointer(str(tmp_path / "acp"), job_id="jf")
    status, mstate = acp_b.resume(table_b, trainer_b)
    assert status["fallback"] is True
    assert status["tag"] == "jf-p000001"
    assert status["next_pass"] == 2
    assert mstate is None  # the snapshot belonged to the lost pass
    assert stats.get("ckpt.resume_fallback") == 1

    got = None
    for p in range(status["next_pass"], N):
        got = _run_pass(ds3, table_b, trainer_b)
        acp_b.after_pass(p, table_b, trainer_b)
    ds3.close()

    # replay reproduces the uninterrupted run exactly
    assert got["count"] == ref["count"]
    np.testing.assert_allclose(got["auc"], ref["auc"], atol=1e-6)
    np.testing.assert_allclose(got["loss"], ref["loss"], rtol=1e-5)
    got_state = table_b.state_dict()
    ia, ib = np.argsort(ref_state["keys"]), np.argsort(got_state["keys"])
    np.testing.assert_array_equal(
        ref_state["keys"][ia], got_state["keys"][ib]
    )
    np.testing.assert_allclose(
        ref_state["values"][ia], got_state["values"][ib], rtol=1e-5, atol=1e-6
    )


# --------------------------------------------------------------------------- #
# NaN policies
# --------------------------------------------------------------------------- #
class TestNanPolicy:
    def test_raise_policy(self, tmp_path):
        ds, table, trainer = _world(
            tmp_path, trainer_conf=TrainerConfig(
                auc_buckets=1 << 10, nan_policy="raise", check_nan_inf=True,
            ),
        )
        table.begin_pass(ds.unique_keys())
        with fault_plan({"train.nan": "first:1"}):
            with pytest.raises(FloatingPointError):
                trainer.train_from_dataset(ds, table)
        table.end_pass()
        ds.close()

    def test_skip_batch_discards_only_the_bad_batch(self, tmp_path):
        clean_ds, clean_table, clean_trainer = _world(tmp_path, sub="c")
        m_clean = _run_pass(clean_ds, clean_table, clean_trainer)
        clean_ds.close()

        ds, table, trainer = _world(
            tmp_path, sub="c",
            trainer_conf=TrainerConfig(
                auc_buckets=1 << 10, nan_policy="skip_batch",
            ),
        )
        with fault_plan({"train.nan": "at:1"}):  # poison the second batch
            m = _run_pass(ds, table, trainer)
        ds.close()
        assert m["steps"] == m_clean["steps"] - 1
        assert trainer.global_step == m["steps"]
        assert stats.get("train.nan_skipped_steps") == 1
        assert stats.get("train.nan_skipped_ins") == B
        # skipped batch's instances are absent from the metrics
        assert m["count"] == m_clean["count"] - B
        # and the model still learned from everything else
        assert np.isfinite(m["loss"])
        assert abs(m["auc"] - m_clean["auc"]) < 0.1

    def test_skip_batch_under_scan(self, tmp_path):
        """Scan groups skip per-tick: one poisoned batch inside a 2-step
        group discards only that tick's update and metrics."""
        ds, table, trainer = _world(
            tmp_path, sub="c3",
            trainer_conf=TrainerConfig(
                auc_buckets=1 << 10, nan_policy="skip_batch", scan_steps=2,
            ),
        )
        with fault_plan({"train.nan": "at:1"}):
            m = _run_pass(ds, table, trainer)
        ds.close()
        assert stats.get("train.nan_skipped_steps") == 1
        assert m["steps"] == 128 // B - 1
        assert m["count"] == 128 - B
        assert trainer.global_step == m["steps"]
        assert np.isfinite(m["loss"])

    def test_skip_batch_is_deterministic(self, tmp_path):
        runs = []
        for _ in range(2):
            faults.clear()
            ds, table, trainer = _world(
                tmp_path, sub="c2",
                trainer_conf=TrainerConfig(
                    auc_buckets=1 << 10, nan_policy="skip_batch",
                ),
            )
            with fault_plan({"train.nan": "at:1"}):
                runs.append(_run_pass(ds, table, trainer))
            ds.close()
        assert runs[0]["auc"] == runs[1]["auc"]
        assert runs[0]["loss"] == runs[1]["loss"]

    def test_rollback_restores_last_completed_pass(self, tmp_path):
        # uninterrupted 2-pass reference
        ds0, table0, trainer0 = _world(tmp_path, sub="r")
        _run_pass(ds0, table0, trainer0)
        ref = _run_pass(ds0, table0, trainer0)
        ref_state = table0.state_dict()
        ds0.close()

        ds, table, trainer = _world(
            tmp_path, sub="r",
            trainer_conf=TrainerConfig(
                auc_buckets=1 << 10, nan_policy="rollback",
            ),
        )
        acp = AutoCheckpointer(str(tmp_path / "acp_rb"), job_id="rb")
        trainer.checkpointer = acp
        _run_pass(ds, table, trainer)
        acp.after_pass(0, table, trainer)
        step_after_p0 = trainer.global_step

        # pass 1 hits a NaN batch -> rolled back to pass 0's checkpoint
        table.begin_pass(ds.unique_keys())
        with fault_plan({"train.nan": "first:1"}):
            with pytest.raises(PassRolledBack) as exc:
                trainer.train_from_dataset(ds, table)
        assert exc.value.status["next_pass"] == 1
        assert not table._in_pass  # pass was aborted, no end_pass needed
        assert trainer.global_step == step_after_p0
        assert stats.get("train.nan_rollback") == 1

        # re-run pass 1 clean: reproduces the uninterrupted run exactly
        got = _run_pass(ds, table, trainer)
        ds.close()
        np.testing.assert_allclose(got["auc"], ref["auc"], atol=1e-6)
        np.testing.assert_allclose(got["loss"], ref["loss"], rtol=1e-5)
        got_state = table.state_dict()
        ia = np.argsort(ref_state["keys"])
        ib = np.argsort(got_state["keys"])
        np.testing.assert_array_equal(
            ref_state["keys"][ia], got_state["keys"][ib]
        )
        np.testing.assert_allclose(
            ref_state["values"][ia], got_state["values"][ib],
            rtol=1e-5, atol=1e-6,
        )

    def test_rollback_without_checkpointer_raises(self, tmp_path):
        ds, table, trainer = _world(
            tmp_path, sub="r2",
            trainer_conf=TrainerConfig(
                auc_buckets=1 << 10, nan_policy="rollback",
            ),
        )
        table.begin_pass(ds.unique_keys())
        with fault_plan({"train.nan": "first:1"}):
            with pytest.raises(FloatingPointError):
                trainer.train_from_dataset(ds, table)
        table.end_pass()
        ds.close()

    def test_bad_policy_rejected(self):
        tconf = SparseTableConfig(embedding_dim=4)
        model = CtrDnn(S, tconf.row_width, dense_dim=DENSE, hidden=(8,))
        with pytest.raises(ValueError, match="nan_policy"):
            Trainer(model, tconf, TrainerConfig(nan_policy="ignore"))


# --------------------------------------------------------------------------- #
# satellites: spill-rm accounting, prefetch close timeout
# --------------------------------------------------------------------------- #
def test_spill_rm_failure_counted(tmp_path):
    from paddlebox_tpu.data.dataset import _DiskSpill

    conf = make_synth_config(n_sparse_slots=S, dense_dim=DENSE, batch_size=B)
    ds = PadBoxSlotDataset(conf, read_threads=1)
    ds._spill = _DiskSpill(
        paths=[str(tmp_path / "gone-1.bin"), str(tmp_path / "gone-2.bin")],
        unique_keys=np.empty(0, np.uint64), n_ins=0,
    )  # paths never existed -> both removals fail
    ds.release_memory()
    assert stats.get("dataset.spill_rm_failed") == 2
    assert ds._spill is None
    ds.close()


class TestServerErrorPaths:
    """Satellite: /healthz readiness + 400 (client) vs 500 (server) split.
    Uses a stubbed score_lines so no artifact/device work is involved —
    the classification mapping is what's under test."""

    def _server(self):
        from types import SimpleNamespace

        from paddlebox_tpu.inference.server import ScoringServer

        s = ScoringServer()
        entry = SimpleNamespace(  # enough for start() and /healthz
            requests=0, instances=0,
            predictor=SimpleNamespace(bucket_shapes=[], n_features=0),
        )
        s._models = {"m": entry}
        s._default = "m"
        port = s.start()
        return s, port

    def _post(self, port, path, body=b"x"):
        import http.client

        conn = http.client.HTTPConnection("127.0.0.1", port, timeout=5)
        conn.request("POST", path, body=body)
        r = conn.getresponse()
        out = (r.status, json.loads(r.read().decode()))
        conn.close()
        return out

    def _get(self, port, path):
        import http.client

        conn = http.client.HTTPConnection("127.0.0.1", port, timeout=5)
        conn.request("GET", path)
        r = conn.getresponse()
        out = (r.status, json.loads(r.read().decode()))
        conn.close()
        return out

    def test_malformed_payload_is_400(self):
        s, port = self._server()
        try:
            s.score_lines = lambda text, name=None: (_ for _ in ()).throw(
                ValueError("bad slot line")
            )
            code, body = self._post(port, "/score")
            assert code == 400
            assert "bad slot line" in body["error"]
        finally:
            s.stop()

    def test_internal_error_is_500(self):
        s, port = self._server()
        try:
            s.score_lines = lambda text, name=None: (_ for _ in ()).throw(
                RuntimeError("device fell over")
            )
            code, body = self._post(port, "/score")
            assert code == 500
            assert "device fell over" in body["error"]
        finally:
            s.stop()

    def test_unknown_model_is_404(self):
        s, port = self._server()
        try:
            s.score_lines = lambda text, name=None: (_ for _ in ()).throw(
                KeyError(name)
            )
            code, _ = self._post(port, "/score/nope")
            assert code == 404
        finally:
            s.stop()

    def test_healthz_readiness(self):
        s, port = self._server()
        try:
            code, body = self._get(port, "/healthz")
            assert code == 200 and body["ready"] is True
            s._models = {}  # models drained -> not ready
            code, body = self._get(port, "/healthz")
            assert code == 503 and body["ready"] is False
        finally:
            s.stop()


def test_prefetch_close_timeout_counted(monkeypatch):
    import threading

    from paddlebox_tpu.train import trainer as trainer_mod

    monkeypatch.setattr(trainer_mod, "_PREFETCH_JOIN_S", 0.05)
    release = threading.Event()

    def stuck_gen():
        release.wait()  # simulates planning/H2D stuck past close()
        yield 1

    pf = trainer_mod._FeedPrefetcher(stuck_gen(), depth=1)
    pf.close()
    assert stats.get("trainer.prefetch_close_timeout") == 1
    release.set()  # let the daemon thread exit
