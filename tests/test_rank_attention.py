"""PV merge + rank_offset + rank_attention (reference:
operators/rank_attention_op.cu + rank_attention.cu.h:27-110;
CopyRankOffsetKernel data_feed.cu:208-258; PV feed data_feed.h:756-774;
python test mirror: test_rank_attention_op.py)."""

import numpy as np
import pytest

from paddlebox_tpu.config import SparseTableConfig, TrainerConfig
from paddlebox_tpu.data.dataset import PadBoxSlotDataset
from paddlebox_tpu.data.feed import build_rank_offset
from paddlebox_tpu.data.synth import make_synth_config, write_synth_files
from paddlebox_tpu.models import RankCtrDnn
from paddlebox_tpu.ops import rank_attention
from paddlebox_tpu.sparse.table import SparseTable
from paddlebox_tpu.train.trainer import Trainer


# --------------------------------------------------------------------------- #
# numpy oracle mirroring the CUDA kernel semantics
# --------------------------------------------------------------------------- #
def np_rank_attention(x, rank_offset, rank_param, max_rank):
    n, f = x.shape
    c = rank_param.shape[-1]
    p = rank_param.reshape(max_rank, max_rank, f, c)
    out = np.zeros((n, c), dtype=x.dtype)
    for i in range(n):
        lower = rank_offset[i, 0] - 1
        if lower < 0:
            continue
        for k in range(max_rank):
            faster = rank_offset[i, 2 * k + 1] - 1
            idx = rank_offset[i, 2 * k + 2]
            if faster < 0 or idx < 0:
                continue
            out[i] += x[idx] @ p[lower, faster]
    return out


def _random_rank_offset(rng, n, max_rank):
    """Random but self-consistent rank_offset (like the reference op test)."""
    mat = np.full((n, 2 * max_rank + 1), -1, dtype=np.int32)
    for i in range(n):
        own = int(rng.integers(0, max_rank + 1))  # 0 = unranked
        mat[i, 0] = own if own else -1
        if own:
            for m in range(max_rank):
                if rng.random() < 0.7:
                    mat[i, 2 * m + 1] = m + 1
                    mat[i, 2 * m + 2] = int(rng.integers(0, n))
    return mat


def test_rank_attention_matches_oracle():
    rng = np.random.default_rng(0)
    n, f, c, k = 17, 5, 4, 3
    x = rng.normal(size=(n, f)).astype(np.float32)
    param = rng.normal(size=(k * k * f, c)).astype(np.float32)
    off = _random_rank_offset(rng, n, k)
    got = np.asarray(rank_attention(x, off, param, k))
    want = np_rank_attention(x, off, param, k)
    np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-5)


def test_rank_attention_grads_flow():
    import jax
    import jax.numpy as jnp

    rng = np.random.default_rng(1)
    n, f, c, k = 9, 3, 2, 2
    x = jnp.asarray(rng.normal(size=(n, f)).astype(np.float32))
    param = jnp.asarray(rng.normal(size=(k * k * f, c)).astype(np.float32))
    off = jnp.asarray(_random_rank_offset(rng, n, k))
    gx, gp = jax.grad(
        lambda a, b: rank_attention(a, off, b, k).sum(), argnums=(0, 1)
    )(x, param)
    assert np.isfinite(np.asarray(gx)).all()
    assert np.isfinite(np.asarray(gp)).all()
    assert np.abs(np.asarray(gp)).sum() > 0


# --------------------------------------------------------------------------- #
# rank_offset construction
# --------------------------------------------------------------------------- #
def test_build_rank_offset_pairs():
    from paddlebox_tpu.data.record import RecordBlock

    # one PV of 3 ads (ranks 1,2,3) + one unranked ad
    n = 4
    block = RecordBlock(
        n_ins=n,
        n_sparse_slots=1,
        keys=np.arange(n, dtype=np.uint64),
        key_offsets=np.arange(n + 1, dtype=np.int64),
        dense=np.zeros((n, 0), np.float32),
        labels=np.zeros(n, np.float32),
        ranks=np.array([1, 2, 3, 0], np.int32),
        cmatches=np.array([222, 223, 222, 222], np.int32),
        search_ids=np.array([7, 7, 7, 8], np.uint64),
    )
    ids = np.arange(4)
    bounds = np.array([0, 3, 4])
    mat = build_rank_offset(block, ids, bounds, batch_size=6, max_rank=3,
                            cmatch_filter=(222, 223))
    assert mat.shape == (6, 7)
    np.testing.assert_array_equal(mat[:, 0], [1, 2, 3, -1, -1, -1])
    # every ranked ad of the PV sees peers at slots by peer rank
    for j in range(3):
        for m in range(3):
            assert mat[j, 2 * m + 1] == m + 1
            assert mat[j, 2 * m + 2] == m  # batch-local peer row
    # unranked ad row stays -1; padding rows stay -1
    assert (mat[3:] == -1).all()
    # cmatch filter drops everything when nothing matches
    mat2 = build_rank_offset(block, ids, bounds, 6, 3, cmatch_filter=(999,))
    assert (mat2 == -1).all()


# --------------------------------------------------------------------------- #
# PV dataset + e2e
# --------------------------------------------------------------------------- #
def _pv_dataset(tmp_path, n_ins=96, batch_size=16):
    conf = make_synth_config(
        n_sparse_slots=3, dense_dim=2, batch_size=batch_size,
        max_feasigns_per_ins=16, parse_logkey=True, enable_pv_merge=True,
        pv_batch_size=8, rank_cmatch_filter=(222, 223),
    )
    files = write_synth_files(
        str(tmp_path), n_files=2, ins_per_file=n_ins // 2, n_sparse_slots=3,
        vocab_per_slot=50, dense_dim=2, seed=3, with_logkey=True,
        max_ads_per_pv=3,
    )
    ds = PadBoxSlotDataset(conf, read_threads=1)
    ds.set_filelist(files)
    ds.load_into_memory()
    return conf, ds


def test_pv_batches(tmp_path):
    conf, ds = _pv_dataset(tmp_path)
    ds.preprocess_instance()
    assert ds.pv_mode and ds.get_pv_data_size() > 0
    total = 0
    for b in ds.batches():
        assert b.rank_offset is not None
        assert b.rank_offset.shape == (conf.batch_size, conf.rank_offset_cols)
        nreal = b.n_real_ins
        total += nreal
        # ranked rows only among real instances; peer indices in-batch
        ro = b.rank_offset
        assert (ro[nreal:, 0] == -1).all()
        idxs = ro[:, 2::2]
        assert idxs.max() < conf.batch_size
        ranked = ro[:, 0] > 0
        # a ranked ad always lists itself as a peer at its own rank slot
        for i in np.nonzero(ranked)[0]:
            m = ro[i, 0] - 1
            assert ro[i, 2 * m + 2] >= 0
    assert total == ds.get_memory_data_size()
    ds.local_shuffle(seed=0)  # PV shuffle keeps groups intact
    sizes = [b.n_real_ins for b in ds.batches()]
    assert sum(sizes) == total
    ds.postprocess_instance()
    assert not ds.pv_mode
    ds.close()


def test_pv_e2e_train(tmp_path):
    conf, ds = _pv_dataset(tmp_path)
    ds.preprocess_instance()
    tconf = SparseTableConfig(embedding_dim=4)
    model = RankCtrDnn(
        3, tconf.row_width, dense_dim=2, hidden=(16,), max_rank=conf.max_rank,
        att_out_dim=8,
    )
    trainer = Trainer(model, tconf, TrainerConfig(auc_buckets=1 << 10))
    table = SparseTable(tconf, seed=0)
    losses = []
    for _ in range(6):
        table.begin_pass(ds.unique_keys())
        m = trainer.train_from_dataset(ds, table)
        table.end_pass()
        losses.append(m["loss"])
    assert np.isfinite(losses).all()
    assert losses[-1] < losses[0]
    ds.close()
