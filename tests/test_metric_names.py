"""Metric-name drift check (tools/check_metric_names.py): every metric
created in code must have a row in ARCHITECTURE.md's Observability
catalog — the tier-1 guard that keeps the catalog honest."""

import os
import subprocess
import sys

TOOL = os.path.join(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
    "tools", "check_metric_names.py",
)


def _run_tool(*args):
    sys.path.insert(0, os.path.dirname(TOOL))
    try:
        import importlib

        mod = importlib.import_module("check_metric_names")
        return mod
    finally:
        sys.path.pop(0)


def test_catalog_covers_every_call_site():
    mod = _run_tool()
    assert mod.main([]) == 0


def test_scanner_finds_known_families():
    mod = _run_tool()
    found = mod.scan_sources()
    # literal names, f-string families, and typed-metric call-sites
    assert "train.nan_rollback" in found
    assert "retry.*.calls" in found
    assert "server.request_seconds" in found
    assert "watchdog.staleness_s" in found


def test_catalog_table_parses():
    mod = _run_tool()
    pats = mod.catalog_patterns()
    assert "trainer.stage_seconds" in pats
    assert "retry.*.calls" in pats  # <site> normalized to a wildcard


def test_cli_exit_code_zero():
    r = subprocess.run(
        [sys.executable, TOOL], capture_output=True, text=True, timeout=60
    )
    assert r.returncode == 0, r.stderr
