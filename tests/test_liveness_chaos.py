"""Distributed-liveness end to end: injected hangs at registered sites must
terminate within 2x the watchdog deadline with a DistributedStallError that
names the stalled process and stage — and an abort-with-rollback must leave
no partially-applied pass (resumed replay reproduces the fault-free run,
PR 1's replay-equality harness).

The single-process tests are tier-1 (fast deadlines, warm compile); the
frozen-worker fleet test spawns 2 localhost ranks and is chaos/slow.
"""

import os
import time

import numpy as np
import pytest

from paddlebox_tpu.config import (
    LivenessConfig,
    SparseTableConfig,
    TrainerConfig,
)
from paddlebox_tpu.data.dataset import PadBoxSlotDataset
from paddlebox_tpu.data.synth import make_synth_config, write_synth_files
from paddlebox_tpu.models import CtrDnn
from paddlebox_tpu.parallel.watchdog import DistributedStallError, Watchdog
from paddlebox_tpu.sparse.table import SparseTable
from paddlebox_tpu.train import AutoCheckpointer, PassRolledBack, Trainer
from paddlebox_tpu.utils import faults
from paddlebox_tpu.utils.faults import FaultPlan
from paddlebox_tpu.utils.monitor import stats

pytestmark = pytest.mark.distributed

S, DENSE, B = 3, 2, 16

FAST = LivenessConfig(
    deadline_s=1.5, heartbeat_interval_s=0.3, poll_interval_s=0.1
)


def _world(tmp_path, liveness=FAST, seed=0):
    conf = make_synth_config(
        n_sparse_slots=S, dense_dim=DENSE, batch_size=B,
        max_feasigns_per_ins=8,
    )
    files = write_synth_files(
        str(tmp_path / "data"), n_files=2, ins_per_file=64, n_sparse_slots=S,
        vocab_per_slot=60, dense_dim=DENSE, seed=9,
    )
    ds = PadBoxSlotDataset(conf, read_threads=1)
    ds.set_filelist(files)
    ds.load_into_memory()
    tconf = SparseTableConfig(embedding_dim=4)
    model = CtrDnn(S, tconf.row_width, dense_dim=DENSE, hidden=(16, 8))
    table = SparseTable(tconf, seed=seed)
    trainer = Trainer(
        model, tconf,
        TrainerConfig(auc_buckets=1 << 10, liveness=liveness), seed=seed,
    )
    return ds, table, trainer


def _pass(ds, table, trainer, mstate=None):
    table.begin_pass(ds.unique_keys())
    m = trainer.train_from_dataset(ds, table, auc_state=mstate)
    table.end_pass()
    return m


def test_step_hang_aborts_within_2x_deadline(tmp_path):
    ds, table, trainer = _world(tmp_path)
    _pass(ds, table, trainer)  # warm: compile happens outside the clock
    faults.install(FaultPlan({"train.step": "hang:at:1"}))
    try:
        table.begin_pass(ds.unique_keys())
        t0 = time.monotonic()
        with pytest.raises(DistributedStallError) as ei:
            trainer.train_from_dataset(ds, table)
        dt = time.monotonic() - t0
        assert dt < 2 * FAST.deadline_s + 1.0, dt
        err = ei.value
        assert err.culprit == 0 and err.kind == "local"
        assert err.stage in ("step", "feed")
        assert stats.get("train.stall_aborts") >= 1
        table.end_pass()
    finally:
        faults.clear()
        ds.close()


def test_data_read_hang_bounded_by_watchdog(tmp_path):
    """A hang in the data-read path (the 'stuck storage' shape) is bounded
    when a watchdog guards the load."""
    conf = make_synth_config(
        n_sparse_slots=S, dense_dim=DENSE, batch_size=B,
        max_feasigns_per_ins=8,
    )
    files = write_synth_files(
        str(tmp_path / "data"), n_files=1, ins_per_file=32, n_sparse_slots=S,
        vocab_per_slot=30, dense_dim=DENSE, seed=2,
    )
    ds = PadBoxSlotDataset(conf, read_threads=1)
    ds.set_filelist(files)
    faults.install(FaultPlan({"data.read": "hang:first:1"}))
    try:
        t0 = time.monotonic()
        with Watchdog(FAST, rank=0, world=1):
            with pytest.raises(DistributedStallError):
                ds.load_into_memory()
        assert time.monotonic() - t0 < 2 * FAST.deadline_s + 1.0
    finally:
        faults.clear()
        ds.close()


def test_stall_rollback_leaves_no_partial_pass(tmp_path):
    """rollback_on_abort: the aborted pass is fully discarded (restore to
    the last completed pass) and replaying it reproduces the fault-free
    run — metrics, dense params and table state (PR 1's replay-equality
    assertions)."""
    # ---- fault-free reference: 2 passes ---------------------------------- #
    ds_ref, table_ref, trainer_ref = _world(tmp_path, liveness=None)
    ref = None
    for _ in range(2):
        ref = _pass(ds_ref, table_ref, trainer_ref)
    ref_state = table_ref.state_dict()
    ds_ref.close()

    # ---- guarded run: pass 0 ok, pass 1 stalls and rolls back ------------ #
    liv = LivenessConfig(
        deadline_s=1.5, heartbeat_interval_s=0.3, poll_interval_s=0.1,
        rollback_on_abort=True,
    )
    ds, table, trainer = _world(tmp_path, liveness=liv)
    acp = AutoCheckpointer(str(tmp_path / "acp"), job_id="stall")
    trainer.checkpointer = acp
    _pass(ds, table, trainer)
    acp.after_pass(0, table, trainer)

    faults.install(FaultPlan({"train.step": "hang:at:1"}))
    try:
        table.begin_pass(ds.unique_keys())
        with pytest.raises(PassRolledBack) as ei:
            trainer.train_from_dataset(ds, table)
        # the rollback chains from the structured stall error
        assert isinstance(ei.value.__context__, DistributedStallError)
        assert ei.value.status["next_pass"] == 1
        assert stats.get("train.nan_rollback") >= 1
        # NOTE: no end_pass() — the pass was aborted and discarded
    finally:
        faults.clear()

    # ---- replay pass 1 cleanly: must equal the fault-free run ------------ #
    got = _pass(ds, table, trainer)
    acp.after_pass(1, table, trainer)
    ds.close()

    assert got["count"] == ref["count"]
    np.testing.assert_allclose(got["auc"], ref["auc"], atol=1e-6)
    np.testing.assert_allclose(got["loss"], ref["loss"], rtol=1e-5)
    import jax

    for a, b in zip(
        jax.tree.leaves(trainer_ref.params), jax.tree.leaves(trainer.params)
    ):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=1e-6, atol=1e-7
        )
    got_state = table.state_dict()
    ia = np.argsort(ref_state["keys"])
    ib = np.argsort(got_state["keys"])
    np.testing.assert_array_equal(ref_state["keys"][ia], got_state["keys"][ib])
    np.testing.assert_allclose(
        ref_state["values"][ia], got_state["values"][ib], rtol=1e-5, atol=1e-6
    )


def test_stall_without_rollback_config_reraises(tmp_path):
    """Default liveness (no rollback_on_abort): the stall error propagates
    even with a checkpointer attached — rollback is an opt-in policy."""
    ds, table, trainer = _world(tmp_path)
    trainer.checkpointer = AutoCheckpointer(str(tmp_path / "acp2"), job_id="x")
    _pass(ds, table, trainer)
    trainer.checkpointer.after_pass(0, table, trainer)
    faults.install(FaultPlan({"train.step": "hang:at:0"}))
    try:
        table.begin_pass(ds.unique_keys())
        with pytest.raises(DistributedStallError):
            trainer.train_from_dataset(ds, table)
        table.end_pass()
    finally:
        faults.clear()
        ds.close()


# --------------------------------------------------------------------------- #
# the real thing: a frozen worker in a 2-rank fleet
# --------------------------------------------------------------------------- #
@pytest.mark.slow
@pytest.mark.chaos
def test_frozen_worker_aborts_fleet_with_named_culprit(tmp_path):
    """Freeze rank 1 (PBOX_FAULT_PLAN hang at hostplane.allgather) in a
    3-process localhost job driving lockstep KV-channel gathers under
    KV-heartbeat watchdogs: the whole fleet must terminate within the
    liveness bound, every rank naming rank 1 as the culprit — the frozen
    rank via its local check, the waiting peers via heartbeat staleness /
    the poison key (a victim blocked waiting on the frozen peer must NOT
    be misnamed)."""
    here = os.path.dirname(__file__)
    from paddlebox_tpu.launch import launch

    deadline = 5.0
    log_dir = str(tmp_path / "logs")
    t0 = time.monotonic()
    rc = launch(
        [
            os.path.join(here, "_stall_child.py"),
            "50",                         # n_steps (never reached)
            "1",                          # stall_rank
            "hostplane.allgather",        # site
            "hang:at:3",                  # freeze at the 4th gather
            str(deadline),
        ],
        nproc=3,
        log_dir=log_dir,
        liveness_deadline_s=deadline,
        job_timeout_s=180.0,  # launcher backstop, never the expected path
    )
    elapsed = time.monotonic() - t0
    logs = {
        f: open(os.path.join(log_dir, f), errors="replace").read()
        for f in sorted(os.listdir(log_dir))
    }
    blob = "\n".join(f"--- {f} ---\n{t[-4000:]}" for f, t in logs.items())
    # the fleet died (stall abort), it did not complete, and it did not
    # need the launcher's last-resort timeout
    assert rc not in (0, 3), f"rc={rc}\n{blob}"
    assert rc != 124, f"launcher backstop fired (no abort)\n{blob}"
    assert elapsed < 120, f"took {elapsed:.0f}s\n{blob}"
    assert "COMPLETED-UNEXPECTEDLY" not in blob, blob
    # the frozen rank detected itself and named the stage
    assert "STALL-ABORT rank=1" in logs["rank1.log"], blob
    assert "process 1" in logs["rank1.log"], blob
    # every healthy rank converged on the same culprit (peer heartbeat /
    # poison path) and raised out of its blocked gather — nobody named a
    # mere victim
    for r in (0, 2):
        assert f"STALL-ABORT rank={r}" in logs[f"rank{r}.log"], blob
        assert "process 1 stalled" in logs[f"rank{r}.log"], blob
