"""Async dense parameter server (BoxPSAsynDenseTable analog) tests."""

import numpy as np
import pytest

from paddlebox_tpu.parallel.async_dense import AsyncDenseTable


def _params():
    rng = np.random.default_rng(0)
    return {
        "w": rng.normal(size=(4, 3)).astype(np.float32),
        "b": np.zeros(3, dtype=np.float32),
    }


class TestAsyncDenseTable:
    def test_sgd_matches_serial(self):
        p0 = _params()
        table = AsyncDenseTable(p0, optimizer="sgd", lr=0.1)
        grads = [
            {"w": np.full((4, 3), g, np.float32), "b": np.full(3, g, np.float32)}
            for g in (1.0, -0.5, 0.25)
        ]
        for g in grads:
            table.push(g)
        table.drain()
        got = table.pull()
        table.stop()
        want_w = p0["w"] - 0.1 * (1.0 - 0.5 + 0.25)
        np.testing.assert_allclose(got["w"], want_w, rtol=1e-6)
        assert table.pushes == 3 and table.applied == 3

    def test_adam_matches_optax(self):
        import jax
        import jax.numpy as jnp
        import optax

        p0 = _params()
        lr = 0.01
        table = AsyncDenseTable(p0, optimizer="adam", lr=lr)
        opt = optax.adam(lr)
        ref = jax.tree.map(jnp.asarray, p0)
        state = opt.init(ref)
        rng = np.random.default_rng(1)
        for _ in range(5):
            g = {
                "w": rng.normal(size=(4, 3)).astype(np.float32),
                "b": rng.normal(size=3).astype(np.float32),
            }
            table.push(g)
            updates, state = opt.update(
                jax.tree.map(jnp.asarray, g), state, ref
            )
            ref = optax.apply_updates(ref, updates)
        table.drain()
        got = table.pull()
        table.stop()
        np.testing.assert_allclose(got["w"], np.asarray(ref["w"]), rtol=1e-4,
                                   atol=1e-6)
        np.testing.assert_allclose(got["b"], np.asarray(ref["b"]), rtol=1e-4,
                                   atol=1e-6)

    def test_pull_is_snapshot(self):
        table = AsyncDenseTable(_params(), optimizer="sgd", lr=1.0)
        snap = table.pull()
        table.push({"w": np.ones((4, 3), np.float32),
                    "b": np.ones(3, np.float32)})
        table.drain()
        after = table.pull()
        table.stop()
        assert not np.allclose(snap["w"], after["w"])

    def test_error_surfaces_on_push(self):
        table = AsyncDenseTable(_params(), optimizer="sgd", lr=1.0)
        # wrong leaf count kills the update thread; next ops must raise
        table.push([np.ones(3, np.float32)] * 5)
        table._thread.join(timeout=5.0)
        with pytest.raises(RuntimeError):
            table.push({"w": np.ones((4, 3), np.float32),
                        "b": np.ones(3, np.float32)})

    def test_drain_and_stop_raise_when_thread_dead(self):
        """A dead update thread with grads still queued must turn drain()
        into a RuntimeError, not a Queue.join() hang at the pass boundary
        (advisor r3 medium)."""
        table = AsyncDenseTable(_params(), optimizer="sgd", lr=1.0,
                                queue_depth=4)
        good = {"w": np.ones((4, 3), np.float32),
                "b": np.ones(3, np.float32)}
        table.push([np.ones(3, np.float32)] * 5)  # kills the thread
        try:
            table.push(good)  # may or may not land before the death
        except RuntimeError:
            pass
        table._thread.join(timeout=5.0)
        assert not table._thread.is_alive()
        with pytest.raises(RuntimeError):
            table.drain()
        with pytest.raises(RuntimeError):
            table.stop()


class TestAsyncTrainingMode:
    def test_multichip_async_learns(self):
        """Full multi-chip pass in sync_dense_mode='async': machinery works,
        staleness-bounded updates still learn on the synthetic task."""
        import tempfile

        from paddlebox_tpu.config import SparseTableConfig, TrainerConfig
        from paddlebox_tpu.data.dataset import PadBoxSlotDataset
        from paddlebox_tpu.data.synth import make_synth_config, write_synth_files
        from paddlebox_tpu.models import CtrDnn
        from paddlebox_tpu.parallel import (
            MultiChipTrainer,
            ShardedSparseTable,
            make_mesh,
        )

        S, DENSE, B, n_dev = 3, 2, 8, 8
        conf = make_synth_config(
            n_sparse_slots=S, dense_dim=DENSE, batch_size=B,
            max_feasigns_per_ins=16,
        )
        tconf = SparseTableConfig(embedding_dim=8)
        trconf = TrainerConfig(
            auc_buckets=1 << 10, sync_dense_mode="async", sync_weight_step=2,
        )
        mesh = make_mesh(n_dev)
        model = CtrDnn(S, tconf.row_width, dense_dim=DENSE, hidden=(32, 16))
        trainer = MultiChipTrainer(model, tconf, mesh, trconf, seed=0)
        table = ShardedSparseTable(tconf, mesh, seed=0)
        with tempfile.TemporaryDirectory() as td:
            files = write_synth_files(
                td, n_files=2, ins_per_file=400, n_sparse_slots=S,
                vocab_per_slot=100, dense_dim=DENSE, seed=5,
            )
            ds = PadBoxSlotDataset(conf, read_threads=1)
            ds.set_filelist(files)
            ds.load_into_memory()
            auc_state = None
            for _ in range(3):  # multiple passes: re-pull + continue
                table.begin_pass(ds.unique_keys())
                metrics = trainer.train_from_dataset(
                    ds, table, auc_state=auc_state
                )
                auc_state = trainer.last_metric_state
                table.end_pass()
            ds.close()
        assert trainer.async_dense is not None
        assert trainer.async_dense.pushes == trainer.async_dense.applied > 0
        # every step's grad was pushed (lagged by one, flushed at pass end)
        assert trainer.async_dense.pushes == trainer.global_step
        assert np.isfinite(metrics["loss"])
        # async training is timing-nondeterministic by design (a pull races
        # the background apply — same in the reference's double buffer), so
        # assert a margin that holds across schedules: clearly better than
        # random on the learnable synth task
        assert metrics["auc"] > 0.52, metrics
        assert metrics["loss"] < 0.693, metrics  # below untrained BCE
        trainer.close()
        assert trainer.async_dense is None
