"""Child program for the durable-cold-tier SIGKILL chaos tests (not pytest).

One deterministic multi-pass training job over a `SparseTable` backed by
the crash-consistent log (`store_log_dir`).  Three modes:

  run     — all passes, uninterrupted; dump the final state (the oracle).
  victim  — same job, but at pass ``kill_pass`` a ``hang:first:1`` fault
            plan is installed for ``site`` (store.segment_write /
            store.compact / store.manifest_commit).  The process freezes
            at that site mid-mutation; a watcher thread touches the
            sentinel file the moment ``faults.hung.<site>`` trips so the
            parent can SIGKILL us at exactly the modeled crash point.
  resume  — open the same root (the table ctor recovers the committed
            log generation), read the atomic progress file, replay the
            unfinished passes, dump the final state.

The parent asserts resume's dump is BIT-exact vs run's: keys, values,
g2sum, and the exact rank-based AUC over scores derived from the final
embeddings (labels = key parity).  The progress file is written only
after ``flush()`` returns — i.e. after the pass's log generation
committed — so "replay from progress" is exactly the recovery contract:
a kill mid-merge leaves progress at the pass being merged, and the log
at the previous generation.

argv: mode root n_passes kill_pass site sentinel
"""

import json
import os
import sys
import threading
import time

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))
os.environ["JAX_PLATFORMS"] = "cpu"
os.environ.setdefault("JAX_ENABLE_X64", "0")

import numpy as np  # noqa: E402

mode = sys.argv[1]
root = sys.argv[2]
n_passes = int(sys.argv[3])
kill_pass = int(sys.argv[4])
site = sys.argv[5]
sentinel = sys.argv[6] if len(sys.argv) > 6 else ""


def make_table():
    from paddlebox_tpu.config import SparseTableConfig
    from paddlebox_tpu.sparse import SparseTable

    conf = SparseTableConfig(
        embedding_dim=4, learning_rate=0.1, initial_g2sum=1.0,
        initial_range=0.5, grad_clip=10.0,
        overlap_pass_boundary=False, hbm_cache_rows=0,
        store_log_dir=os.path.join(root, "log"),
        store_log_buckets=2,
        # compaction is driven explicitly (the store.compact arm), never
        # by the background worker — keeps the kill point deterministic
        store_compact_threshold=10_000,
    )
    return SparseTable(conf, seed=7)


def pass_keys(p: int) -> np.ndarray:
    rs = np.random.RandomState(100 + p)
    return np.unique(rs.randint(1, 5000, size=400).astype(np.uint64))


def run_pass(t, p: int) -> None:
    import jax.numpy as jnp

    t.begin_pass(pass_keys(p))
    cap = int(t.values.shape[0])
    delta = ((np.arange(cap, dtype=np.float32)[:, None] % 7.0) + p) * 0.01
    delta = np.broadcast_to(delta, (cap, t.values.shape[1]))
    t.values = t.values + jnp.asarray(np.ascontiguousarray(delta))
    t.g2sum = t.g2sum + jnp.float32(0.25)
    t.end_pass()


def progress_path() -> str:
    return os.path.join(root, "progress.json")


def write_progress(next_pass: int) -> None:
    tmp = progress_path() + f".tmp-{os.getpid()}"
    with open(tmp, "w") as fh:
        json.dump({"next_pass": next_pass}, fh)
        fh.flush()
        os.fsync(fh.fileno())
    os.replace(tmp, progress_path())


def read_progress() -> int:
    if not os.path.exists(progress_path()):
        return 0
    with open(progress_path()) as fh:
        return int(json.load(fh)["next_pass"])


def exact_auc(scores: np.ndarray, labels: np.ndarray) -> float:
    """Exact rank-based AUC (average ranks on ties)."""
    order = np.argsort(scores, kind="mergesort")
    s = scores[order]
    ranks = np.empty(len(s), dtype=np.float64)
    i = 0
    while i < len(s):
        j = i
        while j + 1 < len(s) and s[j + 1] == s[i]:
            j += 1
        ranks[i : j + 1] = (i + j) / 2.0 + 1.0
        i = j + 1
    r = np.empty_like(ranks)
    r[order] = ranks
    pos = labels > 0
    n_pos, n_neg = int(pos.sum()), int((~pos).sum())
    if n_pos == 0 or n_neg == 0:
        return 0.5
    return float((r[pos].sum() - n_pos * (n_pos + 1) / 2.0)
                 / (n_pos * n_neg))


def dump(t, out_path: str) -> None:
    state = t.state_dict()
    keys, vals = state["keys"], state["values"]
    scores = vals[:, 2:-1].astype(np.float64).sum(axis=1)
    labels = (keys % 2).astype(np.int64)
    np.savez(out_path, keys=keys, values=vals,
             auc=np.float64(exact_auc(scores, labels)))


def main() -> int:
    if mode == "victim":
        from paddlebox_tpu.utils.monitor import stats

        def watch() -> None:
            while True:
                if stats.get(f"faults.hung.{site}") > 0:
                    with open(sentinel, "w") as fh:
                        fh.write("hung\n")
                    return
                time.sleep(0.01)

        threading.Thread(target=watch, daemon=True).start()

    t = make_table()
    start = read_progress() if mode == "resume" else 0
    for p in range(start, n_passes):
        if mode == "victim" and p == kill_pass:
            from paddlebox_tpu.utils import faults

            faults.install(faults.FaultPlan({site: "hang:first:1"}))
        run_pass(t, p)
        t.flush()  # the pass's log generation commits HERE
        write_progress(p + 1)
        if mode == "victim" and site == "store.compact" and p == kill_pass:
            # explicit synchronous compaction: hangs between the staged
            # merge and its swap-manifest commit
            t._log.compact(0)
    dump(t, os.path.join(root, f"state-{mode}.npz"))
    t.close()
    print("DONE", flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
