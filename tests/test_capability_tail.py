"""Capability tail: evaluate (infer_from_dataset), AUC-runner slot
importance, dump fields/params, InputTable / ReplicaCache, disk spill."""

import os

import numpy as np
import pytest

from paddlebox_tpu.config import SparseTableConfig, TrainerConfig
from paddlebox_tpu.data.dataset import PadBoxSlotDataset
from paddlebox_tpu.data.synth import make_synth_config, write_synth_files
from paddlebox_tpu.models import CtrDnn
from paddlebox_tpu.sparse.table import SparseTable
from paddlebox_tpu.train.trainer import Trainer

S, DENSE, B = 3, 2, 32


def _world(tmp_path, n_ins=192, **synth_kw):
    conf = make_synth_config(
        n_sparse_slots=S, dense_dim=DENSE, batch_size=B, max_feasigns_per_ins=16
    )
    files = write_synth_files(
        str(tmp_path / "data"), n_files=2, ins_per_file=n_ins // 2,
        n_sparse_slots=S, vocab_per_slot=40, dense_dim=DENSE, seed=2, **synth_kw,
    )
    ds = PadBoxSlotDataset(conf, read_threads=2)
    ds.set_filelist(files)
    tconf = SparseTableConfig(embedding_dim=4)
    model = CtrDnn(S, tconf.row_width, dense_dim=DENSE, hidden=(16,))
    trainer = Trainer(model, tconf, TrainerConfig(auc_buckets=1 << 10))
    table = SparseTable(tconf, seed=0)
    return conf, ds, trainer, table


def _train_passes(trainer, table, ds, n=4):
    for _ in range(n):
        table.begin_pass(ds.unique_keys())
        m = trainer.train_from_dataset(ds, table)
        table.end_pass()
    return m


def test_evaluate_no_updates(tmp_path):
    _, ds, trainer, table = _world(tmp_path)
    ds.load_into_memory()
    _train_passes(trainer, table, ds)
    store_before = table.state_dict()["values"].copy()
    params_before = [np.asarray(x).copy() for x in
                     __import__("jax").tree.leaves(trainer.params)]
    table.begin_pass(ds.unique_keys())
    m = trainer.evaluate(ds, table)
    table.end_pass()
    assert m["count"] == ds.get_memory_data_size()
    assert m["auc"] > 0.55
    np.testing.assert_array_equal(table.state_dict()["values"], store_before)
    for a, b in zip(__import__("jax").tree.leaves(trainer.params), params_before):
        np.testing.assert_array_equal(np.asarray(a), b)
    ds.close()


def test_auc_runner_slot_importance(tmp_path):
    from paddlebox_tpu.train.auc_runner import AucRunner

    _, ds, trainer, table = _world(tmp_path)
    ds.load_into_memory()
    _train_passes(trainer, table, ds, n=6)
    runner = AucRunner(trainer, table, seed=3)
    out = runner.run(
        ds, {"g_slot0": ["slot0"], "g_all": ["slot0", "slot1", "slot2"]}
    )
    assert out["baseline"]["auc"] > 0.55
    # replacing every slot destroys more signal than replacing one
    assert out["g_all"]["delta"] >= out["g_slot0"]["delta"] - 1e-6
    assert out["g_all"]["delta"] > 0.01
    # dataset block restored
    m2 = None
    table.begin_pass(ds.unique_keys())
    m2 = trainer.evaluate(ds, table)
    table.end_pass()
    assert m2["auc"] == pytest.approx(out["baseline"]["auc"], abs=1e-9)
    ds.close()


def test_dump_fields_and_params(tmp_path):
    conf, ds, trainer, table = _world(tmp_path)
    ds.load_into_memory()
    trainer.conf.need_dump_field = True
    trainer.conf.need_dump_param = True
    trainer.conf.dump_fields = ("dense",)
    trainer.conf.dump_fields_path = str(tmp_path / "dump")
    table.begin_pass(ds.unique_keys())
    trainer.train_from_dataset(ds, table)
    table.end_pass()
    files = sorted(os.listdir(tmp_path / "dump"))
    dump_txt = [f for f in files if f.startswith("dump-")]
    assert dump_txt
    lines = open(tmp_path / "dump" / dump_txt[0]).read().splitlines()
    assert len(lines) == ds.get_memory_data_size()
    cols = lines[0].split("\t")
    assert cols[1] in ("0", "1")  # label
    assert 0.0 <= float(cols[2]) <= 1.0  # pred
    assert cols[3].startswith("dense:")
    assert any(f.startswith("param-") and f.endswith(".dense.npz") for f in files)
    ds.close()


def test_input_table_and_replica_cache():
    import jax.numpy as jnp

    from paddlebox_tpu.sparse.aux_tables import InputTable, ReplicaCache

    t = InputTable(dim=3)
    i1 = t.add_row("ad-1", [1.0, 2.0, 3.0])
    i2 = t.add_row("ad-2", [4.0, 5.0, 6.0])
    assert (i1, i2) == (1, 2)
    idx = t.lookup_idx(["ad-2", "missing", "ad-1"])
    np.testing.assert_array_equal(idx, [2, 0, 1])
    rows = t.lookup_rows(["ad-2", "missing"])
    np.testing.assert_allclose(rows, [[4, 5, 6], [0, 0, 0]])
    # device gather path
    dev = np.asarray(jnp.take(t.rows_device(), jnp.asarray(idx), axis=0))
    np.testing.assert_allclose(dev, [[4, 5, 6], [0, 0, 0], [1, 2, 3]])
    # state roundtrip
    t2 = InputTable(dim=3)
    t2.load_state_dict(t.state_dict())
    np.testing.assert_array_equal(t2.lookup_idx(["ad-1", "ad-2"]), [1, 2])

    cache = ReplicaCache(np.array([[1.0, 1.0], [2.0, 2.0]]))
    out = np.asarray(cache.pull(np.array([1, 2, 0, 99])))
    np.testing.assert_allclose(out, [[1, 1], [2, 2], [0, 0], [0, 0]])


def test_disk_spill_roundtrip(tmp_path):
    conf, ds, trainer, table = _world(tmp_path)
    # memory path reference result
    ds.load_into_memory()
    mem_keys = ds.unique_keys()
    mem_ins = ds.get_memory_data_size()
    mem_batches = [b.keys[: b.n_keys].copy() for b in ds.batches()]
    ds.release_memory()

    ds.preload_into_disk(str(tmp_path / "spill"))
    ds.wait_preload_done()
    assert ds.get_memory_data_size() == mem_ins
    np.testing.assert_array_equal(ds.unique_keys(), mem_keys)
    disk_batches = [b.keys[: b.n_keys].copy() for b in ds.batches()]
    assert len(disk_batches) == len(mem_batches)
    for a, b in zip(disk_batches, mem_batches):
        np.testing.assert_array_equal(a, b)
    # trains from disk
    table.begin_pass(ds.unique_keys())
    m = trainer.train_from_dataset(ds, table)
    table.end_pass()
    assert m["steps"] == len(disk_batches)
    spill_files = list((tmp_path / "spill").glob("*.bin"))
    assert spill_files
    ds.release_memory()
    assert not list((tmp_path / "spill").glob("*.bin"))
    ds.close()


def test_profiler_report(tmp_path):
    _, ds, trainer, table = _world(tmp_path)
    ds.load_into_memory()
    trainer.conf.profile = True
    table.begin_pass(ds.unique_keys())
    m = trainer.train_from_dataset(ds, table)
    table.end_pass()
    prof = m["profile"]
    assert prof["steps"] == m["steps"]
    for stage in ("plan", "feed", "step"):
        assert prof[f"{stage}_sec"] >= 0.0
        assert f"{stage}_ms_per_step" in prof
    assert prof["step_sec"] > 0.0
    ds.close()


def test_disk_spill_bounded_memory(tmp_path):
    """Streaming spill keeps at most read_threads parsed blocks in flight
    (VERDICT r2 weak #8: a larger-than-RAM pass must actually load);
    batches stream back identical to the memory path."""
    from paddlebox_tpu.data.dataset import PadBoxSlotDataset
    from paddlebox_tpu.data.synth import make_synth_config, write_synth_files

    S, DENSE, B = 3, 2, 8
    conf = make_synth_config(
        n_sparse_slots=S, dense_dim=DENSE, batch_size=B,
        max_feasigns_per_ins=8,
    )
    files = write_synth_files(
        str(tmp_path), n_files=12, ins_per_file=20, n_sparse_slots=S,
        vocab_per_slot=50, dense_dim=DENSE, seed=11,
    )
    k = 2
    ds = PadBoxSlotDataset(conf, read_threads=k)
    ds.set_filelist(files)
    ds.load_into_memory()
    mem = [b.keys[: b.n_keys].copy() for b in ds.batches()]
    mem_keys = ds.unique_keys()
    ds.release_memory()

    ds.preload_into_disk(str(tmp_path / "spill"))
    ds.wait_preload_done()
    # bounded high-water mark: never more than k parsed blocks resident
    assert 1 <= ds.spill_peak_inflight <= k
    # one archive per input file, streamed incrementally
    assert len(list((tmp_path / "spill").glob("*.bin"))) == len(files)
    np.testing.assert_array_equal(ds.unique_keys(), mem_keys)
    disk = [b.keys[: b.n_keys].copy() for b in ds.batches()]
    assert len(disk) == len(mem)
    for a, b in zip(disk, mem):
        np.testing.assert_array_equal(a, b)
    ds.release_memory()
    ds.close()
