"""Bucketed host store (sparse/store.py) — the CPU/SSD tier analog."""

import os

import numpy as np
import pytest

from paddlebox_tpu.sparse.store import BucketStore


def _rand_keys(rng, n):
    # uniform uint64 so keys spread across high-bit buckets like real
    # feature-sign hashes do
    return np.unique(rng.integers(0, 2**63, size=n, dtype=np.uint64))


def _vals_for(keys, c, salt=0.0):
    v = np.arange(keys.shape[0] * c, dtype=np.float32).reshape(-1, c)
    return v + np.float32(salt)


class TestBucketStore:
    def test_update_then_lookup_roundtrip(self):
        rng = np.random.default_rng(0)
        st = BucketStore(n_cols=3, n_buckets=16)
        k = _rand_keys(rng, 500)
        v = _vals_for(k, 3)
        st.update(k, v)
        assert st.n == k.shape[0]
        got, found = st.lookup(k)
        assert found.all()
        np.testing.assert_array_equal(got, v)
        # missing keys read zeros, found=False
        miss = np.setdiff1d(_rand_keys(rng, 100), k)
        got, found = st.lookup(miss)
        assert not found.any()
        assert (got == 0).all()

    def test_inplace_vs_insert_accounting(self):
        rng = np.random.default_rng(1)
        st = BucketStore(n_cols=2, n_buckets=16)
        k = _rand_keys(rng, 1000)
        st.update(k, _vals_for(k, 2))
        ins0, rb0 = st.inserted, st.buckets_rebuilt
        assert ins0 == 1000
        # steady state: same keys again -> pure in-place, zero rebuilds
        st.update(k, _vals_for(k, 2, salt=7.0))
        assert st.inserted == ins0
        assert st.buckets_rebuilt == rb0
        assert st.updated_in_place == 1000
        got, found = st.lookup(k)
        assert found.all()
        np.testing.assert_array_equal(got, _vals_for(k, 2, salt=7.0))

    def test_interleaved_new_keys_merge_sorted(self):
        st = BucketStore(n_cols=1, n_buckets=4)
        a = np.array([10, 30, 50], dtype=np.uint64)
        st.update(a, _vals_for(a, 1))
        b = np.array([5, 20, 30, 60], dtype=np.uint64)
        st.update(b, _vals_for(b, 1, salt=100.0))
        keys, vals = st.materialize()
        np.testing.assert_array_equal(keys, [5, 10, 20, 30, 50, 60])
        assert (np.diff(keys.astype(np.int64)) > 0).all()
        # 30 was overwritten by the second update
        np.testing.assert_allclose(vals[keys == 30][0, 0], 102.0)

    def test_materialize_globally_sorted(self):
        rng = np.random.default_rng(2)
        st = BucketStore(n_cols=2, n_buckets=32)
        for salt in range(3):
            k = _rand_keys(rng, 400)
            st.update(k, _vals_for(k, 2, salt=salt))
        keys, vals = st.materialize()
        assert keys.shape[0] == st.n == vals.shape[0]
        assert (np.diff(keys.astype(np.float64)) > 0).all()

    def test_load_bulk_last_duplicate_wins(self):
        st = BucketStore(n_cols=1, n_buckets=8)
        keys = np.array([7, 3, 7, 9], dtype=np.uint64)
        vals = np.array([[1.0], [2.0], [3.0], [4.0]], dtype=np.float32)
        st.load_bulk(keys, vals)
        assert st.n == 3
        got, found = st.lookup(np.array([3, 7, 9], dtype=np.uint64))
        assert found.all()
        np.testing.assert_allclose(got[:, 0], [2.0, 3.0, 4.0])

    def test_decay_evict(self):
        st = BucketStore(n_cols=3, n_buckets=8)
        k = np.array([1, 2, 3, 4], dtype=np.uint64)
        v = np.array(
            [[4.0, 1.0, 9.0], [1.0, 1.0, 9.0], [0.5, 0.0, 9.0], [8.0, 2.0, 9.0]],
            dtype=np.float32,
        )
        st.update(k, v)
        evicted = st.decay_evict(decay_cols=2, decay=0.5, threshold=1.0)
        # decayed shows: 2.0, 0.5, 0.25, 4.0 -> two fall below 1.0
        assert evicted == 2
        keys, vals = st.materialize()
        np.testing.assert_array_equal(keys, [1, 4])
        np.testing.assert_allclose(vals[:, 0], [2.0, 4.0])
        np.testing.assert_allclose(vals[:, 1], [0.5, 1.0])
        np.testing.assert_allclose(vals[:, 2], [9.0, 9.0])  # not decayed

    def test_spill_mode_matches_ram_mode(self, tmp_path):
        rng = np.random.default_rng(3)
        ram = BucketStore(n_cols=2, n_buckets=32)
        disk = BucketStore(n_cols=2, n_buckets=32,
                           spill_dir=str(tmp_path / "spill"), max_resident=4)
        for salt in range(4):
            k = _rand_keys(rng, 600)
            ram.update(k, _vals_for(k, 2, salt=salt))
            disk.update(k, _vals_for(k, 2, salt=salt))
        assert disk.spill_writes > 0  # 32 buckets through 4 resident slots
        assert disk.resident_buckets <= 4
        rk, rv = ram.materialize()
        dk, dv = disk.materialize()
        np.testing.assert_array_equal(rk, dk)
        np.testing.assert_array_equal(rv, dv)
        # lookups agree after spill round-trips
        q = rk[:: max(1, rk.shape[0] // 50)]
        g1, f1 = ram.lookup(q)
        g2, f2 = disk.lookup(q)
        assert f1.all() and f2.all()
        np.testing.assert_array_equal(g1, g2)

    def test_evicted_rows_do_not_resurrect_from_stale_spill(self, tmp_path):
        """After decay_evict empties a previously-spilled bucket, the stale
        .npz on disk must not resurrect the evicted rows when the bucket is
        dropped from residency and reloaded (r4 review finding)."""
        st = BucketStore(n_cols=1, n_buckets=4,
                         spill_dir=str(tmp_path / "s"), max_resident=1)
        k = np.arange(1, 64, dtype=np.uint64)
        v = np.full((k.shape[0], 1), 0.5, np.float32)
        st.update(k, v)  # cycles buckets through the 1-slot residency
        assert st.spill_writes > 0
        evicted = st.decay_evict(decay_cols=1, decay=1.0, threshold=1.0)
        assert evicted == k.shape[0] and st.n == 0
        # force every bucket through spill-evict + reload again
        got, found = st.lookup(k)
        assert not found.any(), "stale spill resurrected evicted rows"
        assert (got == 0).all()
        assert st.n == 0

    def test_bad_bucket_count_rejected(self):
        with pytest.raises(ValueError):
            BucketStore(n_cols=1, n_buckets=3)

    def test_single_bucket_store(self):
        """n_buckets=1 makes the bucket shift 64 — undefined for numpy
        uint64 (x86 leaves the value unchanged); every key must land in
        bucket 0 (r17 review finding)."""
        st = BucketStore(n_cols=2, n_buckets=1)
        k = np.array([1, 2**63, 2**64 - 1], dtype=np.uint64)
        v = _vals_for(k, 2)
        st.update(k, v)
        got, found = st.lookup(k)
        assert found.all()
        np.testing.assert_array_equal(got, v)
        keys, _ = st.materialize()
        np.testing.assert_array_equal(keys, k)

    def test_update_unsorted_or_duplicate_keys_loud(self):
        """The sorted-insert merge silently corrupts buckets on unsorted
        input (keys lost to later searchsorted), so the contract is
        enforced loudly (r17 review finding)."""
        st = BucketStore(n_cols=1, n_buckets=4)
        with pytest.raises(ValueError, match="sorted unique"):
            st.update(np.array([9, 3], dtype=np.uint64),
                      np.zeros((2, 1), np.float32))
        with pytest.raises(ValueError, match="sorted unique"):
            st.update(np.array([3, 3], dtype=np.uint64),
                      np.zeros((2, 1), np.float32))
        assert st.n == 0  # refused before any bucket mutated

    def test_legacy_spill_without_crc_loads(self, tmp_path):
        """Spill files written before the checksum rode along have no
        'crc' entry: they must load unverified (with a warning), not be
        treated as corruption (r17 review finding)."""
        from paddlebox_tpu.utils.monitor import stats

        st = BucketStore(n_cols=1, n_buckets=4,
                         spill_dir=str(tmp_path / "s"), max_resident=1)
        k = np.arange(1, 64, dtype=np.uint64)
        v = np.full((k.shape[0], 1), 2.5, np.float32)
        st.update(k, v)
        assert st.spill_writes > 0
        # rewrite every spilled bucket in the legacy (crc-less) format
        rewritten = 0
        for b in range(st.n_buckets):
            p = st._path(b)
            if not os.path.exists(p):
                continue
            with np.load(p) as z:
                sk, sv = z["keys"], z["vals"]
            np.savez(p, keys=sk, vals=sv)
            rewritten += 1
        assert rewritten > 0
        before = stats.get("store.spill_corrupt")
        got, found = st.lookup(k)  # cycles every bucket through reload
        assert found.all()
        np.testing.assert_array_equal(got, v)
        assert stats.get("store.spill_corrupt") == before


class TestSparseTableIntegration:
    def test_table_spill_pass_lifecycle(self, tmp_path):
        """A SparseTable configured to spill trains a pass and persists
        identically to an in-RAM table."""
        import jax.numpy as jnp

        from paddlebox_tpu.config import SparseTableConfig
        from paddlebox_tpu.sparse.table import SparseTable

        def run(conf):
            t = SparseTable(conf, seed=0)
            keys = np.arange(1, 200, dtype=np.uint64) * np.uint64(2**55)
            t.begin_pass(keys)
            t.values = t.values.at[0, 2].add(1.0)
            t.end_pass()
            t.begin_pass(keys[::2])
            t.end_pass()
            return t.state_dict()

        a = run(SparseTableConfig(embedding_dim=4, store_buckets=16))
        b = run(SparseTableConfig(
            embedding_dim=4, store_buckets=16,
            store_spill_dir=str(tmp_path / "s"), store_max_resident=2))
        np.testing.assert_array_equal(a["keys"], b["keys"])
        np.testing.assert_allclose(a["values"], b["values"])
