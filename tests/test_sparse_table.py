"""Sparse table: pull/push/update parity vs a numpy oracle + pass lifecycle.

Covers VERDICT item 1: numeric parity for pull/push/update and the
begin_pass -> train -> end_pass -> shrink cycle (reference semantics:
fleet/box_wrapper_impl.h:24-255, box_wrapper.cc:609-673,496-499).
"""

import jax.numpy as jnp
import numpy as np

from paddlebox_tpu.config import SparseTableConfig
from paddlebox_tpu.sparse import SparseTable, pull_rows, push_and_update


def _conf(**kw):
    base = dict(embedding_dim=4, learning_rate=0.1, initial_g2sum=1.0,
                initial_range=0.5, grad_clip=10.0)
    base.update(kw)
    return SparseTableConfig(**base)


def _plan_arrays(plan):
    return (jnp.asarray(plan.idx), jnp.asarray(plan.uniq_idx),
            jnp.asarray(plan.inverse), jnp.asarray(plan.key_mask))


def test_begin_pass_initializes_new_rows():
    t = SparseTable(_conf(), seed=0)
    keys = np.array([7, 3, 3, 99], dtype=np.uint64)
    t.begin_pass(keys)
    assert t.capacity >= 4  # 3 unique + dead row, padded
    vals = np.asarray(t.values)
    # show/clk start at 0; embeddings within init range
    np.testing.assert_allclose(vals[:3, :2], 0.0)
    assert (np.abs(vals[:3, 2:]) <= 0.5).all()
    assert np.abs(vals[:3, 2:]).sum() > 0  # actually initialized
    # dead row zero
    np.testing.assert_allclose(vals[t.dead_row], 0.0)


def test_pull_gathers_and_dead_row_reads_zero():
    t = SparseTable(_conf())
    t.begin_pass(np.array([10, 20, 30], dtype=np.uint64))
    K = 6
    keys = np.zeros(K, dtype=np.uint64)
    keys[:4] = [20, 10, 20, 555]  # 555 not in pass census
    plan = t.plan_keys(keys, 4)
    assert plan.n_missing == 1
    rows = np.asarray(pull_rows(t.values, jnp.asarray(plan.idx)))
    vals = np.asarray(t.values)
    pk = np.array([10, 20, 30], dtype=np.uint64)
    np.testing.assert_allclose(rows[0], vals[np.searchsorted(pk, 20)])
    np.testing.assert_allclose(rows[1], vals[np.searchsorted(pk, 10)])
    np.testing.assert_allclose(rows[3], 0.0)  # missing key
    np.testing.assert_allclose(rows[4:], 0.0)  # padding


def test_push_matches_numpy_adagrad_oracle():
    conf = _conf()
    t = SparseTable(conf, seed=1)
    pk = np.array([5, 9, 14], dtype=np.uint64)
    t.begin_pass(pk)
    v0 = np.asarray(t.values).copy()
    K = 8
    keys = np.zeros(K, dtype=np.uint64)
    batch_keys = [9, 5, 9, 14]  # key 9 occurs twice -> grads must merge
    keys[:4] = batch_keys
    clicks = np.array([1.0, 0.0, 0.0, 1.0])
    plan = t.plan_keys(keys, 4)
    rng = np.random.default_rng(2)
    row_grads = np.zeros((K, conf.row_width), dtype=np.float32)
    row_grads[:4, 2:] = rng.normal(size=(4, 4)).astype(np.float32)
    key_clicks = np.zeros(K, dtype=np.float32)
    key_clicks[:4] = clicks

    idx, uniq_idx, inverse, mask = _plan_arrays(plan)
    new_v, new_g2 = push_and_update(
        t.values, t.g2sum, jnp.asarray(row_grads), idx, uniq_idx, inverse,
        mask, jnp.asarray(key_clicks), conf,
    )
    new_v, new_g2 = np.asarray(new_v), np.asarray(new_g2)

    # numpy oracle
    exp_v, exp_g2 = v0.copy(), np.zeros(v0.shape[0], dtype=np.float32)
    for key in set(batch_keys):
        occ = [i for i, k in enumerate(batch_keys) if k == key]
        row = int(np.searchsorted(pk, key))
        g = row_grads[occ, 2:].sum(axis=0)
        g = np.clip(g, -conf.grad_clip, conf.grad_clip)
        add_g2 = float((g * g).mean())
        scale = conf.learning_rate * np.sqrt(
            conf.initial_g2sum / (conf.initial_g2sum + add_g2)
        )
        exp_v[row, 2:] -= scale * g
        exp_v[row, 0] += len(occ)  # show
        exp_v[row, 1] += clicks[occ].sum()  # clk
        exp_g2[row] += add_g2
    np.testing.assert_allclose(new_v, exp_v, rtol=1e-5, atol=1e-6)
    np.testing.assert_allclose(new_g2, exp_g2, rtol=1e-5, atol=1e-6)
    # dead row still zero
    np.testing.assert_allclose(new_v[t.dead_row], 0.0)


def test_missing_key_grads_do_not_corrupt_dead_row():
    conf = _conf()
    t = SparseTable(conf)
    t.begin_pass(np.array([1], dtype=np.uint64))
    K = 4
    keys = np.zeros(K, dtype=np.uint64)
    keys[:2] = [1, 777]  # 777 missing -> dead row
    plan = t.plan_keys(keys, 2)
    grads = np.ones((K, conf.row_width), dtype=np.float32)
    idx, uniq_idx, inverse, mask = _plan_arrays(plan)
    new_v, new_g2 = push_and_update(
        t.values, t.g2sum, jnp.asarray(grads), idx, uniq_idx, inverse,
        mask, jnp.zeros(K), conf,
    )
    np.testing.assert_allclose(np.asarray(new_v)[t.dead_row], 0.0)
    np.testing.assert_allclose(np.asarray(new_g2)[t.dead_row], 0.0)


def test_pass_roundtrip_persists_and_second_pass_sees_updates():
    conf = _conf()
    t = SparseTable(conf, seed=3)
    t.begin_pass(np.array([2, 4], dtype=np.uint64))
    # manually bump a row as if trained
    t.values = t.values.at[0, 2:].set(7.0)
    t.values = t.values.at[0, 0].add(5.0)  # show
    t.end_pass()
    assert t.n_features == 2
    # next pass: one old key, one new
    t.begin_pass(np.array([2, 8], dtype=np.uint64))
    vals = np.asarray(t.values)
    np.testing.assert_allclose(vals[0, 2:], 7.0)  # key 2 kept its update
    np.testing.assert_allclose(vals[0, 0], 5.0)
    t.end_pass()
    assert t.n_features == 3


def test_create_threshold_hides_cold_embeddings():
    conf = _conf(create_threshold=3.0)
    t = SparseTable(conf, seed=4)
    t.begin_pass(np.array([1, 2], dtype=np.uint64))
    t.values = t.values.at[0, 0].set(5.0)  # key 1 hot
    t.values = t.values.at[1, 0].set(1.0)  # key 2 cold
    t.values = t.values.at[:2, 2:].set(1.5)
    keys = np.array([1, 2], dtype=np.uint64)
    plan = t.plan_keys(keys, 2)
    rows = np.asarray(
        pull_rows(t.values, jnp.asarray(plan.idx), create_threshold=3.0)
    )
    np.testing.assert_allclose(rows[0, 2:], 1.5)  # visible
    np.testing.assert_allclose(rows[1, 2:], 0.0)  # hidden
    np.testing.assert_allclose(rows[1, 0], 1.0)  # counters still visible


def test_shrink_decays_and_evicts():
    conf = _conf(delete_threshold=1.0, show_decay_rate=0.5)
    t = SparseTable(conf)
    t.begin_pass(np.array([1, 2], dtype=np.uint64))
    t.values = t.values.at[0, 0].set(4.0)  # -> 2.0 after decay, kept
    t.values = t.values.at[1, 0].set(1.0)  # -> 0.5 after decay, evicted
    t.end_pass()
    evicted = t.shrink()
    assert evicted == 1
    assert t.n_features == 1
    sd = t.state_dict()
    assert sd["keys"][0] == 1
    np.testing.assert_allclose(sd["values"][0, 0], 2.0)


def test_delta_tracking():
    conf = _conf()
    t = SparseTable(conf, seed=5)
    t.begin_pass(np.array([1, 2], dtype=np.uint64))
    t.end_pass()
    delta = t.pop_delta()
    assert set(delta["keys"].tolist()) == {1, 2}
    t.begin_pass(np.array([2, 3], dtype=np.uint64))
    t.end_pass()
    delta = t.pop_delta()
    assert set(delta["keys"].tolist()) == {2, 3}
    # apply_delta restores rows on a fresh table
    t2 = SparseTable(conf)
    t2.apply_delta(delta)
    assert t2.n_features == 2
