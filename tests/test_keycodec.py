"""Property/fuzz tier for the varint sorted-delta key codec
(utils/keycodec.py): round-trip exactness over adversarial key sets and
LOUD structured failure on any damaged buffer — the wire the multi-host
census and shuffle payloads ride must never short-decode silently."""

import numpy as np
import pytest

from paddlebox_tpu.utils import keycodec as kc


# --------------------------------------------------------------------------- #
# round-trip exactness
# --------------------------------------------------------------------------- #
ADVERSARIAL_SETS = [
    np.empty(0, dtype=np.uint64),
    np.asarray([0], dtype=np.uint64),
    np.asarray([np.iinfo(np.uint64).max], dtype=np.uint64),
    np.asarray([0, np.iinfo(np.uint64).max], dtype=np.uint64),
    # duplicates (zero deltas) — run-heavy
    np.asarray([7] * 100, dtype=np.uint64),
    np.sort(np.asarray([3, 3, 5, 5, 5, 9], dtype=np.uint64)),
    # 2^32 boundary straddlers (the num-key-width family: values a 32-bit
    # truncation would silently fold together)
    np.asarray(
        [(1 << 32) - 2, (1 << 32) - 1, 1 << 32, (1 << 32) + 1,
         (1 << 33), (1 << 53), (1 << 63), (1 << 64) - 1],
        dtype=np.uint64,
    ),
    # every 7-bit group-length boundary
    np.asarray(
        [(1 << (7 * k)) - 1 for k in range(1, 10)]
        + [1 << (7 * k) for k in range(1, 10)],
        dtype=np.uint64,
    ),
    np.arange(1000, dtype=np.uint64) * np.uint64(3),
]


@pytest.mark.parametrize("keys", ADVERSARIAL_SETS, ids=range(len(ADVERSARIAL_SETS)))
def test_sorted_roundtrip_adversarial(keys):
    keys = np.sort(keys)
    enc = kc.encode_sorted_u64(keys)
    out = kc.decode_sorted_u64(enc)
    assert out.dtype == np.uint64
    np.testing.assert_array_equal(out, keys)


def test_sorted_roundtrip_fuzz():
    rng = np.random.default_rng(7)
    for trial in range(50):
        n = int(rng.integers(0, 5000))
        # mix of dense runs, duplicates and full-range outliers
        dense = rng.integers(0, 1 << 20, size=n, dtype=np.uint64)
        wide = rng.integers(0, 1 << 63, size=max(n // 8, 1), dtype=np.uint64)
        keys = np.sort(np.concatenate([dense, wide, dense[: n // 4]]))
        out = kc.decode_sorted_u64(kc.encode_sorted_u64(keys))
        np.testing.assert_array_equal(out, keys)


def test_compression_on_zipf_census():
    """The acceptance bar: a Zipf-distributed census (real CTR traffic's
    shape) compresses >= 4x vs raw 8-byte keys."""
    rng = np.random.default_rng(3)
    draws = rng.zipf(1.3, size=200_000) % (1 << 22)
    census = np.unique(draws.astype(np.uint64))
    enc = kc.encode_sorted_u64(census)
    assert census.nbytes / len(enc) >= 4.0, (
        f"compression {census.nbytes / len(enc):.2f}x < 4x "
        f"({census.shape[0]} keys -> {len(enc)} bytes)"
    )


def test_unsorted_input_raises_structured():
    with pytest.raises(kc.KeyCodecError) as ei:
        kc.encode_sorted_u64(np.asarray([5, 3], dtype=np.uint64))
    assert ei.value.reason == "unsorted-input"


def test_perm_roundtrip_preserves_order():
    rng = np.random.default_rng(11)
    keys = rng.integers(0, 1 << 40, size=777, dtype=np.uint64)
    keys[::5] = keys[0]  # heavy duplicates in arbitrary positions
    enc, rank = kc.encode_u64_with_perm(keys)
    np.testing.assert_array_equal(kc.decode_u64_with_perm(enc, rank), keys)
    # perm length/bounds damage is loud
    with pytest.raises(kc.KeyCodecError):
        kc.decode_u64_with_perm(enc, rank[:-1])
    bad = rank.copy()
    bad[0] = len(keys) + 3
    with pytest.raises(kc.KeyCodecError):
        kc.decode_u64_with_perm(enc, bad)


def test_zigzag_delta_roundtrip():
    rng = np.random.default_rng(23)
    for vals in (
        np.empty(0, dtype=np.int32),
        np.asarray([0, -1, 1, np.iinfo(np.int32).min,
                    np.iinfo(np.int32).max], dtype=np.int32),
        rng.integers(-(1 << 30), 1 << 30, size=4096, dtype=np.int32),
        np.full(2048, 4095, dtype=np.int32),  # dead-row run
    ):
        enc = kc.encode_zigzag_delta(vals)
        out = kc.decode_zigzag_delta(enc, vals.shape[0])
        np.testing.assert_array_equal(out.astype(np.int32), vals)
    # the dead-row run must collapse to ~1 byte/entry (the want-matrix win)
    run = np.full(2048, 4095, dtype=np.int32)
    assert len(kc.encode_zigzag_delta(run)) <= 2048 + 4


# --------------------------------------------------------------------------- #
# damaged buffers: structured, never silent
# --------------------------------------------------------------------------- #
def test_truncated_buffer_every_prefix_is_loud():
    """No prefix of a valid stream may decode to a DIFFERENT key set
    silently — truncation either raises or (never) round-trips."""
    keys = np.sort(
        np.random.default_rng(5).integers(0, 1 << 48, 64, dtype=np.uint64)
    )
    enc = kc.encode_sorted_u64(keys)
    for cut in range(len(enc)):
        with pytest.raises(kc.KeyCodecError) as ei:
            kc.decode_sorted_u64(enc[:cut])
        assert ei.value.reason in ("truncated", "count-mismatch")


def test_trailing_garbage_is_loud():
    enc = kc.encode_sorted_u64(np.asarray([1, 2, 3], dtype=np.uint64))
    with pytest.raises(kc.KeyCodecError) as ei:
        kc.decode_sorted_u64(enc + b"\x01")
    assert ei.value.reason == "trailing-bytes"


def test_overlong_varint_is_loud():
    # 11 continuation-ish bytes: an 11-byte group
    with pytest.raises(kc.KeyCodecError) as ei:
        kc.decode_varints(b"\x80" * 10 + b"\x01")
    assert ei.value.reason == "overlong"
    # a 10-byte group whose last byte encodes >= 2 (> 2^64)
    with pytest.raises(kc.KeyCodecError) as ei:
        kc.decode_varints(b"\x80" * 9 + b"\x02")
    assert ei.value.reason == "overlong"


def test_delta_overflow_is_loud():
    # count=2, first = 2^64-1, delta = 1 -> cumsum wraps
    stream = kc.encode_varints(
        np.asarray([2, (1 << 64) - 1, 1], dtype=np.uint64)
    )
    with pytest.raises(kc.KeyCodecError) as ei:
        kc.decode_sorted_u64(stream)
    assert ei.value.reason == "delta-overflow"


def test_count_mismatch_is_loud():
    with pytest.raises(kc.KeyCodecError) as ei:
        kc.decode_varints(b"\x05\x06", expect=3)
    assert ei.value.reason == "count-mismatch"
    with pytest.raises(kc.KeyCodecError):
        kc.decode_sorted_u64(b"")
