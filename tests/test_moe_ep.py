"""Expert parallelism consumed by a real model (MMoE expert_mesh).

VERDICT r3 weak #8 named parallel/expert.py "equally unintegrated"; these
tests pin the consumable path: MMoE with its expert bank sharded over a
4-way ``expert`` mesh produces the SAME logits and trains end-to-end
through the unmodified multi-task Trainer."""

import jax
import numpy as np
import pytest
from jax.sharding import Mesh

from paddlebox_tpu.config import SparseTableConfig, TrainerConfig
from paddlebox_tpu.data.dataset import PadBoxSlotDataset
from paddlebox_tpu.data.synth import make_synth_config, write_synth_files
from paddlebox_tpu.models import MMoE
from paddlebox_tpu.parallel.expert import EXPERT_AXIS
from paddlebox_tpu.sparse.table import SparseTable
from paddlebox_tpu.train.trainer import Trainer

S, DENSE, B, E = 3, 2, 32, 4


def _mesh():
    return Mesh(np.array(jax.devices()[:4]), (EXPERT_AXIS,))


def _data(tmp_path, n_ins=256):
    conf = make_synth_config(
        n_sparse_slots=S, dense_dim=DENSE, batch_size=B,
        max_feasigns_per_ins=8, n_task_labels=1,
    )
    files = write_synth_files(
        str(tmp_path), n_files=1, ins_per_file=n_ins, n_sparse_slots=S,
        vocab_per_slot=50, dense_dim=DENSE, seed=4, n_task_labels=1,
    )
    ds = PadBoxSlotDataset(conf, read_threads=1)
    ds.set_filelist(files)
    ds.load_into_memory()
    return conf, ds


def test_ep_matches_serial(tmp_path):
    conf, ds = _data(tmp_path)
    tconf = SparseTableConfig(embedding_dim=4)
    kw = dict(dense_dim=DENSE, n_tasks=2, n_experts=E,
              expert_hidden=(16,), expert_dim=8, tower_hidden=(8,))
    serial = MMoE(S, tconf.row_width, **kw)
    sharded = MMoE(S, tconf.row_width, expert_mesh=_mesh(), **kw)
    params = serial.init(jax.random.PRNGKey(1))

    table = SparseTable(tconf, seed=0)
    table.begin_pass(ds.unique_keys())
    batch = next(ds.batches(drop_last=True))
    plan = table.plan_batch(batch)
    from paddlebox_tpu.sparse.table import pull_rows
    from paddlebox_tpu.train.trainer import _device_batch

    dev = _device_batch(batch, plan, S)
    rows = pull_rows(table.values, dev["idx"])
    args = (rows, dev["key_segments"], dev["dense"], B)
    l1 = np.asarray(serial.apply(params, *args))
    l2 = np.asarray(sharded.apply(params, *args))
    table.end_pass()
    ds.close()
    assert l1.shape == (B, 2)
    np.testing.assert_allclose(l1, l2, rtol=2e-5, atol=2e-5)


def test_ep_trains_e2e(tmp_path):
    conf, ds = _data(tmp_path, n_ins=512)
    tconf = SparseTableConfig(embedding_dim=4, learning_rate=0.5,
                              initial_range=0.05)
    model = MMoE(S, tconf.row_width, dense_dim=DENSE, n_tasks=2,
                 n_experts=E, expert_hidden=(16,), expert_dim=8,
                 tower_hidden=(8,), expert_mesh=_mesh())
    table = SparseTable(tconf, seed=0)
    trainer = Trainer(model, tconf,
                      TrainerConfig(dense_lr=3e-3, auc_buckets=1 << 10),
                      seed=0)
    losses = []
    for p in range(3):
        table.begin_pass(ds.unique_keys())
        m = trainer.train_from_dataset(ds, table)
        table.end_pass()
        losses.append(m["loss"])
    ds.close()
    assert np.isfinite(losses).all()
    assert losses[-1] < losses[0]
    assert "task1/auc" in m  # multi-task metric streams intact


def test_ep_validates_divisibility():
    with pytest.raises(ValueError, match="divisible"):
        MMoE(S, 6, n_experts=6, expert_mesh=_mesh())
    with pytest.raises(ValueError, match="axis"):
        MMoE(S, 6, n_experts=4,
             expert_mesh=Mesh(np.array(jax.devices()[:4]), ("data",)))


def test_ep_matches_serial_bf16(tmp_path):
    """Cast-policy parity: the EP path upcasts expert outputs to f32 before
    the gate mixing exactly like the serial mlp() does, so sharded ==
    serial under a bf16 bank too (the review's measured failure case)."""
    conf, ds = _data(tmp_path)
    tconf = SparseTableConfig(embedding_dim=4)
    kw = dict(dense_dim=DENSE, n_tasks=2, n_experts=E, expert_hidden=(16,),
              expert_dim=8, tower_hidden=(8,), compute_dtype="bfloat16")
    serial = MMoE(S, tconf.row_width, **kw)
    sharded = MMoE(S, tconf.row_width, expert_mesh=_mesh(), **kw)
    params = serial.init(jax.random.PRNGKey(2))

    table = SparseTable(tconf, seed=0)
    table.begin_pass(ds.unique_keys())
    batch = next(ds.batches(drop_last=True))
    plan = table.plan_batch(batch)
    from paddlebox_tpu.sparse.table import pull_rows
    from paddlebox_tpu.train.trainer import _device_batch

    dev = _device_batch(batch, plan, S)
    rows = pull_rows(table.values, dev["idx"])
    args = (rows, dev["key_segments"], dev["dense"], B)
    l1 = np.asarray(serial.apply(params, *args))
    l2 = np.asarray(sharded.apply(params, *args))
    table.end_pass()
    ds.close()
    np.testing.assert_allclose(l1, l2, rtol=2e-5, atol=2e-5)
