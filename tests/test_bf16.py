"""Mixed-precision (bf16 compute) tests.

The reference's AMP stack (operators/amp/check_finite_and_unscale_op.cc,
meta_optimizers/amp_optimizer.py) maps to a cast policy on TPU (SURVEY.md
§2.9 "bf16 by default on TPU"): params/optimizer/CVM counters stay f32, the
dense towers compute in bf16.  These tests pin (1) the cast policy at the
layer level and (2) training parity — bf16 reaches an AUC close to the f32
run on the same synth data.
"""

import jax.numpy as jnp
import numpy as np
import pytest

from paddlebox_tpu.config import SparseTableConfig, TrainerConfig
from paddlebox_tpu.data.dataset import PadBoxSlotDataset
from paddlebox_tpu.data.synth import make_synth_config, write_synth_files
from paddlebox_tpu.models import CtrDnn
from paddlebox_tpu.models.layers import init_mlp, mlp, resolve_compute_dtype
from paddlebox_tpu.sparse.table import SparseTable
from paddlebox_tpu.train.trainer import Trainer

import jax


def test_resolve_compute_dtype():
    assert resolve_compute_dtype("float32") is None
    assert resolve_compute_dtype("bf16") == jnp.bfloat16
    assert resolve_compute_dtype("bfloat16") == jnp.bfloat16
    assert resolve_compute_dtype() is None  # flag default is float32
    with pytest.raises(ValueError):
        resolve_compute_dtype("int8")


def test_mlp_bf16_close_to_f32_and_returns_f32():
    key = jax.random.PRNGKey(0)
    params = init_mlp(key, 16, (32, 16), 1)
    x = jax.random.normal(jax.random.PRNGKey(1), (8, 16))
    out32 = mlp(params, x)
    out16 = mlp(params, x, jnp.bfloat16)
    assert out16.dtype == jnp.float32  # logits upcast before the loss
    assert np.allclose(np.asarray(out32), np.asarray(out16), atol=0.15)


def test_bf16_grads_and_params_stay_f32():
    params = init_mlp(jax.random.PRNGKey(0), 8, (16,), 1)
    x = jax.random.normal(jax.random.PRNGKey(1), (4, 8))

    def loss(p):
        return mlp(p, x, jnp.bfloat16).sum()

    grads = jax.grad(loss)(params)
    for leaf in jax.tree.leaves(grads):
        assert leaf.dtype == jnp.float32  # cast transpose restores f32


def _train_auc(tmp_path, compute_dtype, n_passes=3):
    B, S, DENSE = 64, 4, 3
    conf = make_synth_config(
        n_sparse_slots=S, dense_dim=DENSE, batch_size=B,
        max_feasigns_per_ins=16,
    )
    files = write_synth_files(
        str(tmp_path), n_files=2, ins_per_file=256, n_sparse_slots=S,
        vocab_per_slot=100, dense_dim=DENSE, seed=3,
    )
    ds = PadBoxSlotDataset(conf, read_threads=2)
    ds.set_filelist(files)
    ds.load_into_memory()
    tconf = SparseTableConfig(embedding_dim=8)
    trconf = TrainerConfig(auc_buckets=1 << 12, compute_dtype=compute_dtype)
    model = CtrDnn(S, tconf.row_width, dense_dim=DENSE, hidden=(32, 16))
    table = SparseTable(tconf, seed=0)
    trainer = Trainer(model, tconf, trconf, seed=0)
    metrics = {}
    for _ in range(n_passes):
        table.begin_pass(ds.unique_keys())
        metrics = trainer.train_from_dataset(ds, table)
        table.end_pass()
    ds.close()
    return metrics


def test_bf16_training_parity(tmp_path):
    m32 = _train_auc(tmp_path / "f32", "float32")
    m16 = _train_auc(tmp_path / "bf16", "bfloat16")
    assert np.isfinite(m16["loss"])
    # same data, same seeds: bf16 must land in the same quality regime
    assert abs(m32["auc"] - m16["auc"]) < 0.03, (m32["auc"], m16["auc"])
    assert abs(m32["loss"] - m16["loss"]) < 0.05
