"""Online model delivery plane (serving_sync/): publish layout +
donefile-last discipline, syncer delta hot-apply bit-exactness, the
fallback ladder (chain gap / corruption -> full reload -> last-good),
versioned registry lineage + rollback, freshness telemetry."""

import json
import os
import threading

import numpy as np
import pytest

from paddlebox_tpu import telemetry
from paddlebox_tpu.config import SparseTableConfig, TrainerConfig
from paddlebox_tpu.data.dataset import PadBoxSlotDataset
from paddlebox_tpu.data.synth import make_synth_config, write_synth_files
from paddlebox_tpu.inference import Predictor, ScoringServer, export_model
from paddlebox_tpu.models import CtrDnn
from paddlebox_tpu.serving_sync import (
    DONEFILE_NAME,
    ModelRegistry,
    ModelVersion,
    Publisher,
    PublishError,
    PublishEntry,
    Syncer,
    parse_donefile,
)
from paddlebox_tpu.sparse.table import SparseTable
from paddlebox_tpu.train.trainer import Trainer
from paddlebox_tpu.utils import faults
from paddlebox_tpu.utils.faults import fault_plan

S, DENSE, B = 3, 2, 8
KCAP = B * 8


class _Job:
    """A tiny trainable CTR job whose table/params evolve per pass —
    the trainer side of the delivery plane under test."""

    def __init__(self, workdir, seed=0):
        self.workdir = str(workdir)
        self.conf = make_synth_config(
            n_sparse_slots=S, dense_dim=DENSE, batch_size=B,
            max_feasigns_per_ins=8,
        )
        self.tconf = SparseTableConfig(embedding_dim=4)
        self.model = CtrDnn(S, self.tconf.row_width, dense_dim=DENSE,
                            hidden=(8,))
        self.table = SparseTable(self.tconf, seed=seed)
        self.trainer = Trainer(self.model, self.tconf,
                               TrainerConfig(auc_buckets=1 << 10), seed=seed)

    def train_pass(self, i):
        files = write_synth_files(
            os.path.join(self.workdir, f"d{i}"), n_files=1, ins_per_file=32,
            n_sparse_slots=S, vocab_per_slot=60, dense_dim=DENSE,
            seed=100 + i,
        )
        ds = PadBoxSlotDataset(self.conf, read_threads=1)
        ds.set_filelist(files)
        ds.load_into_memory()
        self.table.begin_pass(ds.unique_keys())
        self.trainer.train_from_dataset(ds, self.table)
        self.table.end_pass()
        ds.close()

    def publisher(self, root, **kw):
        return Publisher(
            root, staging_dir=os.path.join(self.workdir, "stage"), **kw
        )

    def publish_base(self, pub, tag, **kw):
        return pub.publish_base(
            tag, self.model, self.trainer.params, self.table,
            batch_size=B, key_capacity=KCAP, dense_dim=DENSE,
            feed_conf=self.conf, **kw,
        )

    def fresh_artifact(self, out):
        export_model(
            self.model, self.trainer.params, self.table, out,
            batch_size=B, key_capacity=KCAP, dense_dim=DENSE,
            feed_conf=self.conf,
        )
        return out


def _lines(n, seed=5, vocab=60):
    rng = np.random.default_rng(seed)
    out = []
    for _ in range(n):
        parts = ["1 0"]
        for _s in range(S):
            ks = rng.integers(0, vocab, 2)
            parts.append(f"{len(ks)} " + " ".join(map(str, ks)))
        parts.append(f"{DENSE} " + " ".join(
            f"{v:.3f}" for v in rng.random(DENSE)))
        out.append(" ".join(parts))
    return ("\n".join(out) + "\n").encode()


def _syncer(root, srv, tmp_path, **kw):
    return Syncer(root, srv, "live",
                  cache_dir=str(tmp_path / "cache"),
                  poll_interval_s=0.05, **kw)


# --------------------------------------------------------------------------- #
# publisher: layout, donefile-last, failure atomicity
# --------------------------------------------------------------------------- #
def test_publish_layout_and_sequenced_donefile(tmp_path):
    job = _Job(tmp_path)
    root = str(tmp_path / "pub")
    pub = job.publisher(root)
    job.train_pass(0)
    e0 = job.publish_base(pub, "p0")
    job.train_pass(1)
    e1 = pub.publish_delta("p1", job.table)  # sparse-only delta
    assert (e0.seq, e0.kind, e0.base_tag) == (0, "base", "p0")
    assert (e1.seq, e1.kind, e1.base_tag, e1.prev_tag) == (
        1, "delta", "p0", "p0")
    assert not e1.has_programs and e1.n_rows > 0
    # layout: data dirs with manifests, donefile last
    assert os.path.isdir(os.path.join(root, "base-p0", "sparse"))
    assert os.path.exists(os.path.join(root, "base-p0", "manifest.json"))
    assert os.path.exists(
        os.path.join(root, "delta-p1", "sparse_delta.npz"))
    assert os.path.exists(os.path.join(root, "delta-p1", "manifest.json"))
    with open(os.path.join(root, DONEFILE_NAME), "rb") as fh:
        entries = parse_donefile(fh.read())
    assert [e.seq for e in entries] == [0, 1]
    # the recursive artifact manifest really covers the sparse snapshot
    with open(os.path.join(root, "base-p0", "manifest.json")) as fh:
        files = json.load(fh)["files"]
    assert any(name.startswith("sparse/") for name in files)

    # resume: a new Publisher over the same root continues the sequence
    pub2 = job.publisher(root)
    assert pub2.next_seq == 2 and pub2.base_tag == "p0"
    assert pub2.last_tag == "p1"


def test_failed_delta_publish_keeps_tracker_and_donefile(tmp_path,
                                                         monkeypatch):
    """Donefile-last under injected upload failure: the failed delta never
    becomes visible, its rows stay tracked, and the retried publish ships
    them (at-least-once delivery of every touched row)."""
    monkeypatch.setenv("PBOX_RETRY_MAX_ATTEMPTS", "1")
    monkeypatch.setenv("PBOX_RETRY_BASE_DELAY_S", "0.01")
    job = _Job(tmp_path)
    root = str(tmp_path / "pub")
    pub = job.publisher(root)
    job.train_pass(0)
    job.publish_base(pub, "p0")
    job.train_pass(1)
    n_tracked = job.table.delta_state_dict()["keys"].shape[0]
    assert n_tracked > 0
    with fault_plan({"publish.delta": "first:1"}):
        with pytest.raises(faults.FaultInjected):
            pub.publish_delta("p1", job.table)
    # not visible, rows not lost
    with open(os.path.join(root, DONEFILE_NAME), "rb") as fh:
        assert len(parse_donefile(fh.read())) == 1
    assert job.table.delta_state_dict()["keys"].shape[0] == n_tracked
    # retry publishes the same rows under the next sequence number
    e = pub.publish_delta("p1", job.table)
    assert e.seq == 1 and e.n_rows == n_tracked
    assert job.table.delta_state_dict()["keys"].shape[0] == 0


def test_delta_without_base_refused(tmp_path):
    job = _Job(tmp_path)
    pub = job.publisher(str(tmp_path / "pub"))
    job.train_pass(0)
    with pytest.raises(PublishError, match="publish_base first"):
        pub.publish_delta("p0", job.table)


def test_publish_health_gate(tmp_path):
    from paddlebox_tpu.utils.fleet_util import HealthPolicy, ModelMonitor

    job = _Job(tmp_path)
    pub = job.publisher(str(tmp_path / "pub"),
                        monitor=ModelMonitor(HealthPolicy(min_auc=0.5)))
    job.train_pass(0)
    gated = telemetry.counter("publish.gated")
    before = gated.value()
    assert job.publish_base(pub, "p0", metrics={"auc": 0.2,
                                                "loss": 0.5}) is None
    assert gated.value() == before + 1
    assert pub.next_seq == 0  # nothing shipped
    assert job.publish_base(pub, "p0", metrics={"auc": 0.7,
                                                "loss": 0.5}) is not None


# --------------------------------------------------------------------------- #
# syncer: bit-exact hot apply (the acceptance criterion, k = 3)
# --------------------------------------------------------------------------- #
def test_sync_base_plus_deltas_bit_exact(tmp_path):
    """A server that applied base + 3 deltas scores IDENTICALLY to one
    that loaded a full export at the same pass — and its resolved
    key/value arrays are bit-equal to the fresh snapshot's."""
    job = _Job(tmp_path)
    root = str(tmp_path / "pub")
    pub = job.publisher(root)
    job.train_pass(0)
    job.publish_base(pub, "p0")
    base_features = job.table.n_features
    for i in range(1, 4):
        job.train_pass(i)
        assert pub.publish_delta(
            f"p{i}", job.table, job.model, job.trainer.params
        ).has_programs

    srv = ScoringServer()
    sync = _syncer(root, srv, tmp_path)
    assert sync.poll_once() == 4
    version = sync.registry.current_version("live")
    assert version.base_tag == "p0" and version.deltas_applied == 3
    assert version.tag == "p3" and version.seq == 3

    fresh = Predictor.load(job.fresh_artifact(str(tmp_path / "full")))
    live = srv._models["live"].predictor
    # the delta chain inserted genuinely-new keys, not just updates
    assert live.n_features > base_features
    np.testing.assert_array_equal(live._keys, fresh._keys)
    np.testing.assert_array_equal(live._values, fresh._values)

    body = _lines(23)  # multiple chunks
    synced = srv.score_lines(body, "live")
    srv2 = ScoringServer()
    srv2.register("fresh", str(tmp_path / "full"))
    assert synced == srv2.score_lines(body, "fresh")  # exact, not approx

    # freshness telemetry: fully caught up, age measured from publish
    assert telemetry.gauge("sync.lag_passes").value(model="live") == 0
    assert telemetry.gauge(
        "serve.model_age_seconds").value(model="live") >= 0.0
    # a second poll with nothing new applies nothing
    assert sync.poll_once() == 0


def test_sparse_only_delta_updates_rows_keeps_programs(tmp_path):
    """A delta published without model/params ships rows only: the live
    predictor's sparse snapshot updates, the program objects are shared
    with the previous version (dense intentionally stale)."""
    job = _Job(tmp_path)
    root = str(tmp_path / "pub")
    pub = job.publisher(root)
    job.train_pass(0)
    job.publish_base(pub, "p0")
    srv = ScoringServer()
    sync = _syncer(root, srv, tmp_path)
    sync.poll_once()
    before = srv._models["live"].predictor
    job.train_pass(1)
    pub.publish_delta("p1", job.table)
    assert sync.poll_once() == 1
    after = srv._models["live"].predictor
    assert after is not before  # build-aside, atomic swap
    assert after._programs is before._programs  # shared program cache
    assert not np.array_equal(after._values[: before.n_features],
                              before._values)
    # rows match the live table (full-row replace semantics)
    state = job.table.state_dict()
    w = job.tconf.row_width
    np.testing.assert_array_equal(after._keys, state["keys"])
    np.testing.assert_array_equal(
        after._values, np.asarray(state["values"], np.float32)[:, :w])


# --------------------------------------------------------------------------- #
# fallback ladder
# --------------------------------------------------------------------------- #
def _corrupt(path):
    data = bytearray(open(path, "rb").read())
    data[len(data) // 2] ^= 0xFF
    with open(path, "wb") as fh:
        fh.write(bytes(data))


def test_corrupt_delta_full_reload_no_failed_scores(tmp_path):
    """The acceptance chaos path: a torn/corrupted delta (donefile entry
    whose remote bytes are wrong) must trigger the full-reload fallback
    (counter increments), keep serving the last-good chain, and fail ZERO
    score requests while the syncer churns."""
    job = _Job(tmp_path)
    root = str(tmp_path / "pub")
    pub = job.publisher(root)
    job.train_pass(0)
    job.publish_base(pub, "p0")
    job.train_pass(1)
    pub.publish_delta("p1", job.table, job.model, job.trainer.params)
    srv = ScoringServer()
    sync = _syncer(root, srv, tmp_path)
    sync.poll_once()
    good_keys = srv._models["live"].predictor._keys.copy()

    job.train_pass(2)
    pub.publish_delta("p2", job.table, job.model, job.trainer.params)
    _corrupt(os.path.join(root, "delta-p2", "sparse_delta.npz"))

    body = _lines(5)
    want = srv.score_lines(body, "live")
    failures, stop = [], threading.Event()

    def hammer():
        while not stop.is_set():
            try:
                got = srv.score_lines(body, "live")
                if len(got) != 5 or not all(0.0 < s < 1.0 for s in got):
                    failures.append(got)
            except Exception as e:  # any exception = a failed request
                failures.append(e)

    threads = [threading.Thread(target=hammer) for _ in range(2)]
    for t in threads:
        t.start()
    fallback = telemetry.counter("sync.full_reload_fallback")
    base = fallback.value()
    try:
        advanced = sync.poll_once()
    finally:
        stop.set()
        for t in threads:
            t.join()
    assert not failures  # zero failed requests during the churn
    assert advanced == 0  # p2 unusable; chain held at p1
    assert fallback.value() == base + 1
    live = srv._models["live"].predictor
    np.testing.assert_array_equal(live._keys, good_keys)
    assert srv.score_lines(body, "live") == want
    assert sync.registry.current_version("live").tag == "p1"
    # lag telemetry names the unapplied entry
    assert telemetry.gauge("sync.lag_passes").value(model="live") == 1

    # repair (re-upload the staged copy) and the next poll catches up
    from paddlebox_tpu.utils.fs import LocalFS

    LocalFS().upload(os.path.join(str(tmp_path), "stage", "delta-p2"),
                     os.path.join(root, "delta-p2"))
    assert sync.poll_once() == 1
    assert sync.registry.current_version("live").tag == "p2"


def test_chain_gap_triggers_full_reload(tmp_path):
    """A donefile whose chain skips an entry (gap) must full-reload from
    the newest base instead of applying deltas out of order."""
    job = _Job(tmp_path)
    root = str(tmp_path / "pub")
    pub = job.publisher(root)
    job.train_pass(0)
    job.publish_base(pub, "p0")
    job.train_pass(1)
    pub.publish_delta("p1", job.table, job.model, job.trainer.params)
    job.train_pass(2)
    pub.publish_delta("p2", job.table, job.model, job.trainer.params)
    # doctor the donefile: drop p1's entry -> p2 no longer chains
    done = os.path.join(root, DONEFILE_NAME)
    with open(done, "rb") as fh:
        entries = parse_donefile(fh.read())
    with open(done, "w") as fh:
        for e in entries:
            if e.tag != "p1":
                fh.write(e.to_json() + "\n")

    srv = ScoringServer()
    sync = _syncer(root, srv, tmp_path)
    gaps = telemetry.counter("sync.chain_gap")
    before = gaps.value()
    sync.poll_once()
    assert gaps.value() == before + 1
    # the reload walks the (broken) chain as far as it links: base only
    assert sync.registry.current_version("live").tag == "p0"
    assert srv.score_lines(_lines(3), "live")  # still serving


def test_injected_sync_faults_absorbed_and_counted(tmp_path, monkeypatch):
    """The registered fault sites fire: sync.poll transients are absorbed
    by the retry loop; a sync.apply fault falls back to full reload and
    the delivery still converges (chaos spec for the new sites)."""
    monkeypatch.setenv("PBOX_RETRY_BASE_DELAY_S", "0.01")
    job = _Job(tmp_path)
    root = str(tmp_path / "pub")
    pub = job.publisher(root)
    job.train_pass(0)
    job.publish_base(pub, "p0")
    srv = ScoringServer()
    sync = _syncer(root, srv, tmp_path)
    for site in ("sync.poll", "sync.apply", "publish.delta"):
        assert site in faults.KNOWN_SITES
    fallback = telemetry.counter("sync.full_reload_fallback")
    base = fallback.value()
    with fault_plan({"sync.poll": "first:1", "sync.apply": "first:1"}):
        assert sync.poll_once() == 1  # converged despite both faults
    assert fallback.value() == base + 1  # the apply fault took the ladder
    from paddlebox_tpu.utils.monitor import stats

    assert stats.get("retry.sync.poll.retries") >= 1
    assert sync.registry.current_version("live").base_tag == "p0"


def test_rollback_restores_previous_version(tmp_path):
    job = _Job(tmp_path)
    root = str(tmp_path / "pub")
    pub = job.publisher(root)
    job.train_pass(0)
    job.publish_base(pub, "p0")
    srv = ScoringServer()
    sync = _syncer(root, srv, tmp_path)
    sync.poll_once()
    p0_pred = srv._models["live"].predictor
    job.train_pass(1)
    pub.publish_delta("p1", job.table, job.model, job.trainer.params)
    sync.poll_once()
    assert srv._models["live"].predictor is not p0_pred
    restored = sync.rollback()
    assert restored.tag == "p0"
    assert srv._models["live"].predictor is p0_pred
    assert srv.model_version("live")["tag"] == "p0"
    # nothing older to roll back to
    with pytest.raises(LookupError):
        sync.rollback()


# --------------------------------------------------------------------------- #
# registry + donefile format units
# --------------------------------------------------------------------------- #
def test_parse_donefile_torn_tail_and_corruption():
    good = PublishEntry(seq=0, kind="base", tag="t0", dir="base-t0",
                        base_tag="t0", prev_tag=None, published_at=1.0)
    blob = (good.to_json() + "\n").encode()
    torn = blob + b'{"seq": 1, "kind": "del'
    entries = parse_donefile(torn)
    assert len(entries) == 1 and entries[0].tag == "t0"
    with pytest.raises(ValueError):
        parse_donefile(torn, strict=True)
    # garbage mid-file (entries after it) is corruption, never "torn"
    with pytest.raises(ValueError):
        parse_donefile(b"not json\n" + blob)


def test_registry_history_bounded_and_lineage():
    reg = ModelRegistry(keep_versions=2)
    preds = [object() for _ in range(4)]
    v = ModelVersion(name="m", base_tag="b0", seq=0, published_at=1.0)
    reg.commit("m", v, preds[0])
    for i, e in enumerate([
        PublishEntry(seq=1, kind="delta", tag="d1", dir="x", base_tag="b0",
                     prev_tag="b0", published_at=2.0),
        PublishEntry(seq=2, kind="delta", tag="d2", dir="x", base_tag="b0",
                     prev_tag="d1", published_at=3.0),
        PublishEntry(seq=3, kind="delta", tag="d3", dir="x", base_tag="b0",
                     prev_tag="d2", published_at=4.0),
    ]):
        v = v.extend(e)
        reg.commit("m", v, preds[i + 1])
    assert reg.lineage("m")["deltas_applied"] == 3
    # history bounded at 2: d3 -> d2 -> d1, then exhausted (d1's
    # predecessor b0 was evicted)
    assert reg.rollback("m")[0].tag == "d2"
    assert reg.rollback("m")[0].tag == "d1"
    with pytest.raises(LookupError):
        reg.rollback("m")


def test_version_extend_rejects_base():
    v = ModelVersion(name="m", base_tag="b0")
    with pytest.raises(ValueError):
        v.extend(PublishEntry(seq=1, kind="base", tag="b1", dir="x",
                              base_tag="b1", prev_tag="b0",
                              published_at=1.0))


# --------------------------------------------------------------------------- #
# background agent resilience: the sync thread must never die silently
# (PR-7 satellite: an escaped exception restarts the loop with backoff)
# --------------------------------------------------------------------------- #
def _wait_until(cond, timeout_s=10.0, interval_s=0.01):
    import time

    deadline = time.monotonic() + timeout_s
    while time.monotonic() < deadline:
        if cond():
            return True
        time.sleep(interval_s)
    return cond()


def test_agent_survives_poll_exhaustion_and_marks_degraded(
        tmp_path, monkeypatch):
    """A sync.poll fault that exhausts the retry budget on EVERY tick:
    the agent thread must stay alive (counting sync.poll_errors, backing
    off), advertise degraded on the server past the threshold, then
    recover and clear the flag once the fault lifts."""
    monkeypatch.setenv("PBOX_RETRY_BASE_DELAY_S", "0.001")
    monkeypatch.setenv("PBOX_RETRY_MAX_ATTEMPTS", "2")
    srv = ScoringServer()
    sync = Syncer(str(tmp_path / "pub"), srv, "live",
                  cache_dir=str(tmp_path / "cache"),
                  poll_interval_s=0.01, degraded_after_failures=2)
    errors = telemetry.counter("sync.poll_errors")
    exhausted_base = errors.value()
    plan_cm = fault_plan({"sync.poll": "first:100000"})
    plan_cm.__enter__()
    try:
        sync.start()
        assert _wait_until(lambda: errors.value() >= exhausted_base + 3)
        assert sync._thread.is_alive()  # the loop absorbed every failure
        assert _wait_until(
            lambda: "sync:live" in srv.degraded_reasons())
    finally:
        plan_cm.__exit__(None, None, None)
    # fault lifted: the next clean tick clears the degraded flag and the
    # agent is still the SAME thread — it never died, never restarted
    restarts = telemetry.counter("sync.agent_restarts")
    r_base = restarts.value()
    assert _wait_until(
        lambda: "sync:live" not in srv.degraded_reasons(), timeout_s=20)
    assert sync._thread.is_alive()
    assert restarts.value() == r_base
    sync.stop()


def test_agent_outer_guard_restarts_dead_loop(tmp_path, monkeypatch):
    """Even an exception ESCAPING the inner loop (its own error handling
    raising, a BaseException) must not kill background sync: the outer
    guard logs, counts sync.agent_restarts and restarts the loop."""
    srv = ScoringServer()
    sync = Syncer(str(tmp_path / "pub"), srv, "live",
                  cache_dir=str(tmp_path / "cache"), poll_interval_s=0.01)
    real_loop = sync._agent_loop
    calls = {"n": 0}

    def flaky_loop():
        calls["n"] += 1
        if calls["n"] <= 2:
            raise SystemExit("escaped the inner loop")  # worst case
        real_loop()

    monkeypatch.setattr(sync, "_agent_loop", flaky_loop)
    restarts = telemetry.counter("sync.agent_restarts")
    base = restarts.value()
    sync.start()
    assert _wait_until(lambda: restarts.value() >= base + 2)
    # third incarnation runs the REAL loop: polls tick cleanly (empty
    # root => 0 entries) and the thread stays up
    assert _wait_until(lambda: calls["n"] >= 3)
    assert sync._thread.is_alive()
    sync.stop()
    assert not sync._thread  # stop() joined and cleared it


def test_syncer_lag_marks_degraded(tmp_path):
    """A syncer that falls behind the donefile (lag > threshold) must
    advertise degraded while still serving, and clear on catch-up."""
    from paddlebox_tpu.serving_sync.registry import PublishEntry as PE

    srv = ScoringServer()
    sync = Syncer(str(tmp_path / "pub"), srv, "live",
                  cache_dir=str(tmp_path / "cache"),
                  degraded_lag_entries=2)
    entries = [
        PE(seq=i, kind="delta", tag=f"t{i}", dir=f"d{i}", base_tag="b",
           prev_tag=f"t{i - 1}", published_at=1.0)
        for i in range(5)
    ]
    sync._update_gauges(entries)  # applied_seq=-1 -> lag 5 > 2
    assert "sync_lag:live" in srv.degraded_reasons()
    sync._applied_seq = 4
    sync._update_gauges(entries)  # caught up -> lag 0
    assert "sync_lag:live" not in srv.degraded_reasons()
