"""Export/serving tests: train -> export_model -> Predictor parity."""

import os

import numpy as np
import pytest

from paddlebox_tpu.config import SparseTableConfig, TrainerConfig
from paddlebox_tpu.data.dataset import PadBoxSlotDataset
from paddlebox_tpu.data.synth import make_synth_config, write_synth_files
from paddlebox_tpu.inference import Predictor, export_model
from paddlebox_tpu.models import CtrDnn
from paddlebox_tpu.sparse.table import SparseTable
from paddlebox_tpu.train.trainer import Trainer

S, DENSE, B = 3, 2, 8


def _train_small(td, create_threshold=0.0):
    conf = make_synth_config(
        n_sparse_slots=S, dense_dim=DENSE, batch_size=B, max_feasigns_per_ins=16
    )
    files = write_synth_files(
        td, n_files=1, ins_per_file=64, n_sparse_slots=S, vocab_per_slot=50,
        dense_dim=DENSE, seed=11,
    )
    ds = PadBoxSlotDataset(conf, read_threads=1)
    ds.set_filelist(files)
    ds.load_into_memory()
    tconf = SparseTableConfig(
        embedding_dim=8, create_threshold=create_threshold
    )
    trconf = TrainerConfig(auc_buckets=1 << 10)
    model = CtrDnn(S, tconf.row_width, dense_dim=DENSE, hidden=(16, 8))
    table = SparseTable(tconf, seed=0)
    trainer = Trainer(model, tconf, trconf, seed=0)
    table.begin_pass(ds.unique_keys())
    trainer.train_from_dataset(ds, table)
    table.end_pass()
    return conf, ds, model, table, trainer


def test_export_predict_parity(tmp_path):
    """Predictor output == trainer-side forward on the same batch."""
    import jax
    import jax.numpy as jnp

    conf, ds, model, table, trainer = _train_small(str(tmp_path / "data"))
    art = str(tmp_path / "artifact")
    kcap = conf.batch_key_capacity or (B * conf.max_feasigns_per_ins)
    export_model(
        model, trainer.params, table, art,
        batch_size=B, key_capacity=kcap, dense_dim=DENSE,
    )
    assert os.path.exists(os.path.join(art, "serving.stablehlo"))
    assert os.path.exists(os.path.join(art, "meta.json"))

    pred = Predictor.load(art)
    batch = next(ds.batches(drop_last=False))
    got = pred.predict(batch)
    assert got.shape[0] == int(batch.ins_mask.sum())

    # trainer-side reference forward: resolve rows through the live table
    table.begin_pass(table.state_dict()["keys"])
    plan = table.plan_batch(batch)
    from paddlebox_tpu.sparse.table import pull_rows

    rows = pull_rows(table.values, jnp.asarray(plan.idx))
    logits = model.apply(
        trainer.params, rows, jnp.asarray(batch.key_segments),
        jnp.asarray(batch.dense), B,
    )
    want = np.asarray(jax.nn.sigmoid(logits))[: got.shape[0]]
    table.end_pass()
    ds.close()
    np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-6)


def test_predict_unseen_keys_and_batch_size_guard(tmp_path):
    conf, ds, model, table, trainer = _train_small(str(tmp_path / "data"))
    art = str(tmp_path / "artifact")
    kcap = conf.batch_key_capacity or (B * conf.max_feasigns_per_ins)
    export_model(
        model, trainer.params, table, art,
        batch_size=B, key_capacity=kcap, dense_dim=DENSE,
    )
    pred = Predictor.load(art)
    batch = next(ds.batches(drop_last=False))
    # poison the keys: unseen features must resolve to zero rows, not crash
    batch.keys = batch.keys + np.uint64(10_000_000)
    out = pred.predict(batch)
    assert np.all(np.isfinite(out)) and out.shape[0] > 0

    # a request whose REAL instance/key counts exceed every exported
    # bucket must be rejected with actionable guidance (shape flexibility
    # covers anything smaller via padding, not anything larger)
    with pytest.raises(ValueError):
        pred._pick_bucket(B + 1, 0)
    kcap = pred.meta["key_capacity"]
    with pytest.raises(ValueError):
        pred._pick_bucket(1, kcap + 1)
    ds.close()


def test_predict_rejects_schema_mismatch(tmp_path):
    """A batch built under a different feed schema must be rejected up
    front (ADVICE r4: wrong slot count silently scored garbage — segment
    ids ins*S+slot computed under the wrong S; wider seq feeds silently
    dropped behavior history)."""
    conf, ds, model, table, trainer = _train_small(str(tmp_path / "data"))
    art = str(tmp_path / "artifact")
    kcap = conf.batch_key_capacity or (B * conf.max_feasigns_per_ins)
    export_model(
        model, trainer.params, table, art,
        batch_size=B, key_capacity=kcap, dense_dim=DENSE,
    )
    pred = Predictor.load(art)
    ds.close()

    def batch_from(n_slots, dense_dim):
        c = make_synth_config(
            n_sparse_slots=n_slots, dense_dim=dense_dim, batch_size=B,
            max_feasigns_per_ins=16,
        )
        files = write_synth_files(
            str(tmp_path / f"d{n_slots}x{dense_dim}"), n_files=1,
            ins_per_file=B, n_sparse_slots=n_slots, vocab_per_slot=50,
            dense_dim=dense_dim, seed=3,
        )
        d = PadBoxSlotDataset(c, read_threads=1)
        d.set_filelist(files)
        d.load_into_memory()
        b = next(d.batches(drop_last=False))
        d.close()
        return b

    with pytest.raises(ValueError, match="sparse slots"):
        pred.predict(batch_from(S + 1, DENSE))
    with pytest.raises(ValueError, match="dense"):
        pred.predict(batch_from(S, DENSE + 2))


def test_predict_rejects_seq_len_mismatch(tmp_path):
    """Serving raises on a seq-width mismatch exactly like training does,
    instead of silently truncating behavior history (ADVICE r4)."""
    from paddlebox_tpu.models import LongSeqCtrDnn

    T = 8

    def data(seq_len, tag):
        c = make_synth_config(
            n_sparse_slots=S, dense_dim=DENSE, batch_size=B,
            max_feasigns_per_ins=16, sequence_slot="slot0",
            max_seq_len=seq_len,
        )
        files = write_synth_files(
            str(tmp_path / tag), n_files=1, ins_per_file=32,
            n_sparse_slots=S, vocab_per_slot=50, dense_dim=DENSE, seed=11,
            max_keys_per_slot=6,
        )
        d = PadBoxSlotDataset(c, read_threads=1)
        d.set_filelist(files)
        d.load_into_memory()
        return c, d

    conf, ds = data(T, "train")
    tconf = SparseTableConfig(embedding_dim=8)
    model = LongSeqCtrDnn(S, tconf.row_width, dense_dim=DENSE, hidden=(8,),
                          max_seq_len=T, n_heads=2, head_dim=4)
    table = SparseTable(tconf, seed=0)
    trainer = Trainer(model, tconf, TrainerConfig(auc_buckets=1 << 10), seed=0)
    table.begin_pass(ds.unique_keys())
    trainer.train_from_dataset(ds, table)
    table.end_pass()
    art = str(tmp_path / "artifact")
    kcap = conf.batch_key_capacity or (B * conf.max_feasigns_per_ins)
    export_model(model, trainer.params, table, art,
                 batch_size=B, key_capacity=kcap, dense_dim=DENSE)
    pred = Predictor.load(art)
    # matching width serves fine
    out = pred.predict(next(ds.batches(drop_last=False)))
    assert np.all(np.isfinite(out))
    ds.close()
    # a WIDER feed (more history than the artifact was exported for) must
    # raise, not silently slice
    _, ds_wide = data(2 * T, "wide")
    with pytest.raises(ValueError, match="seq_len"):
        pred.predict(next(ds_wide.batches(drop_last=False)))
    ds_wide.close()


def test_predict_dataset_streams_all(tmp_path):
    conf, ds, model, table, trainer = _train_small(str(tmp_path / "data"))
    art = str(tmp_path / "artifact")
    kcap = conf.batch_key_capacity or (B * conf.max_feasigns_per_ins)
    export_model(
        model, trainer.params, table, art,
        batch_size=B, key_capacity=kcap, dense_dim=DENSE,
    )
    pred = Predictor.load(art)
    total = sum(p.shape[0] for p in pred.predict_dataset(ds))
    assert total == 64
    ds.close()


def test_quantized_export_close_and_smaller(tmp_path):
    """int8 embedx snapshot: predictions close to the f32 artifact, sparse
    payload ~4x smaller."""
    conf, ds, model, table, trainer = _train_small(str(tmp_path / "data"))
    kcap = conf.batch_key_capacity or (B * conf.max_feasigns_per_ins)
    art_f, art_q = str(tmp_path / "f32"), str(tmp_path / "q8")
    for art, quant in ((art_f, False), (art_q, True)):
        export_model(
            model, trainer.params, table, art,
            batch_size=B, key_capacity=kcap, dense_dim=DENSE, quantize=quant,
        )
    pf, pq = Predictor.load(art_f), Predictor.load(art_q)
    batch = next(ds.batches(drop_last=False))
    a, b2 = pf.predict(batch), pq.predict(batch)
    np.testing.assert_allclose(a, b2, atol=2e-2)  # int8 quant noise only
    ds.close()

    def sparse_bytes(art):
        d = os.path.join(art, "sparse")
        return sum(
            os.path.getsize(os.path.join(d, f))
            for f in os.listdir(d)
            if not f.startswith("keys")
        )

    # row: 3 f32 head cols + 5 int8 embedx vs 8 f32 cols -> ~0.53x here;
    # production rows (embedx >> head) approach 0.25x
    assert sparse_bytes(art_q) < 0.6 * sparse_bytes(art_f)


def test_rank_model_export_roundtrip(tmp_path):
    """RankCtrDnn (rank_offset-consuming) exports with the rank matrix as a
    fourth program input and predicts on PV-merged batches."""
    from paddlebox_tpu.models import RankCtrDnn

    conf = make_synth_config(
        n_sparse_slots=S, dense_dim=DENSE, batch_size=B,
        max_feasigns_per_ins=16, parse_logkey=True, enable_pv_merge=True,
        pv_batch_size=4, rank_cmatch_filter=(222, 223),
    )
    files = write_synth_files(
        str(tmp_path / "pv"), n_files=1, ins_per_file=48, n_sparse_slots=S,
        vocab_per_slot=50, dense_dim=DENSE, seed=4, with_logkey=True,
        max_ads_per_pv=3,
    )
    ds = PadBoxSlotDataset(conf, read_threads=1)
    ds.set_filelist(files)
    ds.load_into_memory()
    ds.preprocess_instance()
    tconf = SparseTableConfig(embedding_dim=8)
    model = RankCtrDnn(
        S, tconf.row_width, dense_dim=DENSE, hidden=(16, 8),
        max_rank=conf.max_rank,
    )
    table = SparseTable(tconf, seed=0)
    trainer = Trainer(model, tconf, TrainerConfig(auc_buckets=1 << 10))
    table.begin_pass(ds.unique_keys())
    trainer.train_from_dataset(ds, table)
    table.end_pass()

    art = str(tmp_path / "artifact")
    kcap = conf.batch_key_capacity or (B * conf.max_feasigns_per_ins)
    export_model(
        model, trainer.params, table, art,
        batch_size=next(ds.batches()).batch_size,
        key_capacity=kcap, dense_dim=DENSE,
        rank_offset_cols=conf.rank_offset_cols,
    )
    pred = Predictor.load(art)
    batch = next(ds.batches(drop_last=False))
    out = pred.predict(batch)
    assert out.shape[0] == int(batch.ins_mask.sum())
    assert np.all(np.isfinite(out))
    # without the rank matrix the artifact must refuse
    batch.rank_offset = None
    with pytest.raises(ValueError, match="rank_offset"):
        pred.predict(batch)
    ds.close()


def test_export_respects_create_threshold(tmp_path):
    """Feature admission carries into serving: under-shown features read
    zero embeddings through the predictor's host resolve."""
    conf, ds, model, table, trainer = _train_small(
        str(tmp_path / "data"), create_threshold=1e9  # nothing admitted
    )
    art = str(tmp_path / "artifact")
    kcap = conf.batch_key_capacity or (B * conf.max_feasigns_per_ins)
    export_model(
        model, trainer.params, table, art,
        batch_size=B, key_capacity=kcap, dense_dim=DENSE,
    )
    pred = Predictor.load(art)
    batch = next(ds.batches(drop_last=False))
    rows = pred._resolve_rows(
        batch.keys, batch.n_keys, pred.meta["key_capacity"]
    )
    co = pred.meta["cvm_offset"]
    assert np.all(rows[:, co:] == 0.0)  # embeddings hidden
    assert rows[:, :co].any()  # counters still visible
    ds.close()


def test_shape_buckets_serve_any_smaller_batch(tmp_path):
    """VERDICT r3 missing #5: the artifact serves batches of ANY real size
    that fits a bucket — scores are bucket-invariant (padding rows are zero
    and padding segments drop out of the pooling segment_sum)."""
    conf, ds, model, table, trainer = _train_small(str(tmp_path / "data"))
    kcap = conf.batch_key_capacity or (B * conf.max_feasigns_per_ins)
    art = str(tmp_path / "artifact")
    export_model(
        model, trainer.params, table, art,
        batch_size=B, key_capacity=kcap, dense_dim=DENSE,
        batch_buckets=[(B // 2, kcap // 2), (2 * B, 2 * kcap)],
    )
    pred = Predictor.load(art)
    assert sorted(pred.bucket_shapes) == [
        (B // 2, kcap // 2), (B, kcap), (2 * B, 2 * kcap)
    ]

    batch = next(ds.batches(drop_last=False))
    b_real = int(batch.ins_mask.sum())
    out_primary = pred.predict(batch)
    assert out_primary.shape[0] == b_real

    # shrink to a half batch: the small bucket must produce IDENTICAL
    # scores for the surviving instances
    import dataclasses

    half = B // 2
    nk_half = int((batch.key_segments[: batch.n_keys] < half * S).sum())
    small = dataclasses.replace(
        batch,
        batch_size=half,
        keys=batch.keys[: kcap // 2],
        key_segments=batch.key_segments[: kcap // 2],
        n_keys=nk_half,
        dense=batch.dense[:half],
        labels=batch.labels[:half],
        ins_mask=batch.ins_mask[:half],
    )
    out_small = pred.predict(small)
    np.testing.assert_allclose(out_small, out_primary[:half], rtol=1e-5,
                               atol=1e-6)
    ds.close()
