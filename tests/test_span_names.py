"""Span-name drift check (tools/check_span_names.py): every span/instant
recorded in code must have a row in ARCHITECTURE.md's "Distributed
tracing & postmortems" span catalog and vice versa — the tier-1 guard
that keeps the postmortem vocabulary honest, wired like the metric-name,
fault-site and env-flag guards."""

import os
import subprocess
import sys

TOOL = os.path.join(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
    "tools", "check_span_names.py",
)


def _load_tool():
    sys.path.insert(0, os.path.dirname(TOOL))
    try:
        import importlib

        return importlib.import_module("check_span_names")
    finally:
        sys.path.pop(0)


def test_catalog_covers_every_call_site_both_ways():
    mod = _load_tool()
    missing, stale, found, pats = mod.check()
    assert not missing, f"spans missing from the catalog: {missing}"
    assert not stale, f"stale catalog rows: {stale}"
    assert found and pats


def test_scanner_finds_known_spans():
    mod = _load_tool()
    found = mod.scan_sources()
    # a plain span, an f-string family, an instant marker, a retro span
    assert "server.score" in found
    assert "sync.apply.*" in found
    assert "fleet.failover" in found
    assert "hostplane.allgather" in found
    # the docs' ``span("name")`` placeholder must NOT count as a span
    assert "name" not in found


def test_catalog_table_parses():
    mod = _load_tool()
    pats = mod.catalog_patterns()
    assert "fleet.request" in pats
    assert "sync.apply.*" in pats  # <kind> normalized to a wildcard


def test_cli_exit_code_zero():
    r = subprocess.run(
        [sys.executable, TOOL], capture_output=True, text=True, timeout=60
    )
    assert r.returncode == 0, r.stderr
