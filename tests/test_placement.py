"""Sparsity-aware hybrid parallelism (ISSUE 15): the placement planner's
hysteresis-bounded hot-set decisions, the shared-dictionary census
exchange over a simulated 2-rank fleet (pk equality vs the legacy union,
mirror-vs-real cache membership, cached-vs-uncached lifecycle equality,
byte collapse, loud protocol failures), the bit-exact planned-vs-hash
trained-store pin on both trainer paths, and the zero-retrace pin under
plan churn."""

import dataclasses
import threading

import numpy as np
import pytest

import jax

from paddlebox_tpu.config import SparseTableConfig, TrainerConfig, flags
from paddlebox_tpu.data.dataset import PadBoxSlotDataset
from paddlebox_tpu.data.synth import make_synth_config, write_synth_files
from paddlebox_tpu.models.ctr_dnn import CtrDnn
from paddlebox_tpu.parallel import (
    MultiChipTrainer,
    ShardedSparseTable,
    make_mesh,
)
from paddlebox_tpu.parallel.census import (
    CensusExchange,
    CensusProtocolError,
    FleetCacheMirror,
    InProcessCensusGroup,
    LoopbackTransport,
    legacy_union,
)
from paddlebox_tpu.sparse.placement import PlacementPlanner
from paddlebox_tpu.sparse.table import SparseTable
from paddlebox_tpu.train.trainer import Trainer

S, DENSE = 3, 2


def _make_data(tmp_path, seed=7, n_ins=256, bsz=16, vocab=60):
    conf = make_synth_config(
        n_sparse_slots=S, dense_dim=DENSE, batch_size=bsz,
        max_feasigns_per_ins=16,
    )
    files = write_synth_files(
        str(tmp_path), n_files=2, ins_per_file=n_ins // 2,
        n_sparse_slots=S, vocab_per_slot=vocab, dense_dim=DENSE, seed=seed,
    )
    ds = PadBoxSlotDataset(conf, read_threads=2)
    ds.set_filelist(files)
    ds.load_into_memory()
    return conf, ds


# --------------------------------------------------------------------------- #
# planner units
# --------------------------------------------------------------------------- #
class TestPlanner:
    def test_topk_by_aged_frequency(self):
        p = PlacementPlanner(hot_capacity=2, aging=0.5, enter_freq=1.5,
                             exit_freq=1.0, update_interval=1)
        hot = np.asarray([7, 9], dtype=np.uint64)
        cold = np.asarray([100, 200, 300], dtype=np.uint64)
        for i in range(4):
            census = np.concatenate(
                [hot, cold[i % cold.shape[0]:i % cold.shape[0] + 1]]
            )
            p.observe(census)
        plan = p.update_plan()
        np.testing.assert_array_equal(plan.hot_keys, hot)
        assert plan.version >= 1

    def test_hysteresis_bounds_plan_churn(self):
        """The hot set may mutate at most once per update_interval passes,
        and an incumbent survives down to exit_freq while a challenger
        needs enter_freq — no flapping at the boundary."""
        p = PlacementPlanner(hot_capacity=1, aging=0.5, enter_freq=1.6,
                             exit_freq=0.9, update_interval=3)
        a = np.asarray([11], dtype=np.uint64)
        b = np.asarray([22], dtype=np.uint64)
        for _ in range(4):
            p.observe(a)
        v1 = p.update_plan().version
        np.testing.assert_array_equal(p.plan().hot_keys, a)
        # b becomes the frequent one; a decays but stays >= exit for a while
        p.observe(np.concatenate([a, b]))
        assert p.update_plan().version == v1, \
            "plan changed before update_interval elapsed"
        p.observe(b)
        assert p.update_plan().version == v1
        p.observe(b)
        plan = p.update_plan()  # 3 passes since last update: may change
        assert plan.version == v1 + 1
        np.testing.assert_array_equal(plan.hot_keys, b)

    def test_incumbent_survives_between_exit_and_enter(self):
        p = PlacementPlanner(hot_capacity=4, aging=0.5, enter_freq=1.9,
                             exit_freq=0.9, update_interval=1)
        a = np.asarray([5], dtype=np.uint64)
        for _ in range(5):
            p.observe(a)  # freq -> 1.9375
        p.update_plan()
        np.testing.assert_array_equal(p.plan().hot_keys, a)
        # one absent pass ages it to ~0.97: below enter (a challenger at
        # this freq could never get in) but above exit -> incumbent stays
        p.observe(np.asarray([999], dtype=np.uint64))
        plan = p.update_plan()
        assert 5 in plan.hot_keys.tolist(), \
            "incumbent above exit_freq must not churn out"
        # three absent passes push it below exit_freq -> it leaves
        for _ in range(3):
            p.observe(np.asarray([999], dtype=np.uint64))
            p.update_plan()
        assert 5 not in p.plan().hot_keys.tolist()

    def test_seed_merges_external_frequency(self):
        p = PlacementPlanner(hot_capacity=2, enter_freq=1.5,
                             update_interval=1)
        p.seed(np.asarray([42, 43], np.uint64), np.asarray([5.0, 0.1]))
        p.observe(np.asarray([42, 99], np.uint64))
        plan = p.update_plan()
        assert 42 in plan.hot_keys.tolist()
        assert 43 not in plan.hot_keys.tolist()

    def test_determinism_across_instances(self):
        """Two planners fed the same census stream emit identical plans —
        the property the no-collective dictionary derivation rests on."""
        rng = np.random.default_rng(3)
        p1 = PlacementPlanner(hot_capacity=16, update_interval=2)
        p2 = PlacementPlanner(hot_capacity=16, update_interval=2)
        for _ in range(6):
            census = rng.zipf(1.2, 500).astype(np.uint64) % 300
            p1.observe(census)
            p2.observe(census)
            a, b = p1.update_plan(), p2.update_plan()
            assert a.version == b.version
            np.testing.assert_array_equal(a.hot_keys, b.hot_keys)

    def test_validation(self):
        with pytest.raises(ValueError):
            PlacementPlanner(aging=1.5)
        with pytest.raises(ValueError):
            PlacementPlanner(enter_freq=1.0, exit_freq=2.0)
        with pytest.raises(ValueError):
            PlacementPlanner(update_interval=0)


# --------------------------------------------------------------------------- #
# census exchange: simulated 2-rank fleet
# --------------------------------------------------------------------------- #
def _run_ranks(n, fn):
    out = [None] * n
    errs = []

    def wrap(r):
        try:
            out[r] = fn(r)
        except BaseException as e:  # surfaced below
            errs.append(e)

    ts = [threading.Thread(target=wrap, args=(r,)) for r in range(n)]
    for t in ts:
        t.start()
    for t in ts:
        t.join()
    if errs:
        raise errs[0]
    return out


def _rank_censuses(n_ranks, n_passes, seed=5):
    rng = np.random.default_rng(seed)
    shared = np.arange(0, 400, 3, dtype=np.uint64)
    out = []
    for _ in range(n_passes):
        per_rank = [
            np.unique(np.concatenate([
                shared,
                rng.integers(1000, 4000, 60, dtype=np.uint64),
            ]))
            for _ in range(n_ranks)
        ]
        out.append(per_rank)
    return out


def test_two_rank_exchange_equals_legacy_union():
    """Every rank decodes the identical global census, byte-equal to the
    legacy allgather-union, under planner+mirror+varint."""
    n, passes = 2, 5
    censuses = _rank_censuses(n, passes)
    group = InProcessCensusGroup(n)

    def rank_fn(r):
        ex = CensusExchange(
            group.transport(r),
            planner=PlacementPlanner(hot_capacity=256, update_interval=1),
            mirror=FleetCacheMirror(n, 64, 0.8),
        )
        return [ex.exchange(censuses[p][r]) for p in range(passes)]

    results = _run_ranks(n, rank_fn)
    for p in range(passes):
        want = legacy_union([censuses[p][r] for r in range(n)])
        for r in range(n):
            np.testing.assert_array_equal(results[r][p], want)


def test_two_rank_bytes_collapse_and_codec_ratio():
    """Steady state: planned+varint wire bytes collapse far below the raw
    full-census baseline (O(working set) -> O(cold + dictionary bits)),
    and the codec alone is >= 4x on the sorted censuses."""
    n, passes = 2, 6
    censuses = _rank_censuses(n, passes)

    def arm(planner_on, codec):
        group = InProcessCensusGroup(n)

        def rank_fn(r):
            ex = CensusExchange(
                group.transport(r),
                planner=(
                    PlacementPlanner(hot_capacity=4096, enter_freq=1.5,
                                     update_interval=1)
                    if planner_on else None
                ),
                mirror=FleetCacheMirror(n, 512, 0.8) if planner_on else None,
                codec=codec,
            )
            wire = []
            for p in range(passes):
                ex.exchange(censuses[p][r])
                wire.append(ex.last_wire_bytes)
            return wire
        wires = _run_ranks(n, rank_fn)
        # steady state: skip pass 0 (dictionary empty, all cold)
        return sum(sum(w[1:]) for w in wires) / (passes - 1)

    raw = arm(False, "raw")
    varint = arm(False, "varint")
    planned = arm(True, "varint")
    assert raw / varint >= 4.0, f"codec alone {raw / varint:.2f}x < 4x"
    assert planned < varint < raw
    assert raw / planned >= 8.0, (
        f"planned collapse only {raw / planned:.2f}x "
        f"({raw:.0f} -> {planned:.0f} B/pass)"
    )


def test_mirror_tracks_real_cache_membership():
    """Each rank holds a REAL HbmCache for its own shard; every rank's
    metadata mirror must predict every shard's membership exactly (no
    faults injected) — the property that makes 'exchange only cache
    misses' a pure encoding decision."""
    from paddlebox_tpu.sparse.engine import HbmCache

    n, passes = 2, 5
    censuses = _rank_censuses(n, passes)
    group = InProcessCensusGroup(n)
    cap = 64

    def rank_fn(r):
        ex = CensusExchange(
            group.transport(r),
            mirror=FleetCacheMirror(n, cap, 0.8),
        )
        real = HbmCache(cap, 4, aging=0.8)  # this rank's own shard r
        residents = []
        for p in range(passes):
            pk = ex.exchange(censuses[p][r])
            sk = pk[pk % np.uint64(n) == np.uint64(r)]
            # the real per-shard cached lifecycle: begin (lookup+touch),
            # end (plan_update+commit) — same order the sharded table runs
            plan = real.lookup(sk)
            real.touch(plan)
            upd = real.plan_update(sk, plan)
            real.commit_update(plan, upd)
            residents.append(real.snapshot_keys().copy())
        return ex, residents

    results = _run_ranks(n, rank_fn)
    for owner in range(n):
        _, owner_residents = results[owner]
        for r in range(n):
            ex, _ = results[r]
            np.testing.assert_array_equal(
                ex.mirror.shard_resident(owner), owner_residents[-1],
                err_msg=f"rank {r}'s mirror diverged from shard {owner}",
            )


def test_cached_vs_uncached_lifecycle_equality():
    """The multi-host cached lifecycle (mirror dictionary riding the
    census) and the uncached one (no dictionary) agree on every pass's
    global census — cache state compresses the wire, never changes it."""
    n, passes = 2, 5
    censuses = _rank_censuses(n, passes, seed=11)

    def arm(with_mirror):
        group = InProcessCensusGroup(n)

        def rank_fn(r):
            ex = CensusExchange(
                group.transport(r),
                mirror=FleetCacheMirror(n, 128, 0.8) if with_mirror else None,
            )
            return [ex.exchange(censuses[p][r]) for p in range(passes)]
        return _run_ranks(n, rank_fn)

    cached = arm(True)
    uncached = arm(False)
    for p in range(passes):
        np.testing.assert_array_equal(cached[0][p], uncached[0][p])
        np.testing.assert_array_equal(cached[1][p], uncached[0][p])


def test_protocol_errors_are_loud():
    # a peer speaking a different wire format entirely
    ex = CensusExchange(LoopbackTransport())
    with pytest.raises(CensusProtocolError) as ei:
        ex._decode(b"garbage-not-a-census", sender=1,
                   known=np.empty(0, np.uint64))
    assert ei.value.sender == 1
    # dictionary divergence: rank 1 derives a different hot set (e.g. a
    # mis-configured planner) -> digest mismatch names the sender
    n = 2
    group = InProcessCensusGroup(n)
    censuses = _rank_censuses(n, 3, seed=13)

    def rank_fn(r):
        ex = CensusExchange(
            group.transport(r),
            planner=PlacementPlanner(
                hot_capacity=64 if r == 0 else 8,  # the misconfiguration
                enter_freq=1.0, exit_freq=1.0, update_interval=1,
            ),
        )
        for p in range(3):
            ex.exchange(censuses[p][r])

    with pytest.raises(CensusProtocolError) as ei:
        _run_ranks(n, rank_fn)
    assert "different dictionary" in str(ei.value)


def test_truncated_message_is_loud():
    ex = CensusExchange(LoopbackTransport())
    payload = ex._encode(np.arange(50, dtype=np.uint64),
                         np.empty(0, np.uint64))
    with pytest.raises(CensusProtocolError):
        ex._decode(payload[:-3], sender=0, known=np.empty(0, np.uint64))


# --------------------------------------------------------------------------- #
# bit-exact: planned placement vs hash-only, both trainer paths
# --------------------------------------------------------------------------- #
def _train_sharded(tmp_path, placement, n_passes=3, n_dev=None):
    mesh = make_mesh(n_dev or min(8, len(jax.devices())))
    tconf = SparseTableConfig(
        embedding_dim=4, placement=placement, placement_update_interval=1,
        placement_hot_capacity=64, hbm_cache_rows=64,
    )
    trconf = TrainerConfig(auc_buckets=1 << 10)
    model = CtrDnn(S, tconf.row_width, dense_dim=DENSE, hidden=(8,))
    trainer = MultiChipTrainer(model, tconf, mesh, trconf, seed=3)
    table = ShardedSparseTable(tconf, mesh, seed=5, bucket_slack=8.0)
    auc_state = None
    m = {}
    for p in range(n_passes):
        conf, ds = _make_data(tmp_path / f"{placement}-{p}", seed=20 + p)
        table.begin_pass(ds.unique_keys())
        m = trainer.train_from_dataset(ds, table, auc_state=auc_state,
                                       drop_last=True)
        auc_state = trainer.last_metric_state
        table.end_pass()
        ds.close()
    st = table.state_dict()
    plan = table.placement_plan()
    table.close()
    return st, float(m["auc"]), plan


def test_bitexact_planned_vs_hash_sharded_trainer(tmp_path):
    """3 overlapping-census passes through the MultiChipTrainer: the full
    placement wire path (loopback: encode -> decode in every begin_pass,
    planner + mirrors live) must leave keys, values, g2sum AND AUC
    byte-identical to the hash-only run — placement moves bytes, never
    floats."""
    st_hash, auc_hash, _ = _train_sharded(tmp_path, "hash")
    st_plan, auc_plan, plan = _train_sharded(tmp_path, "loopback")
    assert plan is not None and plan.version >= 1 and plan.n_hot > 0, \
        "the planner never actually planned — the test proved nothing"
    np.testing.assert_array_equal(st_hash["keys"], st_plan["keys"])
    np.testing.assert_array_equal(st_hash["values"], st_plan["values"])
    assert auc_hash == auc_plan


def test_bitexact_single_chip_placement_inert(tmp_path, monkeypatch):
    """Single-chip path: the placement flag must be inert on SparseTable
    (no sharded wire exists) — training under PBOX_PLACEMENT=loopback
    equals the hash run bit-for-bit."""
    states = {}
    for mode in ("hash", "loopback"):
        monkeypatch.setenv("PBOX_PLACEMENT", mode)
        conf, ds = _make_data(tmp_path / f"sc-{mode}", seed=3)
        tconf = SparseTableConfig(embedding_dim=4)
        model = CtrDnn(S, tconf.row_width, dense_dim=DENSE, hidden=(8,))
        table = SparseTable(tconf, seed=0)
        trainer = Trainer(model, tconf,
                          TrainerConfig(auc_buckets=1 << 10), seed=0)
        table.begin_pass(ds.unique_keys())
        m = trainer.train_from_dataset(ds, table)
        table.end_pass()
        st = table.state_dict()
        st["auc"] = float(m["auc"])
        states[mode] = st
        table.close()
        ds.close()
    np.testing.assert_array_equal(states["hash"]["keys"],
                                  states["loopback"]["keys"])
    np.testing.assert_array_equal(states["hash"]["values"],
                                  states["loopback"]["values"])
    assert states["hash"]["auc"] == states["loopback"]["auc"]


# --------------------------------------------------------------------------- #
# realized hybrid placement: deterministic reduction + host-plane pins
# --------------------------------------------------------------------------- #
@pytest.mark.parametrize("n_dev", [2, 4, 8])
def test_hybrid_reduce_bitexact_across_reruns(tmp_path, n_dev):
    """The hot-gradient reduction is an explicitly ordered fold (level-1
    segment_sum over in-batch occurrences, level-2 all_gather + unrolled
    device-ascending adds), so two identical runs on the realized hybrid
    layout must produce byte-identical stores — keys, values AND the
    g2sum column — and the same AUC, at every simulated device count."""
    if len(jax.devices()) < n_dev:
        pytest.skip(f"needs {n_dev} devices")
    st_a, auc_a, plan_a = _train_sharded(tmp_path, "loopback", n_dev=n_dev)
    st_b, auc_b, plan_b = _train_sharded(tmp_path, "loopback", n_dev=n_dev)
    assert plan_a is not None and plan_a.n_hot > 0, (
        "the plan never realized — the reduction under test never ran"
    )
    assert plan_b is not None and plan_b.n_hot == plan_a.n_hot
    np.testing.assert_array_equal(st_a["keys"], st_b["keys"])
    np.testing.assert_array_equal(st_a["values"], st_b["values"])
    assert auc_a == auc_b


def test_hybrid_zero_host_row_bytes_inside_pass(tmp_path):
    """The structural pin of the realized layout: once a key is hot and
    resident, its rows NEVER cross the host plane — zero row bytes of any
    kind inside a pass, and boundary traffic exactly O(cold rows) with a
    steady census (no churn -> zero hot migration bytes too)."""
    from paddlebox_tpu.telemetry import registry

    mesh = make_mesh(min(8, len(jax.devices())))
    tconf = SparseTableConfig(
        embedding_dim=4, placement="loopback",
        placement_update_interval=1, placement_hot_capacity=32,
        hbm_cache_rows=0,  # no cache: every host row move is counted
    )
    trconf = TrainerConfig(auc_buckets=1 << 10)
    model = CtrDnn(S, tconf.row_width, dense_dim=DENSE, hidden=(8,))
    trainer = MultiChipTrainer(model, tconf, mesh, trconf, seed=3)
    table = ShardedSparseTable(tconf, mesh, seed=5, bucket_slack=8.0)
    conf, ds = _make_data(tmp_path / "pin", seed=11)
    keys = ds.unique_keys()
    for _ in range(3):  # aged frequency clears enter_freq; block realizes
        table.begin_pass(keys)
        trainer.train_from_dataset(ds, table)
        table.end_pass()
    n_hot = table.hot_resident_keys().shape[0]
    assert n_hot > 0, "hot block never realized"
    n_cold = int(keys.shape[0]) - n_hot
    row_b = 4 * (tconf.row_width + 1)

    def ctr(snap, name):
        return snap["counters"].get(name, 0)

    s0 = registry.snapshot()
    table.begin_pass(keys)
    s1 = registry.snapshot()
    trainer.train_from_dataset(ds, table)
    s2 = registry.snapshot()
    table.end_pass()
    s3 = registry.snapshot()
    ds.close()
    table.close()
    # inside the pass: zero host-plane row bytes, hot or cold
    for c in ("pass.host_row_bytes_in", "pass.host_row_bytes_out",
              "placement.hot_row_host_bytes"):
        assert ctr(s2, c) == ctr(s1, c), f"{c} moved inside a pass"
    # steady census: zero hot-tier migration bytes across the boundary
    assert ctr(s3, "placement.hot_row_host_bytes") == ctr(
        s0, "placement.hot_row_host_bytes")
    # boundary traffic is exactly the cold tail: resident hot rows ride
    # neither the begin_pass fill nor the end_pass write-back
    assert ctr(s1, "pass.host_row_bytes_in") - ctr(
        s0, "pass.host_row_bytes_in") == n_cold * row_b
    assert ctr(s3, "pass.host_row_bytes_out") - ctr(
        s2, "pass.host_row_bytes_out") == n_cold * row_b


# --------------------------------------------------------------------------- #
# zero-retrace under plan churn (the PR-14 pins must hold)
# --------------------------------------------------------------------------- #
def test_plan_churn_zero_retrace(tmp_path):
    """Plan-version churn (update_interval=1, shifting censuses) must be
    invisible to jit: after warmup, passes with a MUTATING hot set
    trigger zero XLA compiles across every stage — the placement plan
    lives on the wire, never in a traced shape."""
    from paddlebox_tpu.telemetry import compiles

    mesh = make_mesh(min(8, len(jax.devices())))
    tconf = SparseTableConfig(
        embedding_dim=4, placement="loopback",
        placement_update_interval=1, placement_hot_capacity=32,
        hbm_cache_rows=64,
    )
    trconf = TrainerConfig(auc_buckets=1 << 10)
    model = CtrDnn(S, tconf.row_width, dense_dim=DENSE, hidden=(8,))
    trainer = MultiChipTrainer(model, tconf, mesh, trconf, seed=3)
    table = ShardedSparseTable(tconf, mesh, seed=5, bucket_slack=8.0)
    conf, ds = _make_data(tmp_path / "churn", seed=9)
    keys = ds.unique_keys()

    # warmup: compile + capacity-fit recompile, plus the pass where the
    # planner's hot set first clears the hysteresis gate and the hybrid
    # layout realizes on device (first promotion compiles its static-[H]
    # migration machinery once, like the step itself)
    for _ in range(3):
        table.begin_pass(keys)
        trainer.train_from_dataset(ds, table)
        table.end_pass()
    assert table.hot_resident_keys().shape[0] > 0, (
        "warmup never realized the hot block — the measured window "
        "would not cover the hybrid path"
    )

    before = compiles.compiles_by_stage()
    versions = []
    for _ in range(2):
        table.begin_pass(keys)
        trainer.train_from_dataset(ds, table)
        table.end_pass()
        versions.append(table.placement_plan().version)
    after = compiles.compiles_by_stage()
    moved = {k: v - before.get(k, 0) for k, v in after.items()
             if v != before.get(k, 0)}
    ds.close()
    table.close()
    assert not moved, (
        f"plan churn recompiled: {moved} — placement leaked into a "
        "traced shape"
    )
    assert versions[0] >= 1, "the planner never planned"


# --------------------------------------------------------------------------- #
# bench smoke (non-slow, CPU)
# --------------------------------------------------------------------------- #
def test_bench_hostplane_smoke():
    """Fast CPU smoke of bench.py --hostplane: the collapse, the >= 4x
    codec ratio and the bit-exact check all hold at toy scale, and the
    emitted row carries every acceptance field."""
    from bench import bench_hostplane

    res = bench_hostplane(
        3, SparseTableConfig(embedding_dim=4, placement_hot_capacity=512),
        TrainerConfig(auc_buckets=1 << 10), n_slots=2, dense=2, bsz=32,
        ins_per_pass=128, hidden=(8,), vocab_per_slot=300,
    )
    assert res["bitexact"]
    assert res["hot_resident_rows"] > 0, "hybrid arm never realized"
    assert (
        res["hybrid_host_row_bytes_in_last_pass"]
        < res["wire_host_row_bytes_in_last_pass"]
    ), "realized hot rows still paying begin-pass host traffic"
    assert res["census_compression_x"] >= 4.0
    assert (
        res["planned_varint_bytes_per_pass"]
        < res["hash_raw_bytes_per_pass"]
    )
    assert res["shuffle_key_bytes_encoded"] < res["shuffle_key_bytes_raw"]
    for field in ("gather_p50_ms", "gather_p99_ms"):
        assert res[f"planned_varint_{field}"] >= 0
    assert res["samples_per_sec"] > 0
