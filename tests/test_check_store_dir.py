"""tools/check_store_dir.py: durable-log store-root lint (damage vs
crash debris)."""

import json
import os
import sys

import numpy as np

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", "tools"))

from check_store_dir import check_store_root, main  # noqa: E402

from paddlebox_tpu.sparse.logstore import LogStore  # noqa: E402


def _write_root(tmp_path, passes=3, compact=False, **kw):
    root = str(tmp_path / "log")
    ls = LogStore(root, n_cols=3, n_buckets=2, compact_threshold=2, **kw)
    k = np.arange(1, 60, dtype=np.uint64)
    for p in range(passes):
        v = (k.astype(np.float64)[:, None] * [1, 2, 3] * 0.01 + p)
        ls.append(k, v.astype(np.float32))
        ls.commit()
    if compact:
        ls.compact()
    ls.close()
    return root


def _current_manifest(root):
    with open(os.path.join(root, "CURRENT")) as fh:
        name = fh.read().strip()
    return name, json.load(open(os.path.join(root, name)))


def test_clean_root_passes(tmp_path, capsys):
    root = _write_root(tmp_path, compact=True)
    errors, warnings = check_store_root(root)
    assert errors == [] and warnings == []
    assert main([root]) == 0
    assert "OK" in capsys.readouterr().out


def test_fresh_empty_root_passes(tmp_path):
    root = str(tmp_path / "fresh")
    os.makedirs(root)
    assert check_store_root(root) == ([], [])


def test_missing_current_with_data_is_an_error(tmp_path):
    root = _write_root(tmp_path)
    os.remove(os.path.join(root, "CURRENT"))
    errors, _ = check_store_root(root)
    assert errors and "CURRENT missing" in errors[0]
    assert main([root]) == 1


def test_dangling_current_is_an_error(tmp_path):
    root = _write_root(tmp_path)
    name, _ = _current_manifest(root)
    os.remove(os.path.join(root, name))
    errors, _ = check_store_root(root)
    assert errors and "unreadable" in errors[0]


def test_referenced_segment_damage_is_an_error(tmp_path):
    root = _write_root(tmp_path)
    _, man = _current_manifest(root)
    segs = [d["name"] for d in man["segments"]]
    # one missing, one truncated, one bit-flipped (size intact)
    os.remove(os.path.join(root, segs[0]))
    with open(os.path.join(root, segs[1]), "r+b") as fh:
        fh.truncate(os.path.getsize(os.path.join(root, segs[1])) - 5)
    with open(os.path.join(root, segs[2]), "r+b") as fh:
        fh.seek(-1, os.SEEK_END)
        b = fh.read(1)
        fh.seek(-1, os.SEEK_END)
        fh.write(bytes([b[0] ^ 0xFF]))
    errors, _ = check_store_root(root)
    assert any("missing" in e for e in errors)
    assert any("size" in e for e in errors)
    assert any("crc" in e for e in errors)
    assert main([root]) == 1


def test_orphans_and_torn_tails_warn(tmp_path):
    root = _write_root(tmp_path)
    # a torn orphan (crashed segment write) and a clean orphan
    with open(os.path.join(root, "seg-00000099-b001.seg"), "wb") as fh:
        fh.write(b"PBLOG1\x00\n\x20\x00\x00\x00trunc")
    _, man = _current_manifest(root)
    src = os.path.join(root, man["segments"][0]["name"])
    import shutil

    shutil.copy(src, os.path.join(root, "seg-00000098-b000.seg"))
    errors, warnings = check_store_root(root)
    assert errors == []
    assert any("orphan segment" in w and "torn" in w for w in warnings)
    assert any("seg-00000098" in w and "torn" not in w for w in warnings)
    assert main([root]) == 0
    assert main([root, "--strict"]) == 1


def test_manifest_newer_than_current_warns(tmp_path):
    root = _write_root(tmp_path)
    name, man = _current_manifest(root)
    man["gen"] += 3
    with open(os.path.join(root, f"manifest-{man['gen']:08d}.json"),
              "w") as fh:
        json.dump(man, fh)
    errors, warnings = check_store_root(root)
    assert errors == []
    assert any("newer than CURRENT" in w for w in warnings)


def test_manifest_chain_gap_warns(tmp_path):
    # keep_history roots retain every generation (no-history roots sweep
    # old manifests at each commit, so only they can have a chain)
    root = _write_root(tmp_path, passes=4, keep_history=True)
    os.remove(os.path.join(root, "manifest-00000002.json"))
    errors, warnings = check_store_root(root)
    assert errors == []
    assert any("chain gap" in w for w in warnings)


def test_no_history_root_has_no_manifest_chain(tmp_path):
    """keep_history=False commits sweep superseded manifests — a long
    run's root must stay lint-clean with only the committed manifest
    (r17 review finding)."""
    root = _write_root(tmp_path, passes=6)
    manifests = [n for n in os.listdir(root) if n.startswith("manifest-")]
    assert len(manifests) == 1
    assert check_store_root(root) == ([], [])
