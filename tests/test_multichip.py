"""Multi-chip correctness on the 8-device virtual CPU mesh (SURVEY.md §4
tier 3 — the TPU analog of the reference's localhost-subprocess distributed
tests, test_dist_base.py:642: distributed loss must equal local loss)."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from paddlebox_tpu.config import SparseTableConfig, TrainerConfig
from paddlebox_tpu.data.dataset import PadBoxSlotDataset
from paddlebox_tpu.data.synth import make_synth_config, write_synth_files
from paddlebox_tpu.models.ctr_dnn import CtrDnn
from paddlebox_tpu.parallel import (
    MultiChipTrainer,
    ShardedSparseTable,
    make_mesh,
)
from paddlebox_tpu.sparse.table import SparseTable
from paddlebox_tpu.train.trainer import Trainer

N_DEV = 8


@pytest.fixture(scope="module")
def mesh():
    assert len(jax.devices()) >= N_DEV, "conftest must force 8 CPU devices"
    return make_mesh(N_DEV)


def _make_data(tmp_path, n_ins, batch_size, **kw):
    conf = make_synth_config(
        n_sparse_slots=3, dense_dim=2, batch_size=batch_size,
        max_feasigns_per_ins=16, **kw,
    )
    files = write_synth_files(
        str(tmp_path), n_files=2, ins_per_file=n_ins // 2,
        n_sparse_slots=3, vocab_per_slot=50, dense_dim=2, seed=7,
    )
    ds = PadBoxSlotDataset(conf, read_threads=2)
    ds.set_filelist(files)
    ds.load_into_memory()
    return conf, ds


# --------------------------------------------------------------------------- #
# Sharded table unit behavior
# --------------------------------------------------------------------------- #
class TestShardedTable:
    def test_begin_pass_shards_by_mod(self, mesh):
        tconf = SparseTableConfig(embedding_dim=4)
        table = ShardedSparseTable(tconf, mesh, seed=0)
        keys = np.arange(1, 100, dtype=np.uint64)
        table.begin_pass(keys)
        assert table.values.shape[0] == N_DEV
        for o, sk in enumerate(table._shard_keys):
            assert (sk % np.uint64(N_DEV) == o).all()
        assert sum(len(sk) for sk in table._shard_keys) == 99
        table.end_pass()
        assert table.n_features == 99

    def test_roundtrip_preserves_rows(self, mesh):
        tconf = SparseTableConfig(embedding_dim=4, initial_range=0.1)
        table = ShardedSparseTable(tconf, mesh, seed=0)
        keys = np.array([3, 11, 19, 27, 64, 123], dtype=np.uint64)
        table.begin_pass(keys)
        table.end_pass()
        st = table.state_dict()
        # second pass must resolve the same rows back
        table.begin_pass(keys)
        vals = np.asarray(table.values)
        for o, sk in enumerate(table._shard_keys):
            for i, k in enumerate(sk):
                row_in_store = st["values"][np.searchsorted(st["keys"], k)]
                np.testing.assert_allclose(
                    vals[o, i], row_in_store[:-1], rtol=1e-6
                )
        table.end_pass()

    def test_plan_routes_to_owner(self, mesh):
        tconf = SparseTableConfig(embedding_dim=4)
        table = ShardedSparseTable(tconf, mesh, seed=0, bucket_slack=8.0)
        keys = np.arange(1, 65, dtype=np.uint64)
        table.begin_pass(keys)
        from paddlebox_tpu.data.feed import HostBatch

        K = 16
        batches = []
        for d in range(N_DEV):
            kb = np.zeros(K, dtype=np.uint64)
            kb[:4] = [d * 4 + 1, d * 4 + 2, d * 4 + 3, d * 4 + 4]
            batches.append(HostBatch(
                keys=kb, key_segments=np.zeros(K, np.int32), n_keys=4,
                dense=np.zeros((2, 1), np.float32), labels=np.zeros(2, np.float32),
                ins_mask=np.ones(2, np.float32), batch_size=2, n_sparse_slots=2,
            ))
        plan = table.plan_group(batches)
        assert plan.n_missing == 0 and plan.n_overflow == 0
        for d in range(N_DEV):
            for k in batches[d].keys[:4]:
                o = int(k % N_DEV)
                sk = table._shard_keys[o]
                row = int(np.searchsorted(sk, k))
                # shard o must serve that row to requester d, and the dedup
                # map must point the pair at it
                assert row in plan.serve_rows[o, d], (d, k, o)
                assert row in plan.serve_uniq[o], (d, k, o)
        # single-chip plan entry points must be refused on the sharded table
        with pytest.raises(TypeError):
            table.plan_batch(batches[0])
        table.end_pass()

    def test_skewed_group_bumps_capacity_no_drops(self, mesh):
        """A group whose keys all hash to ONE shard must grow the a2a
        bucket (power-of-two bump), not silently drop keys (VERDICT r3
        weak #5: 'counted != handled').  Every key must resolve to its
        owner's row."""
        from paddlebox_tpu.data.feed import HostBatch

        tconf = SparseTableConfig(embedding_dim=4)
        # tight slack -> base bucket C = K*1.0/8 shards rounded to 8
        table = ShardedSparseTable(tconf, mesh, seed=0, bucket_slack=1.0)
        K = 64
        # all keys ≡ 0 mod 8: every key owned by shard 0 (worst skew)
        keys = np.arange(1, K + 1, dtype=np.uint64) * np.uint64(N_DEV)
        table.begin_pass(keys)
        base_C = table.bucket_capacity(K)
        assert base_C < K  # the skewed batch cannot fit the base bucket
        batches = []
        for d in range(N_DEV):
            kb = np.zeros(K, dtype=np.uint64)
            kb[:] = keys  # every device asks shard 0 for ALL K keys
            batches.append(HostBatch(
                keys=kb, key_segments=np.zeros(K, np.int32), n_keys=K,
                dense=np.zeros((2, 1), np.float32),
                labels=np.zeros(2, np.float32),
                ins_mask=np.ones(2, np.float32), batch_size=2,
                n_sparse_slots=2,
            ))
        plan = table.plan_group(batches)
        assert plan.n_overflow == 0, "no key may ever be dropped"
        assert table.capacity_bumps == 1
        C = plan.serve_rows.shape[2]
        assert C >= K and C % base_C == 0  # power-of-two bump over base
        # every key's row is actually served by shard 0 to every requester
        sk = table._shard_keys[0]
        for d in range(N_DEV):
            for k in keys:
                row = int(np.searchsorted(sk, k))
                assert row in plan.serve_rows[0, d]
        # occ routes each occurrence into shard 0's bucket (never the sink)
        assert (plan.occ_flat < N_DEV * C).all()
        assert (plan.occ_flat // C == 0).all()
        table.end_pass()


class TestMultiChipPrefetch:
    def test_prefetch_matches_serial(self, mesh, tmp_path):
        """The background plan+stack+H2D producer must be a pure overlap:
        bitwise-identical metrics to the serial path (VERDICT r3 next #6a
        — the multi-chip tier previously planned serially on the
        critical path)."""
        tconf = SparseTableConfig(embedding_dim=8)

        def run(prefetch, sub):
            conf, ds = _make_data(tmp_path / sub, 256, 8)
            model = CtrDnn(3, tconf.row_width, dense_dim=2, hidden=(16,))
            tr = MultiChipTrainer(
                model, tconf, mesh,
                TrainerConfig(auc_buckets=1 << 10,
                              prefetch_batches=prefetch),
                seed=1,
            )
            table = ShardedSparseTable(tconf, mesh, seed=2)
            table.begin_pass(ds.unique_keys())
            m = tr.train_from_dataset(ds, table)
            table.end_pass()
            sd = table.state_dict()
            ds.close()
            return m, sd

        m0, sd0 = run(0, "serial")
        m2, sd2 = run(2, "prefetch")
        assert m0["steps"] == m2["steps"] > 0
        assert m0["loss"] == pytest.approx(m2["loss"], rel=1e-6)
        assert m0["auc"] == pytest.approx(m2["auc"], rel=1e-6)
        np.testing.assert_array_equal(sd0["keys"], sd2["keys"])
        np.testing.assert_allclose(sd0["values"], sd2["values"], rtol=1e-6)


# --------------------------------------------------------------------------- #
# The tier-3 gate: multi-chip == single-chip
# --------------------------------------------------------------------------- #
class TestMultiChipEqualsSingleChip:
    def test_loss_and_table_match(self, mesh, tmp_path):
        n_ins = 256
        B = 16  # per-device batch; single-chip uses B * N_DEV
        tconf = SparseTableConfig(embedding_dim=8, learning_rate=0.05)
        trconf = TrainerConfig(dense_lr=1e-3, sync_dense_mode="step",
                               auc_buckets=1 << 12)

        # ---- single chip on the concatenated global batch ----
        conf1, ds1 = _make_data(tmp_path / "a", n_ins, B * N_DEV)
        model1 = CtrDnn(3, tconf.row_width, dense_dim=2, hidden=(32, 16))
        t1 = Trainer(model1, tconf, trconf, seed=3)
        table1 = SparseTable(tconf, seed=5)
        table1.begin_pass(ds1.unique_keys())
        m1 = t1.train_from_dataset(ds1, table1)
        table1.end_pass()

        # ---- multi chip: same instances split into per-device batches ----
        conf8, ds8 = _make_data(tmp_path / "b", n_ins, B)
        model8 = CtrDnn(3, tconf.row_width, dense_dim=2, hidden=(32, 16))
        t8 = MultiChipTrainer(model8, tconf, mesh, trconf, seed=3)
        table8 = ShardedSparseTable(tconf, mesh, seed=5, bucket_slack=float(N_DEV))
        table8.begin_pass(ds8.unique_keys())
        m8 = t8.train_from_dataset(ds8, table8)
        table8.end_pass()

        assert m8["steps"] * N_DEV == m1["steps"] * N_DEV  # same data volume
        # losses are means over the same instances -> must match closely
        assert abs(m1["loss"] - m8["loss"]) < 2e-4, (m1["loss"], m8["loss"])
        assert abs(m1["auc"] - m8["auc"]) < 5e-3, (m1["auc"], m8["auc"])
        assert m1["count"] == m8["count"] == n_ins

        # ---- the sparse tables must agree feature-by-feature ----
        s1, s8 = table1.state_dict(), table8.state_dict()
        np.testing.assert_array_equal(s1["keys"], s8["keys"])
        np.testing.assert_allclose(s1["values"], s8["values"], atol=2e-4)

    def test_kstep_sync_runs_and_learns(self, mesh, tmp_path):
        tconf = SparseTableConfig(
            embedding_dim=8, learning_rate=0.5, initial_range=0.05
        )
        trconf = TrainerConfig(sync_dense_mode="kstep", sync_weight_step=4,
                               dense_lr=3e-3, auc_buckets=1 << 12)
        conf, ds = _make_data(tmp_path / "k", 512, 16)
        model = CtrDnn(3, tconf.row_width, dense_dim=2, hidden=(32, 16))
        tr = MultiChipTrainer(model, tconf, mesh, trconf, seed=0)
        table = ShardedSparseTable(tconf, mesh, seed=0)
        results = []
        for _ in range(4):
            table.begin_pass(ds.unique_keys())
            results.append(tr.train_from_dataset(ds, table))
            table.end_pass()
        assert results[-1]["loss"] < results[0]["loss"]
        assert results[-1]["auc"] > 0.6
        # after a sync step the replicas must be identical
        p = jax.tree.leaves(tr.params)[0]
        np.testing.assert_allclose(np.asarray(p)[0], np.asarray(p)[-1], rtol=1e-6)

    def test_dump_fields_multichip(self, mesh, tmp_path):
        """Per-instance field dumping on the mesh (reference: DumpField in
        the production multi-GPU workers, device_worker.cc): every real
        instance dumps exactly once, ragged-tail pad batches dump nothing,
        line format matches the single-chip dumper."""
        import os

        tconf = SparseTableConfig(embedding_dim=4)
        trconf = TrainerConfig(
            auc_buckets=1 << 10, need_dump_field=True,
            dump_fields=("dense",), dump_fields_path=str(tmp_path / "dump"),
        )
        conf, ds = _make_data(tmp_path / "d", 150, 16)  # ragged tail
        model = CtrDnn(3, tconf.row_width, dense_dim=2, hidden=(16,))
        tr = MultiChipTrainer(model, tconf, mesh, trconf, seed=0)
        table = ShardedSparseTable(tconf, mesh, seed=0)
        table.begin_pass(ds.unique_keys())
        m = tr.train_from_dataset(ds, table)
        table.end_pass()
        ds.close()
        assert m["count"] == 150
        files = [f for f in os.listdir(tmp_path / "dump")
                 if f.startswith("dump-")]
        assert len(files) == 1  # single-process: one file
        lines = open(tmp_path / "dump" / files[0]).read().splitlines()
        assert len(lines) == 150
        cols = lines[0].split("\t")
        assert cols[1] in ("0", "1")  # label
        assert 0.0 <= float(cols[2]) <= 1.0  # pred (sigmoid)
        assert cols[3].startswith("dense:")

    def test_ragged_tail_padding(self, mesh, tmp_path):
        """Instance count not divisible by n_dev * B: padded empty batches
        must contribute nothing."""
        tconf = SparseTableConfig(embedding_dim=4)
        trconf = TrainerConfig(auc_buckets=1 << 10)
        conf, ds = _make_data(tmp_path / "r", 150, 16)  # 150 = 9 batches + tail
        model = CtrDnn(3, tconf.row_width, dense_dim=2, hidden=(16,))
        tr = MultiChipTrainer(model, tconf, mesh, trconf, seed=0)
        table = ShardedSparseTable(tconf, mesh, seed=0)
        table.begin_pass(ds.unique_keys())
        m = tr.train_from_dataset(ds, table)
        table.end_pass()
        assert m["count"] == 150


def test_multichip_multitask_metrics_evaluate(tmp_path):
    """Multi-chip parity for the single-chip feature set: MMoE multi-task
    loss + per-task AUC, cmatch/rank metric groups, forward-only evaluate."""
    import jax

    from paddlebox_tpu.data.synth import make_synth_config, write_synth_files
    from paddlebox_tpu.data.dataset import PadBoxSlotDataset
    from paddlebox_tpu.metrics import MetricGroup, MetricSpec
    from paddlebox_tpu.models import MMoE
    from paddlebox_tpu.parallel import MultiChipTrainer, ShardedSparseTable, make_mesh

    n_dev = min(4, len(jax.devices()))
    mesh = make_mesh(n_dev)
    S, DENSE, B = 3, 2, 16
    conf = make_synth_config(
        n_sparse_slots=S, dense_dim=DENSE, batch_size=B,
        max_feasigns_per_ins=16, n_task_labels=1, parse_logkey=True,
    )
    files = write_synth_files(
        str(tmp_path), n_files=2, ins_per_file=B * n_dev * 2, n_sparse_slots=S,
        vocab_per_slot=40, dense_dim=DENSE, seed=4, n_task_labels=1,
        with_logkey=True,
    )
    ds = PadBoxSlotDataset(conf, read_threads=1)
    ds.set_filelist(files)
    ds.load_into_memory()

    tconf = SparseTableConfig(embedding_dim=4)
    group = MetricGroup(
        [MetricSpec("all"), MetricSpec("cm222", cmatch_values=(222,))],
        n_buckets=1 << 10,
    )
    model = MMoE(S, tconf.row_width, dense_dim=DENSE, n_tasks=2, n_experts=2,
                 expert_hidden=(8,), expert_dim=4, tower_hidden=(4,))
    trainer = MultiChipTrainer(
        model, tconf, mesh, TrainerConfig(auc_buckets=1 << 10),
        metric_group=group,
    )
    table = ShardedSparseTable(tconf, mesh, seed=0)
    table.begin_pass(ds.unique_keys())
    m = trainer.train_from_dataset(ds, table)
    assert np.isfinite(m["loss"])
    assert "task1/auc" in m and m["task1/count"] == m["count"]
    assert m["all/count"] == m["count"]
    assert 0 < m["cm222/count"] < m["all/count"]
    # forward-only evaluation inside the same pass
    ev = trainer.evaluate(ds, table)
    assert ev["count"] == ds.get_memory_data_size()
    table.end_pass()
    ds.close()
