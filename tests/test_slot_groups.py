"""Per-slot learning-rate map + variable per-slot embedding dims.

Reference: the BoxPS LR map (box_wrapper.h:631 GetLRMap/SetLRMap) and the
FEATURE_VARIABLE per-slot-dim layout (box_wrapper.cc:404-566 dispatch).
Synth keys are slot-disjoint (slot s owns [s*VOCAB+1, (s+1)*VOCAB]), which
makes per-slot effects directly observable in the table.
"""

import numpy as np
import pytest

from paddlebox_tpu.config import SparseTableConfig, TrainerConfig
from paddlebox_tpu.data.dataset import PadBoxSlotDataset
from paddlebox_tpu.data.synth import make_synth_config, write_synth_files
from paddlebox_tpu.models import CtrDnn
from paddlebox_tpu.sparse.table import SparseTable, _key_uniform
from paddlebox_tpu.train import Trainer

N_SLOTS, DENSE, B, VOCAB = 4, 4, 64, 100


@pytest.fixture(scope="module")
def synth(tmp_path_factory):
    td = tmp_path_factory.mktemp("slotgroups")
    conf = make_synth_config(
        n_sparse_slots=N_SLOTS, dense_dim=DENSE, batch_size=B,
        batch_key_capacity=B * N_SLOTS * 4,
    )
    paths = write_synth_files(
        str(td), n_files=2, ins_per_file=4 * B, n_sparse_slots=N_SLOTS,
        vocab_per_slot=VOCAB, dense_dim=DENSE, seed=21,
    )
    return paths, conf


def _train(paths, conf, tconf, model, passes=1):
    trainer = Trainer(model, tconf, TrainerConfig(auc_buckets=1 << 10))
    table = SparseTable(tconf)
    ds = PadBoxSlotDataset(conf)
    ds.set_filelist(paths)
    ds.load_into_memory()
    m = None
    for _ in range(passes):
        table.begin_pass(ds.unique_keys())
        m = trainer.train_from_dataset(
            ds, table, auc_state=trainer.last_metric_state)
        table.end_pass()
    ds.close()
    return m, table.state_dict()


def _slot_of(keys):
    return (np.asarray(keys, np.int64) - 1) // VOCAB


def test_uniform_lr_map_matches_scalar_lr(synth):
    """An LR map assigning every slot the default lr is bit-identical to
    the scalar path: the map machinery itself changes nothing."""
    paths, conf = synth

    def mk():
        return CtrDnn(n_sparse_slots=N_SLOTS, emb_width=10, dense_dim=DENSE,
                      hidden=(16,))

    base = SparseTableConfig(embedding_dim=8, learning_rate=0.05)
    mapped = SparseTableConfig(
        embedding_dim=8, learning_rate=0.05,
        slot_learning_rates=tuple((s, 0.05) for s in range(N_SLOTS)),
    )
    m1, sd1 = _train(paths, conf, base, mk())
    m2, sd2 = _train(paths, conf, mapped, mk())
    assert m1["loss"] == pytest.approx(m2["loss"], rel=1e-7)
    np.testing.assert_array_equal(sd1["keys"], sd2["keys"])
    np.testing.assert_allclose(sd1["values"], sd2["values"], rtol=1e-7)


def test_per_slot_lr_scales_updates(synth):
    """Slots with a 100x smaller lr must move their embeddings far less;
    a slot's lr must not leak into other slots' updates."""
    paths, conf = synth
    tconf = SparseTableConfig(
        embedding_dim=8, learning_rate=0.05,
        slot_learning_rates=((2, 0.0005), (3, 0.0005)),
    )
    model = CtrDnn(n_sparse_slots=N_SLOTS, emb_width=tconf.row_width,
                   dense_dim=DENSE, hidden=(16,))
    _, sd = _train(paths, conf, tconf, model)
    co, w = tconf.cvm_offset, tconf.row_width
    init = _key_uniform(sd["keys"], seed=0, n_cols=w - co,
                        rng_range=tconf.initial_range)
    moved = np.abs(sd["values"][:, co:w] - init).mean(axis=1)
    slot = _slot_of(sd["keys"])
    fast = moved[slot < 2].mean()
    slow = moved[slot >= 2].mean()
    assert slow > 0  # the slow group still trains...
    assert fast > 20 * slow  # ...but ~100x slower lr moves it far less


def test_variable_dims_freeze_masked_columns(synth):
    """Slots narrowed to dim 3 of 8 must keep their masked embedx columns
    exactly at the deterministic init (zero gradient by construction),
    while their active columns and other slots train normally."""
    paths, conf = synth
    tconf = SparseTableConfig(embedding_dim=8)
    model = CtrDnn(
        n_sparse_slots=N_SLOTS, emb_width=tconf.row_width, dense_dim=DENSE,
        hidden=(16,), slot_embed_dims=((1, 3),),
    )
    m, sd = _train(paths, conf, tconf, model, passes=2)
    assert np.isfinite(m["loss"])
    co, w = tconf.cvm_offset, tconf.row_width
    init = _key_uniform(sd["keys"], seed=0, n_cols=w - co,
                        rng_range=tconf.initial_range)
    slot = _slot_of(sd["keys"])
    narrowed = slot == 1
    # masked columns (3..8 of slot 1) frozen at init
    np.testing.assert_allclose(
        sd["values"][narrowed, co + 3 : w], init[narrowed, 3:], rtol=1e-6
    )
    # active columns of slot 1 did train
    active_moved = np.abs(
        sd["values"][narrowed, co : co + 3] - init[narrowed, :3]
    ).mean()
    assert active_moved > 1e-4
    # full-width slots train across all columns
    wide_moved = np.abs(sd["values"][~narrowed, co:w] - init[~narrowed]).mean()
    assert wide_moved > 1e-4


def test_bad_configs_rejected(synth):
    with pytest.raises(ValueError):
        CtrDnn(n_sparse_slots=2, emb_width=10, slot_embed_dims=((5, 3),))
    with pytest.raises(ValueError):
        CtrDnn(n_sparse_slots=2, emb_width=10, slot_embed_dims=((0, 99),))
    model = CtrDnn(n_sparse_slots=2, emb_width=10)
    with pytest.raises(ValueError):
        Trainer(
            model,
            SparseTableConfig(embedding_dim=8,
                              slot_learning_rates=((7, 0.1),)),
        )


N_DEV = 8


def _train_sharded(paths, tconf, model, n_dev=N_DEV):
    """Train one pass on the 8-device mesh: same files as _train, split into
    per-device batches of B // n_dev so the global batch matches."""
    import jax

    from paddlebox_tpu.data.dataset import PadBoxSlotDataset
    from paddlebox_tpu.parallel import (
        MultiChipTrainer,
        ShardedSparseTable,
        make_mesh,
    )

    assert len(jax.devices()) >= n_dev, "conftest must force 8 CPU devices"
    mesh = make_mesh(n_dev)
    conf = make_synth_config(
        n_sparse_slots=N_SLOTS, dense_dim=DENSE, batch_size=B // n_dev,
        batch_key_capacity=B * N_SLOTS * 4 // n_dev,
    )
    ds = PadBoxSlotDataset(conf)
    ds.set_filelist(paths)
    ds.load_into_memory()
    trainer = MultiChipTrainer(
        model, tconf, mesh, TrainerConfig(auc_buckets=1 << 10), seed=0
    )
    table = ShardedSparseTable(tconf, mesh, seed=0, bucket_slack=float(n_dev))
    table.begin_pass(ds.unique_keys())
    m = trainer.train_from_dataset(ds, table)
    table.end_pass()
    sd = table.state_dict()
    ds.close()
    return m, sd


def test_sharded_uniform_lr_map_matches_scalar(synth):
    """On the 8-device mesh a uniform LR map must be bit-identical to the
    scalar path — the sharded LR plumbing itself changes nothing (VERDICT
    r4 next #5: the map formerly raised NotImplementedError here)."""
    paths, _ = synth

    def mk():
        return CtrDnn(n_sparse_slots=N_SLOTS, emb_width=10, dense_dim=DENSE,
                      hidden=(16,))

    base = SparseTableConfig(embedding_dim=8, learning_rate=0.05)
    mapped = SparseTableConfig(
        embedding_dim=8, learning_rate=0.05,
        slot_learning_rates=tuple((s, 0.05) for s in range(N_SLOTS)),
    )
    m1, sd1 = _train_sharded(paths, base, mk())
    m2, sd2 = _train_sharded(paths, mapped, mk())
    assert m1["loss"] == pytest.approx(m2["loss"], rel=1e-7)
    np.testing.assert_array_equal(sd1["keys"], sd2["keys"])
    np.testing.assert_allclose(sd1["values"], sd2["values"], rtol=1e-7)


def test_sharded_per_slot_lr_matches_single_chip(synth):
    """The LR map must act identically on the sharded path and the
    single-chip path: one pass over the same instances (global batch B as
    8 x B/8), same seeds, table states must agree feature-by-feature
    (reference: the LR map applies in the production multi-GPU push,
    box_wrapper.h:631 / box_wrapper.cc:404-566)."""
    paths, conf = synth
    tconf = SparseTableConfig(
        embedding_dim=8, learning_rate=0.05,
        slot_learning_rates=((2, 0.0005), (3, 0.0005)),
    )

    def mk():
        return CtrDnn(n_sparse_slots=N_SLOTS, emb_width=tconf.row_width,
                      dense_dim=DENSE, hidden=(16,))

    _, sd1 = _train(paths, conf, tconf, mk())
    _, sd8 = _train_sharded(paths, tconf, mk())
    np.testing.assert_array_equal(sd1["keys"], sd8["keys"])
    np.testing.assert_allclose(sd1["values"], sd8["values"], atol=2e-4)
    # and the per-slot effect itself is visible on the sharded table
    co, w = tconf.cvm_offset, tconf.row_width
    init = _key_uniform(sd8["keys"], seed=0, n_cols=w - co,
                        rng_range=tconf.initial_range)
    moved = np.abs(sd8["values"][:, co:w] - init).mean(axis=1)
    slot = _slot_of(sd8["keys"])
    assert moved[slot < 2].mean() > 20 * moved[slot >= 2].mean()
