"""Pipeline parallelism: the microbatch schedule must be numerically
IDENTICAL to running the stages sequentially (same params, same data) —
forward loss, gradients (via one training step), and learning."""

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh

from paddlebox_tpu.parallel.pipeline import (
    PIPE_AXIS,
    PipelineTrainer,
    init_pipeline_params,
    pipeline_forward_loss,
    reference_forward_loss,
)
from paddlebox_tpu.utils.jax_compat import shard_map

P_STAGES, M, MB, D_IN, WIDTH = 4, 8, 16, 10, 32


def _mesh():
    return Mesh(np.array(jax.devices()[:P_STAGES]), (PIPE_AXIS,))


def _data(seed=0):
    rng = np.random.default_rng(seed)
    x = rng.normal(size=(M, MB, D_IN)).astype(np.float32)
    y = (x.mean(-1) > 0).astype(np.float32)  # learnable signal
    mask = np.ones((M, MB), np.float32)
    mask[-1, MB // 2 :] = 0.0  # ragged tail microbatch
    return x, y, mask


def test_forward_matches_sequential():
    mesh = _mesh()
    params = init_pipeline_params(
        jax.random.PRNGKey(0), D_IN, WIDTH, 2, P_STAGES
    )
    x, y, mask = _data()

    from jax.sharding import NamedSharding, PartitionSpec as PS

    piped = jax.jit(
        shard_map(
            lambda p, a, b, c: pipeline_forward_loss(
                jax.tree.map(lambda l: l[0], p), a, b, c
            )[None],
            mesh=mesh,
            in_specs=(PS(PIPE_AXIS), PS(), PS(), PS()),
            out_specs=PS(PIPE_AXIS),
        )
    )
    p_shard = jax.device_put(params, NamedSharding(mesh, PS(PIPE_AXIS)))
    got = np.asarray(piped(p_shard, x, y, mask))
    want = float(reference_forward_loss(params, jnp.asarray(x), jnp.asarray(y), jnp.asarray(mask)))
    np.testing.assert_allclose(got, want, rtol=1e-5)
    # every stage returns the psummed loss: all equal
    assert np.allclose(got, got[0])


def test_train_step_matches_sequential_grads():
    """One pipelined SGD step == one sequential SGD step on the same
    stacked params (grads flow correctly through scan + ppermute).

    SGD, deliberately: the update is LINEAR in the gradient, so the
    comparison is a direct gradient-equivalence check.  Adam's first
    step normalizes (update ≈ lr·g/|g|), which amplifies reduction-order
    float noise at near-zero-gradient coordinates into O(lr)
    differences — that flakiness was measured to live exclusively at
    |grad| < 3e-5 coords and says nothing about the pipeline's grads."""
    import optax

    mesh = _mesh()
    params = init_pipeline_params(
        jax.random.PRNGKey(1), D_IN, WIDTH, 2, P_STAGES
    )
    x, y, mask = _data(1)

    tr = PipelineTrainer(mesh, D_IN, WIDTH, 2, params=params,
                         optimizer=optax.sgd(1e-2))
    tr.train_step(x, y, mask)
    from paddlebox_tpu.parallel.multiprocess import local_view

    got = jax.tree.map(lambda l: local_view(l), tr.params)

    # sequential oracle
    opt = optax.sgd(1e-2)
    o0 = opt.init(params)
    loss, grads = jax.value_and_grad(reference_forward_loss)(
        params, jnp.asarray(x), jnp.asarray(y), jnp.asarray(mask)
    )
    upd, _ = opt.update(grads, o0, params)
    want = optax.apply_updates(params, upd)

    for k in got:
        np.testing.assert_allclose(
            np.asarray(got[k]), np.asarray(want[k]), rtol=2e-4, atol=1e-6,
            err_msg=k,
        )


def test_pipeline_learns():
    mesh = _mesh()
    tr = PipelineTrainer(mesh, D_IN, WIDTH, 2, lr=5e-3, seed=3)
    x, y, mask = _data(3)
    losses = [tr.train_step(x, y, mask) for _ in range(30)]
    assert losses[-1] < losses[0] - 0.05, (losses[0], losses[-1])
