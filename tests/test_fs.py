"""FS manager tests: LocalFS semantics, HadoopFS command construction
against a fake hadoop binary, checkpoint publishing."""

import os
import stat

import numpy as np
import pytest

from paddlebox_tpu.utils.fs import (
    FsError,
    HadoopFS,
    LocalFS,
    publish_checkpoint,
    resolve_fs,
)

FAKE_HADOOP = r"""#!/bin/bash
# fake `hadoop fs` backed by a local directory tree under $FAKE_ROOT.
# Strips -D confs (recording them) then emulates the fs verbs.
echo "$@" >> "$FAKE_ROOT/.calls"
shift  # "fs"
args=()
while [[ $# -gt 0 ]]; do
  if [[ "$1" == "-D" ]]; then shift 2; else args+=("$1"); shift; fi
done
set -- "${args[@]}"
verb=$1; shift
p() { echo "$FAKE_ROOT/${1#hdfs://ns/}"; }
case "$verb" in
  -ls)
    d=$(p "$1"); [[ -d "$d" ]] || exit 1
    echo "Found $(ls "$d" | wc -l) items"
    for f in "$d"/*; do
      echo "-rw-r--r-- 3 u g 0 2026-07-29 00:00 hdfs://ns/${f#$FAKE_ROOT/}"
    done ;;
  -test)
    flag=$1; d=$(p "$2")
    [[ "$flag" == "-d" ]] && { [[ -d "$d" ]]; exit $?; }
    [[ -e "$d" ]] ;;
  -mkdir) [[ "$1" == "-p" ]] && shift; mkdir -p "$(p "$1")" ;;
  -put) [[ "$1" == "-f" ]] && shift; src=$1; dst=$(p "$2")
        mkdir -p "$(dirname "$dst")"; cp -r "$src" "$dst" ;;
  -get) src=$(p "$1"); cp -r "$src" "$2" ;;
  -rm) while [[ "$1" == -* ]]; do shift; done; rm -rf "$(p "$1")" ;;
  -touchz) d=$(p "$1"); mkdir -p "$(dirname "$d")"; : > "$d" ;;
  -cat) cat "$(p "$1")" ;;
  *) exit 2 ;;
esac
"""


@pytest.fixture
def fake_hadoop(tmp_path):
    root = tmp_path / "remote"
    root.mkdir()
    bin_path = tmp_path / "hadoop"
    bin_path.write_text(FAKE_HADOOP)
    bin_path.chmod(bin_path.stat().st_mode | stat.S_IEXEC)
    os.environ["FAKE_ROOT"] = str(root)
    yield str(bin_path), str(root)
    os.environ.pop("FAKE_ROOT", None)


class TestLocalFS:
    def test_roundtrip(self, tmp_path):
        fs = LocalFS()
        src = tmp_path / "a.txt"
        src.write_text("hello")
        fs.mkdir(str(tmp_path / "sub"))
        fs.upload(str(src), str(tmp_path / "sub" / "b.txt"))
        assert fs.exists(str(tmp_path / "sub" / "b.txt"))
        assert fs.cat(str(tmp_path / "sub" / "b.txt")) == b"hello"
        assert str(tmp_path / "sub") in fs.ls(str(tmp_path))
        fs.download(str(tmp_path / "sub" / "b.txt"), str(tmp_path / "c.txt"))
        assert (tmp_path / "c.txt").read_text() == "hello"
        fs.rm(str(tmp_path / "sub"))
        assert not fs.exists(str(tmp_path / "sub"))

    def test_ls_non_dir_raises(self, tmp_path):
        with pytest.raises(FsError):
            LocalFS().ls(str(tmp_path / "nope"))


class TestHadoopFS:
    def test_verbs_and_confs(self, fake_hadoop, tmp_path):
        bin_path, root = fake_hadoop
        fs = HadoopFS(fs_name="hdfs://ns", fs_ugi="user,pass",
                      hadoop_bin=bin_path)
        assert not fs.exists("hdfs://ns/dir/x.txt")
        local = tmp_path / "x.txt"
        local.write_text("payload")
        fs.mkdir("hdfs://ns/dir")
        fs.upload(str(local), "hdfs://ns/dir/x.txt")
        assert fs.exists("hdfs://ns/dir/x.txt")
        assert fs.is_dir("hdfs://ns/dir")
        assert fs.cat("hdfs://ns/dir/x.txt") == b"payload"
        listing = fs.ls("hdfs://ns/dir")
        assert listing == ["hdfs://ns/dir/x.txt"]
        fs.download("hdfs://ns/dir/x.txt", str(tmp_path / "back.txt"))
        assert (tmp_path / "back.txt").read_text() == "payload"
        fs.rm("hdfs://ns/dir")
        assert not fs.exists("hdfs://ns/dir")
        # job confs went on every invocation
        calls = (tmp_path / "remote" / ".calls").read_text()
        assert "fs.default.name=hdfs://ns" in calls
        assert "hadoop.job.ugi=user,pass" in calls

    def test_failure_raises_fserror(self, fake_hadoop):
        bin_path, _ = fake_hadoop
        fs = HadoopFS(hadoop_bin=bin_path, retries=0)
        with pytest.raises(FsError):
            fs.ls("hdfs://ns/absent")


class TestResolveAndPublish:
    def test_resolve_by_scheme(self):
        assert isinstance(resolve_fs("hdfs://ns/a"), HadoopFS)
        assert isinstance(resolve_fs("afs://x/y"), HadoopFS)
        assert isinstance(resolve_fs("/tmp/x"), LocalFS)

    def test_publish_checkpoint(self, tmp_path):
        from paddlebox_tpu.checkpoint import CheckpointManager
        from paddlebox_tpu.config import SparseTableConfig
        from paddlebox_tpu.sparse.table import SparseTable

        tconf = SparseTableConfig(embedding_dim=4)
        table = SparseTable(tconf, seed=0)
        table.begin_pass(np.arange(10, dtype=np.uint64))
        table.end_pass()
        mgr = CheckpointManager(str(tmp_path / "ckpt"))
        mgr.save_base("20260729", table, {"w": np.ones(3, np.float32)}, None)

        remote = str(tmp_path / "published")
        publish_checkpoint(mgr, "20260729", remote)
        assert os.path.isdir(os.path.join(remote, "base-20260729"))
        assert os.path.exists(os.path.join(remote, "donefile.txt"))

        with pytest.raises(FsError):
            publish_checkpoint(mgr, "absent-tag", remote)
