"""Durable cold tier, integration: table recovery, crash replay,
incremental checkpoints with bounded restore, spill-corruption recovery.

In-process crash simulations (raising fault plans + object abandonment)
run in tier-1; the real SIGKILL versions — a child process frozen at
each fault site by a ``hang:`` plan and killed mid-mutation — are marked
``chaos``/``slow`` (run with ``-m chaos``)."""

import os
import signal
import subprocess
import sys
import time

import jax.numpy as jnp
import numpy as np
import pytest

from paddlebox_tpu.config import SparseTableConfig
from paddlebox_tpu.sparse import SparseTable
from paddlebox_tpu.sparse.store import BucketStore, StoreCorrupt
from paddlebox_tpu.utils import faults
from paddlebox_tpu.utils.faults import fault_plan
from paddlebox_tpu.utils.monitor import stats

N_PASSES = 4


def _conf(root, **kw):
    base = dict(
        embedding_dim=4, learning_rate=0.1, initial_g2sum=1.0,
        initial_range=0.5, grad_clip=10.0,
        overlap_pass_boundary=False, hbm_cache_rows=0,
        store_log_dir=os.path.join(str(root), "log"),
        store_log_buckets=2,
        store_compact_threshold=10_000,
    )
    base.update(kw)
    return SparseTableConfig(**base)


def _pass_keys(p):
    rs = np.random.RandomState(100 + p)
    return np.unique(rs.randint(1, 5000, size=400).astype(np.uint64))


def _run_pass(t, p):
    t.begin_pass(_pass_keys(p))
    cap = int(t.values.shape[0])
    delta = ((np.arange(cap, dtype=np.float32)[:, None] % 7.0) + p) * 0.01
    delta = np.broadcast_to(delta, (cap, int(t.values.shape[1])))
    t.values = t.values + jnp.asarray(np.ascontiguousarray(delta))
    t.g2sum = t.g2sum + jnp.float32(0.25)
    t.end_pass()


def _reference_state(root):
    t = SparseTable(_conf(root), seed=7)
    for p in range(N_PASSES):
        _run_pass(t, p)
        t.flush()
    state = t.state_dict()
    t.close()
    return state


# --------------------------------------------------------------------------- #
# recovery + census integration
# --------------------------------------------------------------------------- #
class TestTableRecovery:
    def test_reopen_recovers_bit_exact(self, tmp_path):
        ref = _reference_state(tmp_path / "a")
        # crash-free close + reopen on the same log
        again = SparseTable(_conf(tmp_path / "a"), seed=7)
        got = again.state_dict()
        np.testing.assert_array_equal(got["keys"], ref["keys"])
        np.testing.assert_array_equal(got["values"], ref["values"])
        again.close()

    def test_kill_switch_disables_the_log(self, tmp_path, monkeypatch):
        monkeypatch.setenv("PBOX_DURABLE_STORE", "0")
        t = SparseTable(_conf(tmp_path), seed=7)
        assert t._log is None
        _run_pass(t, 0)
        t.flush()
        t.close()
        assert not os.path.exists(os.path.join(str(tmp_path), "log",
                                               "CURRENT"))

    @pytest.mark.parametrize("site", [
        "store.segment_write", "store.manifest_commit", "store.compact",
    ])
    def test_crash_mid_mutation_recovers_bit_exact(self, tmp_path, site):
        """In-process crash sim: a raising fault interrupts pass 2's
        merge (or its compaction); the dying table is abandoned un-closed
        and a fresh one recovers the last committed generation, replays
        the unfinished passes, and lands bit-exact vs uninterrupted."""
        ref = _reference_state(tmp_path / "ref")

        victim = SparseTable(_conf(tmp_path / "v"), seed=7)
        for p in range(2):
            _run_pass(victim, p)
            victim.flush()
        committed = 2
        with fault_plan({site: "first:1"}):
            if site == "store.compact":
                _run_pass(victim, 2)
                victim.flush()
                committed = 3  # pass 2 landed; the compaction dies after
                with pytest.raises(faults.FaultInjected):
                    victim._log.compact(0)
            else:
                with pytest.raises(faults.FaultInjected):
                    _run_pass(victim, 2)
                    victim.flush()
        del victim  # the crash: no close(), no commit

        resumed = SparseTable(_conf(tmp_path / "v"), seed=7)
        for p in range(committed, N_PASSES):
            _run_pass(resumed, p)
            resumed.flush()
        got = resumed.state_dict()
        np.testing.assert_array_equal(got["keys"], ref["keys"])
        np.testing.assert_array_equal(got["values"], ref["values"])
        resumed.close()

    def test_census_rejects_absent_keys_without_disk(self, tmp_path):
        t = SparseTable(_conf(tmp_path), seed=7)
        _run_pass(t, 0)
        t.flush()
        before = stats.get("store.census_disk_rejects")
        # a fully fresh census: every key misses the store, and the log's
        # blooms prove absence without a single segment read
        t.begin_pass(np.arange(50_000, 50_200, dtype=np.uint64))
        t.end_pass()
        assert stats.get("store.census_disk_rejects") - before > 150
        t.close()

    def test_census_log_hits_refill_store_misses(self, tmp_path):
        """The safety net: rows the RAM store lost but the log still holds
        are re-resolved from the log, bit-exact, and counted."""
        t = SparseTable(_conf(tmp_path), seed=7)
        _run_pass(t, 0)
        t.flush()
        full_k, full_v = t._store.materialize()
        # amputate the RAM store to half its rows, log untouched
        half = full_k.shape[0] // 2
        t._store.load_bulk(full_k[:half], full_v[:half])
        before = stats.get("store.census_log_hits")
        t.begin_pass(full_k)
        vals = np.asarray(t.values)
        t.end_pass()
        assert stats.get("store.census_log_hits") - before >= full_k.shape[0] - half
        # the resolved working set carried the logged rows, not re-inits
        np.testing.assert_array_equal(
            vals[: full_k.shape[0], :], full_v[:, :-1])
        t.close()

    def test_compact_failure_is_absorbed_and_counted(self, tmp_path):
        t = SparseTable(_conf(tmp_path, store_compact_threshold=2), seed=7)
        with fault_plan({"store.compact": "first:8"}):
            before = stats.get("store.compact_failures")
            for p in range(N_PASSES):
                _run_pass(t, p)
                t.flush()
            t.close()  # drains the failed background compaction
            assert stats.get("store.compact_failures") - before > 0
        # the uncompacted log still recovers everything
        ref = _reference_state(tmp_path / "ref")
        again = SparseTable(_conf(tmp_path), seed=7)
        got = again.state_dict()
        np.testing.assert_array_equal(got["keys"], ref["keys"])
        np.testing.assert_array_equal(got["values"], ref["values"])
        again.close()


# --------------------------------------------------------------------------- #
# spill integrity
# --------------------------------------------------------------------------- #
class TestSpillIntegrity:
    def _spilled_bucket(self, store):
        b = np.nonzero(store._spilled)[0]
        assert b.shape[0] > 0, "expected at least one spilled bucket"
        return int(b[0])

    def test_corrupt_spill_recovers_from_log(self, tmp_path):
        conf = _conf(
            tmp_path, store_spill_dir=os.path.join(str(tmp_path), "spill"),
            store_buckets=4, store_max_resident=1,
        )
        t = SparseTable(conf, seed=7)
        _run_pass(t, 0)
        t.flush()
        oracle_k, oracle_v = t._log.materialize()
        b = self._spilled_bucket(t._store)
        with open(os.path.join(str(tmp_path), "spill",
                               f"bucket_{b:05d}.npz"), "wb") as fh:
            fh.write(b"not an npz at all")
        before_c = stats.get("store.spill_corrupt")
        before_r = stats.get("store.spill_recovered")
        keys_b = oracle_k[t._store._bucket_of(oracle_k) == b]
        vals, found = t._store.lookup(keys_b)
        assert found.all()
        idx = np.searchsorted(oracle_k, keys_b)
        np.testing.assert_array_equal(vals, oracle_v[idx])
        assert stats.get("store.spill_corrupt") - before_c == 1
        assert stats.get("store.spill_recovered") - before_r == keys_b.shape[0]
        t.close()

    def test_corrupt_spill_without_log_is_loud(self, tmp_path):
        s = BucketStore(n_cols=3, n_buckets=2, max_resident=1,
                        spill_dir=os.path.join(str(tmp_path), "spill"))
        k = np.arange(1, 200, dtype=np.uint64)
        v = np.ones((199, 3), dtype=np.float32)
        s.update(k, v)
        # cycle the LRU so at least one bucket lands on disk
        for q in (k[:5], k[-5:], k[:5], k[-5:]):
            s.lookup(q)
        b = np.nonzero(s._spilled)[0]
        assert b.shape[0] > 0
        b = int(b[0])
        with open(os.path.join(str(tmp_path), "spill",
                               f"bucket_{b:05d}.npz"), "wb") as fh:
            fh.write(b"garbage")
        with pytest.raises(StoreCorrupt, match="no durable tier"):
            s.lookup(k[s._bucket_of(k) == b])
        s.close()


# --------------------------------------------------------------------------- #
# incremental checkpoints: bounded recovery
# --------------------------------------------------------------------------- #
class TestIncrementalCheckpoints:
    def _ckpt_world(self, root):
        from paddlebox_tpu.checkpoint import IncrementalCheckpointManager

        t = SparseTable(_conf(root, store_log_dir=""), seed=7)
        mgr = IncrementalCheckpointManager(os.path.join(str(root), "ckpt"))
        return t, mgr

    def _train_and_save(self, t, mgr, n=4):
        params = {"w": np.arange(3, dtype=np.float32)}
        for p in range(n):
            _run_pass(t, p)
            tag = f"p{p:03d}"
            params = {"w": params["w"] + p}
            if p == 0:
                mgr.save_base(tag, t, params=params,
                              meta={"pass_index": p})
            else:
                mgr.save_delta(tag, t, params=params,
                               meta={"pass_index": p})
        return params

    def test_restore_newest_is_bit_exact(self, tmp_path):
        t, mgr = self._ckpt_world(tmp_path)
        params = self._train_and_save(t, mgr)
        want = t.state_dict()
        t.close()

        t2, mgr2 = self._ckpt_world(tmp_path)
        got_params, _, meta = mgr2.load(
            t2, params_template={"w": np.zeros(3, dtype=np.float32)})
        assert meta["tag"] == "p003" and meta["pass_index"] == 3
        got = t2.state_dict()
        np.testing.assert_array_equal(got["keys"], want["keys"])
        np.testing.assert_array_equal(got["values"], want["values"])
        np.testing.assert_array_equal(got_params["w"], params["w"])
        t2.close()

    def test_time_travel_to_an_older_tag(self, tmp_path):
        t, mgr = self._ckpt_world(tmp_path)
        snaps = {}
        params = {"w": np.zeros(3, dtype=np.float32)}
        for p in range(3):
            _run_pass(t, p)
            tag = f"p{p:03d}"
            if p == 0:
                mgr.save_base(tag, t, params=params)
            else:
                mgr.save_delta(tag, t, params=params)
            snaps[tag] = t.state_dict()
        t.close()
        t2, mgr2 = self._ckpt_world(tmp_path)
        mgr2.load(t2, upto="p001")
        got = t2.state_dict()
        np.testing.assert_array_equal(got["keys"], snaps["p001"]["keys"])
        np.testing.assert_array_equal(got["values"], snaps["p001"]["values"])
        t2.close()

    def test_delta_save_fault_aborts_clean_and_retries(self, tmp_path):
        t, mgr = self._ckpt_world(tmp_path)
        _run_pass(t, 0)
        mgr.save_base("p000", t)
        _run_pass(t, 1)
        with fault_plan({"ckpt.delta_save": "first:1"}):
            with pytest.raises(faults.FaultInjected):
                mgr.save_delta("p001", t)
            # clean abort: the tag never appeared, the tracker kept its rows
            assert mgr.find_valid_tag() == "p000"
            # retry commits the SAME delta rows
            mgr.save_delta("p001", t)
        assert mgr.find_valid_tag() == "p001"
        want = t.state_dict()
        t.close()
        t2, mgr2 = self._ckpt_world(tmp_path)
        mgr2.load(t2)
        got = t2.state_dict()
        np.testing.assert_array_equal(got["keys"], want["keys"])
        np.testing.assert_array_equal(got["values"], want["values"])
        t2.close()

    def test_corrupt_generation_falls_back_to_older_tag(self, tmp_path):
        t, mgr = self._ckpt_world(tmp_path)
        self._train_and_save(t, mgr)
        t.close()
        # damage the NEWEST generation's freshest segment
        log_root = os.path.join(str(tmp_path), "ckpt", "sparse-log")
        segs = sorted(n for n in os.listdir(log_root) if n.endswith(".seg"))
        with open(os.path.join(log_root, segs[-1]), "r+b") as fh:
            fh.seek(-4, os.SEEK_END)
            fh.write(b"\xde\xad\xbe\xef")
        _, mgr2 = self._ckpt_world(tmp_path)
        tag = mgr2.find_valid_tag()
        assert tag is not None and tag < "p003"

    def test_restore_cost_is_delta_bounded(self, tmp_path):
        """The manifest a tag pins references compacted-base + trailing
        deltas — NOT one segment per historical pass (the classic chain
        walk)."""
        t, mgr = self._ckpt_world(tmp_path)
        mgr.compact_threshold = 2
        self._train_and_save(t, mgr, n=6)
        t.close()
        log = mgr._log()
        # compaction folded history: far fewer live segments than the 6
        # saves x buckets an uncompacted chain would reference
        assert log.n_live_segments <= 2 * 4  # <= ~2 per bucket


def test_auto_checkpointer_incremental_end_to_end(tmp_path):
    """The full training stack (real dataset + CtrDnn + Trainer) over
    log-structured checkpoints: kill after pass 1, resume from the
    incremental manager, replay — metrics and table state match the
    uninterrupted run."""
    from test_auto_checkpoint import N_PASSES as NP
    from test_auto_checkpoint import _run_passes, _world

    from paddlebox_tpu.checkpoint import IncrementalCheckpointManager
    from paddlebox_tpu.train import AutoCheckpointer

    ds, table, trainer = _world(tmp_path)
    ref, _ = _run_passes(ds, table, trainer, 0, NP)
    ref_state = table.state_dict()

    ds2, table_a, trainer_a = _world(tmp_path)
    acp_a = AutoCheckpointer(str(tmp_path / "acp"), job_id="inc",
                             incremental=True)
    assert isinstance(acp_a.ckpt, IncrementalCheckpointManager)
    _run_passes(ds2, table_a, trainer_a, 0, 2, acp=acp_a)
    del table_a, trainer_a, acp_a  # the "kill"

    ds3, table_b, trainer_b = _world(tmp_path)
    acp_b = AutoCheckpointer(str(tmp_path / "acp"), job_id="inc",
                             incremental=True)
    status, mstate = acp_b.resume(
        table_b, trainer_b, metric_template=trainer_b._init_mstate()
    )
    assert status is not None and status["next_pass"] == 2
    got, _ = _run_passes(ds3, table_b, trainer_b, status["next_pass"], NP,
                         acp=acp_b, mstate=mstate)
    assert got["count"] == ref["count"]
    np.testing.assert_allclose(got["auc"], ref["auc"], atol=1e-6)
    got_state = table_b.state_dict()
    ia, ib = np.argsort(ref_state["keys"]), np.argsort(got_state["keys"])
    np.testing.assert_array_equal(ref_state["keys"][ia],
                                  got_state["keys"][ib])
    np.testing.assert_allclose(ref_state["values"][ia],
                               got_state["values"][ib],
                               rtol=1e-5, atol=1e-6)
    for d in (ds, ds2, ds3):
        d.close()


# --------------------------------------------------------------------------- #
# SIGKILL chaos: a real process killed at each crash window
# --------------------------------------------------------------------------- #
@pytest.mark.chaos
@pytest.mark.slow
@pytest.mark.parametrize("site", [
    "store.segment_write", "store.manifest_commit", "store.compact",
])
def test_sigkill_at_fault_site_recovers_bit_exact(tmp_path, site):
    child = os.path.join(os.path.dirname(__file__), "_durable_child.py")
    env = dict(os.environ, JAX_PLATFORMS="cpu")

    def run(mode, root, kill_pass=-1, sentinel=""):
        return subprocess.Popen(
            [sys.executable, child, mode, str(root), str(N_PASSES),
             str(kill_pass), site, sentinel],
            env=env,
        )

    ref_root = tmp_path / "ref"
    vic_root = tmp_path / "vic"
    os.makedirs(ref_root), os.makedirs(vic_root)
    assert run("run", ref_root).wait() == 0

    sentinel = str(tmp_path / "hung")
    victim = run("victim", vic_root, kill_pass=2, sentinel=sentinel)
    deadline = time.time() + 120
    while not os.path.exists(sentinel):
        assert victim.poll() is None, "victim exited instead of hanging"
        assert time.time() < deadline, f"{site}: victim never hung"
        time.sleep(0.02)
    os.kill(victim.pid, signal.SIGKILL)  # mid-mutation, for real
    victim.wait()

    assert run("resume", vic_root).wait() == 0
    ref = np.load(str(ref_root / "state-run.npz"))
    got = np.load(str(vic_root / "state-resume.npz"))
    np.testing.assert_array_equal(got["keys"], ref["keys"])
    np.testing.assert_array_equal(got["values"], ref["values"])
    assert float(got["auc"]) == float(ref["auc"])  # bit-exact, not close
