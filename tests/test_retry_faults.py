"""Unit tests for the fault-tolerance primitives: utils/retry.py (unified
backoff) and utils/faults.py (deterministic injection registry)."""

import subprocess

import pytest

from paddlebox_tpu.utils import faults
from paddlebox_tpu.utils.faults import FaultInjected, FaultPlan, FaultSpec, fault_plan
from paddlebox_tpu.utils.monitor import stats
from paddlebox_tpu.utils.retry import RetryPolicy, default_retryable, retry_call

FAST = RetryPolicy(max_attempts=3, base_delay_s=0.001, max_delay_s=0.002)


@pytest.fixture(autouse=True)
def _clean_state():
    stats.reset()
    faults.clear()
    yield
    faults.clear()


class TestRetry:
    def test_succeeds_after_transient_failures(self):
        calls = []

        def flaky():
            calls.append(1)
            if len(calls) < 3:
                raise OSError("transient")
            return 42

        assert retry_call(flaky, site="t.flaky", policy=FAST) == 42
        assert len(calls) == 3
        snap = stats.snapshot()
        assert snap["retry.t.flaky.calls"] == 1
        assert snap["retry.t.flaky.attempts"] == 3
        assert snap["retry.t.flaky.retries"] == 2
        assert "retry.t.flaky.exhausted" not in snap

    def test_non_retryable_raises_immediately(self):
        calls = []

        def bad():
            calls.append(1)
            raise ValueError("logic error, not transient")

        with pytest.raises(ValueError):
            retry_call(bad, site="t.bad", policy=FAST)
        assert len(calls) == 1

    def test_exhausted_reraises_last_and_counts(self):
        def always():
            raise OSError("down")

        with pytest.raises(OSError):
            retry_call(always, site="t.down", policy=FAST)
        snap = stats.snapshot()
        assert snap["retry.t.down.attempts"] == 3
        assert snap["retry.t.down.exhausted"] == 1

    def test_deadline_bounds_the_call(self):
        calls = []

        def always():
            calls.append(1)
            raise OSError("down")

        slow = RetryPolicy(
            max_attempts=100, base_delay_s=0.05, max_delay_s=0.05,
            deadline_s=0.12,
        )
        with pytest.raises(OSError):
            retry_call(always, site="t.deadline", policy=slow)
        # ~2-3 attempts fit in 120ms of 50ms sleeps, never all 100
        assert len(calls) < 10

    def test_default_retryable_classes(self):
        assert default_retryable(OSError())
        assert default_retryable(subprocess.SubprocessError())
        assert default_retryable(FaultInjected("x"))
        from paddlebox_tpu.utils.fs import FsError

        assert default_retryable(FsError("x"))
        assert not default_retryable(ValueError())
        assert not default_retryable(KeyError())

    def test_backoff_is_deterministic_and_capped(self):
        p = RetryPolicy(base_delay_s=1.0, max_delay_s=5.0, jitter=0.1)
        d1 = [p.delay(a, "site.x") for a in (1, 2, 3, 4)]
        d2 = [p.delay(a, "site.x") for a in (1, 2, 3, 4)]
        assert d1 == d2  # same site+attempt -> same jitter
        assert d1[0] >= 1.0 and d1[-1] <= 5.0 * 1.1
        assert p.delay(1, "site.y") != d1[0]  # sites don't sleep in lockstep


class TestFaultPlan:
    def test_spec_parsing(self):
        assert FaultSpec.parse("first:2") == FaultSpec(fail_first=2)
        assert FaultSpec.parse("at:3,7") == FaultSpec(at=(3, 7))
        assert FaultSpec.parse("p:0.5") == FaultSpec(probability=0.5)
        with pytest.raises(ValueError):
            FaultSpec.parse("sometimes")

    def test_fail_first_n(self):
        plan = FaultPlan({"a.b": "first:2"})
        assert [plan.check("a.b") for _ in range(4)] == [
            True, True, False, False,
        ]

    def test_at_indices(self):
        plan = FaultPlan({"a.b": "at:1,3"})
        assert [plan.check("a.b") for _ in range(5)] == [
            False, True, False, True, False,
        ]

    def test_probability_deterministic_per_seed(self):
        plan1 = FaultPlan({"a": "p:0.5"}, seed=7)
        out1 = [plan1.check("a") for _ in range(20)]
        plan2 = FaultPlan({"a": "p:0.5"}, seed=7)
        out2 = [plan2.check("a") for _ in range(20)]
        assert out1 == out2
        assert any(out1) and not all(out1)

    def test_prefix_wildcard(self):
        plan = FaultPlan({"fs.*": "first:1"})
        assert plan.check("fs.upload")
        # hit counters are per concrete site
        assert plan.check("fs.download")
        assert not plan.check("fs.upload")

    def test_unlisted_site_never_fails(self):
        plan = FaultPlan({"a.b": "first:99"})
        assert not plan.check("other")

    def test_inject_raises_and_counts(self):
        with fault_plan({"x.y": "first:1"}):
            with pytest.raises(FaultInjected):
                faults.inject("x.y")
            faults.inject("x.y")  # second hit passes
        snap = stats.snapshot()
        assert snap["faults.injected.x.y"] == 1
        assert snap["faults.checked.x.y"] == 2

    def test_no_plan_is_noop(self):
        faults.inject("anything")  # must not raise
        assert not faults.fire("anything")

    def test_from_env(self, monkeypatch):
        monkeypatch.setenv(
            "PBOX_FAULT_PLAN", "fs.upload=first:2; data.read=p:0.25"
        )
        monkeypatch.setenv("PBOX_FAULT_SEED", "3")
        plan = FaultPlan.from_env()
        assert plan.seed == 3
        assert plan.sites["fs.upload"] == FaultSpec(fail_first=2)
        assert plan.sites["data.read"] == FaultSpec(probability=0.25)
        monkeypatch.setenv("PBOX_FAULT_PLAN", "")
        assert FaultPlan.from_env() is None

    def test_retry_absorbs_injected_faults(self):
        """The integration the whole design hangs off: a fail-first-N plan
        under a retry loop with > N attempts succeeds."""
        with fault_plan({"t.site": "first:2"}):
            def op():
                faults.inject("t.site")
                return "ok"

            assert retry_call(op, site="t.site", policy=FAST) == "ok"
        snap = stats.snapshot()
        assert snap["faults.injected.t.site"] == 2
        assert snap["retry.t.site.attempts"] == 3
