"""Packaged HTTP scoring server (inference/server.py): multi-artifact
routing, health/metadata endpoints, training-exact scoring through the
same parser/feed as the trainer."""

import json
import urllib.request

import numpy as np
import pytest

from paddlebox_tpu.config import SparseTableConfig, TrainerConfig
from paddlebox_tpu.data.dataset import PadBoxSlotDataset
from paddlebox_tpu.data.synth import make_synth_config, write_synth_files
from paddlebox_tpu.inference import ScoringServer, export_model
from paddlebox_tpu.models import CtrDnn
from paddlebox_tpu.sparse.table import SparseTable
from paddlebox_tpu.train.trainer import Trainer

S, DENSE, B = 3, 2, 16


def _train_and_export(tmp_path, tag, seed, model_fn=None, conf_kw=None,
                      synth_kw=None):
    conf = make_synth_config(n_sparse_slots=S, dense_dim=DENSE, batch_size=B,
                             max_feasigns_per_ins=8, **(conf_kw or {}))
    files = write_synth_files(str(tmp_path / f"d{tag}"), n_files=1,
                              ins_per_file=64, n_sparse_slots=S,
                              vocab_per_slot=40, dense_dim=DENSE, seed=seed,
                              **(synth_kw or {}))
    ds = PadBoxSlotDataset(conf, read_threads=1)
    ds.set_filelist(files)
    ds.load_into_memory()
    tconf = SparseTableConfig(embedding_dim=4)
    model = (model_fn or (lambda tc: CtrDnn(
        S, tc.row_width, dense_dim=DENSE, hidden=(8,))))(tconf)
    table = SparseTable(tconf, seed=seed)
    trainer = Trainer(model, tconf, TrainerConfig(auc_buckets=1 << 10),
                      seed=seed)
    table.begin_pass(ds.unique_keys())
    trainer.train_from_dataset(ds, table)
    table.end_pass()
    ds.close()
    kcap = conf.batch_key_capacity or (B * conf.max_feasigns_per_ins)
    art = str(tmp_path / f"art{tag}")
    export_model(model, trainer.params, table, art,
                 batch_size=B, key_capacity=kcap, dense_dim=DENSE)
    return conf, art


def _lines(n, seed=5):
    rng = np.random.default_rng(seed)
    out = []
    for _ in range(n):
        parts = ["1 0"]
        for s in range(S):
            ks = rng.integers(0, 40, 2)
            parts.append(f"{len(ks)} " + " ".join(map(str, ks)))
        parts.append(f"{DENSE} " + " ".join(
            f"{v:.3f}" for v in rng.random(DENSE)))
        out.append(" ".join(parts))
    return ("\n".join(out) + "\n").encode()


@pytest.fixture
def server(tmp_path):
    conf_a, art_a = _train_and_export(tmp_path, "a", seed=1)
    conf_b, art_b = _train_and_export(tmp_path, "b", seed=2)
    srv = ScoringServer()
    srv.register("a", art_a, conf_a)
    srv.register("b", art_b, conf_b)
    port = srv.start(port=0)
    yield srv, port
    srv.stop()


def _post(port, path, body):
    req = urllib.request.Request(
        f"http://127.0.0.1:{port}{path}", data=body, method="POST")
    with urllib.request.urlopen(req, timeout=30) as r:
        return r.status, json.loads(r.read())


def _get(port, path):
    with urllib.request.urlopen(
            f"http://127.0.0.1:{port}{path}", timeout=30) as r:
        return r.status, json.loads(r.read())


def test_score_default_and_named(server):
    srv, port = server
    body = _lines(23)  # more than one batch -> bucket padding path too
    st, out = _post(port, "/score", body)
    assert st == 200 and len(out["scores"]) == 23
    assert all(0.0 < s < 1.0 for s in out["scores"])
    st, out_a = _post(port, "/score/a", body)
    assert out_a["scores"] == out["scores"]  # default == first registered
    st, out_b = _post(port, "/score/b", body)
    assert out_b["scores"] != out["scores"]  # different model, diff scores


def test_health_models_and_errors(server):
    srv, port = server
    _post(port, "/score", _lines(3))
    st, h = _get(port, "/healthz")
    assert st == 200 and h["ok"]
    assert h["models"]["a"]["requests"] == 1
    assert h["models"]["a"]["instances"] == 3
    assert h["models"]["a"]["n_features"] > 0
    st, m = _get(port, "/models")
    assert set(m["models"]) == {"a", "b"} and m["default"] == "a"

    # unknown model -> 404; garbage body -> 400, server stays up
    import urllib.error

    with pytest.raises(urllib.error.HTTPError) as ei:
        _post(port, "/score/nope", _lines(1))
    assert ei.value.code == 404
    with pytest.raises(urllib.error.HTTPError) as ei:
        _post(port, "/score", b"not a slot line\n")
    assert ei.value.code == 400
    st, out = _post(port, "/score", _lines(2))
    assert st == 200 and len(out["scores"]) == 2


def test_key_dense_requests_split_not_clipped(tmp_path):
    """A request whose key count overflows the feed's batch key capacity
    must be scored by recursive chunk-splitting, not by silently dropping
    features (the builder's training-parity clip).  Scores must equal the
    same instances scored one at a time."""
    from paddlebox_tpu.data.slot_parser import SlotParser
    from paddlebox_tpu.data.feed import BatchBuilder

    conf, art = _train_and_export(tmp_path, "kd", seed=6)
    srv = ScoringServer()
    srv.register("kd", art, conf)

    # key-dense lines: ~6x the per-instance key budget the batch capacity
    # assumes (B=16, max_feasigns_per_ins=8 -> capacity 128 keys/batch;
    # 16 instances x 3 slots x 16 keys = 768 keys)
    rng = np.random.default_rng(11)
    out = []
    for _ in range(16):
        parts = ["1 0"]
        for s in range(S):
            ks = rng.integers(0, 40, 16)
            parts.append(f"{len(ks)} " + " ".join(map(str, ks)))
        parts.append(f"{DENSE} " + " ".join(
            f"{v:.3f}" for v in rng.random(DENSE)))
        out.append(" ".join(parts))
    body = ("\n".join(out) + "\n").encode()

    got = srv.score_lines(body)
    assert len(got) == 16

    # oracle: each instance alone (fits capacity: 48 keys) — no clipping
    want = []
    for line in out:
        want.extend(srv.score_lines((line + "\n").encode()))
    np.testing.assert_allclose(got, want, rtol=1e-6)

    # and the builder really WOULD have clipped these as one batch
    parser = SlotParser(conf)
    block = parser.parse_lines(out)
    b = BatchBuilder(conf)
    b.build(block, np.arange(16))
    assert b.dropped_keys > 0


def test_clipped_single_instance_reported(tmp_path):
    """ONE instance beyond key capacity serves clipped (training parity) —
    and the response says so: score_lines_detail counts it and the HTTP
    payload carries clipped_instances (ADVICE r5)."""
    conf, art = _train_and_export(tmp_path, "clip", seed=9)
    srv = ScoringServer()
    srv.register("clip", art, conf)
    kcap = conf.batch_key_capacity or (B * conf.max_feasigns_per_ins)

    rng = np.random.default_rng(3)
    parts = ["1 0"]
    per_slot = kcap // S + 8  # a single instance over the whole capacity
    for s in range(S):
        ks = rng.integers(0, 40, per_slot)
        parts.append(f"{len(ks)} " + " ".join(map(str, ks)))
    parts.append(f"{DENSE} " + " ".join(
        f"{v:.3f}" for v in rng.random(DENSE)))
    fat = (" ".join(parts) + "\n").encode()

    detail = srv.score_lines_detail(fat)
    assert len(detail["scores"]) == 1
    assert detail["clipped_instances"] == 1
    # an in-capacity request reports zero and the field stays off the wire
    detail = srv.score_lines_detail(_lines(2))
    assert detail["clipped_instances"] == 0

    port = srv.start(port=0)
    try:
        req = urllib.request.Request(
            f"http://127.0.0.1:{port}/score", data=fat, method="POST")
        with urllib.request.urlopen(req, timeout=30) as r:
            out = json.loads(r.read())
        assert out["clipped_instances"] == 1 and len(out["scores"]) == 1
        req = urllib.request.Request(
            f"http://127.0.0.1:{port}/score", data=_lines(2), method="POST")
        with urllib.request.urlopen(req, timeout=30) as r:
            out = json.loads(r.read())
        assert "clipped_instances" not in out
    finally:
        srv.stop()


def test_longseq_artifact_serves(tmp_path):
    """A behavior-sequence model (uses_seq_pos) exports and serves over the
    packaged server: the feed builds seq_pos from the configured
    sequence_slot and the predictor re-buckets it."""
    from paddlebox_tpu.models import LongSeqCtrDnn

    T = 8
    conf, art = _train_and_export(
        tmp_path, "seq", seed=3,
        model_fn=lambda tc: LongSeqCtrDnn(
            S, tc.row_width, dense_dim=DENSE, hidden=(8,), max_seq_len=T,
            n_heads=2, head_dim=4),
        conf_kw={"sequence_slot": "slot0", "max_seq_len": T},
    )

    srv = ScoringServer()
    srv.register("seq", art, conf)
    port = srv.start()
    try:
        st, out = _post(port, "/score", _lines(5))
        assert st == 200 and len(out["scores"]) == 5
        assert all(0.0 < s < 1.0 for s in out["scores"])
    finally:
        srv.stop()

    # a NARROWER client feed (shorter max_seq_len) pads with the bucket's
    # marker and scores identically to the artifact-width feed; a WIDER one
    # still raises (it would drop history — ADVICE r5)
    import dataclasses

    from paddlebox_tpu.data.slot_parser import SlotParser
    from paddlebox_tpu.data.feed import BatchBuilder
    from paddlebox_tpu.inference import Predictor

    pred = Predictor.load(art)
    lines = _lines(4).decode().splitlines()

    def score_at(seq_len):
        c = dataclasses.replace(conf, max_seq_len=seq_len)
        block = SlotParser(c).parse_lines(lines)
        batch = BatchBuilder(c).build(block, np.arange(4))
        return pred.predict(batch)

    np.testing.assert_allclose(score_at(T // 2), score_at(T), rtol=1e-6)
    with pytest.raises(ValueError, match="seq_len"):
        score_at(2 * T)


def test_multitask_artifact_rejected(tmp_path):
    """register() must refuse multi-task artifacts with a clear message
    (predict returns [b, n_tasks], unservable over the slot-text route)."""
    from paddlebox_tpu.models import MMoE

    conf, art = _train_and_export(
        tmp_path, "mt", seed=4,
        model_fn=lambda tc: MMoE(
            S, tc.row_width, dense_dim=DENSE, n_tasks=2, n_experts=2,
            expert_hidden=(8,), expert_dim=4, tower_hidden=(4,)),
        conf_kw={"n_task_labels": 1}, synth_kw={"n_task_labels": 1},
    )
    srv = ScoringServer()
    with pytest.raises(ValueError, match="multi-task"):
        srv.register("mt", art, conf)


def test_self_contained_artifact(tmp_path):
    """export_model(feed_conf=...) embeds the feed schema; register() with
    no config reconstructs it from the artifact alone — a serving host
    needs nothing but the artifact directory."""
    import dataclasses

    conf = make_synth_config(n_sparse_slots=S, dense_dim=DENSE, batch_size=B,
                             max_feasigns_per_ins=8)
    files = write_synth_files(str(tmp_path / "d"), n_files=1, ins_per_file=64,
                              n_sparse_slots=S, vocab_per_slot=40,
                              dense_dim=DENSE, seed=1)
    ds = PadBoxSlotDataset(conf, read_threads=1)
    ds.set_filelist(files)
    ds.load_into_memory()
    tconf = SparseTableConfig(embedding_dim=4)
    model = CtrDnn(S, tconf.row_width, dense_dim=DENSE, hidden=(8,))
    table = SparseTable(tconf, seed=1)
    trainer = Trainer(model, tconf, TrainerConfig(auc_buckets=1 << 10), seed=1)
    table.begin_pass(ds.unique_keys())
    trainer.train_from_dataset(ds, table)
    table.end_pass()
    ds.close()
    kcap = conf.batch_key_capacity or (B * conf.max_feasigns_per_ins)
    art = str(tmp_path / "art")
    export_model(model, trainer.params, table, art,
                 batch_size=B, key_capacity=kcap, dense_dim=DENSE,
                 feed_conf=conf)

    srv = ScoringServer()
    srv.register("auto", art)  # NO feed_conf
    port = srv.start()
    try:
        st, out = _post(port, "/score", _lines(4))
        assert st == 200 and len(out["scores"]) == 4
    finally:
        srv.stop()

    # the reconstructed config round-trips the original
    from paddlebox_tpu.config import DataFeedConfig
    import json as _json

    with open(f"{art}/feed.json") as f:
        raw = _json.load(f)
    rt = DataFeedConfig.from_dict(raw)
    assert dataclasses.asdict(rt) == dataclasses.asdict(conf)
    # a NEWER exporter's unknown key is dropped with a warning, not a crash
    import warnings

    with warnings.catch_warnings(record=True) as w:
        warnings.simplefilter("always")
        rt2 = DataFeedConfig.from_dict({**raw, "future_field": 7})
    assert dataclasses.asdict(rt2) == dataclasses.asdict(conf)
    assert any("future_field" in str(x.message) for x in w)

    # artifact without feed.json -> clear error
    import os

    os.remove(f"{art}/feed.json")
    srv2 = ScoringServer()
    with pytest.raises(ValueError, match="feed.json"):
        srv2.register("x", art)


def test_serve_cli_module(tmp_path):
    """`python -m paddlebox_tpu.serve` registers artifacts (NAME=DIR and
    bare-DIR forms) and serves; drive it in-process with start/stop via
    the module's own pieces."""
    import subprocess
    import sys
    import time

    conf = make_synth_config(n_sparse_slots=S, dense_dim=DENSE, batch_size=B,
                             max_feasigns_per_ins=8)
    files = write_synth_files(str(tmp_path / "d"), n_files=1, ins_per_file=64,
                              n_sparse_slots=S, vocab_per_slot=40,
                              dense_dim=DENSE, seed=1)
    ds = PadBoxSlotDataset(conf, read_threads=1)
    ds.set_filelist(files)
    ds.load_into_memory()
    tconf = SparseTableConfig(embedding_dim=4)
    model = CtrDnn(S, tconf.row_width, dense_dim=DENSE, hidden=(8,))
    table = SparseTable(tconf, seed=1)
    trainer = Trainer(model, tconf, TrainerConfig(auc_buckets=1 << 10), seed=1)
    table.begin_pass(ds.unique_keys())
    trainer.train_from_dataset(ds, table)
    table.end_pass()
    ds.close()
    kcap = conf.batch_key_capacity or (B * conf.max_feasigns_per_ins)
    art = str(tmp_path / "myart")
    export_model(model, trainer.params, table, art,
                 batch_size=B, key_capacity=kcap, dense_dim=DENSE,
                 feed_conf=conf)

    proc = subprocess.Popen(
        [sys.executable, "-m", "paddlebox_tpu.serve", "--artifact",
         f"m={art}", "--artifact", art, "--port", "0", "--cpu"],
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
        cwd="/root/repo",
    )
    try:
        port = None
        t0 = time.time()
        while time.time() - t0 < 120:
            line = proc.stdout.readline()
            if not line:  # pipe closed: the child died at startup
                assert proc.poll() is None, (
                    f"serve CLI exited rc={proc.returncode}"
                )
                time.sleep(0.2)
                continue
            if "serving on http://" in line:
                port = int(line.split(":")[2].split("/")[0])
                break
        assert port, "server never came up"
        st, out = _post(port, "/score/m", _lines(3))
        assert st == 200 and len(out["scores"]) == 3
        st, m = _get(port, "/models")
        assert set(m["models"]) == {"m", "myart"} and m["default"] == "m"
    finally:
        proc.terminate()
        proc.wait(timeout=10)


# --------------------------------------------------------------------------- #
# graceful drain (no artifact needed: the drain machinery is exercised
# against a dummy listener, so these stay runnable on builds where
# export itself cannot)
# --------------------------------------------------------------------------- #
class _DummyHttpd:
    def __init__(self):
        self.shut = False
        self.closed = False

    def shutdown(self):
        self.shut = True

    def server_close(self):
        self.closed = True


def test_stop_waits_for_inflight_then_closes():
    import threading
    import time as _time

    from paddlebox_tpu.inference.server import ScoringServer

    srv = ScoringServer()
    srv._httpd = _DummyHttpd()
    httpd = srv._httpd
    assert srv._begin_request()

    def finish_soon():
        _time.sleep(0.15)
        srv._end_request()

    threading.Thread(target=finish_soon, daemon=True).start()
    t0 = _time.monotonic()
    srv.stop(drain_timeout_s=5.0)
    dt = _time.monotonic() - t0
    assert 0.1 < dt < 2.0  # waited for the request, not the full deadline
    assert httpd.shut and httpd.closed
    # idempotent
    srv.stop()


def test_stop_drain_deadline_counts_and_closes():
    from paddlebox_tpu.inference.server import ScoringServer
    from paddlebox_tpu.utils.monitor import stats

    srv = ScoringServer()
    srv._httpd = _DummyHttpd()
    httpd = srv._httpd
    assert srv._begin_request()  # never finishes
    base = stats.get("server.drain_timeout")
    srv.stop(drain_timeout_s=0.2)
    assert stats.get("server.drain_timeout") == base + 1
    assert httpd.shut and httpd.closed
    srv._end_request()  # late finish after close: no crash


# --------------------------------------------------------------------------- #
# hot swap: replacing a live model must be atomic w.r.t. in-flight scoring
# --------------------------------------------------------------------------- #
def test_register_replace_hot_swap_atomic_under_load(tmp_path):
    """Re-registering a name (and swap_model) while requests are in
    flight: every response must be EXACTLY the old model's scores or the
    new model's — a request that mixed the two predictors (e.g. old
    bucket ladder + new programs) would produce a third sequence."""
    import threading

    conf_a, art_a = _train_and_export(tmp_path, "a", seed=1)
    conf_b, art_b = _train_and_export(tmp_path, "b", seed=2)
    from paddlebox_tpu.inference.predictor import Predictor

    pred_a, pred_b = Predictor.load(art_a), Predictor.load(art_b)
    srv = ScoringServer()
    srv.register("m", art_a, conf_a)
    body = _lines(23)  # several chunks: exercises the per-request pinning
    want_a = srv.score_lines(body, "m")
    srv.swap_model("m", pred_b)
    want_b = srv.score_lines(body, "m")
    assert want_a != want_b
    srv.swap_model("m", pred_a)

    bad, stop = [], threading.Event()

    def hammer():
        while not stop.is_set():
            got = srv.score_lines(body, "m")
            if got != want_a and got != want_b:
                bad.append(got)

    threads = [threading.Thread(target=hammer) for _ in range(3)]
    for t in threads:
        t.start()
    try:
        for i in range(30):
            srv.swap_model("m", pred_b if i % 2 == 0 else pred_a)
    finally:
        stop.set()
        for t in threads:
            t.join()
    assert not bad  # no request observed a half-swapped model


def test_register_replace_preserves_counters_and_default(tmp_path):
    conf_a, art_a = _train_and_export(tmp_path, "a", seed=1)
    conf_b, art_b = _train_and_export(tmp_path, "b", seed=2)
    srv = ScoringServer()
    srv.register("m", art_a, conf_a)
    srv.register("other", art_b, conf_b)
    srv.score_lines(_lines(4), "m")
    assert srv._models["m"].requests == 1
    srv.register("m", art_b, conf_b)  # hot replace
    assert srv._default == "m"
    assert srv._models["m"].requests == 1  # serving history carries over
    assert srv._models["m"].instances == 4
    # swap_model on an unknown name refuses (a delta cannot create models)
    import pytest as _pytest

    with _pytest.raises(KeyError):
        srv.swap_model("nope", srv._models["m"].predictor)


def test_models_endpoint_reports_lineage_and_age(server):
    """GET /models carries per-model version lineage + freshness age and
    refreshes the serve.model_age_seconds gauge."""
    from paddlebox_tpu import telemetry

    srv, port = server
    srv.swap_model("a", srv._models["a"].predictor, version={
        "base_tag": "day0", "tag": "day0-p3", "deltas_applied": 3,
        "seq": 3, "published_at": 123.0,
    })
    st, m = _get(port, "/models")
    assert st == 200 and m["default"] == "a"
    a = m["models"]["a"]
    assert a["base_tag"] == "day0" and a["deltas_applied"] == 3
    assert a["tag"] == "day0-p3" and a["seq"] == 3
    assert a["age_seconds"] > 0
    # a directly-registered model still reports (load-time freshness)
    b = m["models"]["b"]
    assert b["base_tag"] is None and b["deltas_applied"] == 0
    assert b["age_seconds"] >= 0
    gauge = telemetry.gauge("serve.model_age_seconds")
    assert gauge.value(model="a") == a["age_seconds"]


def test_draining_rejects_new_requests():
    from paddlebox_tpu.inference.server import ScoringServer

    srv = ScoringServer()
    srv._httpd = _DummyHttpd()
    with srv._inflight_cv:
        srv._draining = True
    assert not srv._begin_request()
    with srv._inflight_cv:
        srv._draining = False
    assert srv._begin_request()
    srv._end_request()


# --------------------------------------------------------------------------- #
# request-parsing hardening: bounded bodies, validated Content-Length
# (stub scoring — the refusals happen before any model runs)
# --------------------------------------------------------------------------- #
def _hardening_server(max_body_bytes=None):
    from paddlebox_tpu.config import DataFeedConfig, SlotConfig
    from paddlebox_tpu.inference.server import ScoringServer

    class _Stub:
        meta = {"n_tasks": 1, "row_width": 4}
        bucket_shapes = [(8, 64)]
        n_features = 1

    conf = DataFeedConfig(
        slots=(SlotConfig("click", type="float", is_dense=True),
               SlotConfig("s0")),
        batch_size=8,
    )
    srv = ScoringServer(max_body_bytes=max_body_bytes)
    srv.register_predictor("stub", _Stub(), conf)
    srv.score_lines = lambda text, name=None: [
        0.5 for ln in text.decode().splitlines() if ln.strip()
    ]
    return srv


def _raw_post(port, headers, body=b""):
    import http.client

    conn = http.client.HTTPConnection("127.0.0.1", port, timeout=30)
    try:
        conn.putrequest("POST", "/score", skip_host=False)
        for k, v in headers.items():
            conn.putheader(k, v)
        conn.endheaders()
        if body:
            conn.send(body)
        r = conn.getresponse()
        return r.status, json.loads(r.read())
    finally:
        conn.close()


def test_oversized_body_413_without_reading():
    from paddlebox_tpu import telemetry

    srv = _hardening_server(max_body_bytes=64)
    port = srv.start(port=0)
    counter = telemetry.counter("server.oversized_body")
    base = counter.value()
    try:
        st, out = _raw_post(port, {"Content-Length": "100000"})
        assert st == 413 and "max_body_bytes" in out["error"]
        assert counter.value() == base + 1
        # an in-bounds request still serves
        body = b"x\ny\n"
        st, out = _raw_post(
            port, {"Content-Length": str(len(body))}, body)
        assert st == 200 and len(out["scores"]) == 2
    finally:
        srv.stop()


def test_missing_and_absurd_content_length_400():
    from paddlebox_tpu import telemetry

    srv = _hardening_server()
    port = srv.start(port=0)
    counter = telemetry.counter("server.bad_content_length")
    base = counter.value()
    try:
        st, out = _raw_post(port, {})  # no Content-Length at all
        assert st == 400 and "Content-Length" in out["error"]
        st, out = _raw_post(port, {"Content-Length": "-5"})
        assert st == 400
        st, out = _raw_post(port, {"Content-Length": "banana"})
        assert st == 400
        assert counter.value() == base + 3
    finally:
        srv.stop()


def test_healthz_reports_degraded_and_freshness():
    """The enriched probe surface the fleet router routes on: degraded
    reasons, per-model age/seq and queue depth in ONE /healthz read."""
    srv = _hardening_server()
    port = srv.start(port=0)
    try:
        st, h = _get(port, "/healthz")
        assert st == 200 and h["ok"] and not h["degraded"]
        assert h["queue_depth"] == 0
        assert h["models"]["stub"]["age_seconds"] >= 0
        srv.set_degraded("sync:live", "5 entries behind")
        st, h = _get(port, "/healthz")
        assert st == 200  # degraded still SERVES (degrade, don't fail)
        assert h["degraded"] and \
            h["degraded_reasons"] == {"sync:live": "5 entries behind"}
        srv.clear_degraded("sync:live")
        st, h = _get(port, "/healthz")
        assert not h["degraded"]
    finally:
        srv.stop()
