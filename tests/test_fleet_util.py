"""Production fleet utilities (utils/fleet_util.py): health checks, publish
gating, model reports — the fleet_util.py decision layer
(reference: fluid/incubate/fleet/utils/fleet_util.py)."""

import numpy as np
import pytest

from paddlebox_tpu.config import SparseTableConfig, TrainerConfig
from paddlebox_tpu.data.dataset import PadBoxSlotDataset
from paddlebox_tpu.data.synth import make_synth_config, write_synth_files
from paddlebox_tpu.models import CtrDnn
from paddlebox_tpu.sparse.table import SparseTable
from paddlebox_tpu.train.trainer import Trainer
from paddlebox_tpu.utils.fleet_util import (
    HealthPolicy,
    ModelMonitor,
    check_model,
)


def _metrics(auc=0.7, loss=0.5, pred=0.2, actual=0.2, count=100.0):
    return {"auc": auc, "loss": loss, "predicted_ctr": pred,
            "actual_ctr": actual, "count": count}


def test_health_check_passes_and_fails():
    mon = ModelMonitor()
    assert mon.observe(_metrics()).ok
    # AUC collapse vs previous pass
    r = mon.check(_metrics(auc=0.55))
    assert not r.ok and any("dropped" in x for x in r.reasons)
    # worse than chance
    r = mon.check(_metrics(auc=0.45))
    assert not r.ok
    # diverged loss and non-finite loss
    assert not mon.check(_metrics(loss=100.0)).ok
    assert not mon.check(_metrics(loss=float("nan"))).ok
    # calibration gap (dead tower shape)
    r = mon.check(_metrics(pred=0.9, actual=0.2))
    assert not r.ok and any("calibration" in x for x in r.reasons)


def test_publish_gate_tracks_best():
    mon = ModelMonitor(HealthPolicy(max_auc_drop=1.0))
    mon.observe(_metrics(auc=0.80))
    assert mon.should_publish(_metrics(auc=0.79))  # within tolerance
    assert not mon.should_publish(_metrics(auc=0.70))  # far behind best
    assert mon.should_publish(_metrics(auc=0.81))


def test_check_model_and_global_auc(tmp_path):
    conf = make_synth_config(n_sparse_slots=3, dense_dim=2, batch_size=32,
                             max_feasigns_per_ins=8)
    files = write_synth_files(str(tmp_path), n_files=1, ins_per_file=128,
                              n_sparse_slots=3, vocab_per_slot=40,
                              dense_dim=2, seed=2)
    ds = PadBoxSlotDataset(conf, read_threads=1)
    ds.set_filelist(files)
    ds.load_into_memory()
    tconf = SparseTableConfig(embedding_dim=4)
    model = CtrDnn(3, tconf.row_width, dense_dim=2, hidden=(8,))
    table = SparseTable(tconf, seed=0)
    trainer = Trainer(model, tconf, TrainerConfig(auc_buckets=1 << 10), seed=0)
    table.begin_pass(ds.unique_keys())
    m = trainer.train_from_dataset(ds, table)
    table.end_pass()
    ds.close()

    rep = check_model(table, trainer)
    assert rep["n_features"] > 0 and rep["sparse_finite"]
    assert rep["dense_params"] > 0 and rep["dense_finite"]
    assert rep["sparse_bytes"] > 0 and rep["dense_bytes"] > 0

    g = ModelMonitor.global_auc(trainer)
    assert g == pytest.approx(m["auc"], abs=1e-9)

    fresh = Trainer(model, tconf, TrainerConfig(auc_buckets=1 << 10))
    with pytest.raises(RuntimeError):
        ModelMonitor.global_auc(fresh)
