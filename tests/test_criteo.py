"""Criteo-format adapter: TSV parse, hashing stability, conversion into
the canonical pipeline, and an e2e learnability gate on the spec-exact
sample (reference analog: the dist-CTR e2e tier, ctr_dataset_reader.py,
whose data download is unavailable offline — BASELINE.md blocker)."""

import numpy as np
import pytest

from paddlebox_tpu.config import SparseTableConfig, TrainerConfig
from paddlebox_tpu.data.criteo import (
    CRITEO_N_CAT,
    CRITEO_N_DENSE,
    CriteoTSVGenerator,
    convert_criteo_files,
    criteo_feed_config,
    criteo_key,
    dense_transform,
    write_criteo_format_sample,
)
from paddlebox_tpu.data.dataset import PadBoxSlotDataset
from paddlebox_tpu.models import CtrDnn
from paddlebox_tpu.sparse.table import SparseTable
from paddlebox_tpu.train.trainer import Trainer


def test_key_hash_stable_and_slot_mixed():
    assert criteo_key(0, "68fd1e64") == criteo_key(0, "68fd1e64")
    assert criteo_key(0, "68fd1e64") != criteo_key(1, "68fd1e64")
    assert criteo_key(3, "") != 0 and criteo_key(5, "x") != 0
    assert 0 < criteo_key(7, "abc") < (1 << 64)


def test_dense_transform_recipe():
    assert dense_transform("") == 0.0
    assert dense_transform(None) == 0.0
    assert dense_transform("junk") == 0.0
    assert dense_transform("nan") == 0.0  # must not poison the pass
    assert dense_transform("inf") == 0.0
    assert dense_transform("-3") == 0.0  # clipped at zero
    assert dense_transform("0") == 0.0
    assert dense_transform("1") == pytest.approx(np.log1p(1.0))
    assert dense_transform("100") == pytest.approx(np.log1p(100.0))


def test_tsv_line_parses_with_empty_fields():
    conf = criteo_feed_config(8)
    gen = CriteoTSVGenerator(conf)
    ints = ["5", ""] + ["2"] * (CRITEO_N_DENSE - 2)
    cats = ["aa11bb22", ""] + ["cc33dd44"] * (CRITEO_N_CAT - 2)
    line = "\t".join(["1"] + ints + cats)
    (ins,) = list(gen.generate_sample(line))
    by = dict(ins)
    assert by["click"] == [1.0]
    assert len(by["dense0"]) == CRITEO_N_DENSE
    assert by["dense0"][0] == pytest.approx(np.log1p(5.0))
    assert by["dense0"][1] == 0.0
    assert by["cat0"] == [criteo_key(0, "aa11bb22")]
    assert by["cat1"] == []  # empty categorical emits no key
    # ragged line (short tail) still parses
    (ins2,) = list(gen.generate_sample("0\t1\t2"))
    by2 = dict(ins2)
    assert by2["click"] == [0.0] and by2["cat25"] == []


def test_convert_and_pipeline_roundtrip(tmp_path):
    tsv = write_criteo_format_sample(str(tmp_path / "s.tsv"), n_lines=256,
                                     seed=3)
    shards = convert_criteo_files([tsv], str(tmp_path / "out"),
                                  batch_size=64, lines_per_shard=100)
    assert len(shards) == 3  # 256 lines / 100 per shard
    conf = criteo_feed_config(64)
    ds = PadBoxSlotDataset(conf, read_threads=2)
    ds.set_filelist(shards)
    ds.load_into_memory()
    batches = list(ds.batches(drop_last=False))
    total = sum(int(b.ins_mask.sum()) for b in batches)
    assert total == 256
    b0 = batches[0]
    assert b0.n_sparse_slots == CRITEO_N_CAT
    assert b0.dense.shape[1] == CRITEO_N_DENSE
    assert b0.n_keys > 0 and (b0.keys[: b0.n_keys] > 0).all()
    labels = np.concatenate(
        [b.labels[b.ins_mask.astype(bool)] for b in batches])
    assert set(np.unique(labels)) <= {0.0, 1.0} and 0 < labels.mean() < 1
    ds.close()


def test_gzip_input(tmp_path):
    import gzip

    tsv = write_criteo_format_sample(str(tmp_path / "s.tsv"), n_lines=32)
    gz = str(tmp_path / "s.tsv.gz")
    with open(tsv, "rb") as f, gzip.open(gz, "wb") as g:
        g.write(f.read())
    shards = convert_criteo_files([gz], str(tmp_path / "out"), batch_size=8)
    conf = criteo_feed_config(8)
    ds = PadBoxSlotDataset(conf, read_threads=1)
    ds.set_filelist(shards)
    ds.load_into_memory()
    assert sum(int(b.ins_mask.sum()) for b in ds.batches(drop_last=False)) == 32
    ds.close()


def test_criteo_sample_e2e_learns(tmp_path):
    """The full path on the spec-exact sample: convert -> native parse ->
    3-pass CTR-DNN -> the planted signal must be learned (AUC gate)."""
    tsv = write_criteo_format_sample(str(tmp_path / "s.tsv"), n_lines=2048,
                                     seed=1)
    shards = convert_criteo_files([tsv], str(tmp_path / "out"),
                                  batch_size=128)
    conf = criteo_feed_config(128)
    ds = PadBoxSlotDataset(conf, read_threads=2)
    ds.set_filelist(shards)
    ds.load_into_memory()
    tconf = SparseTableConfig(embedding_dim=8)
    model = CtrDnn(CRITEO_N_CAT, tconf.row_width, dense_dim=CRITEO_N_DENSE,
                   hidden=(64, 32))
    table = SparseTable(tconf, seed=0)
    trainer = Trainer(model, tconf, TrainerConfig(auc_buckets=1 << 12),
                      seed=0)
    m = None
    for _ in range(3):
        table.begin_pass(ds.unique_keys())
        m = trainer.train_from_dataset(
            ds, table, auc_state=trainer.last_metric_state)
        table.end_pass()
    ds.close()
    assert m["count"] == 3 * 2048
    assert np.isfinite(m["loss"])
    assert m["auc"] > 0.62, f"planted Criteo signal not learned: {m['auc']}"
