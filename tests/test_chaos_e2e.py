"""Chaos end-to-end: a multi-pass training run survives transient fs
failures, a malformed input line, one NaN batch (skip_batch), a failed
publish attempt, and a truncated checkpoint across a restart — and its
final dense params and AUC match the fault-free run.

The quarantined line is appended corruption (so skipping it restores the
clean stream) and the NaN-skipped batch happens in a pass that is later
replayed from checkpoint after the simulated crash, so the end state is
EXACTLY the fault-free one; the stats registry carries the full accounting
of what was absorbed along the way.
"""

import os

import numpy as np
import pytest

from paddlebox_tpu.config import SparseTableConfig, TrainerConfig
from paddlebox_tpu.data.dataset import PadBoxSlotDataset
from paddlebox_tpu.data.synth import make_synth_config, write_synth_files
from paddlebox_tpu.models import CtrDnn
from paddlebox_tpu.sparse.table import SparseTable
from paddlebox_tpu.train import AutoCheckpointer, Trainer
from paddlebox_tpu.utils import faults
from paddlebox_tpu.utils.faults import FaultPlan
from paddlebox_tpu.utils.fs import publish_checkpoint
from paddlebox_tpu.utils.monitor import stats

pytestmark = [pytest.mark.slow, pytest.mark.chaos]

S, DENSE, B = 3, 2, 16
N_PASSES = 3


def _trainer(seed=0, nan_policy="raise"):
    tconf = SparseTableConfig(embedding_dim=4)
    model = CtrDnn(S, tconf.row_width, dense_dim=DENSE, hidden=(16, 8))
    table = SparseTable(tconf, seed=seed)
    trainer = Trainer(
        model, tconf,
        TrainerConfig(auc_buckets=1 << 10, nan_policy=nan_policy),
        seed=seed,
    )
    return table, trainer


def _dataset(files, malformed_policy="raise"):
    conf = make_synth_config(
        n_sparse_slots=S, dense_dim=DENSE, batch_size=B,
        max_feasigns_per_ins=8, malformed_policy=malformed_policy,
    )
    ds = PadBoxSlotDataset(conf, read_threads=1)
    ds.set_filelist(files)
    ds.load_into_memory()
    return ds


def _run_pass(ds, table, trainer):
    table.begin_pass(ds.unique_keys())
    m = trainer.train_from_dataset(ds, table)
    table.end_pass()
    return m


def test_chaos_run_matches_fault_free_run(tmp_path, monkeypatch):
    monkeypatch.setenv("PBOX_RETRY_BASE_DELAY_S", "0.001")
    monkeypatch.setenv("PBOX_RETRY_MAX_DELAY_S", "0.002")
    stats.reset()
    faults.clear()

    clean_files = write_synth_files(
        str(tmp_path / "clean"), n_files=2, ins_per_file=64,
        n_sparse_slots=S, vocab_per_slot=60, dense_dim=DENSE, seed=9,
    )
    # the chaos copy of the data carries one malformed trailing line
    chaos_files = write_synth_files(
        str(tmp_path / "chaos"), n_files=2, ins_per_file=64,
        n_sparse_slots=S, vocab_per_slot=60, dense_dim=DENSE, seed=9,
    )
    with open(chaos_files[-1], "a") as fh:
        fh.write("corrupt log line that is not slot format\n")

    # ---- fault-free reference ------------------------------------------- #
    ds_ref = _dataset(clean_files)
    table_ref, trainer_ref = _trainer()
    ref = [_run_pass(ds_ref, table_ref, trainer_ref) for _ in range(N_PASSES)]
    ref_state = table_ref.state_dict()
    ds_ref.close()

    # ---- chaos run, part 1 (until the "crash") -------------------------- #
    faults.install(FaultPlan({
        "data.read": "first:1",       # transient read failure on load
        "publish.upload": "first:1",  # transient publish failure
        "train.nan": "at:10",         # one poisoned batch in pass 1
    }))
    ds = _dataset(chaos_files, malformed_policy="skip")
    # the appended corrupt line was quarantined: clean stream restored
    assert ds.get_memory_data_size() == 128
    table, trainer = _trainer(nan_policy="skip_batch")
    acp = AutoCheckpointer(str(tmp_path / "acp"), job_id="chaos")
    remote = str(tmp_path / "published")
    for p in range(2):
        _run_pass(ds, table, trainer)
        acp.after_pass(p, table, trainer)
        publish_checkpoint(acp.ckpt, f"chaos-p{p:06d}", remote)
    ds.close()
    # the injected NaN batch in pass 1 was skipped, not fatal
    assert stats.get("train.nan_skipped_steps") == 1
    assert stats.get("faults.injected.train.nan") == 1
    assert stats.get("faults.injected.data.read") == 1
    assert stats.get("faults.injected.publish.upload") == 1
    assert stats.get("retry.publish.upload.retries") >= 1
    assert stats.get("data.quarantined_lines") == 1

    # ---- the crash: newest checkpoint truncated ------------------------- #
    newest = acp.ckpt.list_checkpoints()[-1]
    path = os.path.join(newest.dirname, "sparse.npz")
    data = open(path, "rb").read()
    open(path, "wb").write(data[: len(data) // 2])
    faults.clear()

    # ---- restart: fallback resume + clean replay ------------------------ #
    ds2 = _dataset(chaos_files, malformed_policy="skip")
    table2, trainer2 = _trainer(nan_policy="skip_batch")
    acp2 = AutoCheckpointer(str(tmp_path / "acp"), job_id="chaos")
    status, _ = acp2.resume(table2, trainer2)
    assert status["fallback"] is True
    assert status["next_pass"] == 1  # pass 1 (with the skipped batch) replays
    assert stats.get("ckpt.resume_fallback") == 1

    got = None
    for p in range(status["next_pass"], N_PASSES):
        got = _run_pass(ds2, table2, trainer2)
        acp2.after_pass(p, table2, trainer2)
    publish_checkpoint(acp2.ckpt, f"chaos-p{N_PASSES - 1:06d}", remote)
    ds2.close()

    # ---- the whole point: end state matches the fault-free run ---------- #
    assert got["count"] == ref[-1]["count"]
    np.testing.assert_allclose(got["auc"], ref[-1]["auc"], atol=1e-6)
    np.testing.assert_allclose(got["loss"], ref[-1]["loss"], rtol=1e-5)
    import jax

    for a, b in zip(
        jax.tree.leaves(trainer_ref.params), jax.tree.leaves(trainer2.params)
    ):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=1e-6, atol=1e-7
        )
    got_state = table2.state_dict()
    ia = np.argsort(ref_state["keys"])
    ib = np.argsort(got_state["keys"])
    np.testing.assert_array_equal(ref_state["keys"][ia], got_state["keys"][ib])
    np.testing.assert_allclose(
        ref_state["values"][ia], got_state["values"][ib], rtol=1e-5, atol=1e-6
    )
    # the published remote is complete and verifiable
    from paddlebox_tpu.checkpoint import verify_checkpoint_dir

    assert os.path.exists(os.path.join(remote, "donefile.txt"))
    verify_checkpoint_dir(
        os.path.join(remote, f"base-chaos-p{0:06d}")
    )
