"""Sequence parallelism: ring + Ulysses attention must match full attention
on the gathered sequence, forward AND backward, causal and not."""

import functools

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from paddlebox_tpu.parallel.sequence import (
    SEQ_AXIS,
    full_attention,
    ring_attention,
    ulysses_attention,
)
from paddlebox_tpu.utils.jax_compat import shard_map

P_DEV, B, T_LOCAL, H, D = 4, 2, 8, 4, 8
T = P_DEV * T_LOCAL


def _mesh():
    return Mesh(np.array(jax.devices()[:P_DEV]), (SEQ_AXIS,))


def _qkv(seed=0):
    rng = np.random.default_rng(seed)
    return [
        rng.normal(size=(B, T, H, D)).astype(np.float32) * 0.5
        for _ in range(3)
    ]


def _sharded(mesh, fn, causal):
    spec = P(None, SEQ_AXIS)  # shard the T axis

    return jax.jit(
        shard_map(
            functools.partial(fn, causal=causal),
            mesh=mesh,
            in_specs=(spec, spec, spec),
            out_specs=spec,
        )
    )


@pytest.mark.parametrize("causal", [False, True], ids=["bidir", "causal"])
@pytest.mark.parametrize(
    "fn", [ring_attention, ulysses_attention], ids=["ring", "ulysses"]
)
def test_matches_full_attention(fn, causal):
    mesh = _mesh()
    q, k, v = _qkv()
    want = np.asarray(full_attention(
        jnp.asarray(q), jnp.asarray(k), jnp.asarray(v), causal=causal
    ))
    got = np.asarray(_sharded(mesh, fn, causal)(q, k, v))
    np.testing.assert_allclose(got, want, rtol=2e-5, atol=2e-6)


@pytest.mark.parametrize("causal", [False, True], ids=["bidir", "causal"])
@pytest.mark.parametrize(
    "fn", [ring_attention, ulysses_attention], ids=["ring", "ulysses"]
)
def test_gradients_match_full_attention(fn, causal):
    mesh = _mesh()
    q, k, v = _qkv(1)
    tgt = np.asarray(
        np.random.default_rng(9).normal(size=(B, T, H, D)), np.float32
    )

    def loss_full(q_, k_, v_):
        return jnp.mean(
            (full_attention(q_, k_, v_, causal=causal) - tgt) ** 2
        )

    want = jax.grad(loss_full, argnums=(0, 1, 2))(
        jnp.asarray(q), jnp.asarray(k), jnp.asarray(v)
    )

    spec = P(None, SEQ_AXIS)

    def loss_sharded(q_, k_, v_):
        body = shard_map(
            functools.partial(fn, causal=causal),
            mesh=mesh,
            in_specs=(spec, spec, spec),
            out_specs=spec,
        )
        return jnp.mean((body(q_, k_, v_) - tgt) ** 2)

    got = jax.jit(jax.grad(loss_sharded, argnums=(0, 1, 2)))(q, k, v)
    for g, w in zip(got, want):
        np.testing.assert_allclose(
            np.asarray(g), np.asarray(w), rtol=5e-4, atol=1e-6
        )


def test_ulysses_rejects_indivisible_heads():
    mesh = _mesh()
    rng = np.random.default_rng(0)
    bad = [rng.normal(size=(B, T, 6, D)).astype(np.float32) for _ in range(3)]
    with pytest.raises(ValueError, match="divisible"):
        _sharded(mesh, ulysses_attention, False)(*bad)
