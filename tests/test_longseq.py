"""Long-sequence CTR: ordered behavior feed + attention tower + seq mesh.

VERDICT r3 weak #8: sequence parallelism was "well-tested pure functions no
model consumes".  These tests pin the full consumable path: the feed's
seq_pos construction, masked attention (key_valid) parity, LongSeqCtrDnn
training end-to-end through the unmodified Trainer, and single-device vs
sequence-parallel (ring AND ulysses) output parity on the virtual mesh.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh

from paddlebox_tpu.config import SparseTableConfig, TrainerConfig
from paddlebox_tpu.data.dataset import PadBoxSlotDataset
from paddlebox_tpu.data.synth import make_synth_config, write_synth_files
from paddlebox_tpu.models import LongSeqCtrDnn
from paddlebox_tpu.parallel.sequence import SEQ_AXIS, full_attention
from paddlebox_tpu.sparse.table import SparseTable
from paddlebox_tpu.train.trainer import Trainer

S, DENSE, B, T = 3, 2, 32, 16


def _config(**kw):
    return make_synth_config(
        n_sparse_slots=S, dense_dim=DENSE, batch_size=B,
        max_feasigns_per_ins=24, sequence_slot="slot0", max_seq_len=T, **kw
    )


def _dataset(tmp_path, n_ins=256):
    files = write_synth_files(
        str(tmp_path), n_files=1, ins_per_file=n_ins, n_sparse_slots=S,
        vocab_per_slot=50, dense_dim=DENSE, seed=11, max_keys_per_slot=9,
    )
    conf = _config()
    ds = PadBoxSlotDataset(conf, read_threads=1)
    ds.set_filelist(files)
    ds.load_into_memory()
    return conf, ds


def test_feed_seq_pos_points_at_slot_keys_in_order(tmp_path):
    conf, ds = _dataset(tmp_path)
    batch = next(ds.batches(drop_last=False))
    assert batch.seq_pos is not None and batch.seq_pos.shape == (B, T)
    K = batch.keys.shape[0]
    for i in range(min(8, int(batch.ins_mask.sum()))):
        pos = batch.seq_pos[i]
        real = pos[pos < K]
        # every position belongs to instance i's slot0 segment, in order
        assert (batch.key_segments[real] == i * S).all()
        assert (np.diff(real) == 1).all()  # contiguous run, file order
        # count matches the instance's slot0 key count (<= T)
        n_slot0 = int((batch.key_segments[: batch.n_keys] == i * S).sum())
        assert real.shape[0] == min(n_slot0, T)
    ds.close()


def test_masked_full_attention_matches_dense_reference():
    rng = np.random.default_rng(0)
    b, t, h, d = 2, 8, 2, 4
    q, k, v = (
        jnp.asarray(rng.normal(size=(b, t, h, d)).astype(np.float32))
        for _ in range(3)
    )
    valid = jnp.asarray(
        np.array([[1, 1, 1, 0, 0, 0, 0, 0], [1] * 8], dtype=bool)
    )
    got = np.asarray(full_attention(q, k, v, key_valid=valid))
    # dense reference: softmax over valid keys only
    qn, kn, vn = (np.asarray(x).transpose(0, 2, 1, 3) for x in (q, k, v))
    s = qn @ kn.transpose(0, 1, 3, 2) / np.sqrt(d)
    s = np.where(np.asarray(valid)[:, None, None, :], s, -np.inf)
    p = np.exp(s - s.max(-1, keepdims=True))
    p /= p.sum(-1, keepdims=True)
    want = (p @ vn).transpose(0, 2, 1, 3)
    np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-6)


@pytest.mark.parametrize("impl", ["ring", "ulysses"])
def test_seq_parallel_matches_single_device(tmp_path, impl):
    """The SAME model, single-device vs sharded over a 4-way seq mesh, must
    produce identical logits (ring/ulysses reduce to full attention)."""
    conf, ds = _dataset(tmp_path)
    tconf = SparseTableConfig(embedding_dim=8)
    mesh = Mesh(np.array(jax.devices()[:4]), (SEQ_AXIS,))
    kw = dict(dense_dim=DENSE, hidden=(16,), max_seq_len=T, n_heads=4,
              head_dim=8)
    single = LongSeqCtrDnn(S, tconf.row_width, **kw)
    sharded = LongSeqCtrDnn(S, tconf.row_width, seq_mesh=mesh,
                            seq_impl=impl, **kw)
    params = single.init(jax.random.PRNGKey(3))

    table = SparseTable(tconf, seed=0)
    table.begin_pass(ds.unique_keys())
    batch = next(ds.batches(drop_last=True))
    plan = table.plan_batch(batch)
    from paddlebox_tpu.train.trainer import _device_batch

    dev = _device_batch(batch, plan, S)
    from paddlebox_tpu.sparse.table import pull_rows

    rows = pull_rows(table.values, dev["idx"])
    args = (rows, dev["key_segments"], dev["dense"], B, dev["seq_pos"])
    l1 = np.asarray(single.apply(params, *args))
    l2 = np.asarray(sharded.apply(params, *args))
    table.end_pass()
    ds.close()
    np.testing.assert_allclose(l1, l2, rtol=2e-5, atol=2e-5)


def test_longseq_trains_e2e_and_attention_gets_gradients(tmp_path):
    """Full Trainer pass: finite loss, qkv projection receives gradients
    (the attention tower is live, not dead weight), and a second pass
    improves the loss."""
    conf, ds = _dataset(tmp_path, n_ins=512)
    tconf = SparseTableConfig(embedding_dim=8, learning_rate=0.5,
                              initial_range=0.05)
    model = LongSeqCtrDnn(S, tconf.row_width, dense_dim=DENSE, hidden=(32,),
                          max_seq_len=T)
    table = SparseTable(tconf, seed=0)
    trainer = Trainer(model, tconf,
                      TrainerConfig(dense_lr=3e-3, auc_buckets=1 << 12),
                      seed=0)
    qkv0 = np.asarray(trainer.params["qkv"]).copy()
    losses = []
    for p in range(3):
        table.begin_pass(ds.unique_keys())
        m = trainer.train_from_dataset(ds, table)
        table.end_pass()
        losses.append(m["loss"])
    ds.close()
    assert np.isfinite(losses).all()
    assert losses[-1] < losses[0]
    assert np.abs(np.asarray(trainer.params["qkv"]) - qkv0).max() > 1e-6


def test_seq_model_without_seq_feed_raises(tmp_path):
    files = write_synth_files(
        str(tmp_path), n_files=1, ins_per_file=64, n_sparse_slots=S,
        vocab_per_slot=50, dense_dim=DENSE, seed=1,
    )
    conf = make_synth_config(  # NO sequence_slot configured
        n_sparse_slots=S, dense_dim=DENSE, batch_size=B,
        max_feasigns_per_ins=24,
    )
    ds = PadBoxSlotDataset(conf, read_threads=1)
    ds.set_filelist(files)
    ds.load_into_memory()
    tconf = SparseTableConfig(embedding_dim=8)
    model = LongSeqCtrDnn(S, tconf.row_width, dense_dim=DENSE, hidden=(8,),
                          max_seq_len=T)
    table = SparseTable(tconf, seed=0)
    trainer = Trainer(model, tconf, TrainerConfig(auc_buckets=1 << 10))
    table.begin_pass(ds.unique_keys())
    with pytest.raises(RuntimeError, match="sequence_slot"):
        trainer.train_from_dataset(ds, table)
    table.end_pass()
    ds.close()


def test_longseq_export_and_predict(tmp_path):
    """The sequence model exports and serves: Predictor scores equal the
    in-process forward, including through a smaller shape bucket."""
    from paddlebox_tpu.inference import Predictor, export_model

    conf, ds = _dataset(tmp_path / "data")
    tconf = SparseTableConfig(embedding_dim=8)
    model = LongSeqCtrDnn(S, tconf.row_width, dense_dim=DENSE, hidden=(16,),
                          max_seq_len=T)
    table = SparseTable(tconf, seed=0)
    trainer = Trainer(model, tconf, TrainerConfig(auc_buckets=1 << 10), seed=0)
    table.begin_pass(ds.unique_keys())
    trainer.train_from_dataset(ds, table)
    table.end_pass()

    kcap = conf.batch_key_capacity or (B * conf.max_feasigns_per_ins)
    art = str(tmp_path / "artifact")
    export_model(
        model, trainer.params, table, art,
        batch_size=B, key_capacity=kcap, dense_dim=DENSE,
    )
    pred = Predictor.load(art)
    assert pred.meta["seq_len"] == T
    batch = next(ds.batches(drop_last=True))
    out = pred.predict(batch)

    # in-process reference forward on the same batch
    table.begin_pass(ds.unique_keys())
    plan = table.plan_batch(batch)
    from paddlebox_tpu.sparse.table import pull_rows
    from paddlebox_tpu.train.trainer import _device_batch

    dev = _device_batch(batch, plan, S)
    rows = pull_rows(table.values, dev["idx"])
    logits = model.apply(trainer.params, rows, dev["key_segments"],
                         dev["dense"], B, seq_pos=dev["seq_pos"])
    table.end_pass()
    ds.close()
    want = np.asarray(jax.nn.sigmoid(logits))[: out.shape[0]]
    np.testing.assert_allclose(out, want, rtol=1e-4, atol=1e-5)


def test_longseq_multichip_trains(tmp_path):
    """LongSeqCtrDnn under MultiChipTrainer on the 8-device mesh: the seq
    feed stacks per device and the step runs (the plumbing finding)."""
    from paddlebox_tpu.parallel import make_mesh
    from paddlebox_tpu.parallel.sharded_table import ShardedSparseTable
    from paddlebox_tpu.parallel.trainer import MultiChipTrainer

    conf, ds = _dataset(tmp_path, n_ins=512)
    tconf = SparseTableConfig(embedding_dim=8)
    mesh = make_mesh(8)
    model = LongSeqCtrDnn(S, tconf.row_width, dense_dim=DENSE, hidden=(16,),
                          max_seq_len=T)
    st = ShardedSparseTable(tconf, mesh)
    mt = MultiChipTrainer(model, tconf, mesh,
                          TrainerConfig(auc_buckets=1 << 10))
    st.begin_pass(ds.unique_keys())
    m = mt.train_from_dataset(ds, st)
    st.end_pass()
    ds.close()
    assert np.isfinite(m["loss"]) and m["steps"] > 0
