"""Native C++ batch planner (_native/plan_resolve.cpp): exact parity with
the numpy plan_keys path on every output, including scratch-row layout,
missing keys, duplicates, and padding."""

import numpy as np
import pytest

from paddlebox_tpu._native import build_census_index
from paddlebox_tpu.config import SparseTableConfig, flags
from paddlebox_tpu.sparse.table import SparseTable

native_available = build_census_index(np.arange(4, dtype=np.uint64)) is not None
pytestmark = pytest.mark.skipif(
    not native_available, reason="native planner did not build"
)


def _plans(pass_keys, keys, n_real, conf=None):
    """(native plan, numpy plan) for identical inputs through the REAL
    SparseTable.plan_keys — flag-flipped, so the test also pins that the
    flag routes."""
    conf = conf or SparseTableConfig(embedding_dim=4, plan_scratch_rows=64)
    plans = {}
    for native in (True, False):
        flags.set("use_native_planner", native)
        try:
            t = SparseTable(conf, seed=0)
            t.begin_pass(pass_keys)
            plans[native] = (t.plan_keys(keys, n_real), t.missing_key_count)
            t.end_pass()
        finally:
            flags.set("use_native_planner", True)
    return plans[True], plans[False]


def _assert_equal(a, b):
    """Order-insensitive plan equivalence: the native planner numbers
    unique slots in first-seen order (numpy: sorted order), so compare
    the training-visible quantities — idx (order-free), mask, missing
    counts — and the per-occurrence PUSH TARGET uniq_idx[inverse[occ]],
    which must agree wherever it aims at a live row (scratch targets
    differ by slot numbering; their deltas are zero or discarded)."""
    plan_a, miss_a = a
    plan_b, miss_b = b
    np.testing.assert_array_equal(plan_a.idx, plan_b.idx)
    np.testing.assert_array_equal(plan_a.key_mask, plan_b.key_mask)
    assert plan_a.n_missing == plan_b.n_missing
    assert miss_a == miss_b
    # per-occurrence push target: for occurrences whose key is IN the
    # census, the target is the pull row (order-free, must match exactly);
    # missing-key occurrences aim at scratch rows whose numbering is
    # slot-order-dependent — assert both sides agree on WHICH occurrences
    # those are, and that their targets are valid scratch/dead rows
    tgt_a = plan_a.uniq_idx[plan_a.inverse]
    tgt_b = plan_b.uniq_idx[plan_b.inverse]
    found_a = (plan_a.idx == tgt_a) & (plan_a.key_mask > 0)
    found_b = (plan_b.idx == tgt_b) & (plan_b.key_mask > 0)
    np.testing.assert_array_equal(found_a, found_b)
    np.testing.assert_array_equal(tgt_a[found_a], plan_b.idx[found_b])
    # occurrences sharing a key must share a slot (both planners)
    for plan in (plan_a, plan_b):
        real = plan.key_mask > 0
        inv = plan.inverse[real]
        assert len(set(zip(inv.tolist(), plan.idx[real].tolist()))) == \
            len(set(inv.tolist()))


def test_parity_random_batches():
    rng = np.random.default_rng(0)
    pass_keys = np.unique(rng.integers(1, 1 << 40, 5000).astype(np.uint64))
    for trial in range(5):
        K = int(rng.integers(64, 512))
        n_real = int(rng.integers(0, K + 1))
        keys = np.zeros(K, np.uint64)
        # mix of census keys (with duplicates) and unseen keys
        n_hit = n_real * 3 // 4
        keys[:n_hit] = rng.choice(pass_keys, n_hit)
        keys[n_hit:n_real] = rng.integers(1 << 41, 1 << 42,
                                          n_real - n_hit).astype(np.uint64)
        _assert_equal(*_plans(pass_keys, keys, n_real))


def test_parity_edge_cases():
    pass_keys = np.array([5, 9, 12, 700], dtype=np.uint64)
    K = 16
    # all-padding batch
    _assert_equal(*_plans(pass_keys, np.zeros(K, np.uint64), 0))
    # every key the same (heavy duplication)
    keys = np.full(K, 9, np.uint64)
    _assert_equal(*_plans(pass_keys, keys, K))
    # keys below/above the whole census (boundary searches)
    keys = np.array([1, 1, 900, 900, 5, 700] + [0] * 10, np.uint64)
    _assert_equal(*_plans(pass_keys, keys, 6))


def test_parity_under_provisioned_scratch():
    """Scratch clamping (the dead-row fallback) must match bit-for-bit."""
    conf = SparseTableConfig(embedding_dim=4, plan_scratch_rows=2)
    pass_keys = np.arange(1, 900, dtype=np.uint64)
    rng = np.random.default_rng(1)
    K = 256
    keys = np.zeros(K, np.uint64)
    keys[:100] = rng.choice(pass_keys, 100)
    _assert_equal(*_plans(pass_keys, keys, 100, conf=conf))


def test_e2e_training_same_result(tmp_path):
    """One real training pass, native vs numpy planner: identical loss and
    table state (the planner feeds the jitted step, so full-step parity is
    the end-to-end proof)."""
    from paddlebox_tpu.config import TrainerConfig
    from paddlebox_tpu.data.dataset import PadBoxSlotDataset
    from paddlebox_tpu.data.synth import make_synth_config, write_synth_files
    from paddlebox_tpu.models import CtrDnn
    from paddlebox_tpu.train.trainer import Trainer

    conf = make_synth_config(n_sparse_slots=3, dense_dim=2, batch_size=32,
                             max_feasigns_per_ins=8)
    files = write_synth_files(str(tmp_path), n_files=1, ins_per_file=128,
                              n_sparse_slots=3, vocab_per_slot=40,
                              dense_dim=2, seed=3)

    def run(native):
        flags.set("use_native_planner", native)
        try:
            ds = PadBoxSlotDataset(conf, read_threads=1)
            ds.set_filelist(files)
            ds.load_into_memory()
            tconf = SparseTableConfig(embedding_dim=4)
            model = CtrDnn(3, tconf.row_width, dense_dim=2, hidden=(8,))
            table = SparseTable(tconf, seed=0)
            trainer = Trainer(model, tconf,
                              TrainerConfig(auc_buckets=1 << 10), seed=0)
            table.begin_pass(ds.unique_keys())
            m = trainer.train_from_dataset(ds, table)
            table.end_pass()
            state = table.state_dict()
            ds.close()
            return m, state
        finally:
            flags.set("use_native_planner", True)

    m1, s1 = run(True)
    m2, s2 = run(False)
    assert m1["loss"] == m2["loss"]
    np.testing.assert_array_equal(s1["keys"], s2["keys"])
    np.testing.assert_array_equal(s1["values"], s2["values"])


def test_sharded_plan_native_matches_numpy(tmp_path):
    """Sharded plan_group, native vs numpy: one multi-chip training pass
    must produce identical metrics and table state (the sharded analog of
    the single-chip e2e equality above)."""
    import jax

    from paddlebox_tpu.config import TrainerConfig
    from paddlebox_tpu.data.dataset import PadBoxSlotDataset
    from paddlebox_tpu.data.synth import make_synth_config, write_synth_files
    from paddlebox_tpu.models import CtrDnn
    from paddlebox_tpu.parallel import make_mesh
    from paddlebox_tpu.parallel.sharded_table import ShardedSparseTable
    from paddlebox_tpu.parallel.trainer import MultiChipTrainer

    conf = make_synth_config(n_sparse_slots=3, dense_dim=2, batch_size=16,
                             max_feasigns_per_ins=8)
    files = write_synth_files(str(tmp_path), n_files=1, ins_per_file=256,
                              n_sparse_slots=3, vocab_per_slot=40,
                              dense_dim=2, seed=6)

    def run(native):
        flags.set("use_native_planner", native)
        try:
            ds = PadBoxSlotDataset(conf, read_threads=1)
            ds.set_filelist(files)
            ds.load_into_memory()
            mesh = make_mesh(4)
            tconf = SparseTableConfig(embedding_dim=4)
            model = CtrDnn(3, tconf.row_width, dense_dim=2, hidden=(8,))
            table = ShardedSparseTable(tconf, mesh, seed=0)
            trainer = MultiChipTrainer(
                model, tconf, mesh, TrainerConfig(auc_buckets=1 << 10),
                seed=0,
            )
            table.begin_pass(ds.unique_keys())
            m = trainer.train_from_dataset(ds, table)
            table.end_pass()
            state = table.state_dict()
            ds.close()
            return m, state
        finally:
            flags.set("use_native_planner", True)

    m1, s1 = run(True)
    m2, s2 = run(False)
    assert m1["loss"] == m2["loss"]
    assert m1["auc"] == m2["auc"]
    np.testing.assert_array_equal(s1["keys"], s2["keys"])
    np.testing.assert_array_equal(s1["values"], s2["values"])
