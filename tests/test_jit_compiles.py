"""Runtime retrace witness (telemetry/compiles.py): per-stage
``jit.compiles`` attribution, and the steady-state ZERO-retrace pins —
after warmup, a training pass (both trainer paths) and a serving
predict must trigger no XLA compile at all.  A moving per-stage count
is the silent regression the ``jit-retrace-hazard`` static pass exists
to catch; these pins witness it at runtime."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from paddlebox_tpu.config import SparseTableConfig, TrainerConfig
from paddlebox_tpu.data.dataset import PadBoxSlotDataset
from paddlebox_tpu.data.synth import make_synth_config, write_synth_files
from paddlebox_tpu.models.ctr_dnn import CtrDnn
from paddlebox_tpu.sparse.table import SparseTable
from paddlebox_tpu.telemetry import compiles
from paddlebox_tpu.train.trainer import Trainer

S, DENSE = 3, 2


def _counts() -> dict:
    return compiles.compiles_by_stage()


def _delta(before: dict, after: dict) -> dict:
    """Per-stage compile-count movement, zero entries dropped."""
    out = {}
    for stage, n in after.items():
        d = n - before.get(stage, 0)
        if d:
            out[stage] = d
    return out


# --------------------------------------------------------------------------- #
# witness units
# --------------------------------------------------------------------------- #
def test_counted_jit_counts_per_stage_and_stops_when_cached():
    f = compiles.counted_jit(lambda x: x * 3, stage="unit.counted")
    before = _counts().get("unit.counted", 0)
    f(jnp.ones(3))
    warm = _counts().get("unit.counted", 0)
    assert warm > before, "warmup compile must land on the stage label"
    assert f.retraces() == 1
    f(jnp.ones(3))
    assert _counts().get("unit.counted", 0) == warm, \
        "a cached dispatch must not move jit.compiles"
    f(jnp.ones(5))  # new shape: a real retrace
    assert _counts().get("unit.counted", 0) > warm
    assert f.retraces() == 2


def test_counted_jit_decorator_form_and_static_args():
    @compiles.counted_jit(stage="unit.deco", static_argnames=("flag",))
    def g(x, flag=False):
        return -x if flag else x

    out = g(jnp.ones(2), flag=True)
    assert np.asarray(out)[0] == -1.0
    assert _counts().get("unit.deco", 0) >= 1
    # attribute passthrough: the wrapper still looks like the jitted fn
    assert hasattr(g, "lower")


def test_stage_scope_innermost_wins():
    with compiles.stage_scope("outer"):
        with compiles.stage_scope("inner.scope"):
            jax.jit(lambda x: x + 7)(jnp.ones(4))
    assert _counts().get("inner.scope", 0) >= 1
    assert compiles.current_stage() == compiles.UNTAGGED


def test_listener_install_is_idempotent():
    assert compiles.install_compile_listener()
    assert compiles.install_compile_listener()
    before = _counts().get("unit.idem", 0)
    with compiles.stage_scope("unit.idem"):
        jax.jit(lambda x: x * 11)(jnp.ones(6))
    # exactly one registration: one compile is not double-counted
    assert _counts().get("unit.idem", 0) - before <= 2


# --------------------------------------------------------------------------- #
# steady-state pins
# --------------------------------------------------------------------------- #
def _make_data(td, n_ins=64, batch_size=8):
    conf = make_synth_config(
        n_sparse_slots=S, dense_dim=DENSE, batch_size=batch_size,
        max_feasigns_per_ins=16,
    )
    files = write_synth_files(
        str(td), n_files=1, ins_per_file=n_ins, n_sparse_slots=S,
        vocab_per_slot=50, dense_dim=DENSE, seed=11,
    )
    ds = PadBoxSlotDataset(conf, read_threads=1)
    ds.set_filelist(files)
    ds.load_into_memory()
    return conf, ds


def test_steady_state_zero_retrace_single_chip_trainer(tmp_path):
    """After warmup, every pass over same-shape feeds is dispatch-only —
    across EVERY stage, untagged pass-boundary ops included.  Warmup is
    TWO passes: pass 1 compiles the step, pass 2 recompiles it once when
    the table capacity shrinks from the cold-census default to the
    fitted size (and the HBM cache transitions cold->warm); from pass 3
    on, zero compiles.  This is the tier-1 pin for the single-chip path."""
    conf, ds = _make_data(tmp_path)
    tconf = SparseTableConfig(embedding_dim=8)
    model = CtrDnn(S, tconf.row_width, dense_dim=DENSE, hidden=(16, 8))
    table = SparseTable(tconf, seed=0)
    trainer = Trainer(
        model, tconf, TrainerConfig(auc_buckets=1 << 10), seed=0)
    keys = ds.unique_keys()

    for _ in range(2):  # warmup: compile + capacity-fit recompile
        table.begin_pass(keys)
        trainer.train_from_dataset(ds, table)
        table.end_pass()

    before = _counts()
    for _ in range(2):  # steady state
        table.begin_pass(keys)
        trainer.train_from_dataset(ds, table)
        table.end_pass()
    moved = _delta(before, _counts())
    ds.close()
    assert not moved, (
        f"steady-state single-chip passes recompiled: {moved} — a "
        "shape-varying feed or fresh jit wrapper crept into the loop"
    )


def test_steady_state_zero_retrace_multichip_trainer(tmp_path):
    """The SPMD path's pin: shard_mapped step/sync stages stay cached
    across steady-state passes on the 8-device mesh."""
    from paddlebox_tpu.parallel import (
        MultiChipTrainer,
        ShardedSparseTable,
        make_mesh,
    )

    assert len(jax.devices()) >= 8, "conftest must force 8 CPU devices"
    mesh = make_mesh(8)
    conf, ds = _make_data(tmp_path, n_ins=128, batch_size=8)
    tconf = SparseTableConfig(embedding_dim=8, learning_rate=0.05)
    trconf = TrainerConfig(dense_lr=1e-3, sync_dense_mode="step",
                           auc_buckets=1 << 10)
    model = CtrDnn(S, tconf.row_width, dense_dim=DENSE, hidden=(16, 8))
    trainer = MultiChipTrainer(model, tconf, mesh, trconf, seed=3)
    table = ShardedSparseTable(tconf, mesh, seed=5, bucket_slack=8.0)
    keys = ds.unique_keys()

    for _ in range(2):  # warmup: compile + capacity-fit recompile
        table.begin_pass(keys)
        trainer.train_from_dataset(ds, table)
        table.end_pass()

    before = _counts()
    table.begin_pass(keys)
    trainer.train_from_dataset(ds, table)
    table.end_pass()
    moved = _delta(before, _counts())
    ds.close()
    assert not moved, (
        f"steady-state SPMD pass recompiled: {moved} — the padded-bucket "
        "shape discipline or the cached step wrapper broke"
    )


def test_steady_state_zero_retrace_serving_predictor(tmp_path):
    """The serving pin: after the exported bucket program's first call,
    every same-bucket predict is dispatch-only (the micro-batching fast
    path's latency floor depends on it)."""
    import os

    from paddlebox_tpu.inference import Predictor, export_model

    conf, ds = _make_data(tmp_path / "data")
    tconf = SparseTableConfig(embedding_dim=8)
    model = CtrDnn(S, tconf.row_width, dense_dim=DENSE, hidden=(16, 8))
    table = SparseTable(tconf, seed=0)
    trainer = Trainer(
        model, tconf, TrainerConfig(auc_buckets=1 << 10), seed=0)
    table.begin_pass(ds.unique_keys())
    trainer.train_from_dataset(ds, table)
    table.end_pass()

    art = str(tmp_path / "artifact")
    kcap = conf.batch_key_capacity or (8 * conf.max_feasigns_per_ins)
    export_model(model, trainer.params, table, art,
                 batch_size=8, key_capacity=kcap, dense_dim=DENSE)
    assert os.path.exists(os.path.join(art, "meta.json"))

    pred = Predictor.load(art)
    batches = list(ds.batches(drop_last=False))
    pred.predict(batches[0])  # warmup: deserialization + first compile
    warm = _counts()
    assert warm.get("serve.predict", 0) >= 1, \
        "warmup compile must be attributed to serve.predict"

    for b in batches[:4] + batches[:4]:  # steady state, same bucket
        pred.predict(b)
    moved = _delta(warm, _counts())
    ds.close()
    assert not moved, (
        f"steady-state serving predict recompiled: {moved} — the bucket "
        "ladder stopped absorbing shape variance"
    )
