"""Live resharding of ShardedSparseTable (PR 16: elastic fleet).

The contract pinned here is the one reshard()'s docstring promises:
growing or shrinking the shard count at a pass boundary is bit-identical
— keys, values, g2sum, AUC — to tearing the table down and rebuilding it
at the new shard count from a checkpoint.  On top of the equality pin:
steady-state stages stay ZERO-retrace once post-cutover warmup settles,
a checkpoint saved mid-roll restores onto the new shard count, and an
injected migrate/cutover failure aborts cleanly back to the old shard
map (the reshard half of the PR-16 chaos contract; the fleet half lives
in tests/test_elastic_fleet.py)."""

import numpy as np
import pytest

import jax

from paddlebox_tpu import telemetry
from paddlebox_tpu.checkpoint import CheckpointManager
from paddlebox_tpu.config import SparseTableConfig, TrainerConfig
from paddlebox_tpu.data.dataset import PadBoxSlotDataset
from paddlebox_tpu.data.synth import make_synth_config, write_synth_files
from paddlebox_tpu.models.ctr_dnn import CtrDnn
from paddlebox_tpu.parallel import (
    MultiChipTrainer,
    ShardedSparseTable,
    make_mesh,
)
from paddlebox_tpu.parallel.sharded_table import (
    _decode_migration,
    _encode_migration,
)
from paddlebox_tpu.telemetry import compiles
from paddlebox_tpu.utils.faults import FaultInjected, fault_plan

S, DENSE = 3, 2
N_INS, B = 128, 8  # 16 per-device batches: divisible by 2 AND 4 devices


@pytest.fixture(scope="module")
def mesh2():
    assert len(jax.devices()) >= 4, "conftest must force 8 CPU devices"
    return make_mesh(2)


@pytest.fixture(scope="module")
def mesh4():
    return make_mesh(4)


def _data(tmp_path, sub="d"):
    conf = make_synth_config(
        n_sparse_slots=S, dense_dim=DENSE, batch_size=B,
        max_feasigns_per_ins=16,
    )
    files = write_synth_files(
        str(tmp_path / sub), n_files=2, ins_per_file=N_INS // 2,
        n_sparse_slots=S, vocab_per_slot=50, dense_dim=DENSE, seed=7,
    )
    ds = PadBoxSlotDataset(conf, read_threads=2)
    ds.set_filelist(files)
    ds.load_into_memory()
    return ds


def _trainer(tconf, mesh, seed=3, **tkw):
    model = CtrDnn(S, tconf.row_width, dense_dim=DENSE, hidden=(16,))
    return MultiChipTrainer(
        model, tconf, mesh, TrainerConfig(auc_buckets=1 << 10, **tkw),
        seed=seed,
    )


def _run_pass(trainer, table, ds):
    table.begin_pass(ds.unique_keys())
    m = trainer.train_from_dataset(ds, table)
    table.end_pass()
    return m


# --------------------------------------------------------------------------- #
# migration payload framing (the PBR1 wire format)
# --------------------------------------------------------------------------- #
class TestMigrationCodec:
    def test_round_trip_preserves_hottest_first_order(self):
        keys = np.array([901, 3, 77, 41, 500], dtype=np.uint64)  # unsorted
        rows = np.arange(5 * 6, dtype=np.float32).reshape(5, 6) * 0.25
        dk, dr = _decode_migration(_encode_migration(keys, rows))
        np.testing.assert_array_equal(dk, keys)
        np.testing.assert_array_equal(dr, rows)

    def test_empty_payload_round_trips(self):
        dk, dr = _decode_migration(_encode_migration(
            np.empty(0, np.uint64), np.empty((0, 5), np.float32)
        ))
        assert dk.shape == (0,) and dr.shape == (0, 5)

    def test_bad_magic_raises(self):
        buf = bytearray(_encode_migration(
            np.array([1, 2], dtype=np.uint64),
            np.zeros((2, 3), np.float32),
        ))
        buf[:4] = b"XXXX"
        with pytest.raises(ValueError, match="magic"):
            _decode_migration(bytes(buf))

    def test_truncated_payload_raises(self):
        buf = _encode_migration(
            np.array([1, 2], dtype=np.uint64), np.zeros((2, 3), np.float32)
        )
        with pytest.raises(ValueError):
            _decode_migration(buf + b"\x00\x00\x00\x00")


# --------------------------------------------------------------------------- #
# lifecycle guards
# --------------------------------------------------------------------------- #
class TestReshardGuards:
    def test_reshard_inside_pass_refused_then_works(self, mesh2, mesh4):
        tconf = SparseTableConfig(embedding_dim=4)
        table = ShardedSparseTable(tconf, mesh2, seed=0)
        table.begin_pass(np.arange(1, 60, dtype=np.uint64))
        with pytest.raises(RuntimeError, match="between passes"):
            table.reshard(mesh4)
        table.end_pass()
        # the refusal left the table healthy: the boundary call works
        assert table.reshard(mesh4) > 0
        assert table.n_shards == 4
        table.close()

    def test_cutover_merges_hottest_first_payload(self, mesh2):
        """Multi-host staged rows arrive hottest-first (unsorted), but
        BucketStore.update requires sorted unique keys — the cutover must
        re-sort before merging, or buckets lose their sorted invariant
        and migrated rows silently vanish from later lookups (r17 review
        finding; exercised directly since tier-1 runs single-process)."""
        tconf = SparseTableConfig(embedding_dim=4)
        table = ShardedSparseTable(tconf, mesh2, seed=0)
        rng = np.random.default_rng(5)
        keys = np.unique(rng.integers(1, 2**63, size=64, dtype=np.uint64))
        rows = rng.standard_normal(
            (keys.shape[0], tconf.row_width + 1)  # +g2sum, the store row
        ).astype(np.float32)
        order = rng.permutation(keys.shape[0])  # wire order: by frequency
        staged = {
            "multi": True,
            "drop_keys": np.empty(0, np.uint64),
            "in_keys": keys[order],
            "in_rows": rows[order],
        }
        table._reshard_cutover(mesh2, staged)
        got, found = table._store.lookup(keys)
        assert found.all(), "migrated rows vanished after cutover merge"
        np.testing.assert_array_equal(got, rows)
        table.close()

    def test_same_mesh_reshard_is_a_no_op(self, mesh2):
        tconf = SparseTableConfig(embedding_dim=4)
        table = ShardedSparseTable(tconf, mesh2, seed=0)
        table.begin_pass(np.arange(1, 40, dtype=np.uint64))
        table.end_pass()
        assert table.reshard(mesh2) == 0
        assert table.n_shards == 2
        table.close()


# --------------------------------------------------------------------------- #
# the PR-16 equality pin: live reshard == teardown-and-rebuild
# --------------------------------------------------------------------------- #
def _live_vs_rebuilt(tmp_path, mesh_old, mesh_new):
    """Pass 1 on the old split, then pass 2 on the new split — once via
    live reshard, once via state_dict -> fresh table at the new shard
    count.  Everything downstream must be bit-exact."""
    tconf = SparseTableConfig(embedding_dim=8)
    ds = _data(tmp_path)

    live = ShardedSparseTable(tconf, mesh_old, seed=5)
    _run_pass(_trainer(tconf, mesh_old), live, ds)
    moved = live.reshard(mesh_new)
    assert moved > 0, "growing/shrinking the split must move owners"
    m_live = _run_pass(_trainer(tconf, mesh_new), live, ds)

    base = ShardedSparseTable(tconf, mesh_old, seed=5)
    _run_pass(_trainer(tconf, mesh_old), base, ds)
    rebuilt = ShardedSparseTable(tconf, mesh_new, seed=5)
    rebuilt.load_state_dict(base.state_dict())
    m_base = _run_pass(_trainer(tconf, mesh_new), rebuilt, ds)

    s_live, s_base = live.state_dict(), rebuilt.state_dict()
    np.testing.assert_array_equal(s_live["keys"], s_base["keys"])
    # full-row equality: embeds AND the g2sum column (last) — bit-exact
    np.testing.assert_array_equal(s_live["values"], s_base["values"])
    np.testing.assert_array_equal(
        s_live["values"][:, -1], s_base["values"][:, -1]
    )
    assert m_live["steps"] == m_base["steps"] > 0
    assert m_live["loss"] == m_base["loss"]
    assert m_live["auc"] == m_base["auc"]
    for t in (live, base, rebuilt):
        t.close()
    ds.close()


class TestReshardBitExact:
    def test_grow_2_to_4(self, tmp_path, mesh2, mesh4):
        _live_vs_rebuilt(tmp_path, mesh2, mesh4)

    def test_shrink_4_to_2(self, tmp_path, mesh2, mesh4):
        _live_vs_rebuilt(tmp_path, mesh4, mesh2)


# --------------------------------------------------------------------------- #
# steady state after cutover: zero retrace
# --------------------------------------------------------------------------- #
def _counts():
    return compiles.compiles_by_stage()


def _delta(before, after):
    out = {}
    for stage, n in after.items():
        d = n - before.get(stage, 0)
        if d:
            out[stage] = d
    return out


def test_zero_retrace_after_cutover(tmp_path, mesh2, mesh4):
    """Two passes after the cutover settle the new split's shapes
    (compile + capacity-fit recompile); the third must not move
    jit.compiles for ANY stage."""
    tconf = SparseTableConfig(embedding_dim=8)
    ds = _data(tmp_path)
    table = ShardedSparseTable(tconf, mesh2, seed=5, bucket_slack=8.0)
    _run_pass(_trainer(tconf, mesh2), table, ds)
    assert table.reshard(mesh4) > 0
    tr = _trainer(tconf, mesh4)
    _run_pass(tr, table, ds)  # warmup: first compile on the new split
    _run_pass(tr, table, ds)  # capacity-fit recompile settles
    # default hybrid placement realizes the hot block once the planner's
    # aged frequencies cross enter_freq — one more settle pass absorbs
    # that boundary's one-time eager shape warm-ups (the steady-state
    # hybrid pin itself lives in test_placement.py)
    _run_pass(tr, table, ds)
    before = _counts()
    _run_pass(tr, table, ds)
    assert not _delta(before, _counts()), \
        "steady-state pass after cutover must be zero-retrace"
    table.close()
    ds.close()


# --------------------------------------------------------------------------- #
# checkpoint saved mid-roll restores on the new shard count
# --------------------------------------------------------------------------- #
def test_checkpoint_mid_roll_restores_on_new_shard_count(
    tmp_path, mesh2, mesh4
):
    tconf = SparseTableConfig(embedding_dim=8)
    ds = _data(tmp_path)
    table = ShardedSparseTable(tconf, mesh2, seed=5)
    _run_pass(_trainer(tconf, mesh2), table, ds)
    assert table.reshard(mesh4) > 0
    tr = _trainer(tconf, mesh4)

    mgr = CheckpointManager(str(tmp_path / "ckpt"))
    p, o = tr.dense_state()
    mgr.save_base("midroll", table, p, o)

    # the restore world starts DIRECTLY on the new shard count
    table2 = ShardedSparseTable(tconf, mesh4, seed=5)
    tr2 = _trainer(tconf, mesh4)
    p2, o2, _ = mgr.load(table2, *tr2.dense_state())
    tr2.load_dense_state(p2, o2)

    s1, s2 = table.state_dict(), table2.state_dict()
    np.testing.assert_array_equal(s1["keys"], s2["keys"])
    np.testing.assert_array_equal(s1["values"], s2["values"])
    # and the restored world trains on at the new split
    m = _run_pass(tr2, table2, ds)
    assert m["steps"] > 0 and np.isfinite(m["loss"])
    table.close()
    table2.close()
    ds.close()


# --------------------------------------------------------------------------- #
# chaos: injected failures abort back to the old shard map
# --------------------------------------------------------------------------- #
def _assert_abort_clean(tmp_path, site, mesh_old, mesh_new):
    tconf = SparseTableConfig(embedding_dim=8)
    ds = _data(tmp_path)
    table = ShardedSparseTable(tconf, mesh_old, seed=5)
    tr_old = _trainer(tconf, mesh_old)
    _run_pass(tr_old, table, ds)
    old_n = table.n_shards
    before_sd = table.state_dict()
    aborts0 = telemetry.counter("reshard.aborts").value()

    with fault_plan({site: "first:1"}):
        with pytest.raises(FaultInjected):
            table.reshard(mesh_new)

    # old shard map fully intact: count, mesh, every row
    assert table.n_shards == old_n
    assert table.mesh is mesh_old
    assert telemetry.counter("reshard.aborts").value() == aborts0 + 1
    after_sd = table.state_dict()
    np.testing.assert_array_equal(before_sd["keys"], after_sd["keys"])
    np.testing.assert_array_equal(before_sd["values"], after_sd["values"])

    # training continues on the old map as if nothing happened...
    m = _run_pass(tr_old, table, ds)
    assert m["steps"] > 0 and np.isfinite(m["loss"])
    # ...and a later retry (fault cleared) commits
    assert table.reshard(mesh_new) > 0
    m2 = _run_pass(_trainer(tconf, mesh_new), table, ds)
    assert m2["steps"] > 0 and np.isfinite(m2["loss"])
    table.close()
    ds.close()


class TestReshardChaos:
    def test_migrate_fault_aborts_cleanly(self, tmp_path, mesh2, mesh4):
        _assert_abort_clean(tmp_path, "reshard.migrate", mesh2, mesh4)

    def test_cutover_fault_aborts_cleanly(self, tmp_path, mesh2, mesh4):
        _assert_abort_clean(tmp_path, "reshard.cutover", mesh2, mesh4)
