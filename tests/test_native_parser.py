"""Native C++ parser: builds, and produces byte-identical RecordBlocks to the
pure-Python reference implementation on every feature (labels, task labels,
dense, sparse, skip slots, ins_id, logkey, gz, errors)."""

import gzip

import numpy as np
import pytest

from paddlebox_tpu.config import DataFeedConfig, SlotConfig
from paddlebox_tpu.data.slot_parser import SlotParser

pytestmark = pytest.mark.skipif(
    __import__("paddlebox_tpu._native", fromlist=["get_lib"]).get_lib() is None,
    reason="native parser unavailable (no g++?)",
)


def _conf(**kw):
    slots = [
        SlotConfig(name="click", type="float", is_dense=True, shape=(1,)),
        SlotConfig(name="conv", type="float", is_dense=True, shape=(1,)),
        SlotConfig(name="sa", type="uint64"),
        SlotConfig(name="unused", type="uint64", is_used=False),
        SlotConfig(name="sb", type="uint64"),
        SlotConfig(name="dx", type="float", is_dense=True, shape=(3,)),
    ]
    return DataFeedConfig(
        slots=slots, label_slot="click", task_label_slots=("conv",), **kw
    )


LINES = [
    "1 1 1 0 2 11 12 1 5 1 21 3 0.1 -0.2 3e-1",
    "1 0 1 1 1 13 0 0 3 0.4 0.5 0.6",
    "1 1 1 0 3 14 15 18446744073709551615 2 9 9 2 22 23 3 -0.7 0.8 0.9",
]


def _both(conf, text):
    p_native = SlotParser(conf)
    native = p_native._native_parser()
    assert native is not None
    got = native.parse_bytes(text.encode())
    p_py = SlotParser(conf)
    want = p_py.parse_lines(text.splitlines())
    return got, want


def _assert_same(got, want):
    assert got.n_ins == want.n_ins
    np.testing.assert_array_equal(got.keys, want.keys)
    np.testing.assert_array_equal(got.key_offsets, want.key_offsets)
    np.testing.assert_allclose(got.dense, want.dense, rtol=1e-6)
    np.testing.assert_allclose(got.labels, want.labels, rtol=1e-6)
    if want.task_labels is None:
        assert got.task_labels is None or got.task_labels.shape[1] == 0
    else:
        np.testing.assert_allclose(got.task_labels, want.task_labels, rtol=1e-6)
    for f in ("search_ids", "ranks", "cmatches"):
        w = getattr(want, f)
        g = getattr(got, f)
        if w is None:
            assert g is None
        else:
            np.testing.assert_array_equal(g, w)
    assert got.ins_ids == want.ins_ids


def test_parity_plain():
    got, want = _both(_conf(), "\n".join(LINES) + "\n")
    _assert_same(got, want)
    # uint64 extremes survive
    assert got.keys.max() == np.uint64(18446744073709551615)


def test_parity_ins_id_logkey():
    conf = _conf(parse_ins_id=True, parse_logkey=True)
    lines = [
        f"id-{i} {1000 + i}:{i % 3}:{222 + (i % 2)} {l}"
        for i, l in enumerate(LINES)
    ]
    got, want = _both(conf, "\n".join(lines) + "\n")
    _assert_same(got, want)


def test_parity_blank_lines_and_no_trailing_newline():
    got, want = _both(_conf(), LINES[0] + "\n\n  \n" + LINES[1])
    _assert_same(got, want)
    assert got.n_ins == 2


def test_native_errors_match_python():
    conf = _conf()
    bad = [
        "1 1 1 0 2 11",  # truncated sparse
        "1 1 1 0 2 11 x 1 5 1 21 3 0.1 0.2 0.3",  # bad feasign
        "2 1 1 0 1 11 1 5 1 21 3 0.1 0.2 0.3",  # label width mismatch
        LINES[0] + " 9 9",  # trailing tokens
    ]
    for line in bad:
        p = SlotParser(conf)
        native = p._native_parser()
        with pytest.raises(ValueError):
            native.parse_bytes((line + "\n").encode())
        with pytest.raises(ValueError):
            SlotParser(conf).parse_lines([line])


def test_gz_and_dataset_path(tmp_path):
    conf = _conf()
    text = "\n".join(LINES) + "\n"
    gz = tmp_path / "part-0.gz"
    with gzip.open(gz, "wt") as f:
        f.write(text)
    block = SlotParser(conf).parse_file(str(gz))
    want = SlotParser(conf).parse_lines(LINES)
    _assert_same(block, want)


def test_empty_input():
    got, want = _both(_conf(), "")
    assert got.n_ins == 0 == want.n_ins
