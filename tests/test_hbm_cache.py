"""Device-resident embedding engine correctness (ARCHITECTURE.md
"Device-resident embedding engine").

The acceptance bar (ISSUE 6): the cached lifecycle — persistent HBM
hot-key cache, miss-only promotion fetch, in-place hit update, LFU-with-
aging admission/eviction, dirty-row drain at barriers — must be BIT-exact
vs ``hbm_cache_rows=0`` over multiple passes with overlapping censuses on
BOTH trainer paths (keys, values, g2sum, AUC), including a checkpoint
save/restore and a shrink mid-run.  Plus: the begin-pass promotion patch
shrinks to the cold-key count, the chaos sites ``cache.fetch`` /
``cache.admit`` degrade without corrupting rows, and the cache telemetry
rides the per-pass ``pass_end`` JSONL record.
"""

import dataclasses
import json

import jax
import numpy as np
import pytest

from paddlebox_tpu.config import (
    SparseTableConfig,
    TelemetryConfig,
    TrainerConfig,
)
from paddlebox_tpu.data.dataset import PadBoxSlotDataset
from paddlebox_tpu.data.synth import make_synth_config, write_synth_files
from paddlebox_tpu.models import CtrDnn
from paddlebox_tpu.sparse.table import SparseTable
from paddlebox_tpu.train.trainer import Trainer
from paddlebox_tpu.utils import faults

N_SLOTS = 3
DENSE = 2
N_PASSES = 3


def _tconf(cache_rows: int, **kw) -> SparseTableConfig:
    # placement="hash": this suite pins the HBM-cache engine itself.  The
    # default (realized hybrid placement) would promote the tiny toy
    # census into the replicated hot block after a couple of passes,
    # leaving the cache no cold tail to exercise — the hybrid lifecycle
    # has its own suite (test_placement.py).
    kw.setdefault("placement", "hash")
    return SparseTableConfig(
        embedding_dim=4, learning_rate=0.4, initial_range=0.05,
        store_buckets=16, plan_scratch_rows=64, hbm_cache_rows=cache_rows,
        **kw,
    )


@pytest.fixture(scope="module")
def pass_datasets(tmp_path_factory):
    """N_PASSES loaded datasets over a SHARED key space (vocab 40: heavy
    census overlap, so steady-state passes have real cache hits)."""
    conf = make_synth_config(
        n_sparse_slots=N_SLOTS, dense_dim=DENSE, batch_size=64,
        max_feasigns_per_ins=16,
    )
    datasets = []
    for p in range(N_PASSES):
        d = tmp_path_factory.mktemp(f"cpass{p}")
        files = write_synth_files(
            str(d), n_files=2, ins_per_file=192, n_sparse_slots=N_SLOTS,
            vocab_per_slot=40, dense_dim=DENSE, seed=23 + p,
        )
        ds = PadBoxSlotDataset(conf, read_threads=2)
        ds.set_filelist(files)
        ds.load_into_memory()
        datasets.append(ds)
    yield conf, datasets
    for ds in datasets:
        ds.close()


def _run_single_chip(datasets, cache_rows: int, shrink_at: int = 1,
                     ckpt_at: int = 1):
    """Train N_PASSES with prepare_pass staging, a checkpoint snapshot +
    restore round-trip at ``ckpt_at`` and a shrink at ``shrink_at``."""
    tconf = _tconf(cache_rows, show_decay_rate=0.5)
    table = SparseTable(tconf, seed=3)
    model = CtrDnn(N_SLOTS, tconf.row_width, dense_dim=DENSE, hidden=(16, 8))
    trainer = Trainer(
        model, tconf, TrainerConfig(dense_lr=3e-3, auc_buckets=1 << 12),
        seed=3,
    )
    auc_state = None
    metrics = None
    for p, ds in enumerate(datasets):
        table.begin_pass(ds.unique_keys())
        nxt = (
            datasets[p + 1].unique_keys if p + 1 < len(datasets) else None
        )
        metrics = trainer.train_from_dataset(
            ds, table, auc_state=auc_state, drop_last=True,
            next_pass_keys=nxt,
        )
        auc_state = trainer.last_metric_state
        table.end_pass()
        if p == ckpt_at:
            # checkpoint save/restore round-trip mid-run: the drained
            # state must be complete, and the restore must invalidate
            # whatever the cache held
            snap = table.state_dict()
            table.load_state_dict(snap)
        if p == shrink_at:
            table.shrink()
    sd = table.state_dict()
    delta = table.pop_delta()
    return sd, delta, metrics, table


def _assert_state_equal(a, b):
    assert np.array_equal(a["keys"], b["keys"])
    # values carry [show, clk, embed..., g2sum]: exact equality pins the
    # counters, the embeddings AND the optimizer state bit-for-bit
    assert np.array_equal(a["values"], b["values"])


class TestBitExact:
    def test_single_chip_cached_matches_uncached(self, pass_datasets):
        _, datasets = pass_datasets
        sd_u, delta_u, m_u, _ = _run_single_chip(datasets, 0)
        sd_c, delta_c, m_c, table = _run_single_chip(datasets, 1 << 16)
        _assert_state_equal(sd_u, sd_c)
        _assert_state_equal(delta_u, delta_c)
        assert m_u["auc"] == m_c["auc"]
        assert m_u["loss"] == m_c["loss"]
        # the cache actually participated: post-shrink passes re-warm it
        assert table.last_cache_hits + table.last_cache_misses > 0

    def test_single_chip_tiny_cache_eviction_churn(self, pass_datasets):
        # capacity far below the working set: admission + eviction every
        # pass, rows bouncing cache<->store — still bit-exact
        _, datasets = pass_datasets
        sd_u, delta_u, m_u, _ = _run_single_chip(datasets, 0)
        sd_c, delta_c, m_c, table = _run_single_chip(datasets, 8)
        _assert_state_equal(sd_u, sd_c)
        _assert_state_equal(delta_u, delta_c)
        assert m_u["auc"] == m_c["auc"]
        assert table._caches()[0].resident <= 8

    def test_multichip_cached_matches_uncached(self, pass_datasets):
        if len(jax.devices()) < 8:
            pytest.skip("needs the conftest 8-device CPU mesh")
        from paddlebox_tpu.parallel import (
            MultiChipTrainer,
            ShardedSparseTable,
            make_mesh,
        )

        _, datasets = pass_datasets

        def run(cache_rows):
            mesh = make_mesh(8)
            tconf = _tconf(cache_rows, show_decay_rate=0.5)
            table = ShardedSparseTable(tconf, mesh, seed=3)
            model = CtrDnn(
                N_SLOTS, tconf.row_width, dense_dim=DENSE, hidden=(16, 8)
            )
            trainer = MultiChipTrainer(
                model, tconf, mesh,
                TrainerConfig(dense_lr=3e-3, auc_buckets=1 << 12), seed=3,
            )
            metrics = None
            for p, ds in enumerate(datasets):
                table.begin_pass(ds.unique_keys())
                nxt = (
                    datasets[p + 1].unique_keys
                    if p + 1 < len(datasets) else None
                )
                metrics = trainer.train_from_dataset(
                    ds, table, drop_last=True, next_pass_keys=nxt,
                )
                table.end_pass()
                if p == 1:
                    snap = table.state_dict()
                    table.load_state_dict(snap)
                    table.shrink()
            return table.state_dict(), table.pop_delta(), metrics, table

        sd_u, delta_u, m_u, _ = run(0)
        sd_c, delta_c, m_c, table = run(1 << 16)
        _assert_state_equal(sd_u, sd_c)
        _assert_state_equal(delta_u, delta_c)
        assert m_u["auc"] == m_c["auc"]
        # the shrink at pass 1 invalidated the cache, so the FINAL pass is
        # an all-miss re-warm; the per-shard hit path itself is pinned by
        # TestCacheBehavior::test_sharded_hot_rows_skip_store
        assert table.last_cache_misses > 0


class TestCacheBehavior:
    def test_promotion_patch_shrinks_to_cold_keys(self):
        from paddlebox_tpu import telemetry

        t = SparseTable(_tconf(1 << 16), seed=0)
        keys = np.arange(1, 100, dtype=np.uint64)
        t.begin_pass(keys)
        assert t.last_cache_misses == 99 and t.last_cache_hits == 0
        t.values = t.values + 1.0
        t.end_pass()
        # same census again: everything is hot, the host supplies nothing
        t.begin_pass(keys)
        assert t.last_cache_hits == 99 and t.last_cache_misses == 0
        assert (np.asarray(t.values)[:99, 0] == 1.0).all()
        g = telemetry.registry.snapshot()["gauges"]
        assert g["cache.hit_rate"] == 1.0
        t.end_pass()
        # a half-new census fetches exactly the cold half
        keys2 = np.arange(50, 150, dtype=np.uint64)
        t.begin_pass(keys2)
        assert t.last_cache_hits == 50 and t.last_cache_misses == 50
        t.end_pass()
        t.flush()

    def test_hot_rows_skip_store_until_drain(self):
        """Hits never leave HBM: the store stays empty across passes and
        only the flush() barrier (drain) lands the rows."""
        t = SparseTable(_tconf(1 << 16), seed=0)
        keys = np.arange(1, 50, dtype=np.uint64)
        for p in range(3):
            t.begin_pass(keys)
            t.values = t.values + 1.0
            t.end_pass()
        assert t._store.n == 0  # nothing cold, nothing evicted
        assert t.n_features == 49  # the barrier drains the dirty rows
        vals, found = t._store.lookup(keys)
        assert found.all() and (vals[:, 0] == 3.0).all()

    def test_eviction_writes_rows_back(self):
        from paddlebox_tpu import telemetry

        before = telemetry.registry.snapshot()["counters"].get(
            "cache.evicted_rows", 0
        )
        t = SparseTable(_tconf(8), seed=0)
        a = np.arange(1, 9, dtype=np.uint64)
        b = np.arange(100, 108, dtype=np.uint64)
        t.begin_pass(a)
        t.values = t.values + 7.0
        t.end_pass()
        # disjoint census twice: a's aged-out rows must be evicted for b
        # and their values preserved through the store
        for _ in range(2):
            t.begin_pass(b)
            t.end_pass()
        t.flush()
        vals, found = t._store.lookup(a)
        assert found.all() and (vals[:, 0] == 7.0).all()
        after = telemetry.registry.snapshot()["counters"]["cache.evicted_rows"]
        assert after > before
        assert t._caches()[0].resident <= 8

    def test_sharded_hot_rows_skip_store(self):
        if len(jax.devices()) < 8:
            pytest.skip("needs the conftest 8-device CPU mesh")
        from paddlebox_tpu.parallel import ShardedSparseTable, make_mesh

        t = ShardedSparseTable(_tconf(1 << 16), make_mesh(8), seed=0)
        keys = np.arange(1, 80, dtype=np.uint64)
        for _ in range(2):
            t.begin_pass(keys)
            t.values = t.values + 1.0
            t.end_pass()
        assert t.last_cache_hits == 79
        assert t._store.n == 0
        assert t.n_features == 79


class TestChaos:
    def test_fetch_fault_falls_back_to_host_resolve(self, pass_datasets):
        """An injected cache.fetch failure must degrade to the synchronous
        host resolve — the run stays bit-exact with the uncached one."""
        _, datasets = pass_datasets
        sd_u, delta_u, m_u, _ = _run_single_chip(datasets, 0)
        with faults.fault_plan({"cache.fetch": "at:1"}):
            sd_c, delta_c, m_c, _ = _run_single_chip(datasets, 1 << 16)
            assert faults.active().hits("cache.fetch") > 0
        _assert_state_equal(sd_u, sd_c)
        _assert_state_equal(delta_u, delta_c)
        assert m_u["auc"] == m_c["auc"]

    def test_fetch_fault_in_stage_and_sync(self, pass_datasets):
        # first:2 fails the staged fetch AND the sync fallback fetch: the
        # pass must degrade all the way to the uncached resolve
        from paddlebox_tpu import telemetry

        _, datasets = pass_datasets
        sd_u, delta_u, m_u, _ = _run_single_chip(datasets, 0)
        with faults.fault_plan({"cache.fetch": "first:2"}):
            sd_c, delta_c, m_c, _ = _run_single_chip(datasets, 1 << 16)
        _assert_state_equal(sd_u, sd_c)
        assert m_u["auc"] == m_c["auc"]
        counters = telemetry.registry.snapshot()["counters"]
        assert counters.get("cache.fetch_fallbacks", 0) >= 1

    def test_admit_fault_falls_back_to_full_writeback(self, pass_datasets):
        from paddlebox_tpu import telemetry

        _, datasets = pass_datasets
        sd_u, delta_u, m_u, _ = _run_single_chip(datasets, 0)
        with faults.fault_plan({"cache.admit": "at:1"}):
            sd_c, delta_c, m_c, _ = _run_single_chip(datasets, 1 << 16)
            assert faults.active().hits("cache.admit") > 0
        _assert_state_equal(sd_u, sd_c)
        _assert_state_equal(delta_u, delta_c)
        assert m_u["auc"] == m_c["auc"]
        counters = telemetry.registry.snapshot()["counters"]
        assert counters.get("cache.admit_fallbacks", 0) >= 1

    def test_fetch_fault_simple_lifecycle_values_survive(self):
        """Direct (trainer-free) check: rows trained before the fault are
        intact after the degraded pass."""
        with faults.fault_plan({"cache.fetch": "at:1"}):
            t = SparseTable(_tconf(1 << 16), seed=0)
            keys = np.arange(1, 40, dtype=np.uint64)
            t.begin_pass(keys)  # fetch hit 0: clean
            t.values = t.values + 5.0
            t.end_pass()
            t.begin_pass(keys)  # fetch hit 1: injected -> degraded resolve
            assert (np.asarray(t.values)[:39, 0] == 5.0).all()
            t.values = t.values + 1.0
            t.end_pass()
            t.flush()
            sd = t.state_dict()
            assert (sd["values"][:, 0] == 6.0).all()


class TestTelemetryAndKillSwitch:
    def test_pass_end_jsonl_carries_cache_metrics(self, pass_datasets,
                                                  tmp_path):
        from paddlebox_tpu.telemetry import events

        _, datasets = pass_datasets
        path = str(tmp_path / "events.jsonl")
        events.close_event_log()
        tconf = _tconf(1 << 16)
        table = SparseTable(tconf, seed=1)
        model = CtrDnn(N_SLOTS, tconf.row_width, dense_dim=DENSE,
                       hidden=(8,))
        trainer = Trainer(
            model, tconf,
            TrainerConfig(auc_buckets=1 << 10,
                          telemetry=TelemetryConfig(events_path=path)),
            seed=1,
        )
        try:
            for ds in datasets[:2]:
                table.begin_pass(ds.unique_keys())
                trainer.train_from_dataset(ds, table, drop_last=True)
                table.end_pass()
            table.flush()
        finally:
            events.close_event_log()
        recs = [json.loads(ln) for ln in open(path)]
        passes = [r for r in recs if r["event"] == "pass_end"]
        assert len(passes) == 2
        gauges = passes[-1]["telemetry"]["gauges"]
        assert "cache.hit_rate" in gauges
        assert gauges["cache.hit_rate"] > 0  # overlapping censuses hit
        hists = passes[0]["telemetry"]["histograms"]
        assert "cache.miss_fetch_seconds" in hists

    def test_kill_switch_disables_cache(self, monkeypatch):
        monkeypatch.setenv("PBOX_HBM_CACHE", "0")
        t = SparseTable(_tconf(1 << 16), seed=0)
        keys = np.arange(1, 30, dtype=np.uint64)
        t.begin_pass(keys)
        t.end_pass()
        assert t._caches() == []
        t.flush()  # the write-back merge is async under overlap
        assert t._store.n == 29  # full write-back: the uncached lifecycle

    def test_store_stats_report_host_tier_pressure(self, tmp_path):
        from paddlebox_tpu.sparse.store import BucketStore

        store = BucketStore(
            n_cols=3, n_buckets=8, spill_dir=str(tmp_path / "spill"),
            max_resident=2,
        )
        keys = np.arange(0, 4000, dtype=np.uint64)
        store.update(keys, np.ones((4000, 3), np.float32))
        st = store.stats()
        assert st["n"] == 4000
        assert st["spilled_buckets"] > 0  # max_resident 2 of 8 buckets
        assert 0 < st["resident_rows"] < 4000
        ram = BucketStore(n_cols=3, n_buckets=8)
        ram.update(keys, np.ones((4000, 3), np.float32))
        st = ram.stats()
        assert st["spilled_buckets"] == 0 and st["resident_rows"] == 4000


def test_bench_hbm_cache_smoke():
    """Fast CPU smoke of the bench ablation: bit-exact, a positive hit
    rate on the skewed stream, and the cached promotion patch strictly
    below the census (the cold-key count)."""
    from bench import bench_hbm_cache

    res = bench_hbm_cache(
        3, SparseTableConfig(embedding_dim=4),
        TrainerConfig(auc_buckets=1 << 10), n_slots=2, dense=2, bsz=32,
        ins_per_pass=64, hidden=(8,), vocab_per_slot=300,
    )
    assert res["bitexact"]
    assert res["cached_hit_rate"] > 0
    assert (
        res["cached_promotion_patch_rows"]
        < res["uncached_promotion_patch_rows"]
    )
