"""Model family e2e: every model trains (loss decreases) on synthetic CTR
data; MMoE exercises multi-task labels + per-task AUC; MetricGroup exercises
the cmatch/rank-masked AUC variants (reference: MetricMsg family,
box_wrapper.cc:1222-1270)."""

import numpy as np
import pytest

from paddlebox_tpu.config import SparseTableConfig, TrainerConfig
from paddlebox_tpu.data.dataset import PadBoxSlotDataset
from paddlebox_tpu.data.synth import make_synth_config, write_synth_files
from paddlebox_tpu.metrics import MetricGroup, MetricSpec
from paddlebox_tpu.models import DCN, DeepFM, MMoE, WideDeep, XDeepFM
from paddlebox_tpu.sparse.table import SparseTable
from paddlebox_tpu.train.trainer import Trainer

S, DENSE, B = 3, 2, 32


def _dataset(tmp_path, n_task_labels=0, with_logkey=False, n_ins=128):
    conf = make_synth_config(
        n_sparse_slots=S, dense_dim=DENSE, batch_size=B,
        max_feasigns_per_ins=16, n_task_labels=n_task_labels,
        parse_logkey=with_logkey,
    )
    files = write_synth_files(
        str(tmp_path), n_files=1, ins_per_file=n_ins, n_sparse_slots=S,
        vocab_per_slot=40, dense_dim=DENSE, seed=11,
        n_task_labels=n_task_labels, with_logkey=with_logkey,
    )
    ds = PadBoxSlotDataset(conf, read_threads=1)
    ds.set_filelist(files)
    ds.load_into_memory()
    return conf, ds


def _train(model, ds, passes=6, metric_group=None):
    tconf = SparseTableConfig(embedding_dim=4)
    trainer = Trainer(
        model, tconf, TrainerConfig(auc_buckets=1 << 10),
        metric_group=metric_group,
    )
    table = SparseTable(tconf, seed=0)
    losses, metrics = [], None
    for _ in range(passes):
        table.begin_pass(ds.unique_keys())
        metrics = trainer.train_from_dataset(ds, table)
        table.end_pass()
        losses.append(metrics["loss"])
    return losses, metrics


WIDTH = SparseTableConfig(embedding_dim=4).row_width


# Pass budgets are per-model: wide_deep and deepfm spike for ~5 passes
# before converging on this synthetic set (their linear/FM terms
# overshoot early at the shared sparse learning rate — measured: loss
# 0.81 -> 1.06 by pass 3, then monotonically down through 0.64 and AUC
# 0.73 by pass 20, 0.90 by pass 30), so a 6-pass budget judged the
# transient, not the model.  dcn/xdeepfm clear the bar in 6.
@pytest.mark.parametrize(
    "model_fn,passes",
    [
        (lambda: WideDeep(S, WIDTH, dense_dim=DENSE, hidden=(16,)), 20),
        (lambda: DeepFM(S, WIDTH, dense_dim=DENSE, hidden=(16,)), 20),
        (lambda: DCN(S, WIDTH, dense_dim=DENSE, hidden=(16,), n_cross=2),
         6),
        (lambda: XDeepFM(
            S, WIDTH, dense_dim=DENSE, hidden=(16,), cin_layers=(8, 8)
        ), 6),
    ],
    ids=["wide_deep", "deepfm", "dcn", "xdeepfm"],
)
def test_model_learns(tmp_path, model_fn, passes):
    _, ds = _dataset(tmp_path)
    losses, metrics = _train(model_fn(), ds, passes=passes)
    assert np.isfinite(losses).all()
    assert losses[-1] < losses[0]
    assert metrics["auc"] > 0.5
    ds.close()


def test_models_handle_wide_cvm_offset(tmp_path):
    """cvm_offset > 2 (conv/pcoc row layouts): the default CVM transform
    still emits exactly 2 counter columns, so every model's input_dim
    accounting must shrink accordingly (regression: r3 review finding)."""
    from paddlebox_tpu.models import CtrDnn

    tconf = SparseTableConfig(embedding_dim=4, cvm_offset=3)
    W = tconf.row_width
    conf, ds = _dataset(tmp_path)
    for model in (
        CtrDnn(S, W, dense_dim=DENSE, hidden=(8,), cvm_offset=3),
        DeepFM(S, W, dense_dim=DENSE, hidden=(8,), cvm_offset=3),
        DCN(S, W, dense_dim=DENSE, hidden=(8,), n_cross=1, cvm_offset=3),
        XDeepFM(S, W, dense_dim=DENSE, hidden=(8,), cin_layers=(4,),
                cvm_offset=3),
        WideDeep(S, W, dense_dim=DENSE, hidden=(8,), cvm_offset=3),
    ):
        trainer = Trainer(model, tconf, TrainerConfig(auc_buckets=1 << 10))
        table = SparseTable(tconf, seed=0)
        table.begin_pass(ds.unique_keys())
        metrics = trainer.train_from_dataset(ds, table)
        table.end_pass()
        assert np.isfinite(metrics["loss"]), type(model).__name__
    ds.close()


def test_pooled_width_matches_op_output():
    """pooled_width() == the actual fused-op per-slot width, across layouts
    and cvm_offsets (regression: conv with cvm_offset=4 used to disagree)."""
    import jax.numpy as jnp

    from paddlebox_tpu.ops import (
        fused_seqpool_cvm,
        fused_seqpool_cvm_with_conv,
        pooled_width,
    )

    B, S_, K = 2, 3, 12
    for W, co, use_cvm, layout, show_filter in [
        (6, 2, True, "default", False),
        (7, 3, True, "default", False),
        (7, 3, False, "default", False),
        (7, 3, True, "conv", False),
        (8, 4, True, "conv", False),
        (7, 3, True, "conv", True),
    ]:
        rows = jnp.ones((K, W))
        segs = jnp.asarray(np.arange(K) % (B * S_), np.int32)
        if layout == "conv":
            out = fused_seqpool_cvm_with_conv(
                rows, segs, B, S_, use_cvm=use_cvm, cvm_offset=co,
                show_filter=show_filter,
            )
        else:
            out = fused_seqpool_cvm(
                rows, segs, B, S_, use_cvm=use_cvm, cvm_offset=co
            )
        want = pooled_width(W, co, use_cvm, layout=layout,
                            show_filter=show_filter)
        assert out.shape == (B, S_ * want), (W, co, use_cvm, layout, out.shape)


def test_xdeepfm_cin_matches_naive():
    """The CIN einsum == the textbook double sum over field pairs."""
    import jax.numpy as jnp

    rng = np.random.default_rng(0)
    B, m, D, H = 4, 3, 5, 6
    x0 = rng.normal(size=(B, m, D)).astype(np.float32)
    w = rng.normal(size=(H, m, m)).astype(np.float32)

    got = np.asarray(jnp.einsum("hij,bid,bjd->bhd", w, jnp.asarray(x0), jnp.asarray(x0)))
    want = np.zeros((B, H, D), np.float32)
    for b in range(B):
        for h in range(H):
            for i in range(m):
                for j in range(m):
                    want[b, h] += w[h, i, j] * x0[b, i] * x0[b, j]
    np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-5)


def test_mmoe_multitask(tmp_path):
    conf, ds = _dataset(tmp_path, n_task_labels=2)
    model = MMoE(
        S, WIDTH, dense_dim=DENSE, n_tasks=3, n_experts=2,
        expert_hidden=(16,), expert_dim=8, tower_hidden=(8,),
    )
    # 12 passes: MMoE shares wide_deep's early transient on this set
    # (loss dips below its start at pass ~8; see test_model_learns note)
    losses, metrics = _train(model, ds, passes=12)
    assert np.isfinite(losses).all()
    assert losses[-1] < losses[0]
    for t in range(3):
        assert f"task{t}/auc" in metrics
        assert 0.0 <= metrics[f"task{t}/auc"] <= 1.0
    # primary AUC == task0 AUC (same stream)
    assert metrics["auc"] == pytest.approx(metrics["task0/auc"], abs=1e-9)
    ds.close()


def test_mmoe_requires_task_labels(tmp_path):
    _, ds = _dataset(tmp_path, n_task_labels=0)
    model = MMoE(S, WIDTH, dense_dim=DENSE, n_tasks=2, n_experts=2,
                 expert_hidden=(8,), expert_dim=4, tower_hidden=(4,))
    with pytest.raises(RuntimeError, match="task_label_slots"):
        _train(model, ds, passes=1)
    ds.close()


def test_metric_group_cmatch_rank(tmp_path):
    conf, ds = _dataset(tmp_path, with_logkey=True)
    group = MetricGroup(
        [
            MetricSpec("all"),
            MetricSpec("cm222", cmatch_values=(222,)),
            MetricSpec("rank1", rank_values=(1,)),
            MetricSpec("none", cmatch_values=(999,)),
        ],
        n_buckets=1 << 10,
    )
    from paddlebox_tpu.models import CtrDnn

    model = CtrDnn(S, WIDTH, dense_dim=DENSE, hidden=(16,))
    losses, metrics = _train(model, ds, passes=2, metric_group=group)
    # unfiltered variant tracks the primary AUC stream exactly
    assert metrics["all/auc"] == pytest.approx(metrics["auc"], abs=1e-9)
    assert metrics["all/count"] == metrics["count"]
    # filtered variants saw strict subsets
    assert 0 < metrics["cm222/count"] < metrics["all/count"]
    assert 0 < metrics["rank1/count"] < metrics["all/count"]
    assert metrics["none/count"] == 0.0
    ds.close()


def test_metric_spec_requires_logkey(tmp_path):
    _, ds = _dataset(tmp_path, with_logkey=False)
    from paddlebox_tpu.models import CtrDnn

    group = MetricGroup([MetricSpec("cm", cmatch_values=(222,))], n_buckets=1 << 8)
    model = CtrDnn(S, WIDTH, dense_dim=DENSE, hidden=(8,))
    with pytest.raises(ValueError, match="cmatch"):
        _train(model, ds, passes=1, metric_group=group)
    ds.close()


def test_extended_embeddings(tmp_path):
    """expand_dim > 0: the pull_box_extended_sparse equivalent — table rows
    carry a base + expand embedding, the model pools them into separate
    feature blocks, push updates both (reference:
    operators/pull_box_extended_sparse_op.*)."""
    import jax.numpy as jnp

    from paddlebox_tpu.models import CtrDnn
    from paddlebox_tpu.ops import fused_seqpool_cvm_extended, seqpool

    _, ds = _dataset(tmp_path)
    tconf = SparseTableConfig(embedding_dim=4, expand_dim=3)
    model = CtrDnn(S, tconf.row_width, dense_dim=DENSE, hidden=(16,),
                   expand_dim=tconf.expand_dim)
    trainer = Trainer(model, tconf, TrainerConfig(auc_buckets=1 << 10))
    table = SparseTable(tconf, seed=0)
    losses = []
    # converges after an initial adam-warmup bump from the extra random
    # expand features, hence the longer run
    for _ in range(12):
        table.begin_pass(ds.unique_keys())
        m = trainer.train_from_dataset(ds, table)
        table.end_pass()
        losses.append(m["loss"])
    assert np.isfinite(losses).all()
    assert losses[-1] < losses[0]
    # the expand tail received real (nonzero) updates
    assert np.abs(table.state_dict()["values"][:, -tconf.expand_dim - 1 : -1]).sum() > 0

    # split semantics: base block == cvm(all-but-expand), expand == raw pool
    rng = np.random.default_rng(0)
    rows = rng.normal(size=(10, tconf.row_width)).astype(np.float32)
    rows[:, 0:2] = np.abs(rows[:, 0:2])
    segs = np.sort(rng.integers(0, 2 * S, size=10)).astype(np.int32)
    base, expand = fused_seqpool_cvm_extended(
        jnp.asarray(rows), jnp.asarray(segs), 2, S, tconf.expand_dim
    )
    pooled = np.asarray(seqpool(jnp.asarray(rows), jnp.asarray(segs), 2, S))
    np.testing.assert_allclose(
        np.asarray(expand).reshape(2, S, -1), pooled[..., -tconf.expand_dim:],
        rtol=1e-5,
    )
    assert base.shape == (2, S * (2 + tconf.embedding_dim))
    ds.close()
