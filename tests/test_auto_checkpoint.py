"""Kill-and-resume determinism for the AutoCheckpointer (VERDICT r2 weak /
missing #4: auto-checkpoint + deterministic pass replay; reference:
incubate/checkpoint/auto_checkpoint.py, SURVEY.md §5.3)."""

import numpy as np

from paddlebox_tpu.config import SparseTableConfig, TrainerConfig
from paddlebox_tpu.data.dataset import PadBoxSlotDataset
from paddlebox_tpu.data.synth import make_synth_config, write_synth_files
from paddlebox_tpu.models import CtrDnn
from paddlebox_tpu.sparse.table import SparseTable
from paddlebox_tpu.train import AutoCheckpointer, Trainer

S, DENSE, B = 3, 2, 16
N_PASSES = 4


def _world(tmp_path, seed=0):
    conf = make_synth_config(
        n_sparse_slots=S, dense_dim=DENSE, batch_size=B,
        max_feasigns_per_ins=8,
    )
    files = write_synth_files(
        str(tmp_path / "data"), n_files=2, ins_per_file=64, n_sparse_slots=S,
        vocab_per_slot=60, dense_dim=DENSE, seed=9,
    )
    ds = PadBoxSlotDataset(conf, read_threads=1)
    ds.set_filelist(files)
    ds.load_into_memory()
    tconf = SparseTableConfig(embedding_dim=4)
    model = CtrDnn(S, tconf.row_width, dense_dim=DENSE, hidden=(16, 8))
    table = SparseTable(tconf, seed=seed)
    trainer = Trainer(model, tconf, TrainerConfig(auc_buckets=1 << 10), seed=seed)
    return ds, table, trainer


def _run_passes(ds, table, trainer, lo, hi, acp=None, mstate=None):
    m = None
    for p in range(lo, hi):
        table.begin_pass(ds.unique_keys())
        m = trainer.train_from_dataset(ds, table, auc_state=mstate)
        table.end_pass()
        mstate = trainer.last_metric_state
        if acp is not None:
            acp.after_pass(p, table, trainer, metric_state=mstate)
    return m, mstate


def test_kill_and_resume_reproduces_uninterrupted_metrics(tmp_path):
    # --- uninterrupted reference run ---
    ds, table, trainer = _world(tmp_path)
    ref, _ = _run_passes(ds, table, trainer, 0, N_PASSES)
    ref_state = table.state_dict()

    # --- run A: passes 0..1 with auto-checkpoint, then "die" ---
    ds2, table_a, trainer_a = _world(tmp_path)
    acp_a = AutoCheckpointer(str(tmp_path / "acp"), job_id="job1")
    _run_passes(ds2, table_a, trainer_a, 0, 2, acp=acp_a)
    del table_a, trainer_a, acp_a  # the "kill"

    # --- run B: fresh objects, resume, replay passes 2..3 ---
    ds3, table_b, trainer_b = _world(tmp_path)
    acp_b = AutoCheckpointer(str(tmp_path / "acp"), job_id="job1")
    status, mstate = acp_b.resume(
        table_b, trainer_b, metric_template=trainer_b._init_mstate()
    )
    assert status is not None and status["next_pass"] == 2
    got, _ = _run_passes(
        ds3, table_b, trainer_b, status["next_pass"], N_PASSES,
        acp=acp_b, mstate=mstate,
    )

    # metrics and table state match the uninterrupted run exactly
    assert got["count"] == ref["count"]
    np.testing.assert_allclose(got["auc"], ref["auc"], atol=1e-6)
    np.testing.assert_allclose(got["loss"], ref["loss"], rtol=1e-5)
    got_state = table_b.state_dict()
    ia = np.argsort(ref_state["keys"])
    ib = np.argsort(got_state["keys"])
    np.testing.assert_array_equal(
        ref_state["keys"][ia], got_state["keys"][ib]
    )
    np.testing.assert_allclose(
        ref_state["values"][ia], got_state["values"][ib], rtol=1e-5, atol=1e-6
    )
    for d in (ds, ds2, ds3):
        d.close()


def test_fresh_job_resume_is_none(tmp_path):
    ds, table, trainer = _world(tmp_path)
    acp = AutoCheckpointer(str(tmp_path / "acp"), job_id="nope")
    status, mstate = acp.resume(table, trainer)
    assert status is None and mstate is None
    ds.close()


def test_crash_between_checkpoint_and_status_rereuns_pass(tmp_path):
    """A checkpoint without its status line must be invisible to resume:
    the pass re-runs rather than being skipped (write order guarantees
    at-least-once pass execution)."""
    ds, table, trainer = _world(tmp_path)
    acp = AutoCheckpointer(str(tmp_path / "acp"), job_id="job2")
    _run_passes(ds, table, trainer, 0, 1, acp=acp)
    # simulate the crash: checkpoint for pass 1 lands, status write doesn't
    table.begin_pass(ds.unique_keys())
    trainer.train_from_dataset(ds, table)
    table.end_pass()
    acp.ckpt.save_delta("job2-p000001", table, *trainer.dense_state())
    # (no status update)

    ds2, table_b, trainer_b = _world(tmp_path)
    acp_b = AutoCheckpointer(str(tmp_path / "acp"), job_id="job2")
    status, _ = acp_b.resume(table_b, trainer_b)
    assert status["next_pass"] == 1  # pass 1 will re-run
    ds.close()
    ds2.close()
