"""Parity tests for the CTR op set vs numpy oracles.

Mirrors the reference op tests (python/paddle/fluid/tests/unittests/
test_cvm_op.py, test_fusion_seqpool_cvm_concat_op.py) — SURVEY.md §4 tier 1.
"""

import jax
import jax.numpy as jnp
import numpy as np

from paddlebox_tpu.ops import cvm, fused_seqpool_cvm, seqpool


def _make_batch(rng, B=4, S=3, W=6, max_len=5):
    """Random padded-CSR batch like HostBatch: rows per occurrence + segs."""
    lens = rng.integers(0, max_len, size=(B, S))
    K_real = int(lens.sum())
    K = B * S * max_len  # capacity with padding tail
    rows = rng.normal(size=(K, W)).astype(np.float32)
    rows[:, 0] = rng.integers(1, 10, size=K)  # show
    rows[:, 1] = rng.integers(0, 5, size=K)  # clk
    segs = np.full(K, B * S, dtype=np.int32)
    seg_ids = np.repeat(np.arange(B * S), lens.reshape(-1))
    segs[:K_real] = seg_ids
    rows[K_real:] = 0.0  # padding rows read zeros (dead table row)
    return rows, segs, lens


def _oracle_pool(rows, segs, B, S, W):
    out = np.zeros((B, S, W), dtype=np.float64)
    for k in range(rows.shape[0]):
        if segs[k] < B * S:
            out[segs[k] // S, segs[k] % S] += rows[k]
    return out


def test_seqpool_matches_oracle():
    rng = np.random.default_rng(0)
    B, S, W = 4, 3, 6
    rows, segs, _ = _make_batch(rng, B, S, W)
    got = np.asarray(seqpool(jnp.asarray(rows), jnp.asarray(segs), B, S))
    np.testing.assert_allclose(got, _oracle_pool(rows, segs, B, S, W), rtol=1e-5)


def test_fused_seqpool_cvm_use_cvm():
    rng = np.random.default_rng(1)
    B, S, W = 4, 3, 6
    rows, segs, _ = _make_batch(rng, B, S, W)
    got = np.asarray(
        fused_seqpool_cvm(jnp.asarray(rows), jnp.asarray(segs), B, S, use_cvm=True)
    )
    pooled = _oracle_pool(rows, segs, B, S, W)
    exp = pooled.copy()
    exp[..., 0] = np.log(pooled[..., 0] + 1)
    exp[..., 1] = np.log(pooled[..., 1] + 1) - np.log(pooled[..., 0] + 1)
    np.testing.assert_allclose(got, exp.reshape(B, -1), rtol=1e-4, atol=1e-5)


def test_fused_seqpool_cvm_no_cvm_drops_counters():
    rng = np.random.default_rng(2)
    B, S, W = 2, 2, 5
    rows, segs, _ = _make_batch(rng, B, S, W)
    got = np.asarray(
        fused_seqpool_cvm(jnp.asarray(rows), jnp.asarray(segs), B, S, use_cvm=False)
    )
    exp = _oracle_pool(rows, segs, B, S, W)[..., 2:].reshape(B, -1)
    assert got.shape == (B, S * (W - 2))
    np.testing.assert_allclose(got, exp, rtol=1e-4, atol=1e-5)


def test_fused_seqpool_cvm_occurrence_filter():
    """need_filter drops a low-score occurrence entirely before pooling
    (reference formula fused_seqpool_cvm_op.cu:104:
    (show - click) * show_coeff + click * clk_coeff < threshold)."""
    B, S, W = 1, 2, 4
    rows = np.zeros((4, W), dtype=np.float32)
    rows[0] = [1, 0, 5.0, 5.0]  # slot 0: (1-0)*0.2 = 0.2 < 1.0 -> filtered
    rows[1] = [10, 3, 2.0, 2.0]  # slot 1: (10-3)*0.2+3 = 4.4 >= 1.0 -> kept
    segs = np.array([0, 1, B * S, B * S], dtype=np.int32)
    got = np.asarray(
        fused_seqpool_cvm(
            jnp.asarray(rows), jnp.asarray(segs), B, S,
            use_cvm=False, need_filter=True, show_coeff=0.2, clk_coeff=1.0,
            threshold=1.0,
        )
    ).reshape(B, S, W - 2)
    np.testing.assert_allclose(got[0, 0], [0.0, 0.0])
    np.testing.assert_allclose(got[0, 1], [2.0, 2.0])


def test_cvm_forward_and_no_counter_grad():
    rng = np.random.default_rng(3)
    x = rng.normal(size=(5, 7)).astype(np.float32)
    x[:, 0] = np.abs(x[:, 0]) + 1
    x[:, 1] = np.abs(x[:, 1])
    got = np.asarray(cvm(jnp.asarray(x)))
    exp = x.copy()
    exp[:, 0] = np.log(x[:, 0] + 1)
    exp[:, 1] = np.log(x[:, 1] + 1) - np.log(x[:, 0] + 1)
    np.testing.assert_allclose(got, exp, rtol=1e-4, atol=1e-5)
    # counters carry no gradient; pass-through columns carry identity grad
    g = jax.grad(lambda v: cvm(v).sum())(jnp.asarray(x))
    np.testing.assert_allclose(np.asarray(g[:, :2]), 0.0)
    np.testing.assert_allclose(np.asarray(g[:, 2:]), 1.0)


def test_cvm_use_cvm_false():
    x = np.arange(12, dtype=np.float32).reshape(3, 4)
    got = np.asarray(cvm(jnp.asarray(x), use_cvm=False))
    np.testing.assert_allclose(got, x[:, 2:])


def test_seqpool_padding_gets_zero_grad():
    """Gradient wrt padding rows is exactly zero (dead-row hygiene)."""
    rng = np.random.default_rng(4)
    B, S, W = 3, 2, 4
    rows, segs, lens = _make_batch(rng, B, S, W)
    K_real = int(lens.sum())

    def f(r):
        return fused_seqpool_cvm(r, jnp.asarray(segs), B, S).sum()

    g = np.asarray(jax.grad(f)(jnp.asarray(rows)))
    np.testing.assert_allclose(g[K_real:], 0.0)
    # counters never receive gradient either
    np.testing.assert_allclose(g[:, :2], 0.0)
