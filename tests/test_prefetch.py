"""Double-buffered device feed: prefetch path must be bit-identical to the
serial path (same batches, same order — only overlap changes), and producer
exceptions must surface at the train loop."""

import numpy as np
import pytest

from paddlebox_tpu.config import SparseTableConfig, TrainerConfig
from paddlebox_tpu.data.dataset import PadBoxSlotDataset
from paddlebox_tpu.data.synth import make_synth_config, write_synth_files
from paddlebox_tpu.models import CtrDnn
from paddlebox_tpu.sparse.table import SparseTable
from paddlebox_tpu.train.trainer import Trainer, _FeedPrefetcher

S, DENSE, B = 3, 2, 8


def _run(tmp_path, prefetch: int, scan_steps: int = 1):
    conf = make_synth_config(
        n_sparse_slots=S, dense_dim=DENSE, batch_size=B, max_feasigns_per_ins=16
    )
    files = write_synth_files(
        str(tmp_path / f"d{prefetch}"), n_files=1, ins_per_file=96,
        n_sparse_slots=S, vocab_per_slot=60, dense_dim=DENSE, seed=2,
    )
    ds = PadBoxSlotDataset(conf, read_threads=1)
    ds.set_filelist(files)
    ds.load_into_memory()
    tconf = SparseTableConfig(embedding_dim=8)
    trconf = TrainerConfig(
        auc_buckets=1 << 10, prefetch_batches=prefetch, scan_steps=scan_steps
    )
    model = CtrDnn(S, tconf.row_width, dense_dim=DENSE, hidden=(16, 8))
    table = SparseTable(tconf, seed=0)
    trainer = Trainer(model, tconf, trconf, seed=0)
    table.begin_pass(ds.unique_keys())
    metrics = trainer.train_from_dataset(ds, table)
    table.end_pass()
    ds.close()
    state = table.state_dict()
    return metrics, state["values"].copy()


def test_prefetch_parity(tmp_path):
    m_serial, v_serial = _run(tmp_path, prefetch=0)
    m_pre, v_pre = _run(tmp_path, prefetch=2)
    assert m_pre["steps"] == m_serial["steps"]
    assert m_pre["loss"] == m_serial["loss"]
    assert m_pre["auc"] == m_serial["auc"]
    np.testing.assert_array_equal(v_pre, v_serial)


def test_scan_steps_parity(tmp_path):
    """k-steps-per-dispatch (lax.scan) must reproduce the serial path
    exactly — including a ragged tail (12 batches, k=5 -> 2 scans + 2
    singles)."""
    m_serial, v_serial = _run(tmp_path, prefetch=0)
    m_scan, v_scan = _run(tmp_path, prefetch=2, scan_steps=5)
    assert m_scan["steps"] == m_serial["steps"]
    assert np.isclose(m_scan["loss"], m_serial["loss"], rtol=1e-6)
    # scan compiles a different XLA program: allow float-level divergence
    # (bucket flips at boundaries), unlike the identical-program prefetch test
    assert np.isclose(m_scan["auc"], m_serial["auc"], atol=1e-3)
    np.testing.assert_allclose(v_scan, v_serial, rtol=1e-6, atol=1e-7)


def test_producer_exception_propagates():
    def bad_gen():
        yield 1, {}
        raise ValueError("producer exploded")

    pf = _FeedPrefetcher(bad_gen(), depth=2)
    out = next(pf)
    assert out[0] == 1
    with pytest.raises(ValueError, match="producer exploded"):
        next(pf)
    pf.close()


def test_close_unblocks_full_queue():
    def slow_gen():
        for i in range(100):
            yield i

    pf = _FeedPrefetcher(slow_gen(), depth=1)
    next(pf)
    pf.close()  # producer blocked on a full queue must exit
    assert not pf._thread.is_alive()
