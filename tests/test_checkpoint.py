"""Base/delta checkpoint + resume (reference: SaveBase/SaveDelta
box_wrapper.cc:1411-1460, reload InitializeGPUAndLoadModel cc:1329)."""

import numpy as np
import pytest

from paddlebox_tpu.checkpoint import CheckpointManager
from paddlebox_tpu.config import SparseTableConfig, TrainerConfig
from paddlebox_tpu.data.dataset import PadBoxSlotDataset
from paddlebox_tpu.data.synth import make_synth_config, write_synth_files
from paddlebox_tpu.models import CtrDnn
from paddlebox_tpu.sparse.table import SparseTable
from paddlebox_tpu.train.trainer import Trainer

S, DENSE, B = 3, 2, 16


def _dataset(tmp_path, seed=0, n_ins=64):
    conf = make_synth_config(
        n_sparse_slots=S, dense_dim=DENSE, batch_size=B, max_feasigns_per_ins=16
    )
    files = write_synth_files(
        str(tmp_path / f"d{seed}"), n_files=1, ins_per_file=n_ins,
        n_sparse_slots=S, vocab_per_slot=40, dense_dim=DENSE, seed=seed,
    )
    ds = PadBoxSlotDataset(conf, read_threads=1)
    ds.set_filelist(files)
    ds.load_into_memory()
    return ds


def _world(seed=0):
    tconf = SparseTableConfig(embedding_dim=4)
    model = CtrDnn(S, tconf.row_width, dense_dim=DENSE, hidden=(16,))
    trainer = Trainer(model, tconf, TrainerConfig(auc_buckets=1 << 10), seed=seed)
    table = SparseTable(tconf, seed=seed)
    return tconf, model, trainer, table


def _train_pass(trainer, table, ds):
    table.begin_pass(ds.unique_keys())
    m = trainer.train_from_dataset(ds, table)
    table.end_pass()
    return m


def test_base_roundtrip(tmp_path):
    ds = _dataset(tmp_path)
    _, _, trainer, table = _world()
    _train_pass(trainer, table, ds)
    mgr = CheckpointManager(str(tmp_path / "ckpt"))
    params, opt = trainer.dense_state()
    mgr.save_base("20260729", table, params, opt, meta={"step": trainer.global_step})

    _, _, trainer2, table2 = _world(seed=99)  # different init
    p2, o2, meta = mgr.load(table2, trainer2.params, trainer2.opt_state)
    trainer2.load_dense_state(p2, o2)
    assert meta["tag"] == "20260729"
    sd, sd2 = table.state_dict(), table2.state_dict()
    np.testing.assert_array_equal(sd2["keys"], sd["keys"])
    np.testing.assert_allclose(sd2["values"], sd["values"], rtol=1e-6)
    for a, b in zip(
        __import__("jax").tree.leaves(trainer.params),
        __import__("jax").tree.leaves(trainer2.params),
    ):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=1e-6)
    ds.close()


def test_delta_chain_equals_full_store(tmp_path):
    ds1 = _dataset(tmp_path, seed=0)
    ds2 = _dataset(tmp_path, seed=1)
    _, _, trainer, table = _world()
    _train_pass(trainer, table, ds1)
    mgr = CheckpointManager(str(tmp_path / "ckpt"))
    mgr.save_base("base0", table)
    _train_pass(trainer, table, ds2)
    params, opt = trainer.dense_state()
    mgr.save_delta("delta1", table, params, opt)

    # delta contains only the keys of pass 2 (plus nothing else)
    ckpts = mgr.list_checkpoints()
    assert [c.kind for c in ckpts] == ["base", "delta"]

    _, _, _, table2 = _world(seed=5)
    mgr.load(table2)
    sd, sd2 = table.state_dict(), table2.state_dict()
    np.testing.assert_array_equal(sd2["keys"], sd["keys"])
    np.testing.assert_allclose(sd2["values"], sd["values"], rtol=1e-6)
    ds1.close()
    ds2.close()


def test_resume_matches_uninterrupted(tmp_path):
    """checkpoint/restore mid-run == continuous run, bit-for-bit."""
    ds1 = _dataset(tmp_path, seed=0)
    ds2 = _dataset(tmp_path, seed=1)

    # continuous: pass1 then pass2
    _, _, tr_a, tab_a = _world()
    _train_pass(tr_a, tab_a, ds1)
    m_a = _train_pass(tr_a, tab_a, ds2)

    # interrupted: pass1, save, restore into fresh world, pass2
    _, _, tr_b, tab_b = _world()
    _train_pass(tr_b, tab_b, ds1)
    mgr = CheckpointManager(str(tmp_path / "ckpt"))
    p, o = tr_b.dense_state()
    mgr.save_base("mid", tab_b, p, o)

    _, _, tr_c, tab_c = _world(seed=7)
    pc, oc, _ = mgr.load(tab_c, tr_c.params, tr_c.opt_state)
    tr_c.load_dense_state(pc, oc)
    m_c = _train_pass(tr_c, tab_c, ds2)

    assert m_c["loss"] == pytest.approx(m_a["loss"], rel=1e-5)
    sd_a, sd_c = tab_a.state_dict(), tab_c.state_dict()
    np.testing.assert_array_equal(sd_c["keys"], sd_a["keys"])
    np.testing.assert_allclose(sd_c["values"], sd_a["values"], rtol=1e-5)
    ds1.close()
    ds2.close()


def test_load_upto_and_missing(tmp_path):
    ds = _dataset(tmp_path)
    _, _, trainer, table = _world()
    mgr = CheckpointManager(str(tmp_path / "ckpt"))
    with pytest.raises(FileNotFoundError):
        mgr.load(table)
    _train_pass(trainer, table, ds)
    mgr.save_base("a", table)
    store_at_a = {k: v.copy() for k, v in table.state_dict().items()}
    _train_pass(trainer, table, ds)
    mgr.save_delta("b", table)
    _, _, _, t2 = _world(seed=3)
    mgr.load(t2, upto="a")
    np.testing.assert_allclose(t2.state_dict()["values"], store_at_a["values"], rtol=1e-6)
    with pytest.raises(FileNotFoundError):
        mgr.load(t2, upto="nope")
    ds.close()


def test_sharded_table_checkpoint(tmp_path):
    """ShardedSparseTable shares the host-store format — same manager works."""
    import jax

    from paddlebox_tpu.parallel import MultiChipTrainer, ShardedSparseTable, make_mesh

    n_dev = min(4, len(jax.devices()))
    mesh = make_mesh(n_dev)
    tconf = SparseTableConfig(embedding_dim=4)
    ds = _dataset(tmp_path, n_ins=B * n_dev * 2)
    model = CtrDnn(S, tconf.row_width, dense_dim=DENSE, hidden=(16,))
    trainer = MultiChipTrainer(model, tconf, mesh, TrainerConfig(auc_buckets=1 << 10))
    table = ShardedSparseTable(tconf, mesh, seed=0)
    table.begin_pass(ds.unique_keys())
    trainer.train_from_dataset(ds, table)
    table.end_pass()
    mgr = CheckpointManager(str(tmp_path / "ckpt"))
    p, o = trainer.dense_state()
    mgr.save_base("x", table, p, o)

    table2 = ShardedSparseTable(tconf, mesh, seed=9)
    trainer2 = MultiChipTrainer(model, tconf, mesh, TrainerConfig(auc_buckets=1 << 10), seed=9)
    p2, o2, _ = mgr.load(table2, *trainer2.dense_state())
    trainer2.load_dense_state(p2, o2)
    sd, sd2 = table.state_dict(), table2.state_dict()
    np.testing.assert_array_equal(sd2["keys"], sd["keys"])
    np.testing.assert_allclose(sd2["values"], sd["values"], rtol=1e-6)
    # restored world trains on
    table2.begin_pass(ds.unique_keys())
    m = trainer2.train_from_dataset(ds, table2)
    table2.end_pass()
    assert np.isfinite(m["loss"])
    ds.close()
