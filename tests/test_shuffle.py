"""Global shuffle routing + exchange (reference: ShuffleData/ReceiveSuffleData
data_set.cc:1916-2090) and the binary archive wire/disk format
(framework/archive.h, data_feed.h:1544-1559)."""

import threading

import numpy as np
import pytest

from paddlebox_tpu.data.archive import (
    block_from_bytes,
    block_to_bytes,
    read_archive,
    write_archive,
)
from paddlebox_tpu.data.dataset import PadBoxSlotDataset
from paddlebox_tpu.data.record import RecordBlock
from paddlebox_tpu.data.shuffle import (
    InProcessShuffleGroup,
    TcpShuffler,
    route_ids,
    split_by_route,
)
from paddlebox_tpu.data.synth import make_synth_config, write_synth_files


def _block(n_ins=20, s=2, seed=0, with_meta=True):
    rng = np.random.default_rng(seed)
    lens = rng.integers(1, 4, size=n_ins * s)
    offs = np.zeros(n_ins * s + 1, dtype=np.int64)
    np.cumsum(lens, out=offs[1:])
    return RecordBlock(
        n_ins=n_ins,
        n_sparse_slots=s,
        keys=rng.integers(1, 1000, size=int(offs[-1])).astype(np.uint64),
        key_offsets=offs,
        dense=rng.normal(size=(n_ins, 3)).astype(np.float32),
        labels=rng.integers(0, 2, size=n_ins).astype(np.float32),
        ins_ids=[f"ins-{seed}-{i}" for i in range(n_ins)] if with_meta else None,
        search_ids=rng.integers(0, 1 << 40, size=n_ins).astype(np.uint64)
        if with_meta
        else None,
        ranks=np.zeros(n_ins, dtype=np.int32) if with_meta else None,
        cmatches=np.zeros(n_ins, dtype=np.int32) if with_meta else None,
    )


# --------------------------------------------------------------------------- #
# archive
# --------------------------------------------------------------------------- #
def test_archive_roundtrip(tmp_path):
    blocks = [_block(seed=0), _block(seed=1, with_meta=False), _block(n_ins=0)]
    b2 = block_from_bytes(block_to_bytes(blocks[0]))
    np.testing.assert_array_equal(b2.keys, blocks[0].keys)
    assert b2.ins_ids == blocks[0].ins_ids
    path = str(tmp_path / "a.bin")
    assert write_archive(path, blocks) == 3
    back = list(read_archive(path))
    assert [b.n_ins for b in back] == [b.n_ins for b in blocks]
    np.testing.assert_array_equal(back[0].search_ids, blocks[0].search_ids)
    assert back[1].search_ids is None


# --------------------------------------------------------------------------- #
# routing
# --------------------------------------------------------------------------- #
def test_route_by_search_id_deterministic():
    b = _block()
    d1 = route_ids(b, 4, "search_id")
    d2 = route_ids(b, 4, "search_id")
    np.testing.assert_array_equal(d1, d2)
    np.testing.assert_array_equal(d1, (b.search_ids % 4).astype(np.int32))


def test_route_modes_partition():
    b = _block(n_ins=50)
    for mode in ("search_id", "ins_id", "random"):
        dest = route_ids(b, 3, mode, seed=1)
        parts = split_by_route(b, dest, 3)
        assert sum(p.n_ins for p in parts) == b.n_ins
        # each instance in exactly one part, content preserved
        all_labels = np.concatenate([p.labels for p in parts])
        assert sorted(all_labels.tolist()) == sorted(b.labels.tolist())


def test_route_requires_meta():
    b = _block(with_meta=False)
    with pytest.raises(ValueError):
        route_ids(b, 2, "search_id")
    with pytest.raises(ValueError):
        route_ids(b, 2, "ins_id")


# --------------------------------------------------------------------------- #
# in-process exchange
# --------------------------------------------------------------------------- #
def _run_workers(n, fn):
    results, errs = [None] * n, []

    def wrap(i):
        try:
            results[i] = fn(i)
        except Exception as e:  # pragma: no cover
            errs.append(e)

    ts = [threading.Thread(target=wrap, args=(i,)) for i in range(n)]
    [t.start() for t in ts]
    [t.join(timeout=60) for t in ts]
    assert not errs, errs
    return results


def test_inprocess_exchange_routes_every_record():
    n = 4
    group = InProcessShuffleGroup(n, mode="search_id")
    blocks = [_block(seed=i) for i in range(n)]

    results = _run_workers(n, lambda i: group.shuffler(i).exchange(blocks[i]))
    total = sum(r.n_ins for r in results)
    assert total == sum(b.n_ins for b in blocks)
    for wid, r in enumerate(results):
        if r.n_ins:
            np.testing.assert_array_equal(
                (r.search_ids % n).astype(np.int32), np.full(r.n_ins, wid)
            )
    # reusable for a second pass
    results2 = _run_workers(n, lambda i: group.shuffler(i).exchange(blocks[i]))
    assert sum(r.n_ins for r in results2) == total


def test_dataset_with_shuffler(tmp_path):
    """Two datasets (workers) loading disjoint files exchange at load time."""
    n = 2
    conf = make_synth_config(n_sparse_slots=2, dense_dim=2, batch_size=8)
    files = write_synth_files(
        str(tmp_path), n_files=2, ins_per_file=32, n_sparse_slots=2,
        vocab_per_slot=30, dense_dim=2,
    )
    group = InProcessShuffleGroup(n, mode="random", seed=3)

    def load(i):
        ds = PadBoxSlotDataset(conf, read_threads=1)
        ds.set_filelist([files[i]])
        ds.shuffler = group.shuffler(i)
        ds.load_into_memory()
        return ds

    dss = _run_workers(n, load)
    assert sum(ds.get_memory_data_size() for ds in dss) == 64
    # both got some records (random routing over 32 each)
    assert all(ds.get_memory_data_size() > 0 for ds in dss)
    for ds in dss:
        ds.global_shuffle(seed=0)
        assert sum(1 for _ in ds.batches()) >= 1
        ds.close()


# --------------------------------------------------------------------------- #
# tcp exchange
# --------------------------------------------------------------------------- #
def test_tcp_exchange():
    n = 3
    shufflers = [
        TcpShuffler([("127.0.0.1", 0)] * n, i, mode="search_id") for i in range(n)
    ]
    # bind with OS-assigned ports, then share the real endpoints
    for s in shufflers:
        s.endpoints = list(s.endpoints)
        s.start()
    endpoints = [("127.0.0.1", s.bound_port()) for s in shufflers]
    for s in shufflers:
        s.endpoints = endpoints
    blocks = [_block(seed=10 + i) for i in range(n)]
    try:
        results = _run_workers(n, lambda i: shufflers[i].exchange(blocks[i]))
        assert sum(r.n_ins for r in results) == sum(b.n_ins for b in blocks)
        for wid, r in enumerate(results):
            if r.n_ins:
                np.testing.assert_array_equal(
                    (r.search_ids % n).astype(np.int32), np.full(r.n_ins, wid)
                )
    finally:
        for s in shufflers:
            s.close()


def test_wire_codec_roundtrip_and_compression():
    """block_to_wire/block_from_wire: every codec round-trips the block
    exactly; the varint frame shrinks the key column; legacy npz stays
    decodable; an unknown framing fails loudly (WireCodecError)."""
    from paddlebox_tpu.data import archive

    b = _block(n_ins=200, seed=4)
    for codec in ("varint", "raw", "legacy"):
        payload, raw_kb, wire_kb = archive.block_to_wire(b, codec)
        out = archive.block_from_wire(payload)
        np.testing.assert_array_equal(out.keys, b.keys)
        np.testing.assert_array_equal(out.key_offsets, b.key_offsets)
        np.testing.assert_array_equal(out.dense, b.dense)
        np.testing.assert_array_equal(out.labels, b.labels)
        assert raw_kb == b.keys.nbytes
        if codec == "varint":
            assert wire_kb < raw_kb, "key column must shrink under varint"
        else:
            assert wire_kb == raw_kb
    # legacy bare npz (an OLD sender) decodes through the wire reader
    legacy = archive.block_to_bytes(b)
    np.testing.assert_array_equal(
        archive.block_from_wire(legacy).keys, b.keys
    )
    # garbage/unknown framing is loud, never a misparse
    with pytest.raises(archive.WireCodecError):
        archive.block_from_wire(b"\x00\x01\x02\x03not-a-frame")
    with pytest.raises(archive.WireCodecError):
        archive.block_from_wire(archive._WIRE_MAGIC + b"\x07rest")


def test_tcp_exchange_varint_codec_bitexact_and_counted():
    """A 2-worker TCP exchange under the varint wire codec delivers the
    exact same routed records as the in-process reference, and the
    shuffle.exchange_bytes histogram records the raw->encoded shrink."""
    from paddlebox_tpu import telemetry

    n = 2
    shufflers = [
        TcpShuffler([("127.0.0.1", 0)] * n, i, mode="search_id",
                    codec="varint")
        for i in range(n)
    ]
    for s in shufflers:
        s.endpoints = list(s.endpoints)
        s.start()
    endpoints = [("127.0.0.1", s.bound_port()) for s in shufflers]
    for s in shufflers:
        s.endpoints = endpoints
    blocks = [_block(n_ins=120, seed=20 + i) for i in range(n)]
    try:
        results = _run_workers(n, lambda i: shufflers[i].exchange(blocks[i]))
        assert sum(r.n_ins for r in results) == sum(b.n_ins for b in blocks)
        for wid, r in enumerate(results):
            if r.n_ins:
                np.testing.assert_array_equal(
                    (r.search_ids % n).astype(np.int32),
                    np.full(r.n_ins, wid),
                )
    finally:
        for s in shufflers:
            s.close()
    h = telemetry.registry.get("shuffle.exchange_bytes")
    assert h is not None
    series = {k: v for k, v in h.series().items()}
    raw = sum(s.sum for k, s in series.items() if ("kind", "raw") in k)
    enc = sum(s.sum for k, s in series.items() if ("kind", "encoded") in k)
    assert raw > 0 and enc > 0 and enc < raw


# --------------------------------------------------------------------------- #
# tcp transport robustness (distributed-liveness tier)
# --------------------------------------------------------------------------- #
def test_tcp_exchange_records_collective_digest():
    """Each exchange round leaves a (channel, seq, op) digest in the
    flight ring (the pbox_doctor cross-rank witness).  A single-worker
    shuffler exchanges with nobody but still stamps its round."""
    from paddlebox_tpu.telemetry import flight

    rec = flight.reset_for_tests()
    s = TcpShuffler([("127.0.0.1", 0)], 0, timeout=1.0)
    try:
        for _ in range(2):
            s.exchange(_block(seed=3))
    finally:
        s.close()
        digests = [
            r for r in rec.snapshot()
            if r["kind"] == "collective" and r.get("channel") == "shuffle"
        ]
        flight.reset_for_tests()
    assert [(d["seq"], d["op"], d["rank"]) for d in digests] == [
        (0, "exchange", 0), (1, "exchange", 0),
    ]


def test_tcp_close_idempotent():
    s = TcpShuffler([("127.0.0.1", 0)], 0)
    s.start()
    s.close()
    s.close()  # second close must be a no-op, not an OSError
    # and close() without start() on a fresh instance is safe too
    TcpShuffler([("127.0.0.1", 0)], 0).close()


def test_tcp_connection_refused_names_peer(monkeypatch):
    from paddlebox_tpu.data.shuffle import ShufflePeerError

    monkeypatch.setenv("PBOX_RETRY_MAX_ATTEMPTS", "2")
    monkeypatch.setenv("PBOX_RETRY_BASE_DELAY_S", "0.01")
    monkeypatch.setenv("PBOX_RETRY_MAX_DELAY_S", "0.02")
    # worker 0 up, worker 1's endpoint is a dead port (bind + close)
    import socket as _socket

    probe = _socket.socket()
    probe.bind(("127.0.0.1", 0))
    dead_port = probe.getsockname()[1]
    probe.close()
    s = TcpShuffler(
        [("127.0.0.1", 0), ("127.0.0.1", dead_port)], 0, mode="random",
        timeout=1.0,
    )
    s.start()
    try:
        with pytest.raises(ShufflePeerError) as ei:
            s.exchange(_block(n_ins=30, seed=5))
        msg = str(ei.value)
        assert "worker 1" in msg and f"127.0.0.1:{dead_port}" in msg
        assert ei.value.worker_id == 1
        assert ei.value.endpoint == ("127.0.0.1", dead_port)
    finally:
        s.close()


def test_tcp_exchange_timeout_names_missing_workers():
    # both listeners up, but worker 1 never exchanges: worker 0's wait must
    # time out naming worker 1 and its endpoint, not hang
    shufflers = [TcpShuffler([("127.0.0.1", 0)] * 2, i, timeout=0.6)
                 for i in range(2)]
    for s in shufflers:
        s.start()
    endpoints = [("127.0.0.1", s.bound_port()) for s in shufflers]
    for s in shufflers:
        s.endpoints = endpoints
    try:
        with pytest.raises(TimeoutError) as ei:
            shufflers[0].exchange(_block(seed=3))
        msg = str(ei.value)
        assert "worker 1" in msg and str(endpoints[1][1]) in msg
        assert "round 0" in msg
    finally:
        for s in shufflers:
            s.close()


def test_tcp_exchange_fault_site():
    from paddlebox_tpu.utils import faults

    s = TcpShuffler([("127.0.0.1", 0)], 0)
    s.start()
    try:
        with faults.fault_plan({"shuffle.exchange": "first:1"}):
            with pytest.raises(faults.FaultInjected):
                s.exchange(_block(seed=1))
        # next exchange (single worker: no peers) succeeds
        out = s.exchange(_block(seed=1, n_ins=4))
        assert out.n_ins == 4
    finally:
        s.close()


def test_tcp_connect_retry_absorbs_transient_refusal(monkeypatch):
    """A peer listener that comes up a moment late is absorbed by the
    shuffle.connect retry loop instead of failing the exchange."""
    monkeypatch.setenv("PBOX_RETRY_MAX_ATTEMPTS", "5")
    monkeypatch.setenv("PBOX_RETRY_BASE_DELAY_S", "0.05")
    monkeypatch.setenv("PBOX_RETRY_MAX_DELAY_S", "0.1")
    from paddlebox_tpu.utils.monitor import stats

    a = TcpShuffler([("127.0.0.1", 0)] * 2, 0, mode="random", timeout=5.0)
    a.start()
    # reserve b's port without listening yet
    import socket as _socket

    placeholder = _socket.socket()
    placeholder.bind(("127.0.0.1", 0))
    b_port = placeholder.getsockname()[1]
    placeholder.close()
    endpoints = [("127.0.0.1", a.bound_port()), ("127.0.0.1", b_port)]
    a.endpoints = endpoints
    b = TcpShuffler(endpoints, 1, mode="random", timeout=5.0)

    base_retries = stats.get("retry.shuffle.connect.retries")

    def late_start_and_exchange():
        import time as _t

        _t.sleep(0.3)  # a's first connect attempts hit a dead port
        b.start()
        return b.exchange(_block(seed=21, n_ins=16))

    try:
        res = _run_workers(
            2,
            lambda i: a.exchange(_block(seed=20, n_ins=16))
            if i == 0
            else late_start_and_exchange(),
        )
        assert sum(r.n_ins for r in res) == 32
        assert stats.get("retry.shuffle.connect.retries") > base_retries
    finally:
        a.close()
        b.close()
