"""pbox-lint (tools/pbox_analyze/): the concurrency- and JAX-aware
static-analysis framework.

Per rule: a good fixture (no finding), a bad fixture (finding at the
expected line), a suppressed fixture (inline ``# pbox-lint: ignore``),
and — once — a baselined fixture.  Plus the framework plumbing
(suppression placement, baseline schema/order/staleness hygiene,
--changed line filtering) and the tier-1 gate: zero non-baselined
findings over the repo's default roots.
"""

import json
import os
import subprocess
import sys
import textwrap

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
TOOLS = os.path.join(REPO, "tools")
CLI = os.path.join(TOOLS, "pbox_analyze.py")

sys.path.insert(0, TOOLS)

from pbox_analyze import baseline as baseline_mod  # noqa: E402
from pbox_analyze import (  # noqa: E402
    rules_clock,
    rules_except,
    rules_locks,
    rules_threads,
    rules_tracer,
)
from pbox_analyze.core import Context, SourceFile  # noqa: E402


def _ctx(tmp_path, source: str) -> Context:
    path = tmp_path / "fixture.py"
    path.write_text(textwrap.dedent(source))
    return Context(paths=[str(path)], repo=str(tmp_path))


def _run(mod, tmp_path, source: str):
    ctx = _ctx(tmp_path, source)
    findings = mod.run(ctx)
    # apply inline suppressions the way the CLI does
    return [
        f for f in findings
        if not ctx.by_rel[f.file].suppressed(f)
    ]


# --------------------------------------------------------------------------- #
# swallowed-exception
# --------------------------------------------------------------------------- #
BAD_EXCEPT = """\
    def f():
        try:
            risky()
        except Exception:
            pass
"""


def test_swallowed_exception_bad(tmp_path):
    (finding,) = _run(rules_except, tmp_path, BAD_EXCEPT)
    assert finding.rule == "swallowed-exception"
    assert finding.line == 4


@pytest.mark.parametrize("body", [
    "raise",                                # re-raise
    "logger.warning('x', exc_info=True)",   # log
    "stats.add('x.errors')",                # counter
    "flight.dump_now('boom')",              # flight dump
    "print('x')",                           # stderr surfacing
])
def test_swallowed_exception_good(tmp_path, body):
    src = BAD_EXCEPT.replace("pass", body)
    assert _run(rules_except, tmp_path, src) == []


def test_swallowed_exception_stored_latch_good(tmp_path):
    src = """\
        def f(self):
            try:
                risky()
            except BaseException as e:
                self._err = e
    """
    assert _run(rules_except, tmp_path, src) == []


def test_narrow_except_is_not_flagged(tmp_path):
    src = BAD_EXCEPT.replace("Exception", "ValueError")
    assert _run(rules_except, tmp_path, src) == []


def test_swallowed_exception_suppressed(tmp_path):
    src = BAD_EXCEPT.replace(
        "except Exception:",
        "# pbox-lint: ignore[swallowed-exception] fixture reason\n"
        "    except Exception:",
    )
    assert _run(rules_except, tmp_path, src) == []


def test_multiline_reason_comment_still_covers_the_site(tmp_path):
    src = BAD_EXCEPT.replace(
        "except Exception:",
        "# pbox-lint: ignore[swallowed-exception] a reason so long it\n"
        "    # wraps onto a second comment line before the code\n"
        "    except Exception:",
    )
    assert _run(rules_except, tmp_path, src) == []


# --------------------------------------------------------------------------- #
# clock-misuse
# --------------------------------------------------------------------------- #
def test_clock_misuse_literal_deadline(tmp_path):
    src = """\
        import time
        deadline = time.time() + 10.0
    """
    (finding,) = _run(rules_clock, tmp_path, src)
    assert finding.rule == "clock-misuse"
    assert finding.line == 2


def test_clock_misuse_timeout_name_and_compare(tmp_path):
    src = """\
        import time
        state = {"deadline": time.time() + hang_timeout}
        if time.time() > state["deadline"]:
            boom()
    """
    lines = {f.line for f in _run(rules_clock, tmp_path, src)}
    assert lines == {2, 3}


def test_clock_wallclock_timestamps_are_legal(tmp_path):
    src = """\
        import time
        published_at = time.time()
        lag = time.time() - rec.event_ts
        fresh = time.time() - oldest
    """
    assert _run(rules_clock, tmp_path, src) == []


def test_clock_misuse_suppressed(tmp_path):
    src = """\
        import time
        # pbox-lint: ignore[clock-misuse] fixture reason
        deadline = time.time() + 10.0
    """
    assert _run(rules_clock, tmp_path, src) == []


# --------------------------------------------------------------------------- #
# lock-order / lock-held-blocking
# --------------------------------------------------------------------------- #
LOCK_CYCLE = """\
    import threading

    class Gate:
        def __init__(self):
            self._a = threading.Lock()
            self._b = threading.Lock()

        def one(self):
            with self._a:
                with self._b:
                    pass

        def two(self):
            with self._b:
                with self._a:
                    pass
"""


def test_lock_order_cycle(tmp_path):
    findings = _run(rules_locks, tmp_path, LOCK_CYCLE)
    assert {f.rule for f in findings} == {"lock-order"}
    assert {f.line for f in findings} == {10, 15}


def test_lock_order_consistent_is_legal(tmp_path):
    src = LOCK_CYCLE.replace(
        "with self._b:\n                with self._a:",
        "with self._a:\n                with self._b:",
    )
    assert "def two" in src and src.count("with self._a:") == 2
    assert _run(rules_locks, tmp_path, src) == []


def test_lock_order_interprocedural(tmp_path):
    src = """\
        import threading

        class Gate:
            def __init__(self):
                self._a = threading.Lock()
                self._b = threading.Lock()

            def outer(self):
                with self._a:
                    self.inner()

            def inner(self):
                with self._b:
                    pass

            def reversed(self):
                with self._b:
                    with self._a:
                        pass
    """
    assert any(
        f.rule == "lock-order"
        for f in _run(rules_locks, tmp_path, src)
    )


BLOCKING = """\
    import threading
    import time

    class Gate:
        def __init__(self):
            self._lock = threading.Lock()
            self._cond = threading.Condition()
            self.sock = None

        def bad(self):
            with self._lock:
                time.sleep(1.0)
                self.sock.recv(4096)
                self._cond.wait()

        def good(self):
            with self._cond:
                self._cond.wait()
            time.sleep(1.0)
"""


def test_lock_held_blocking(tmp_path):
    findings = _run(rules_locks, tmp_path, BLOCKING)
    assert {f.rule for f in findings} == {"lock-held-blocking"}
    assert {f.line for f in findings} == {12, 13, 14}


def test_lock_held_blocking_suppressed(tmp_path):
    src = BLOCKING.replace(
        "time.sleep(1.0)\n                self.sock.recv",
        "time.sleep(1.0)  # pbox-lint: ignore[lock-held-blocking] reason\n"
        "                self.sock.recv",
    )
    assert "ignore[lock-held-blocking]" in src
    lines = {f.line for f in _run(rules_locks, tmp_path, src)}
    assert lines == {13, 14}  # only the sleep was waved through


# --------------------------------------------------------------------------- #
# thread-shared-state
# --------------------------------------------------------------------------- #
SHARED = """\
    import threading

    class Worker:
        def __init__(self):
            self._lock = threading.Lock()
            self.count = 0
            self._thread = threading.Thread(target=self._loop)

        def _loop(self):
            self.count += 1

        def read(self):
            return self.count
"""


def test_thread_shared_state_bad(tmp_path):
    (finding,) = _run(rules_threads, tmp_path, SHARED)
    assert finding.rule == "thread-shared-state"
    assert finding.line == 10
    assert "count" in finding.message


def test_thread_shared_state_locked_is_legal(tmp_path):
    src = SHARED.replace(
        "def _loop(self):\n        self.count += 1",
        "def _loop(self):\n        with self._lock:\n"
        "            self.count += 1",
    ).replace(
        "return self.count",
        "with self._lock:\n            return self.count",
    )
    assert _run(rules_threads, tmp_path, src) == []


def test_thread_shared_state_sync_attrs_exempt(tmp_path):
    src = """\
        import threading

        class Worker:
            def __init__(self):
                self._stop = threading.Event()
                self._thread = threading.Thread(target=self._loop)

            def _loop(self):
                self._stop.wait(1.0)

            def close(self):
                self._stop.set()
                self._thread = None
    """
    assert _run(rules_threads, tmp_path, src) == []


def test_thread_shared_state_suppressed(tmp_path):
    src = SHARED.replace(
        "self.count += 1",
        "# pbox-lint: ignore[thread-shared-state] fixture reason\n"
        "        self.count += 1",
    )
    assert _run(rules_threads, tmp_path, src) == []


# --------------------------------------------------------------------------- #
# jax-tracer-safety
# --------------------------------------------------------------------------- #
def test_tracer_host_effect_and_branch(tmp_path):
    src = """\
        import jax
        import numpy as np

        @jax.jit
        def step(x):
            print("trace-time only")
            y = np.asarray(x)
            if x > 0:
                return y
            return -y
    """
    findings = _run(rules_tracer, tmp_path, src)
    assert {f.rule for f in findings} == {"jax-tracer-safety"}
    assert {f.line for f in findings} == {6, 7, 8}


def test_tracer_static_idioms_are_legal(tmp_path):
    src = """\
        import jax
        import jax.numpy as jnp
        import numpy as np

        @jax.jit
        def step(x, mask=None):
            k = x.shape[0]
            pad = np.zeros((4,), np.float32)
            if mask is None:
                mask = jnp.ones((k,))
            if k > 128:
                x = x[:128]
            jax.debug.print("ok {}", x)
            return x * mask + pad
    """
    assert _run(rules_tracer, tmp_path, src) == []


def test_tracer_scan_body_by_callsite(tmp_path):
    src = """\
        import jax

        def body(carry, x):
            print("host effect in scan body")
            return carry, x

        def outer(xs):
            return jax.lax.scan(body, 0, xs)
    """
    (finding,) = _run(rules_tracer, tmp_path, src)
    assert finding.line == 4


def test_tracer_untraced_function_is_free(tmp_path):
    src = """\
        def host_loop(x):
            print("fine: nobody traces this")
            if x > 0:
                return 1
    """
    assert _run(rules_tracer, tmp_path, src) == []


def test_tracer_suppressed(tmp_path):
    src = """\
        import jax

        @jax.jit
        def step(x):
            # pbox-lint: ignore[jax-tracer-safety] fixture reason
            print("deliberate trace-time banner")
            return x
    """
    assert _run(rules_tracer, tmp_path, src) == []


# --------------------------------------------------------------------------- #
# suppression plumbing
# --------------------------------------------------------------------------- #
def test_suppression_only_masks_the_named_rule(tmp_path):
    path = tmp_path / "s.py"
    path.write_text(
        "import time\n"
        "# pbox-lint: ignore[swallowed-exception] wrong rule named\n"
        "deadline = time.time() + 10.0\n"
    )
    ctx = Context(paths=[str(path)], repo=str(tmp_path))
    findings = rules_clock.run(ctx)
    assert findings and not ctx.by_rel[findings[0].file].suppressed(
        findings[0])


def test_suppression_multiple_rules_one_marker(tmp_path):
    sf = SourceFile.__new__(SourceFile)  # placement parsing only
    path = tmp_path / "m.py"
    path.write_text(
        "x = 1  # pbox-lint: ignore[rule-a, rule-b] both at once\n")
    sf = SourceFile(str(path), repo=str(tmp_path))
    assert sf.suppressions[1] == {"rule-a", "rule-b"}


# --------------------------------------------------------------------------- #
# baseline hygiene
# --------------------------------------------------------------------------- #
def _entry(rule="clock-misuse", file="a.py", snippet="x = 1", reason="r"):
    return {"rule": rule, "file": file, "snippet": snippet, "reason": reason}


def test_baseline_matches_by_snippet_not_line(tmp_path):
    src = """\
        import time


        deadline = time.time() + 10.0
    """
    ctx = _ctx(tmp_path, src)
    (finding,) = rules_clock.run(ctx)
    entries = [_entry(file="fixture.py",
                      snippet="deadline = time.time() + 10.0")]
    kept, baselined, stale = baseline_mod.apply([finding], entries)
    assert kept == [] and stale == [] and len(baselined) == 1


def test_stale_baseline_entry_is_an_error(tmp_path):
    entries = [_entry(snippet="code that no longer exists")]
    kept, baselined, stale = baseline_mod.apply([], entries)
    assert baselined == [] and len(stale) == 1
    assert stale[0].rule == "stale-baseline"


def test_baseline_schema_rejects_bad_entries(tmp_path):
    for bad in (
        {"rule": "r", "file": "f"},                      # missing keys
        {**_entry(), "extra": 1},                        # unknown key
        {**_entry(), "reason": "   "},                   # empty reason
    ):
        p = tmp_path / "b.json"
        p.write_text(json.dumps([bad]))
        with pytest.raises(baseline_mod.BaselineError):
            baseline_mod.load(str(p))


def test_baseline_must_be_sorted(tmp_path):
    p = tmp_path / "b.json"
    p.write_text(json.dumps([
        _entry(rule="z-rule"), _entry(rule="a-rule"),
    ]))
    with pytest.raises(baseline_mod.BaselineError):
        baseline_mod.load(str(p))


def test_checked_in_baseline_is_valid():
    # the repo's own baseline must always load (sorted, schema-clean)
    baseline_mod.load()


# --------------------------------------------------------------------------- #
# CLI + the tier-1 gate
# --------------------------------------------------------------------------- #
def test_tier1_gate_repo_is_clean():
    """THE gate: zero non-baselined findings over paddlebox_tpu/, tools/
    and bench.py.  A new finding means fix it, suppress it with a
    reason, or (legacy only) baseline it — not ignore it."""
    r = subprocess.run(
        [sys.executable, CLI, "--all"],
        capture_output=True, text=True, timeout=120,
    )
    assert r.returncode == 0, f"pbox-lint found:\n{r.stdout}\n{r.stderr}"


def test_cli_json_shape():
    r = subprocess.run(
        [sys.executable, CLI, "--all", "--json"],
        capture_output=True, text=True, timeout=120,
    )
    assert r.returncode == 0, r.stderr
    assert json.loads(r.stdout) == []


def test_cli_names_rule_file_line_on_regression(tmp_path):
    """The acceptance scenario: a seeded clock regression exits non-zero
    and the output names the rule, file and line."""
    bad = tmp_path / "regress.py"
    bad.write_text("import time\ndeadline = time.time() + 10.0\n")
    r = subprocess.run(
        [sys.executable, CLI, str(bad)],
        capture_output=True, text=True, timeout=60,
    )
    assert r.returncode == 1
    assert "clock-misuse" in r.stdout
    assert "regress.py:2" in r.stdout


def test_cli_rules_filter_and_unknown_rule(tmp_path):
    bad = tmp_path / "regress.py"
    bad.write_text(
        "import time\n"
        "deadline = time.time() + 10.0\n"
        "try:\n"
        "    pass\n"
        "except Exception:\n"
        "    pass\n"
    )
    r = subprocess.run(
        [sys.executable, CLI, str(bad), "--rules", "swallowed-exception"],
        capture_output=True, text=True, timeout=60,
    )
    assert r.returncode == 1
    assert "swallowed-exception" in r.stdout
    assert "clock-misuse" not in r.stdout
    r = subprocess.run(
        [sys.executable, CLI, "--rules", "no-such-rule"],
        capture_output=True, text=True, timeout=60,
    )
    assert r.returncode == 2


def test_cli_list_rules():
    r = subprocess.run(
        [sys.executable, CLI, "--list-rules"],
        capture_output=True, text=True, timeout=60,
    )
    assert r.returncode == 0
    for rule in ("lock-order", "lock-held-blocking", "thread-shared-state",
                 "swallowed-exception", "clock-misuse", "jax-tracer-safety",
                 "metric-name-drift", "fault-site-drift", "env-flag-drift",
                 "span-name-drift"):
        assert rule in r.stdout


def test_cli_changed_mode_clean():
    """--changed vs HEAD on a clean-or-dirty tree must not crash and must
    honor the touched-lines filter (findings subset of a full run)."""
    r = subprocess.run(
        [sys.executable, CLI, "--changed", "HEAD"],
        capture_output=True, text=True, timeout=120,
    )
    assert r.returncode in (0, 1), r.stderr
    assert "changed vs HEAD" in r.stderr


# --------------------------------------------------------------------------- #
# call graph (callgraph.py)
# --------------------------------------------------------------------------- #
from pbox_analyze import rules_protocol, rules_resources  # noqa: E402
from pbox_analyze.callgraph import CallGraph  # noqa: E402
from pbox_analyze.cli import parse_changed_diff  # noqa: E402


def _graph(tmp_path, files: dict) -> CallGraph:
    for name, src in files.items():
        p = tmp_path / name
        p.parent.mkdir(parents=True, exist_ok=True)
        p.write_text(textwrap.dedent(src))
    ctx = Context(
        paths=[str(tmp_path / n) for n in files], repo=str(tmp_path))
    return CallGraph.of(ctx)


def test_callgraph_resolves_cross_module_calls(tmp_path):
    cg = _graph(tmp_path, {
        "util.py": "def helper():\n    pass\n",
        "main.py": (
            "from util import helper\n"
            "def drive():\n"
            "    helper()\n"
        ),
    })
    assert "util:helper" in cg.callees("main:drive")


def test_callgraph_self_and_attr_dispatch(tmp_path):
    cg = _graph(tmp_path, {"m.py": """\
        class Store:
            def merge(self):
                pass

        class Table:
            def __init__(self):
                self._store = Store()

            def flush(self):
                self._store.merge()

            def state_dict(self):
                self.flush()
    """})
    assert "m:Table.flush" in cg.callees("m:Table.state_dict")
    assert "m:Store.merge" in cg.callees("m:Table.flush")
    assert "m:Store.merge" in cg.transitive_callees("m:Table.state_dict")


def test_callgraph_property_read_is_a_call(tmp_path):
    cg = _graph(tmp_path, {"m.py": """\
        class T:
            @property
            def n(self):
                self.flush()
                return 0

            def flush(self):
                pass

            def shrink(self):
                if self.n == 0:
                    return 0
    """})
    assert "m:T.n" in cg.callees("m:T.shrink")
    assert "m:T.flush" in cg.transitive_callees("m:T.shrink")


def test_callgraph_thread_edges_are_kinded(tmp_path):
    cg = _graph(tmp_path, {"m.py": """\
        import threading

        class W:
            def start(self):
                self._t = threading.Thread(target=self._run, daemon=True)
                self._t.start()

            def _run(self):
                pass
    """})
    kinds = {(e.callee, e.kind) for e in cg.edges["m:W.start"]}
    assert ("m:W._run", "thread") in kinds
    # thread edges are excluded from the synchronous-call closure
    assert "m:W._run" not in cg.transitive_callees("m:W.start")


# --------------------------------------------------------------------------- #
# typestate protocols (rules_protocol.py + protocols.py)
# --------------------------------------------------------------------------- #
def test_protocol_sparse_pass_double_begin(tmp_path):
    src = """\
        def drive(conf, k):
            table = SparseTable(conf)
            table.begin_pass(k)
            table.begin_pass(k)
            table.end_pass()
    """
    findings = _run(rules_protocol, tmp_path, src)
    assert [f.rule for f in findings] == ["protocol-sparse-pass"]
    assert findings[0].line == 4


def test_protocol_sparse_pass_loop_without_end(tmp_path):
    # second loop iteration re-begins an unclosed pass
    src = """\
        def drive(conf, passes):
            table = SparseTable(conf)
            for k in passes:
                table.begin_pass(k)
                train(table)
    """
    findings = _run(rules_protocol, tmp_path, src)
    assert any(f.rule == "protocol-sparse-pass" and f.line == 4
               for f in findings)


def test_protocol_sparse_pass_good_loop(tmp_path):
    src = """\
        def drive(conf, passes):
            table = SparseTable(conf)
            for k in passes:
                table.begin_pass(k)
                table.end_pass()
            state = table.state_dict()
            return state
    """
    assert _run(rules_protocol, tmp_path, src) == []


def test_protocol_sparse_pass_checkpoint_inside_pass(tmp_path):
    src = """\
        def drive(conf, k):
            table = SparseTable(conf)
            table.begin_pass(k)
            snap = table.state_dict()
            table.end_pass()
            return snap
    """
    findings = _run(rules_protocol, tmp_path, src)
    assert any("state_dict" in f.message for f in findings)


def test_protocol_sparse_pass_interprocedural_summary(tmp_path):
    # the helper ends the pass — the call graph summary must see it
    good = """\
        def finish(t):
            t.end_pass()

        def drive(conf, k):
            table = SparseTable(conf)
            table.begin_pass(k)
            finish(table)
    """
    assert _run(rules_protocol, tmp_path, good) == []
    bad = good.replace("t.end_pass()", "pass")
    findings = _run(rules_protocol, tmp_path, bad)
    assert any(f.rule == "protocol-sparse-pass" for f in findings)


def test_protocol_stream_close_on_running(tmp_path):
    src = """\
        def drive(lines):
            source = IterableSource(lines)
            source.start()
            source.close()
    """
    findings = _run(rules_protocol, tmp_path, src)
    assert [f.rule for f in findings] == ["protocol-stream-lifecycle"]
    assert findings[0].line == 4


def test_protocol_stream_two_phase_good(tmp_path):
    src = """\
        def drive(lines):
            source = IterableSource(lines)
            source.start()
            source.stop()
            source.close()
    """
    assert _run(rules_protocol, tmp_path, src) == []


def test_protocol_admission_release_every_path(tmp_path):
    src = """\
        def score(server, body):
            server.gate.admit(1.0)
            return run(body)
    """
    findings = _run(rules_protocol, tmp_path, src)
    assert [f.rule for f in findings] == ["protocol-admission-ticket"]
    assert "held" in findings[0].message


def test_protocol_admission_release_not_finally_guarded(tmp_path):
    src = """\
        def score(server, body):
            server.gate.admit(1.0)
            out = run(body)
            server.gate.release(0.1)
            return out
    """
    findings = _run(rules_protocol, tmp_path, src)
    assert any("finally" in f.message for f in findings)


def test_protocol_admission_try_finally_good(tmp_path):
    src = """\
        def score(server, body):
            server.gate.admit(1.0)
            try:
                return run(body)
            finally:
                server.gate.release(0.1)
    """
    assert _run(rules_protocol, tmp_path, src) == []


def test_protocol_admission_shed_handler_is_not_a_leak(tmp_path):
    # admit() raising means NO slot was taken: the except path must not
    # be reported as holding a ticket
    src = """\
        def score(server, body):
            try:
                server.gate.admit(1.0)
            except ShedRequest:
                return None
            try:
                return run(body)
            finally:
                server.gate.release(0.1)
    """
    assert _run(rules_protocol, tmp_path, src) == []


def test_protocol_publish_order_donefile_last(tmp_path):
    bad = """\
        class P:
            def publish(self, table, local):
                self._append_donefile(entry)
                write_manifest(local, "manifest.json")
                self._upload(local, "x", site="s")
                table.clear_delta()
    """
    findings = _run(rules_protocol, tmp_path, bad)
    assert any(f.rule == "protocol-publish-order" and f.line == 3
               for f in findings)

    good = """\
        class P:
            def publish(self, table, local):
                write_manifest(local, "manifest.json")
                self._upload(local, "x", site="s")
                self._append_donefile(entry)
                table.clear_delta()
    """
    assert _run(rules_protocol, tmp_path, good) == []


def test_protocol_publish_order_clear_before_visible(tmp_path):
    src = """\
        class P:
            def publish(self, table, local):
                write_manifest(local, "manifest.json")
                self._upload(local, "x", site="s")
                table.clear_delta()
                self._append_donefile(entry)
    """
    findings = _run(rules_protocol, tmp_path, src)
    assert any("clear_delta" in f.message for f in findings)


def test_protocol_span_pairing(tmp_path):
    bad = """\
        def trace(x):
            s = span("step")
            s.__enter__()
            return x
    """
    findings = _run(rules_protocol, tmp_path, bad)
    assert [f.rule for f in findings] == ["protocol-span-pairing"]

    good = bad.replace("return x",
                       "s.__exit__(None, None, None)\n    return x")
    assert _run(rules_protocol, tmp_path, good) == []

    with_form = """\
        def trace(x):
            with span("step"):
                return x
    """
    assert _run(rules_protocol, tmp_path, with_form) == []


def test_protocol_impl_obligation_fixture(tmp_path):
    # a class NAMED SparseTable whose state_dict forgets the flush
    # barrier trips the obligation; adding it back clears it
    bad = """\
        class SparseTable:
            def flush(self):
                pass

            def state_dict(self):
                return {}
    """
    findings = _run(rules_protocol, tmp_path, bad)
    assert any(f.rule == "protocol-impl-requires"
               and "state_dict" in f.message for f in findings)
    good = bad.replace("return {}", "self.flush()\n        return {}")
    assert not [f for f in _run(rules_protocol, tmp_path, good)
                if "state_dict() must" in f.message]


def test_protocol_segment_writer_read_before_seal(tmp_path):
    bad = """\
        def write(root, keys, vals):
            writer = SegmentWriter(root, 0, 1)
            writer.append(keys, vals)
            blocks = writer.info()
            writer.seal()
            return blocks
    """
    findings = _run(rules_protocol, tmp_path, bad)
    assert any(f.rule == "protocol-segment-lifecycle" and f.line == 4
               for f in findings)

    good = """\
        def write(root, keys, vals):
            writer = SegmentWriter(root, 0, 1)
            writer.append(keys, vals)
            writer.seal()
            return writer.info()
    """
    assert _run(rules_protocol, tmp_path, good) == []


def test_protocol_segment_writer_leaked_open(tmp_path):
    # a scope that neither seals nor aborts leaks an unsynced segment
    bad = """\
        def write(root, keys, vals):
            writer = SegmentWriter(root, 0, 1)
            writer.append(keys, vals)
    """
    findings = _run(rules_protocol, tmp_path, bad)
    assert any(f.rule == "protocol-segment-lifecycle" for f in findings)

    aborted = bad.replace("writer.append(keys, vals)",
                          "writer.append(keys, vals)\n    writer.abort()")
    assert _run(rules_protocol, tmp_path, aborted) == []


def test_protocol_segment_compact_swap_before_commit(tmp_path):
    bad = """\
        class S:
            def compact(self, b):
                staged = self._compact_write(b)
                self._swap_segments(b, [staged], [])
                self._commit_manifest([[staged]])
    """
    findings = _run(rules_protocol, tmp_path, bad)
    assert any(f.rule == "protocol-segment-lifecycle" and f.line == 4
               for f in findings)

    good = """\
        class S:
            def compact(self, b):
                staged = self._compact_write(b)
                self._commit_manifest([[staged]])
                self._swap_segments(b, [staged], [])
    """
    assert _run(rules_protocol, tmp_path, good) == []


def test_protocol_suppressed(tmp_path):
    src = """\
        def drive(conf, k):
            table = SparseTable(conf)
            table.begin_pass(k)
            # pbox-lint: ignore[protocol-sparse-pass] fixture reason
            table.begin_pass(k)
            table.end_pass()
    """
    assert _run(rules_protocol, tmp_path, src) == []


# --------------------------------------------------------------------------- #
# resource lifecycle (rules_resources.py)
# --------------------------------------------------------------------------- #
def test_thread_unjoined_bad(tmp_path):
    src = """\
        import threading

        class W:
            def start(self):
                self._t = threading.Thread(target=self._run)
                self._t.start()

            def _run(self):
                pass
    """
    findings = _run(rules_resources, tmp_path, src)
    assert [f.rule for f in findings] == ["thread-unjoined"]


@pytest.mark.parametrize("fix", [
    # daemonized
    "self._t = threading.Thread(target=self._run, daemon=True)",
    # joined elsewhere in the class (added below)
    None,
])
def test_thread_unjoined_good(tmp_path, fix):
    src = """\
        import threading

        class W:
            def start(self):
                self._t = threading.Thread(target=self._run)
                self._t.start()

            def close(self):
                self._t.join(timeout=5.0)

            def _run(self):
                pass
    """
    if fix:
        src = src.replace(
            "self._t = threading.Thread(target=self._run)", fix)
    assert _run(rules_resources, tmp_path, src) == []


def test_thread_join_through_loop_alias(tmp_path):
    src = """\
        import threading

        class W:
            def start(self):
                self._a = threading.Thread(target=self._run)
                self._b = threading.Thread(target=self._run)

            def close(self):
                for t in (self._a, self._b):
                    t.join(timeout=2.0)

            def _run(self):
                pass
    """
    assert _run(rules_resources, tmp_path, src) == []


def test_executor_shutdown_bad_and_good(tmp_path):
    bad = """\
        from concurrent.futures import ThreadPoolExecutor

        class S:
            def warm(self):
                self._pool = ThreadPoolExecutor(max_workers=2)
    """
    findings = _run(rules_resources, tmp_path, bad)
    assert [f.rule for f in findings] == ["executor-shutdown"]

    good = bad + """\

            def close(self):
                pool, self._pool = self._pool, None
                if pool is not None:
                    pool.shutdown(wait=False)
    """
    assert _run(rules_resources, tmp_path, good) == []


def test_executor_shutdown_lazy_channel_pool_shape(tmp_path):
    """The KvChannel lifecycle shape (ISSUE 15 satellite): a LAZILY built
    peer-read pool (created under an is-None guard inside the hot method)
    must still be flagged when nothing retires it, and the real pattern —
    ``close()`` shutting the pool down and dropping the attribute, wired
    into trainer teardown — must pass clean."""
    bad = """\
        from concurrent.futures import ThreadPoolExecutor

        class Channel:
            def __init__(self, name):
                self.name = name
                self._pool = None

            def allgather(self, peers):
                if self._pool is None:
                    self._pool = ThreadPoolExecutor(max_workers=4)
                return [self._pool.submit(lambda p: p, r) for r in peers]
    """
    findings = _run(rules_resources, tmp_path, bad)
    assert "executor-shutdown" in [f.rule for f in findings]

    good = bad + """\

            def close(self):
                if self._pool is not None:
                    self._pool.shutdown(wait=False)
                    self._pool = None
    """
    assert _run(rules_resources, tmp_path, good) == []


def test_resource_passes_clean_on_host_plane_and_census(tmp_path):
    """Pin the REAL host-plane modules clean under the resource passes:
    KvChannel's lazy pool + close() and the census plane must never
    regress into a leak (the trainer closes the plan channel, the sharded
    table closes its census channel)."""
    import pathlib

    root = pathlib.Path(__file__).resolve().parents[1]
    ctx = Context(
        paths=[str(root / "paddlebox_tpu" / "parallel" / "host_plane.py"),
               str(root / "paddlebox_tpu" / "parallel" / "census.py")],
        repo=str(root),
    )
    findings = [
        f for f in rules_resources.run(ctx)
        if not ctx.by_rel[f.file].suppressed(f)
    ]
    assert findings == [], [str(f) for f in findings]


def test_resource_leak_on_early_return(tmp_path):
    src = """\
        def read(path, skip):
            fh = open(path)
            if skip:
                return None
            data = fh.read()
            fh.close()
            return data
    """
    findings = _run(rules_resources, tmp_path, src)
    assert [f.rule for f in findings] == ["resource-leak"]
    assert findings[0].line == 4

    fixed = src.replace("return None", "fh.close()\n        return None")
    assert _run(rules_resources, tmp_path, fixed) == []

    with_form = """\
        def read(path, skip):
            with open(path) as fh:
                if skip:
                    return None
                return fh.read()
    """
    assert _run(rules_resources, tmp_path, with_form) == []


def test_lock_manual_release_shapes(tmp_path):
    bad = """\
        import threading

        class G:
            def __init__(self):
                self._lock = threading.Lock()

            def work(self):
                self._lock.acquire()
                compute()
                self._lock.release()
    """
    findings = _run(rules_resources, tmp_path, bad)
    assert [f.rule for f in findings] == ["lock-manual-release"]

    good = """\
        import threading

        class G:
            def __init__(self):
                self._lock = threading.Lock()

            def work(self):
                self._lock.acquire()
                try:
                    compute()
                finally:
                    self._lock.release()
    """
    assert _run(rules_resources, tmp_path, good) == []

    trylock = """\
        import threading

        class G:
            def __init__(self):
                self._lock = threading.Lock()

            def work(self):
                if self._lock.acquire(blocking=False):
                    try:
                        compute()
                    finally:
                        self._lock.release()
    """
    assert _run(rules_resources, tmp_path, trylock) == []


# --------------------------------------------------------------------------- #
# interprocedural / cross-class lock analysis
# --------------------------------------------------------------------------- #
def test_lock_order_cross_class_cycle(tmp_path):
    src = """\
        import threading

        class Router:
            def __init__(self, sup: Supervisor):
                self._la = threading.Lock()
                self.sup = sup

            def route(self):
                with self._la:
                    self.sup.poke()

        class Supervisor:
            def __init__(self, router: Router):
                self._lb = threading.Lock()
                self.router = router

            def poke(self):
                with self._lb:
                    pass

            def back(self):
                with self._lb:
                    self.router.route()
    """
    findings = _run(rules_locks, tmp_path, src)
    assert any(f.rule == "lock-order" for f in findings)


def test_lock_held_blocking_through_callee(tmp_path):
    src = """\
        import threading
        import time

        class G:
            def __init__(self):
                self._lock = threading.Lock()

            def slow(self):
                time.sleep(1.0)

            def work(self):
                with self._lock:
                    self.slow()
    """
    findings = _run(rules_locks, tmp_path, src)
    assert any(
        f.rule == "lock-held-blocking" and "slow()" in f.message
        and f.line == 13
        for f in findings
    )


def test_lock_split_helper_wait_on_own_cond_is_legal(tmp_path):
    src = """\
        import threading

        class G:
            def __init__(self):
                self._cv = threading.Condition()

            def _wait_locked(self):
                self._cv.wait(timeout=1.0)

            def take(self):
                with self._cv:
                    self._wait_locked()
    """
    assert _run(rules_locks, tmp_path, src) == []


# --------------------------------------------------------------------------- #
# --changed diff parsing robustness
# --------------------------------------------------------------------------- #
FABRICATED_DIFF = """\
diff --git a/kept.py b/kept.py
index 111..222 100644
--- a/kept.py
+++ b/kept.py
@@ -10,0 +11,2 @@ def f():
+new line
+another
diff --git a/gone.py b/gone.py
deleted file mode 100644
index 333..000
--- a/gone.py
+++ /dev/null
@@ -1,5 +0,0 @@
-removed
diff --git a/old_name.py b/new_name.py
similarity index 90%
rename from old_name.py
rename to new_name.py
--- a/old_name.py
+++ b/new_name.py
@@ -3,0 +4 @@ def g():
+renamed-file line
diff --git a/pure_rename.py b/also_pure.py
similarity index 100%
rename from pure_rename.py
rename to also_pure.py
"""


def test_parse_changed_diff_handles_rename_and_delete():
    touched = parse_changed_diff(FABRICATED_DIFF)
    assert touched["kept.py"] == {11, 12}
    # the deleted file's hunks must not bleed onto the previous file,
    # nor appear under /dev/null
    assert "gone.py" not in touched
    assert not any("dev/null" in k for k in touched)
    # renamed file is tracked under its NEW path
    assert touched["new_name.py"] == {4}
    assert "old_name.py" not in touched
    # a 100%-similarity rename has no hunks and touches nothing
    assert "pure_rename.py" not in touched and "also_pure.py" not in touched


def test_changed_mode_survives_unparsable_file(tmp_path):
    """A mid-edit syntax error must surface as parse-error, not crash."""
    bad = tmp_path / "broken.py"
    bad.write_text("def f(:\n")
    r = subprocess.run(
        [sys.executable, CLI, str(bad)],
        capture_output=True, text=True, timeout=60,
    )
    assert r.returncode == 1
    assert "parse-error" in r.stdout


def test_cli_names_protocol_rule_on_regression(tmp_path):
    bad = tmp_path / "regress.py"
    bad.write_text(
        "def drive(conf, k):\n"
        "    table = SparseTable(conf)\n"
        "    table.begin_pass(k)\n"
        "    table.begin_pass(k)\n"
        "    table.end_pass()\n"
    )
    r = subprocess.run(
        [sys.executable, CLI, str(bad)],
        capture_output=True, text=True, timeout=60,
    )
    assert r.returncode == 1
    assert "protocol-sparse-pass" in r.stdout
    assert "regress.py:4" in r.stdout


def test_new_rules_listed():
    r = subprocess.run(
        [sys.executable, CLI, "--list-rules"],
        capture_output=True, text=True, timeout=60,
    )
    assert r.returncode == 0
    for rule in ("protocol-sparse-pass", "protocol-stream-lifecycle",
                 "protocol-admission-ticket", "protocol-publish-order",
                 "protocol-span-pairing", "protocol-impl-requires",
                 "thread-unjoined", "executor-shutdown", "resource-leak",
                 "lock-manual-release"):
        assert rule in r.stdout


def test_full_run_wall_time_budget():
    """The interprocedural passes must not regress lint latency: a full
    --all run stays under the 5s budget (pre-commit viability).  Best of
    two runs: the budget pins the ANALYZER, not transient machine load
    from the surrounding suite (jax worker threads, page-cache misses) —
    a genuinely slow lint fails both attempts."""
    import time as _time

    best = None
    for _ in range(2):
        t0 = _time.monotonic()
        r = subprocess.run(
            [sys.executable, CLI, "--all"],
            capture_output=True, text=True, timeout=60,
        )
        elapsed = _time.monotonic() - t0
        assert r.returncode == 0, f"repo not clean:\n{r.stdout}"
        best = elapsed if best is None else min(best, elapsed)
        if best <= 5.0:
            break
    assert best <= 5.0, f"pbox-lint --all took {best:.2f}s (> 5s)"


# --------------------------------------------------------------------------- #
# SPMD safety (rules_spmd.py + spmd_catalog.py)
# --------------------------------------------------------------------------- #
from pbox_analyze import rules_spmd  # noqa: E402

#: mirrors sharded_table.begin_pass with the census gather moved inside a
#: rank guard — the seeded-bug shape from the acceptance criteria
SPMD_SEEDED_BUG = """\
    import jax


    class ShardedTable:
        def begin_pass(self, pass_keys):
            if jax.process_index() == 0:
                self.chan.allgather(pass_keys)
            self.live = True
"""


def test_spmd_rank_divergence_bad(tmp_path):
    findings = _run(rules_spmd, tmp_path, SPMD_SEEDED_BUG)
    rules = {f.rule for f in findings}
    assert "spmd-rank-divergence" in rules
    assert "spmd-collective-sequence" in rules
    div = [f for f in findings if f.rule == "spmd-rank-divergence"]
    assert div[0].line == 7  # the allgather call
    seq = [f for f in findings if f.rule == "spmd-collective-sequence"]
    assert seq[0].line == 6  # the rank-conditional branch


def test_spmd_rank_divergence_early_return(tmp_path):
    src = """\
        import jax

        def export(table, x):
            if jax.process_index() != 0:
                return None
            return host_allgather(x)
    """
    findings = _run(rules_spmd, tmp_path, src)
    assert any(f.rule == "spmd-rank-divergence" and f.line == 6
               for f in findings)


def test_spmd_rank_divergence_through_callee(tmp_path):
    src = """\
        import jax

        def helper(chan, x):
            chan.allgather(x)

        def drive(chan, x):
            if jax.process_index() == 0:
                helper(chan, x)
    """
    findings = _run(rules_spmd, tmp_path, src)
    assert any(f.rule == "spmd-rank-divergence" and "helper" in f.message
               for f in findings)


def test_spmd_rank_divergence_env_seed(tmp_path):
    src = """\
        import os

        def drive(chan, x):
            if os.environ.get("PBOX_PROCESS_ID", "0") == "0":
                chan.allgather(x)
    """
    findings = _run(rules_spmd, tmp_path, src)
    assert any(f.rule == "spmd-rank-divergence" for f in findings)


def test_spmd_rank_guarded_side_effects_are_legal(tmp_path):
    # the donefile-write / rank-0 log-line / rank-label family: rank used
    # for non-collective work produces ZERO findings, no suppressions
    src = """\
        import jax

        def publish(entry, path):
            if jax.process_index() == 0:
                with open(path, "w") as fh:
                    fh.write(entry)

        def banner(merged):
            if jax.process_index() == 0:
                print(merged, flush=True)

        def dump_suffix(multiproc):
            return f"-r{jax.process_index()}" if multiproc else ""
    """
    assert _run(rules_spmd, tmp_path, src) == []


def test_spmd_all_paths_raise_branch_is_legal(tmp_path):
    src = """\
        import jax

        def validate(mesh, x):
            pid = jax.process_index()
            if pid >= mesh:
                raise RuntimeError("bad layout")
            return host_allgather(x)
    """
    assert _run(rules_spmd, tmp_path, src) == []


def test_spmd_uniform_world_gate_is_legal(tmp_path):
    # process_count is the same value on every rank — the standard
    # `if is_multiprocess():` gate must never fire the rule
    src = """\
        import jax

        def gather(x):
            if jax.process_count() > 1:
                return host_allgather(x)
            return x
    """
    assert _run(rules_spmd, tmp_path, src) == []


def test_spmd_watchdog_peer_loop_shape_is_legal(tmp_path):
    # watchdog._check_peers: `if rank == self.rank: continue` guards only
    # non-collective abort bookkeeping (watchdog.py:488 acceptance shape)
    src = """\
        class W:
            def check_peers(self, now):
                for rank in range(self.world):
                    if rank == self.rank:
                        continue
                    self.observe(rank, now)

            def observe(self, rank, now):
                self.seen[rank] = now
    """
    assert _run(rules_spmd, tmp_path, src) == []


def test_spmd_rank_divergence_suppressed(tmp_path):
    src = SPMD_SEEDED_BUG.replace(
        "self.chan.allgather(pass_keys)",
        "# pbox-lint: ignore[spmd-rank-divergence, spmd-collective-sequence]"
        " fixture reason\n"
        "            self.chan.allgather(pass_keys)",
    )
    # the sequence finding lands on the `if` line; suppress it there too
    src = src.replace(
        "if jax.process_index() == 0:",
        "if jax.process_index() == 0:"
        "  # pbox-lint: ignore[spmd-collective-sequence] fixture reason",
    )
    assert _run(rules_spmd, tmp_path, src) == []


def test_spmd_sequence_order_swap(tmp_path):
    # both arms gather on both channels but in opposite order: sequence
    # divergence WITHOUT rank-divergence (nothing is skipped)
    src = """\
        import jax

        def plan(a, b, x):
            rank = jax.process_index()
            if rank % 2 == 0:
                a.allgather(x)
                b.allgather(x)
            else:
                b.allgather(x)
                a.allgather(x)
    """
    findings = _run(rules_spmd, tmp_path, src)
    assert [f.rule for f in findings] == ["spmd-collective-sequence"]
    assert findings[0].line == 5


def test_spmd_sequence_loop_continue_skip(tmp_path):
    src = """\
        def drain(chan, items, rank):
            for it in items:
                if it.owner == rank:
                    continue
                chan.allgather(it)
    """
    findings = _run(rules_spmd, tmp_path, src)
    assert any(f.rule == "spmd-collective-sequence" for f in findings)
    assert any(f.rule == "spmd-rank-divergence" and f.line == 5
               for f in findings)


def test_spmd_sequence_same_both_arms_is_legal(tmp_path):
    src = """\
        import jax

        def plan(chan, x, rank):
            if rank == 0:
                y = chan.allgather(x)
            else:
                y = chan.allgather(x)
            return y
    """
    assert _run(rules_spmd, tmp_path, src) == []


def test_spmd_collective_on_thread_bad(tmp_path):
    src = """\
        import threading

        class Stager:
            def start(self):
                self._t = threading.Thread(target=self._stage, daemon=True)
                self._t.start()

            def _stage(self):
                host_allgather_varlen(self.keys)
    """
    findings = _run(rules_spmd, tmp_path, src)
    assert [f.rule for f in findings] == ["spmd-collective-on-thread"]
    assert findings[0].line == 5  # the Thread(...) edge
    assert "host_allgather_varlen" in findings[0].message


def test_spmd_collective_on_executor_submit(tmp_path):
    src = """\
        class Stager:
            def kick(self):
                self._pool.submit(self._job)

            def _job(self):
                host_allgather(self.keys)
    """
    findings = _run(rules_spmd, tmp_path, src)
    assert [f.rule for f in findings] == ["spmd-collective-on-thread"]


def test_spmd_kvchannel_on_thread_is_legal(tmp_path):
    # KvChannel.allgather exists precisely to run off-thread (the
    # feed-producer plans concurrently with the device step)
    src = """\
        import threading

        class Producer:
            def start(self):
                self._t = threading.Thread(target=self._plan, daemon=True)
                self._t.start()

            def _plan(self):
                self.chan.allgather(self.keys)
    """
    assert _run(rules_spmd, tmp_path, src) == []


def test_spmd_collective_on_thread_suppressed(tmp_path):
    src = """\
        import threading

        class Stager:
            def start(self):
                # pbox-lint: ignore[spmd-collective-on-thread] fixture
                self._t = threading.Thread(target=self._stage, daemon=True)
                self._t.start()

            def _stage(self):
                host_allgather_varlen(self.keys)
    """
    assert _run(rules_spmd, tmp_path, src) == []


def test_spmd_mesh_axis_unbound(tmp_path):
    src = """\
        import jax
        from jax.experimental.shard_map import shard_map

        def body(x):
            return jax.lax.psum(x, "seq")

        def outer(x):
            sm = shard_map(body, in_specs=("s",), out_specs=None,
                           axis_names={"expert"})
            return sm(x)
    """
    findings = _run(rules_spmd, tmp_path, src)
    assert [f.rule for f in findings] == ["spmd-mesh-axis"]
    assert findings[0].line == 5
    assert "'seq'" in findings[0].message


def test_spmd_mesh_axis_bound_through_constant_and_default(tmp_path):
    # EXPERT_AXIS-style module constant flows through the param default
    # and the axis_names set literal — the composed-mesh idiom
    src = """\
        import jax
        from jax.experimental.shard_map import shard_map

        EXPERT_AXIS = "expert"

        def mix(h, axis_name=EXPERT_AXIS):
            return jax.lax.psum(h, axis_name)

        def body(h):
            return mix(h)

        def outer(h):
            sm = shard_map(body, in_specs=("s",), out_specs=None,
                           axis_names={EXPERT_AXIS})
            return sm(h)
    """
    assert _run(rules_spmd, tmp_path, src) == []


def test_spmd_mesh_axis_unknown_mesh_is_conservative(tmp_path):
    src = """\
        import jax
        from jax.experimental.shard_map import shard_map

        def body(x):
            return jax.lax.psum(x, "anything")

        def outer(self, x):
            sm = shard_map(body, mesh=self.mesh, in_specs=("s",),
                           out_specs=None)
            return sm(x)
    """
    assert _run(rules_spmd, tmp_path, src) == []


def test_spmd_mesh_axis_in_specs_arity(tmp_path):
    src = """\
        from jax.experimental.shard_map import shard_map

        def body(a, b):
            return a + b

        def outer(mesh, a, b):
            sm = shard_map(body, mesh=mesh, in_specs=("x", "y", "z"),
                           out_specs=None)
            return sm(a, b)
    """
    findings = _run(rules_spmd, tmp_path, src)
    assert [f.rule for f in findings] == ["spmd-mesh-axis"]
    assert "3 entr" in findings[0].message

    good = src.replace('("x", "y", "z")', '("x", "y")')
    assert _run(rules_spmd, tmp_path, good) == []


def test_spmd_mesh_axis_suppressed(tmp_path):
    src = """\
        import jax
        from jax.experimental.shard_map import shard_map

        def body(x):
            # pbox-lint: ignore[spmd-mesh-axis] fixture reason
            return jax.lax.psum(x, "seq")

        def outer(x):
            sm = shard_map(body, in_specs=("s",), out_specs=None,
                           axis_names={"expert"})
            return sm(x)
    """
    assert _run(rules_spmd, tmp_path, src) == []


def test_cli_names_spmd_rules_on_seeded_regression(tmp_path):
    """Acceptance scenario: the seeded begin_pass bug is flagged by BOTH
    spmd-rank-divergence and spmd-collective-sequence, naming file+line."""
    bad = tmp_path / "regress.py"
    bad.write_text(textwrap.dedent(SPMD_SEEDED_BUG))
    r = subprocess.run(
        [sys.executable, CLI, str(bad), "--rules", "spmd-*"],
        capture_output=True, text=True, timeout=60,
    )
    assert r.returncode == 1
    assert "spmd-rank-divergence" in r.stdout
    assert "spmd-collective-sequence" in r.stdout
    assert "regress.py:7" in r.stdout  # the moved allgather
    assert "regress.py:6" in r.stdout  # the rank-conditional branch


def test_cli_rules_glob_selects_spmd_family(tmp_path):
    bad = tmp_path / "regress.py"
    bad.write_text(
        "import time\n"
        "deadline = time.time() + 10.0\n"
    )
    # the glob selects only the spmd family: the clock regression is NOT
    # reported under --rules spmd-*
    r = subprocess.run(
        [sys.executable, CLI, str(bad), "--rules", "spmd-*"],
        capture_output=True, text=True, timeout=60,
    )
    assert r.returncode == 0, r.stdout
    r = subprocess.run(
        [sys.executable, CLI, "--rules", "nope-*"],
        capture_output=True, text=True, timeout=60,
    )
    assert r.returncode == 2


def test_spmd_rules_listed():
    r = subprocess.run(
        [sys.executable, CLI, "--list-rules"],
        capture_output=True, text=True, timeout=60,
    )
    assert r.returncode == 0
    for rule in ("spmd-rank-divergence", "spmd-collective-sequence",
                 "spmd-collective-on-thread", "spmd-mesh-axis"):
        assert rule in r.stdout


def test_spmd_repo_is_clean_without_suppressions():
    """The acceptance bar: the four SPMD rules over the default roots
    produce zero findings AND zero spmd suppressions were needed at the
    existing rank-guarded non-collective sites (donefile writes, rank-0
    log lines, watchdog.py peer loop)."""
    r = subprocess.run(
        [sys.executable, CLI, "--all", "--rules", "spmd-*", "--json"],
        capture_output=True, text=True, timeout=120,
    )
    assert r.returncode == 0, r.stdout
    assert json.loads(r.stdout) == []
    # no inline spmd ignores anywhere in the analyzed roots
    for root in ("paddlebox_tpu", "tools"):
        for d, _, fs in os.walk(os.path.join(REPO, root)):
            for f in fs:
                if not f.endswith(".py"):
                    continue
                with open(os.path.join(d, f), encoding="utf-8") as fh:
                    assert "ignore[spmd" not in fh.read(), (
                        f"unexpected spmd suppression in {d}/{f}"
                    )


def test_wrapper_cli_contract_survives_context_fields():
    """The five thin tools/check_*.py wrappers monkeypatch-import the
    framework: their module APIs and the Context surface they ride on
    must survive new fields (here: Context.caches for the SPMD memos)."""
    from pbox_analyze.core import Context as _Ctx

    ctx = _Ctx(paths=[CLI])
    assert hasattr(ctx, "caches") and isinstance(ctx.caches, dict)
    assert hasattr(ctx, "files") and hasattr(ctx, "by_rel")

    import check_env_flags
    import check_fault_sites
    import check_metric_names
    import check_publish_dir
    import check_span_names

    assert callable(check_metric_names.scan_sources)
    assert callable(check_metric_names.catalog_patterns)
    assert isinstance(check_metric_names.scan_sources(), dict)
    assert callable(check_span_names.scan_sources)
    assert callable(check_env_flags.main)
    assert callable(check_fault_sites.main)
    assert callable(check_publish_dir.main)


# --------------------------------------------------------------------------- #
# numerics & recompilation safety (rules_numerics.py + num_catalog.py)
# --------------------------------------------------------------------------- #
from pbox_analyze import rules_numerics  # noqa: E402


# -- num-dtype-flow ---------------------------------------------------------- #
BAD_DEQUANT = """\
    import numpy as np
    from paddlebox_tpu.inference.quant import quantize_rows

    def publish(values):
        head, codes, scales = quantize_rows(values, 2, "int8")
        rows = codes.astype(np.float32) * scales[:, None]
        return rows
"""


def test_dtype_flow_bad_dequant_outside_fused_gather(tmp_path):
    (finding,) = _run(rules_numerics, tmp_path, BAD_DEQUANT)
    assert finding.rule == "num-dtype-flow"
    assert finding.line == 6
    assert "fused gather" in finding.message


def test_dtype_flow_good_codes_stay_quantized(tmp_path):
    src = """\
        import numpy as np
        from paddlebox_tpu.inference.quant import quantize_rows

        def publish(values):
            head, codes, scales = quantize_rows(values, 2, "int8")
            np.save("head.npy", head)
            np.save("codes.npy", codes)
            np.save("scales.npy", scales)
    """
    assert _run(rules_numerics, tmp_path, src) == []


def test_dtype_flow_bad_merge_mixing(tmp_path):
    src = """\
        import numpy as np

        def merge(values, embedx_q):
            head = values.astype(np.float32)
            return np.concatenate([head, embedx_q], axis=1)
    """
    (finding,) = _run(rules_numerics, tmp_path, src)
    assert finding.rule == "num-dtype-flow"
    assert "EmbeddingDtypeMismatch" in finding.message


def test_dtype_flow_good_merge_same_dtype(tmp_path):
    src = """\
        import numpy as np

        def merge(a, b):
            x = a.astype(np.float32)
            y = b.astype(np.float32)
            return np.concatenate([x, y], axis=1)
    """
    assert _run(rules_numerics, tmp_path, src) == []


def test_dtype_flow_suppressed(tmp_path):
    src = BAD_DEQUANT.replace(
        "        rows = codes.astype(np.float32) * scales[:, None]",
        "        # pbox-lint: ignore[num-dtype-flow] fixture reason\n"
        "        rows = codes.astype(np.float32) * scales[:, None]",
    )
    assert _run(rules_numerics, tmp_path, src) == []


# -- num-key-width ----------------------------------------------------------- #
BAD_KEY_CAST = """\
    import numpy as np

    def bucketize(keys):
        return keys.astype(np.float32) / 7.0
"""


def test_key_width_bad_float_cast(tmp_path):
    findings = _run(rules_numerics, tmp_path, BAD_KEY_CAST)
    assert findings and all(f.rule == "num-key-width" for f in findings)
    assert findings[0].line == 4
    assert "2^53" in findings[0].message


@pytest.mark.parametrize("expr,needle", [
    ("np.int64(batch.keys)", "sign"),
    ("keys * 0.5", "float arithmetic"),
    ("jnp.asarray(keys)", "uint32"),
    ("float(keys[0])", "2^53"),
])
def test_key_width_bad_sink_family(tmp_path, expr, needle):
    src = f"""\
        import numpy as np
        import jax.numpy as jnp

        def f(keys, batch):
            return {expr}
    """
    findings = _run(rules_numerics, tmp_path, src)
    assert findings, expr
    assert findings[0].rule == "num-key-width"
    assert needle in findings[0].message


def test_key_width_good_split_convention(tmp_path):
    """The split itself — shift/mask with np.uint64 then narrow — is the
    sanctioned uint64->uint32 path (ops/pallas_sparse.py split_u64)."""
    src = """\
        import numpy as np

        def split_u64(keys):
            keys = np.asarray(keys, dtype=np.uint64)
            out = np.empty((keys.shape[0], 2), np.uint32)
            out[:, 0] = (keys >> np.uint64(32)).astype(np.uint32)
            out[:, 1] = (keys & np.uint64(0xFFFFFFFF)).astype(np.uint32)
            return out
    """
    assert _run(rules_numerics, tmp_path, src) == []


def test_key_width_good_comparisons_and_searchsorted(tmp_path):
    src = """\
        import numpy as np

        def resolve(keys, batch_keys):
            pos = np.searchsorted(keys, batch_keys)
            found = keys[np.minimum(pos, keys.shape[0] - 1)] == batch_keys
            return pos, found
    """
    assert _run(rules_numerics, tmp_path, src) == []


def test_key_width_bad_32bit_recombine(tmp_path):
    src = """\
        from paddlebox_tpu.ops.pallas_sparse import split_u64

        def roundtrip(keys):
            pairs = split_u64(keys)
            hi = pairs[:, 0]
            lo = pairs[:, 1]
            return (hi << 32) | lo
    """
    (finding,) = _run(rules_numerics, tmp_path, src)
    assert finding.rule == "num-key-width"
    assert "np.uint64(hi)" in finding.message


def test_key_width_suppressed(tmp_path):
    src = BAD_KEY_CAST.replace(
        "    return keys.astype(np.float32) / 7.0",
        "    # pbox-lint: ignore[num-key-width] fixture reason\n"
        "    return keys.astype(np.float32) / 7.0",
    )
    assert _run(rules_numerics, tmp_path, src) == []


# -- jit-retrace-hazard ------------------------------------------------------ #
BAD_FRESH_WRAPPER = """\
    import jax

    def merge(tree):
        return jax.jit(lambda t: t)(tree)
"""


def test_retrace_bad_fresh_wrapper_per_call(tmp_path):
    """The merge_device_axis bug this PR fixed: jit built and invoked in
    one expression retraces on every call."""
    (finding,) = _run(rules_numerics, tmp_path, BAD_FRESH_WRAPPER)
    assert finding.rule == "jit-retrace-hazard"
    assert finding.line == 4


def test_retrace_bad_wrap_in_loop(tmp_path):
    src = """\
        import jax

        def f(fns, x):
            for fn in fns:
                g = jax.jit(fn)
                x = g(x)
            return x
    """
    (finding,) = _run(rules_numerics, tmp_path, src)
    assert finding.rule == "jit-retrace-hazard"
    assert "loop" in finding.message


def test_retrace_bad_shape_varying_arg(tmp_path):
    src = """\
        import jax
        import numpy as np

        step = jax.jit(lambda x: x)

        def f(batch):
            return step(np.unique(batch))
    """
    (finding,) = _run(rules_numerics, tmp_path, src)
    assert finding.rule == "jit-retrace-hazard"
    assert "padded-bucket" in finding.message


def test_retrace_bad_python_scalar_arg(tmp_path):
    src = """\
        import jax

        step = jax.jit(lambda x, n: x)

        def f(x, ys):
            return step(x, len(ys))
    """
    (finding,) = _run(rules_numerics, tmp_path, src)
    assert finding.rule == "jit-retrace-hazard"
    assert "scalar" in finding.message


def test_retrace_bad_closure_captured_device_array(tmp_path):
    src = """\
        import jax
        import jax.numpy as jnp

        def build(w):
            scale = jnp.asarray(w)

            def body(x):
                return x * scale

            return jax.jit(body)
    """
    (finding,) = _run(rules_numerics, tmp_path, src)
    assert finding.rule == "jit-retrace-hazard"
    assert "scale" in finding.message and "constant" in finding.message


def test_retrace_good_cached_factory_and_padded_args(tmp_path):
    """The repo's own discipline: build the wrapper once through a
    factory, pad feeds to a fixed buffer before dispatch."""
    src = """\
        import jax
        import numpy as np

        class T:
            def _build(self):
                return jax.jit(lambda x: x)

            def go(self, feeds):
                self._fn = self._build()
                buf = np.zeros(1024)
                for f in feeds:
                    buf[: f.size] = f
                    self._fn(buf)
    """
    assert _run(rules_numerics, tmp_path, src) == []


def test_retrace_suppressed(tmp_path):
    src = BAD_FRESH_WRAPPER.replace(
        "    return jax.jit(lambda t: t)(tree)",
        "    # pbox-lint: ignore[jit-retrace-hazard] fixture reason\n"
        "    return jax.jit(lambda t: t)(tree)",
    )
    assert _run(rules_numerics, tmp_path, src) == []


# -- host-sync-in-hot-loop --------------------------------------------------- #
BAD_HOT_SYNC = """\
    import jax

    step = jax.jit(lambda x: x)

    def train(feeds):
        for dev in feeds:
            loss = step(dev)
            x = jax.device_get(loss)
        return x
"""


def test_host_sync_bad_device_get_in_hot_loop(tmp_path):
    (finding,) = _run(rules_numerics, tmp_path, BAD_HOT_SYNC)
    assert finding.rule == "host-sync-in-hot-loop"
    assert finding.line == 8


def test_host_sync_bad_float_in_batches_loop(tmp_path):
    src = """\
        import jax

        step = jax.jit(lambda x: x)

        def train(ds):
            out = []
            for batch in ds.batches():
                loss = step(batch)
                out.append(float(loss))
            return out
    """
    (finding,) = _run(rules_numerics, tmp_path, src)
    assert finding.rule == "host-sync-in-hot-loop"


def test_host_sync_bad_through_callee_summary(tmp_path):
    """The 133-candidate-site reality: the sync hides one call down.
    The callee summary carries it back to the hot-loop call site."""
    src = """\
        import jax
        import numpy as np

        step = jax.jit(lambda x: x)

        def readback(v):
            return np.asarray(v)

        def train(ds):
            for batch in ds.batches():
                loss = step(batch)
                r = readback(loss)
            return r
    """
    (finding,) = _run(rules_numerics, tmp_path, src)
    assert finding.rule == "host-sync-in-hot-loop"
    assert "readback" in finding.message


def test_host_sync_good_pass_boundary_and_prof_guard(tmp_path):
    """The two designed idioms: D2H after the loop (pass boundary), and
    a profiling-gated readback inside it — neither needs an annotation."""
    src = """\
        import jax
        import numpy as np

        step = jax.jit(lambda x: x)

        def train(ds, prof):
            for batch in ds.batches():
                loss = step(batch)
                if prof.enabled:
                    loss.block_until_ready()
            return float(loss)
    """
    assert _run(rules_numerics, tmp_path, src) == []


def test_host_sync_good_shape_read_is_not_a_sync(tmp_path):
    src = """\
        import jax

        step = jax.jit(lambda x: x)

        def train(feeds):
            n = 0
            for dev in feeds:
                loss = step(dev)
                n += int(loss.shape[0])
            return n
    """
    assert _run(rules_numerics, tmp_path, src) == []


def test_host_sync_suppressed(tmp_path):
    src = BAD_HOT_SYNC.replace(
        "        x = jax.device_get(loss)",
        "        # pbox-lint: ignore[host-sync-in-hot-loop] fixture reason\n"
        "        x = jax.device_get(loss)",
    )
    assert _run(rules_numerics, tmp_path, src) == []


# -- CLI / tooling ----------------------------------------------------------- #
def test_cli_names_num_key_width_on_seeded_regression(tmp_path):
    """Acceptance scenario: a seeded uint64->float regression exits
    non-zero and the output names rule, file and line via the CLI."""
    bad = tmp_path / "regress.py"
    bad.write_text(
        "import numpy as np\n"
        "def shard_of(keys, n):\n"
        "    return keys.astype(np.float64) % n\n"
    )
    r = subprocess.run(
        [sys.executable, CLI, str(bad)],
        capture_output=True, text=True, timeout=60,
    )
    assert r.returncode == 1
    assert "num-key-width" in r.stdout
    assert "regress.py:3" in r.stdout


def test_cli_rules_glob_selects_num_and_jit_families(tmp_path):
    bad = tmp_path / "regress.py"
    bad.write_text(
        "import numpy as np\n"
        "import jax\n"
        "def f(keys, tree):\n"
        "    jax.jit(lambda t: t)(tree)\n"
        "    return keys * 0.5\n"
    )
    r = subprocess.run(
        [sys.executable, CLI, str(bad), "--rules", "num-*"],
        capture_output=True, text=True, timeout=60,
    )
    assert r.returncode == 1
    assert "num-key-width" in r.stdout
    assert "jit-retrace-hazard" not in r.stdout
    r = subprocess.run(
        [sys.executable, CLI, str(bad), "--rules", "jit-*"],
        capture_output=True, text=True, timeout=60,
    )
    assert r.returncode == 1
    assert "jit-retrace-hazard" in r.stdout
    assert "num-key-width" not in r.stdout


def test_changed_mode_picks_up_numerics_rules(tmp_path, monkeypatch, capsys):
    """--changed REF reports a new-rule finding when its line is in the
    diff, and filters it out when only other lines were touched."""
    from pbox_analyze import cli as cli_mod

    bad = tmp_path / "touched.py"
    bad.write_text(
        "import numpy as np\n"
        "def f(keys):\n"
        "    return keys.astype(np.float32)\n"
    )
    rel = os.path.relpath(str(bad), cli_mod.REPO)

    monkeypatch.setattr(cli_mod, "_changed_lines", lambda ref: {rel: {3}})
    rc = cli_mod.main(["--changed", "HEAD", str(bad)])
    out = capsys.readouterr().out
    assert rc == 1
    assert "num-key-width" in out

    monkeypatch.setattr(cli_mod, "_changed_lines", lambda ref: {rel: {1}})
    rc = cli_mod.main(["--changed", "HEAD", str(bad)])
    out = capsys.readouterr().out
    assert rc == 0
    assert "num-key-width" not in out


def test_numerics_rules_listed():
    r = subprocess.run(
        [sys.executable, CLI, "--list-rules"],
        capture_output=True, text=True, timeout=60,
    )
    assert r.returncode == 0
    for rule in ("num-dtype-flow", "num-key-width", "jit-retrace-hazard",
                 "host-sync-in-hot-loop"):
        assert rule in r.stdout


def test_numerics_repo_is_clean(tmp_path):
    """The acceptance bar: the four numerics rules over the default roots
    produce zero findings (intentional sites carry inline reasons; the
    baseline stays empty)."""
    r = subprocess.run(
        [sys.executable, CLI, "--all", "--json",
         "--rules", "num-*,jit-*,host-sync-in-hot-loop"],
        capture_output=True, text=True, timeout=120,
    )
    assert r.returncode == 0, r.stdout
    assert json.loads(r.stdout) == []


def test_numerics_memos_live_in_context_caches(tmp_path):
    """Per-function dtype envs and sync summaries are memoized under
    Context.caches so repeated pass runs (and the wall-time budget) don't
    re-derive them."""
    ctx = _ctx(tmp_path, BAD_KEY_CAST)
    rules_numerics.run(ctx)
    cache = ctx.caches.get("numerics")
    assert cache is not None
    assert cache["dtype_env"], "dtype envs must be memoized per function"
    # second run hits the memo table (same object, no rebuild)
    envs = cache["dtype_env"]
    rules_numerics.run(ctx)
    assert ctx.caches["numerics"]["dtype_env"] is envs
