"""Pass-boundary pipelining correctness (ARCHITECTURE.md "Pass-boundary
pipelining").

The overlapped lifecycle — async end-pass write-back behind a pending-merge
overlay, next-pass pre-promotion with the begin_pass intersection patch,
thread-pooled bucket store — must be BIT-exact vs the serial escape hatch:
same keys, same values, same g2sum, same AUC, over multiple passes, on both
trainer paths.  Plus: the overlay stays read-your-writes under an injected
slow merge (chaos site ``store.merge``), and checkpoint/shrink barrier on
the background merge before touching the store.
"""

import threading
import time

import jax
import numpy as np
import pytest

from paddlebox_tpu.config import SparseTableConfig, TrainerConfig
from paddlebox_tpu.data.dataset import PadBoxSlotDataset
from paddlebox_tpu.data.synth import make_synth_config, write_synth_files
from paddlebox_tpu.models import CtrDnn
from paddlebox_tpu.sparse.table import SparseTable
from paddlebox_tpu.train.trainer import Trainer
from paddlebox_tpu.utils import faults

N_SLOTS = 3
DENSE = 2
N_PASSES = 3


def _tconf(overlap: bool, **kw) -> SparseTableConfig:
    # hbm_cache_rows=0: these tests pin the PR-5 overlay/write-back
    # machinery itself — with the device cache on, steady-state passes
    # write back almost nothing and the overlay paths go unexercised
    # (the cached lifecycle has its own suite, tests/test_hbm_cache.py)
    return SparseTableConfig(
        embedding_dim=4, learning_rate=0.4, initial_range=0.05,
        store_buckets=16, plan_scratch_rows=64, hbm_cache_rows=0,
        overlap_pass_boundary=overlap, store_threads=4 if overlap else 0,
        **kw,
    )


@pytest.fixture(scope="module")
def pass_datasets(tmp_path_factory):
    """N_PASSES loaded datasets over a SHARED key space (vocab 40: heavy
    census overlap between passes — the begin_pass patch path must carry
    pass p's updates into pass p+1's staged buffer)."""
    conf = make_synth_config(
        n_sparse_slots=N_SLOTS, dense_dim=DENSE, batch_size=64,
        max_feasigns_per_ins=16,
    )
    datasets = []
    for p in range(N_PASSES):
        d = tmp_path_factory.mktemp(f"pass{p}")
        files = write_synth_files(
            str(d), n_files=2, ins_per_file=192, n_sparse_slots=N_SLOTS,
            vocab_per_slot=40, dense_dim=DENSE, seed=11 + p,
        )
        ds = PadBoxSlotDataset(conf, read_threads=2)
        ds.set_filelist(files)
        ds.load_into_memory()
        datasets.append(ds)
    yield conf, datasets
    for ds in datasets:
        ds.close()


def _run_single_chip(datasets, overlap: bool, prepare: bool):
    tconf = _tconf(overlap)
    table = SparseTable(tconf, seed=3)
    model = CtrDnn(N_SLOTS, tconf.row_width, dense_dim=DENSE, hidden=(16, 8))
    trainer = Trainer(
        model, tconf, TrainerConfig(dense_lr=3e-3, auc_buckets=1 << 12),
        seed=3,
    )
    auc_state = None
    metrics = None
    for p, ds in enumerate(datasets):
        table.begin_pass(ds.unique_keys())
        nxt = (
            datasets[p + 1].unique_keys
            if prepare and p + 1 < len(datasets) else None
        )
        metrics = trainer.train_from_dataset(
            ds, table, auc_state=auc_state, drop_last=True,
            next_pass_keys=nxt,
        )
        auc_state = trainer.last_metric_state
        table.end_pass()
    sd = table.state_dict()
    delta = table.pop_delta()
    return sd, delta, metrics


def _run_multichip(datasets, overlap: bool, prepare: bool):
    from paddlebox_tpu.parallel import (
        MultiChipTrainer,
        ShardedSparseTable,
        make_mesh,
    )

    mesh = make_mesh(8)
    tconf = _tconf(overlap)
    table = ShardedSparseTable(tconf, mesh, seed=3)
    model = CtrDnn(N_SLOTS, tconf.row_width, dense_dim=DENSE, hidden=(16, 8))
    trainer = MultiChipTrainer(
        model, tconf, mesh, TrainerConfig(dense_lr=3e-3, auc_buckets=1 << 12),
        seed=3,
    )
    metrics = None
    for p, ds in enumerate(datasets):
        table.begin_pass(ds.unique_keys())
        nxt = (
            datasets[p + 1].unique_keys
            if prepare and p + 1 < len(datasets) else None
        )
        metrics = trainer.train_from_dataset(
            ds, table, drop_last=True, next_pass_keys=nxt,
        )
        table.end_pass()
    return table.state_dict(), metrics


def _assert_state_equal(a, b):
    assert np.array_equal(a["keys"], b["keys"])
    # values carry [show, clk, embed..., g2sum]: exact equality pins the
    # counters, the embeddings AND the optimizer state bit-for-bit
    assert np.array_equal(a["values"], b["values"])


class TestBitExactness:
    def test_single_chip_overlap_matches_serial(self, pass_datasets):
        _, datasets = pass_datasets
        sd_s, delta_s, m_s = _run_single_chip(datasets, False, False)
        sd_o, delta_o, m_o = _run_single_chip(datasets, True, True)
        _assert_state_equal(sd_s, sd_o)
        _assert_state_equal(delta_s, delta_o)
        assert m_s["auc"] == m_o["auc"]
        assert m_s["loss"] == m_o["loss"]

    def test_single_chip_overlap_without_prepare_matches(self, pass_datasets):
        # async write-back alone (no staging): begin_pass resolves through
        # the overlay synchronously — still bit-exact
        _, datasets = pass_datasets
        sd_s, _, m_s = _run_single_chip(datasets, False, False)
        sd_o, _, m_o = _run_single_chip(datasets, True, False)
        _assert_state_equal(sd_s, sd_o)
        assert m_s["auc"] == m_o["auc"]

    def test_multichip_overlap_matches_serial(self, pass_datasets):
        if len(jax.devices()) < 8:
            pytest.skip("needs the conftest 8-device CPU mesh")
        _, datasets = pass_datasets
        sd_s, m_s = _run_multichip(datasets, False, False)
        sd_o, m_o = _run_multichip(datasets, True, True)
        _assert_state_equal(sd_s, sd_o)
        assert m_s["auc"] == m_o["auc"]


class TestOverlayReadYourWrites:
    def test_lookup_and_begin_pass_see_unmerged_writeback(self):
        # PBOX_FAULT_PLAN-style hang at store.merge: the background merge
        # freezes, yet every read must already see the pass's rows
        with faults.fault_plan({"store.merge": "hang:first:1"}):
            t = SparseTable(_tconf(True), seed=0)
            keys = np.arange(1, 60, dtype=np.uint64)
            t.begin_pass(keys)
            t.values = t.values + 2.0  # show col: 0 -> 2
            t0 = time.monotonic()
            t.end_pass()
            assert time.monotonic() - t0 < 2.0, "end_pass waited on the merge"
            vals, found = t._lookup_with_overlay(keys)
            assert found.all() and (vals[:, 0] == 2.0).all()
            # a new pass over an overlapping census resolves from the
            # overlay, not the (stale) store
            t.begin_pass(keys[:30])
            assert (np.asarray(t.values)[:30, 0] == 2.0).all()
            t.abort_pass()
            faults.release_hangs()
            t.flush()
            sd = t.state_dict()
            assert (sd["values"][:, 0] == 2.0).all()

    def test_staged_pass_patched_with_final_rows(self):
        # prepare_pass BEFORE end_pass: the staged buffer resolves the OLD
        # rows; begin_pass must patch the census intersection from the
        # finished pass's write-back
        t = SparseTable(_tconf(True), seed=0)
        keys = np.arange(1, 40, dtype=np.uint64)
        t.begin_pass(keys)
        t.values = t.values + 5.0
        t.prepare_pass(keys)  # staged against the PRE-pass store
        t.end_pass()
        t.begin_pass(keys)  # consumes the stage + patches
        assert (np.asarray(t.values)[: len(keys), 0] == 5.0).all()
        t.end_pass()
        t.flush()

    def test_stage_discarded_on_census_mismatch(self):
        from paddlebox_tpu.utils.monitor import stats

        t = SparseTable(_tconf(True), seed=0)
        t.prepare_pass(np.arange(1, 10, dtype=np.uint64))
        before = stats.get("pass.stage_discards")
        t.begin_pass(np.arange(1, 30, dtype=np.uint64))  # different census
        assert stats.get("pass.stage_discards") == before + 1
        assert t.capacity > 0  # synchronous fallback still promoted
        t.end_pass()
        t.flush()


class TestBarriers:
    def test_state_dict_waits_for_hung_merge(self):
        with faults.fault_plan({"store.merge": "hang:first:1"}):
            t = SparseTable(_tconf(True), seed=0)
            keys = np.arange(1, 50, dtype=np.uint64)
            t.begin_pass(keys)
            t.values = t.values + 3.0
            t.end_pass()
            release = threading.Timer(0.3, faults.release_hangs)
            release.start()
            t0 = time.monotonic()
            sd = t.state_dict()  # must barrier on the in-flight merge
            assert time.monotonic() - t0 >= 0.25
            assert (sd["values"][:, 0] == 3.0).all()
            release.join()

    def test_shrink_barriers_and_discards_stage(self):
        # decay at shrink must see the write-back, and a staged buffer
        # resolved pre-shrink must not resurrect undecayed rows
        tconf = _tconf(True, show_decay_rate=0.5)
        serial = _tconf(False, show_decay_rate=0.5)

        def run(tc, prepare):
            t = SparseTable(tc, seed=0)
            keys = np.arange(1, 30, dtype=np.uint64)
            t.begin_pass(keys)
            t.values = t.values + 4.0
            if prepare:
                t.prepare_pass(keys)
                t.staged_pass_keys()  # ensure the stage resolved pre-shrink
            t.end_pass()
            t.shrink()
            t.begin_pass(keys)
            vals = np.asarray(t.values).copy()
            t.end_pass()
            t.flush()
            return vals

        v_serial = run(serial, False)
        v_overlap = run(tconf, True)
        assert (v_serial[:29, 0] == 2.0).all()  # 4.0 decayed by 0.5
        assert np.array_equal(v_serial, v_overlap)

    def test_merge_failure_surfaces_at_flush(self):
        with faults.fault_plan({"store.merge": "first:1"}):
            t = SparseTable(_tconf(True), seed=0)
            keys = np.arange(1, 20, dtype=np.uint64)
            t.begin_pass(keys)
            t.values = t.values + 1.0
            t.end_pass()
            with pytest.raises(faults.FaultInjected):
                t.flush()
            # the failed write-back is still readable through the overlay
            vals, found = t._lookup_with_overlay(keys)
            assert found.all() and (vals[:, 0] == 1.0).all()

    def test_failed_merge_poisons_later_merges_not_reads(self):
        # a later pass must NOT land in the store on top of a missing one
        # (the overlay layering would go stale-ordered); reads keep seeing
        # the newest write-back and every barrier raises
        with faults.fault_plan({"store.merge": "first:1"}):
            t = SparseTable(_tconf(True), seed=0)
            keys = np.arange(1, 20, dtype=np.uint64)
            t.begin_pass(keys)
            t.values = t.values + 1.0
            t.end_pass()  # this merge fails
            t.begin_pass(keys)  # resolves 1.0 through the overlay
            assert (np.asarray(t.values)[:19, 0] == 1.0).all()
            t.values = t.values + 1.0
            t.end_pass()  # this merge is poisoned, store stays empty
            time.sleep(0.1)  # let the poisoned merge job run
            vals, found = t._lookup_with_overlay(keys)
            assert found.all() and (vals[:, 0] == 2.0).all()  # newest wins
            assert t._store.n == 0  # nothing ever landed
            with pytest.raises(faults.FaultInjected):
                t.flush()


class TestParallelStore:
    def test_parallel_store_matches_serial(self):
        from paddlebox_tpu.sparse.store import BucketStore

        rng = np.random.default_rng(0)
        serial = BucketStore(n_cols=5, n_buckets=32, n_threads=0)
        pooled = BucketStore(n_cols=5, n_buckets=32, n_threads=4)
        for i in range(5):
            keys = np.unique(
                rng.integers(0, 10_000, size=2000).astype(np.uint64)
            )
            vals = rng.normal(size=(keys.shape[0], 5)).astype(np.float32)
            serial.update(keys, vals)
            pooled.update(keys, vals)
        q = np.unique(rng.integers(0, 12_000, size=3000).astype(np.uint64))
        vs, fs = serial.lookup(q)
        vp, fp = pooled.lookup(q)
        assert np.array_equal(fs, fp) and np.array_equal(vs, vp)
        es = serial.decay_evict(decay_cols=2, decay=0.5, threshold=0.0)
        ep = pooled.decay_evict(decay_cols=2, decay=0.5, threshold=0.0)
        assert es == ep
        ks, vvs = serial.materialize()
        kp, vvp = pooled.materialize()
        assert np.array_equal(ks, kp) and np.array_equal(vvs, vvp)

    def test_store_close_retires_pool_and_stays_usable(self):
        # the pbox-lint executor-shutdown finding: the bucket pool's
        # workers must not outlive the store — close() retires them, and
        # a later use lazily respawns the pool (close is a quiesce, not
        # a poison pill)
        from paddlebox_tpu.sparse.store import BucketStore

        store = BucketStore(n_cols=3, n_buckets=16, n_threads=4)
        keys = np.arange(0, 2000, dtype=np.uint64)
        store.update(keys, np.ones((2000, 3), np.float32))
        assert store._pool is not None  # pool was actually exercised
        store.close()
        assert store._pool is None
        store.close()  # idempotent
        v, f = store.lookup(keys)  # respawns the pool transparently
        assert f.all() and (v == 1.0).all()
        assert store._pool is not None
        store.close()

    def test_table_close_flushes_and_retires(self):
        t = SparseTable(_tconf(True), seed=0)
        keys = np.arange(1, 120, dtype=np.uint64)
        t.begin_pass(keys)
        with pytest.raises(RuntimeError):
            t.close()  # close inside a pass is a contract violation
        t.end_pass()
        t.close()
        assert t._store._pool is None
        # still checkpointable after close: the pool respawns on demand
        state = t.state_dict()
        assert state["keys"].shape[0] == keys.shape[0]

    def test_concurrent_lookup_update_disjoint_keys(self):
        # merge thread (update) and staging thread (lookup) on disjoint
        # key ranges must not corrupt each other under the pool
        from paddlebox_tpu.sparse.store import BucketStore

        store = BucketStore(n_cols=3, n_buckets=16, n_threads=4)
        base = np.arange(0, 4000, dtype=np.uint64)
        store.update(base, np.ones((4000, 3), np.float32))
        errs = []

        def reader():
            try:
                for _ in range(20):
                    v, f = store.lookup(base[:2000])
                    assert f.all() and (v == 1.0).all()
            except Exception as e:  # pragma: no cover - failure path
                errs.append(e)

        def writer():
            try:
                for i in range(20):
                    store.update(
                        base[2000:],
                        np.full((2000, 3), float(i + 2), np.float32),
                    )
            except Exception as e:  # pragma: no cover - failure path
                errs.append(e)

        threads = [threading.Thread(target=reader),
                   threading.Thread(target=writer)]
        for th in threads:
            th.start()
        for th in threads:
            th.join()
        assert not errs
        v, f = store.lookup(base[2000:])
        assert f.all() and (v == 21.0).all()
