"""Multi-process (multi-host tier) parity: N localhost processes x K virtual
CPU devices each must reproduce the single-process N*K-device run.

This is the reference's localhost-pserver test discipline
(test_dist_base.py:754-900 spawns local subprocesses, :642 asserts dist loss
== local loss) applied to the JAX coordination service: the parent runs the
8-device single-process reference in-process (conftest's CPU mesh), then
launches 2 ranks x 4 devices via paddlebox_tpu.launch running
tests/_mp_child.py, and compares pass metrics + trained dense params.
"""

import json
import os

import numpy as np
import pytest

from paddlebox_tpu.config import SparseTableConfig, TrainerConfig
from paddlebox_tpu.data.dataset import PadBoxSlotDataset
from paddlebox_tpu.data.synth import make_synth_config, write_synth_files
from paddlebox_tpu.models import CtrDnn

S, DENSE, B = 3, 2, 8
HERE = os.path.dirname(__file__)


def _write_data(tmp_path):
    # 20 batches of 8 -> 2 full device groups + 1 ragged (padded) group
    return write_synth_files(
        str(tmp_path / "data"), n_files=4, ins_per_file=40, n_sparse_slots=S,
        vocab_per_slot=200, dense_dim=DENSE, seed=3,
    )


def _reference_run(files, slot_lr=()):
    """Single-process 8-device run (the 'local' side of the parity)."""
    import jax

    from paddlebox_tpu.parallel import (
        MultiChipTrainer,
        ShardedSparseTable,
        make_mesh,
    )

    conf = make_synth_config(
        n_sparse_slots=S, dense_dim=DENSE, batch_size=B, max_feasigns_per_ins=16
    )
    ds = PadBoxSlotDataset(conf, read_threads=1)
    ds.set_filelist(files)
    ds.load_into_memory()
    mesh = make_mesh(8)
    tconf = SparseTableConfig(embedding_dim=8, slot_learning_rates=slot_lr)
    trconf = TrainerConfig(auc_buckets=1 << 10)
    model = CtrDnn(S, tconf.row_width, dense_dim=DENSE, hidden=(32, 16))
    trainer = MultiChipTrainer(model, tconf, mesh, trconf, seed=0)
    table = ShardedSparseTable(tconf, mesh, seed=0)
    table.begin_pass(ds.unique_keys())
    metrics = trainer.train_from_dataset(ds, table)
    table.end_pass()
    ds.close()
    params, _ = trainer.dense_state()
    metrics["param_abs_sum"] = float(
        sum(np.abs(np.asarray(l)).sum() for l in jax.tree.leaves(params))
    )
    metrics["total_features"] = table.n_features
    return metrics


@pytest.mark.slow
@pytest.mark.parametrize("lrmap", [False, True])
def test_two_process_parity(tmp_path, lrmap):
    """lrmap arm: the per-slot LR map's packed want+lr allgather must agree
    across the host-plane KV channel exactly like the plain plan does
    (the single-process reference uses host_allgather; parity proves the
    two transports carry the packed matrix identically)."""
    files = _write_data(tmp_path)
    slot_lr = ((1, 0.005), (2, 0.5)) if lrmap else ()
    ref = _reference_run(files, slot_lr=slot_lr)

    from paddlebox_tpu.launch import launch

    out_json = str(tmp_path / "rank0.json")
    log_dir = str(tmp_path / "logs")
    child_args = [
        os.path.join(HERE, "_mp_child.py"), os.path.dirname(files[0]),
        out_json,
    ] + ([f"lrmap={json.dumps(slot_lr)}"] if lrmap else [])
    rc = launch(
        child_args,
        nproc=2,
        devices_per_proc=4,
        log_dir=log_dir,
    )
    if rc != 0:
        logs = "\n".join(
            f"--- {f} ---\n" + open(os.path.join(log_dir, f)).read()[-3000:]
            for f in sorted(os.listdir(log_dir))
        )
        pytest.fail(f"launch rc={rc}\n{logs}")
    with open(out_json) as f:
        got = json.load(f)

    assert got["steps"] == ref["steps"]
    assert got["count"] == ref["count"]
    # same data, same deterministic key init, same collective math -> metrics
    # agree to float tolerance; AUC histograms are integer so near-exact
    assert np.isclose(got["loss"], ref["loss"], rtol=1e-4), (got, ref["loss"])
    assert abs(got["auc"] - ref["auc"]) < 2e-3, (got["auc"], ref["auc"])
    assert np.isclose(
        got["param_abs_sum"], ref["param_abs_sum"], rtol=1e-4
    ), (got["param_abs_sum"], ref["param_abs_sum"])
    # the two rank-local stores partition the global feature census
    assert got["total_features"] == ref["total_features"]
