#!/usr/bin/env python
"""Streaming online learning, end to end: live feed → served scores with
second-level, MEASURED freshness.

A writer appends slot-text records to a stream directory (the live feed).
A TailingFileSource follows it; a MiniPassScheduler cuts mini-pass
windows and computes each census off-thread; StreamingTrainer trains
window by window (metric state carried, pass boundaries overlapped); a
DeadlinePublishPolicy ships sparse deltas on a max-staleness deadline;
a Syncer'd ScoringServer hot-applies them; and a confirmation poller
records the true event-time→served-score latency
(`stream.freshness_seconds`).

Halfway through, the writer FLIPS the label of a hot key pattern — watch
the served score move within seconds.

    python examples/streaming_online.py [--seconds 12] [--staleness 1.5]
"""

import argparse
import json
import os
import sys
import tempfile
import threading
import time
import urllib.request

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

# this image's sitecustomize forces jax_platforms="axon,cpu" (the real-TPU
# tunnel, a single-client resource) over the env var; the example must run
# anywhere, so pin CPU before any backend init — same guard as day_loop.py
import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--seconds", type=float, default=12.0,
                    help="how long the live stream runs")
    ap.add_argument("--staleness", type=float, default=1.5,
                    help="freshness budget (s): publish deadline")
    ap.add_argument("--rate", type=float, default=400.0,
                    help="records/s the writer appends")
    args = ap.parse_args()

    import numpy as np

    from paddlebox_tpu.config import SparseTableConfig, TrainerConfig
    from paddlebox_tpu.data.slot_parser import SlotParser
    from paddlebox_tpu.data.synth import make_synth_config, stream_line
    from paddlebox_tpu.inference import ScoringServer
    from paddlebox_tpu.models import CtrDnn
    from paddlebox_tpu.serving_sync import Publisher, Syncer
    from paddlebox_tpu.sparse.table import SparseTable
    from paddlebox_tpu.streaming import (
        DeadlinePublishPolicy,
        MiniPassScheduler,
        StreamingTrainer,
        TailingFileSource,
    )
    from paddlebox_tpu.train.trainer import Trainer

    S, DENSE, B = 2, 2, 16
    conf = make_synth_config(n_sparse_slots=S, dense_dim=DENSE,
                             batch_size=B, max_feasigns_per_ins=8)
    tconf = SparseTableConfig(embedding_dim=4, learning_rate=0.3,
                              store_buckets=8, plan_scratch_rows=64)
    model = CtrDnn(S, tconf.row_width, dense_dim=DENSE, hidden=(8,))
    table = SparseTable(tconf, seed=0)
    trainer = Trainer(model, tconf, TrainerConfig(auc_buckets=1 << 12),
                      seed=0)

    work = tempfile.mkdtemp(prefix="pbox_streaming_")
    root = os.path.join(work, "publish")
    stream = os.path.join(work, "stream")
    os.makedirs(stream)
    rng = np.random.default_rng(0)

    def line(label: int) -> str:
        """One record: the hot key pair (5, 1005) plus one noise key each."""
        return stream_line(rng, label, n_sparse_slots=S, dense_dim=DENSE,
                           hot_keys=(5, 1005))

    # -- warm start: one tiny batch pass anchors the delta chain ------------ #
    parser = SlotParser(conf)
    warm = [line(1) for _ in range(4 * B)]
    block = parser.parse_lines(warm)

    from paddlebox_tpu.streaming.minipass import MiniPassWindow, WindowDataset
    from paddlebox_tpu.data.feed import BatchBuilder

    w0 = MiniPassWindow(0, block, np.unique(block.keys), len(warm),
                        time.time(), time.time(), "warm", time.time())
    table.begin_pass(w0.census)
    trainer.train_from_dataset(WindowDataset(w0, BatchBuilder(conf)), table)
    table.end_pass()

    pub = Publisher(root, staging_dir=os.path.join(work, "staging"))
    kcap = B * conf.max_feasigns_per_ins
    pub.publish_base("base", model, trainer.params, table,
                     lineage="warmup", batch_size=B, key_capacity=kcap,
                     dense_dim=DENSE, feed_conf=conf)

    # -- serving side -------------------------------------------------------- #
    server = ScoringServer()
    syncer = Syncer(root, server, "live",
                    cache_dir=os.path.join(work, "cache"),
                    poll_interval_s=0.1)
    syncer.poll_once()
    syncer.start()
    port = server.start(port=0)
    probe = b"1 0 2 5 30 2 1005 1030 2 0.0 0.0\n"

    def score() -> float:
        req = urllib.request.Request(
            f"http://127.0.0.1:{port}/score/live", data=probe, method="POST")
        with urllib.request.urlopen(req, timeout=30) as r:
            return json.loads(r.read())["scores"][0]

    # -- streaming plane ------------------------------------------------------ #
    source = TailingFileSource(stream, poll_interval_s=0.02)
    sched = MiniPassScheduler(source, conf, window_records=4 * B,
                              window_seconds=0.5)
    policy = DeadlinePublishPolicy(pub, args.staleness, scheduler=sched)
    runner = StreamingTrainer(
        trainer, table, sched, policy=policy, model=model,
        served_seq_fn=lambda: (server.model_version("live") or {}).get("seq"),
    )
    source.start()
    sched.start()

    flip_at = args.seconds / 2
    flipped = threading.Event()

    def writer():
        t0 = time.monotonic()
        path = os.path.join(stream, "part-000")
        with open(path, "w", buffering=1) as fh:
            while time.monotonic() - t0 < args.seconds:
                late = time.monotonic() - t0 >= flip_at
                if late and not flipped.is_set():
                    flipped.set()
                    print(f"[writer] t+{time.monotonic() - t0:.1f}s: "
                          "LABEL FLIP 1 -> 0 for the hot keys")
                fh.write(line(0 if late else 1))
                time.sleep(1.0 / args.rate)
        runner.stop()  # drain-and-checkpoint shutdown

    def reporter():
        while not runner._stop_evt.is_set():
            try:
                s = score()
            except Exception:
                s = float("nan")
            info = server.model_version("live") or {}
            print(f"[serve] score={s:.4f} seq={info.get('seq')} "
                  f"freshness={policy.last_freshness_s and round(policy.last_freshness_s, 2)}s "
                  f"windows={runner.windows_trained}")
            time.sleep(1.0)

    threading.Thread(target=writer, daemon=True).start()
    threading.Thread(target=reporter, daemon=True).start()
    summary = runner.run()

    final = score()
    syncer.stop()
    server.stop()
    print("\nstream summary:", json.dumps(summary, indent=2))
    print(f"final served score: {final:.4f}")
    print("workdir:", work)


if __name__ == "__main__":
    main()
