"""Host-store scale benchmark: pass-boundary merge cost vs store size.

Measures the bucketed store (sparse/store.py) against the round-3
monolithic merge (concat + argsort of the whole store) at 1e6 → 1e8
features, plus a full SparseTable begin_pass/end_pass at the 1e8 point —
the VERDICT r3 "scale-real host store" evidence (missing #2 / next #3).
Results land in BASELINE.md.

Pure host work: forces the CPU backend so it can never touch the TPU
tunnel.  Run:  python examples/bench_store.py [--max-exp 8]
"""

import argparse
import os
import resource
import sys
import time

os.environ.setdefault("JAX_PLATFORMS", "cpu")
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax  # noqa: E402

# this image's sitecustomize forces jax_platforms="axon,cpu" via
# jax.config.update, which OUTRANKS the env var — re-force CPU before any
# backend init or the --table-pass path would touch the TPU tunnel
jax.config.update("jax_platforms", "cpu")

import numpy as np  # noqa: E402


def rss_gb() -> float:
    return resource.getrusage(resource.RUSAGE_SELF).ru_maxrss / 1e6


def legacy_merge(store_keys, store_vals, keys, vals):
    """The round-3 monolithic merge (sparse/table.py@cc38e89:185-198):
    in-place for found, concat + argsort-the-world for new keys."""
    pos = np.searchsorted(store_keys, keys)
    pos_c = np.minimum(pos, store_keys.shape[0] - 1)
    found = store_keys[pos_c] == keys
    store_vals[pos_c[found]] = vals[found]
    if (~found).any():
        all_keys = np.concatenate([store_keys, keys[~found]])
        all_vals = np.concatenate([store_vals, vals[~found]])
        order = np.argsort(all_keys, kind="stable")
        return all_keys[order], all_vals[order]
    return store_keys, store_vals


def make_pass(rng, store_keys, n_exist, n_new):
    """A pass working set: n_exist existing keys + n_new unseen keys."""
    idx = rng.integers(0, store_keys.shape[0], size=n_exist)
    exist = store_keys[idx]
    new = rng.integers(2**63, 2**64 - 1, dtype=np.uint64, size=n_new)
    return np.unique(np.concatenate([exist, new]))


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--max-exp", type=int, default=8,
                    help="largest store size as 10^exp (default 1e8)")
    ap.add_argument("--pass-keys", type=int, default=2_000_000)
    ap.add_argument("--new-frac", type=float, default=0.05)
    ap.add_argument("--skip-legacy-at", type=int, default=9,
                    help="skip legacy merge timing at/above 10^exp")
    ap.add_argument("--table-pass", action="store_true",
                    help="also run a full SparseTable pass at the largest size")
    args = ap.parse_args()

    from paddlebox_tpu.sparse.store import BucketStore

    C = 11  # [show, clk, emb8] + g2sum
    rng = np.random.default_rng(0)
    print(f"pass size: {args.pass_keys:,} keys, {args.new_frac:.0%} new; "
          f"row width {C} f32", flush=True)
    print(f"{'store size':>12} | {'bucketed merge':>14} | {'legacy merge':>13} "
          f"| {'lookup':>8} | {'RSS GB':>6}", flush=True)

    biggest_store = None
    for exp in range(6, args.max_exp + 1):
        n = 10 ** exp
        # build the store in one bulk load (construction isn't what we bench)
        keys = np.unique(
            rng.integers(0, 2**63, size=int(n * 1.05), dtype=np.uint64)
        )[:n]
        vals = np.zeros((keys.shape[0], C), dtype=np.float32)
        vals[:, 0] = 1.0
        st = BucketStore(C, n_buckets=256)
        st.load_bulk(keys, vals)

        n_new = int(args.pass_keys * args.new_frac)
        pk = make_pass(rng, keys, args.pass_keys - n_new, n_new)
        pv = np.ones((pk.shape[0], C), dtype=np.float32)

        t0 = time.perf_counter()
        st.update(pk, pv)
        t_bucket = time.perf_counter() - t0

        t0 = time.perf_counter()
        _ = st.lookup(pk)
        t_lookup = time.perf_counter() - t0

        if exp < args.skip_legacy_at:
            lk, lv = keys.copy(), vals.copy()
            t0 = time.perf_counter()
            lk, lv = legacy_merge(lk, lv, pk, pv)
            t_legacy = f"{time.perf_counter() - t0:>11.2f}s"
            del lk, lv
        else:
            t_legacy = "     skipped"

        print(f"{n:>12,} | {t_bucket:>13.2f}s | {t_legacy} "
              f"| {t_lookup:>7.2f}s | {rss_gb():>6.1f}", flush=True)
        if exp == args.max_exp:
            biggest_store = (st, keys)
        else:
            del st, keys, vals

    if args.table_pass and biggest_store is not None:
        st, keys = biggest_store
        from paddlebox_tpu.config import SparseTableConfig
        from paddlebox_tpu.sparse.table import SparseTable

        tconf = SparseTableConfig(embedding_dim=8)
        table = SparseTable(tconf, seed=0)
        table._store = st  # adopt the pre-built 1e8-feature store
        pk = make_pass(rng, keys, args.pass_keys, int(args.pass_keys * 0.05))
        t0 = time.perf_counter()
        table.begin_pass(pk)
        t_begin = time.perf_counter() - t0
        t0 = time.perf_counter()
        table.end_pass()
        t_end = time.perf_counter() - t0
        print(f"SparseTable @ {st.n:,} features: "
              f"begin_pass({pk.shape[0]:,})={t_begin:.2f}s "
              f"end_pass={t_end:.2f}s RSS={rss_gb():.1f}GB", flush=True)


if __name__ == "__main__":
    main()
