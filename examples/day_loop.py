#!/usr/bin/env python
"""The full production day loop, end to end.

This is the shape a BandaryGithub/PaddleBox production job has — the
reference spreads it across BoxHelper (pass driver), the join/update phase
flip (box_wrapper.h:627-630), ShrinkTable at day boundaries
(box_wrapper.cc:496-499), SaveBase/SaveDelta (cc:1411-1460), donefile
publication (fleet_util/fs), and operator-side monitoring — here it is one
readable loop over this framework's pieces:

  day d:
    pass p:                       (preload pass p+1 while p trains)
      join phase  -> update phase (two programs, one shared sparse table)
      monitor.observe(metrics)    (AUC floor/drop, loss, calibration)
      save_delta                  (incremental checkpoint)
    shrink()                      (decay show/clk, evict cold features)
    save_base + publish gate      (only a healthy model ships)

    python examples/day_loop.py [--days 2] [--passes 2]
"""

import argparse
import os
import sys
import tempfile

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

# this image's sitecustomize forces jax_platforms="axon,cpu" (the real-TPU
# tunnel, a single-client resource) over the env var; the example must run
# anywhere, so pin CPU before any backend init — same guard as
# examples/bench_store.py
import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--days", type=int, default=2)
    ap.add_argument("--passes", type=int, default=2)
    args = ap.parse_args()
    if args.days < 1 or args.passes < 1:
        ap.error("--days and --passes must be >= 1")

    from paddlebox_tpu.checkpoint import CheckpointManager
    from paddlebox_tpu.config import SparseTableConfig, TrainerConfig
    from paddlebox_tpu.data.dataset import PadBoxSlotDataset
    from paddlebox_tpu.data.synth import make_synth_config, write_synth_files
    from paddlebox_tpu.models import CtrDnn
    from paddlebox_tpu.sparse.table import SparseTable
    from paddlebox_tpu.train.two_phase import PhaseSpec, TwoPhaseTrainer
    from paddlebox_tpu.utils.fleet_util import (
        HealthPolicy,
        ModelMonitor,
        check_model,
    )

    S, DENSE, B = 6, 4, 128
    conf = make_synth_config(
        n_sparse_slots=S, dense_dim=DENSE, batch_size=B,
        max_feasigns_per_ins=16,
    )
    tconf = SparseTableConfig(
        embedding_dim=8, learning_rate=0.5, initial_range=0.05,
        show_decay_rate=0.9, delete_threshold=0.5,  # day-boundary shrink
    )
    trconf = TrainerConfig(dense_lr=3e-3, auc_buckets=1 << 16)

    # join phase trains the user/context slots, update phase all slots —
    # two dense programs over ONE shared sparse table
    join_model = CtrDnn(S, tconf.row_width, dense_dim=DENSE, hidden=(64, 32))
    update_model = CtrDnn(S, tconf.row_width, dense_dim=DENSE, hidden=(64, 32))
    tp = TwoPhaseTrainer(
        [
            PhaseSpec("join", join_model, slots=tuple(range(S // 2))),
            PhaseSpec("update", update_model, slots=None),
        ],
        tconf, trconf,
    )
    table = SparseTable(tconf, seed=0)
    monitor = ModelMonitor(HealthPolicy(min_auc=0.5, max_auc_drop=0.2))

    work = tempfile.mkdtemp(prefix="pbox_dayloop_")
    cm = CheckpointManager(os.path.join(work, "ckpt"))
    rng_seed = 0

    for day in range(args.days):
        date = f"202607{28 + day:02d}"
        print(f"== day {date}")
        for p in range(args.passes):
            with tempfile.TemporaryDirectory() as td:
                files = write_synth_files(
                    td, n_files=2, ins_per_file=512, n_sparse_slots=S,
                    vocab_per_slot=300, dense_dim=DENSE, seed=rng_seed,
                )
                rng_seed += 1
                ds = PadBoxSlotDataset(conf, read_threads=2)
                ds.set_filelist(files)
                ds.set_date(date)
                ds.load_into_memory()
                table.begin_pass(ds.unique_keys())
                metrics = tp.train_pass(ds, table)
                table.end_pass()
                ds.close()
            up = metrics["update"]
            report = monitor.observe(up)
            print(
                f"  pass {p}: join auc={metrics['join']['auc']:.4f} "
                f"update auc={up['auc']:.4f} loss={up['loss']:.4f} "
                f"healthy={bool(report)}"
            )
            cm.save_delta(f"{date}-p{p}", table)
        evicted = table.shrink()
        rep = check_model(table, tp.trainers["update"])
        print(
            f"  shrink evicted {evicted}; features={rep['n_features']} "
            f"sparse={rep['sparse_bytes'] / 1e6:.1f}MB finite={rep['sparse_finite']}"
        )
        if monitor.should_publish(up):
            params, opt = tp.trainers["update"].dense_state()
            path = cm.save_base(f"{date}-base", table, params, opt)
            print(f"  published base checkpoint: {os.path.basename(path)}")
        else:
            print("  publish gate held the model back")
    print("day loop done;", work)


if __name__ == "__main__":
    main()
