#!/usr/bin/env python
"""Criteo CTR-DNN end to end: raw TSV -> convert -> train -> AUC.

With real Criteo data (day_0, day_1, ... or train.txt, optionally .gz):

    python examples/train_criteo.py --input day_0 --passes 2

Without (zero-egress environments — BASELINE.md documents the blocker):
a spec-exact synthetic sample is generated first (real FORMAT, synthetic
VALUES, planted learnable signal), so the full path — Criteo TSV parse,
categorical hashing, log1p dense transform, native slot parse, pass loop,
AUC — runs and is measured either way:

    python examples/train_criteo.py --lines 8192 --passes 3

Reference analog: the dist-CTR e2e tier (ctr_dataset_reader.py), which
downloads its click data at test time; the model/feature recipe here is
the published Criteo one (26 hashed categorical + 13 log1p ints).
"""

import argparse
import os
import sys
import tempfile
import time

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--input", nargs="*", default=None,
                    help="real Criteo TSV file(s); omit to synthesize")
    ap.add_argument("--lines", type=int, default=8192,
                    help="synthetic sample size when no --input")
    ap.add_argument("--passes", type=int, default=3)
    ap.add_argument("--batch-size", type=int, default=2048)
    ap.add_argument("--emb", type=int, default=8)
    ap.add_argument("--tpu", action="store_true",
                    help="use the real accelerator (default: CPU — the "
                         "axon tunnel is a single-client resource reserved "
                         "for bench.py; see ARCHITECTURE.md)")
    args = ap.parse_args()
    if args.passes < 1:
        ap.error("--passes must be >= 1")

    if not args.tpu:
        # this image's sitecustomize forces jax_platforms="axon,cpu" (the
        # single-client TPU tunnel) over the env var; examples default to
        # CPU like every other script here
        import jax

        jax.config.update("jax_platforms", "cpu")

    from paddlebox_tpu.config import SparseTableConfig, TrainerConfig
    from paddlebox_tpu.data.criteo import (
        CRITEO_N_CAT,
        CRITEO_N_DENSE,
        convert_criteo_files,
        criteo_feed_config,
        write_criteo_format_sample,
    )
    from paddlebox_tpu.data.dataset import PadBoxSlotDataset
    from paddlebox_tpu.models import CtrDnn
    from paddlebox_tpu.sparse.table import SparseTable
    from paddlebox_tpu.train.trainer import Trainer

    with tempfile.TemporaryDirectory() as td:
        inputs = args.input
        kind = "real"
        if not inputs:
            kind = "criteo-format synthetic (see BASELINE.md blocker)"
            inputs = [write_criteo_format_sample(
                os.path.join(td, "sample.tsv"), n_lines=args.lines)]
        t0 = time.perf_counter()
        shards = convert_criteo_files(inputs, os.path.join(td, "slots"),
                                      batch_size=args.batch_size)
        t_conv = time.perf_counter() - t0
        conf = criteo_feed_config(args.batch_size)
        ds = PadBoxSlotDataset(conf, read_threads=4)
        ds.set_filelist(shards)
        t0 = time.perf_counter()
        ds.load_into_memory()
        t_parse = time.perf_counter() - t0

        tconf = SparseTableConfig(embedding_dim=args.emb)
        model = CtrDnn(CRITEO_N_CAT, tconf.row_width,
                       dense_dim=CRITEO_N_DENSE, hidden=(512, 256, 128))
        table = SparseTable(tconf, seed=0)
        trainer = Trainer(model, tconf,
                          TrainerConfig(auc_buckets=1 << 16), seed=0)
        m = None
        t_train = 0.0
        for p in range(args.passes):
            table.begin_pass(ds.unique_keys())
            t0 = time.perf_counter()
            m = trainer.train_from_dataset(
                ds, table, auc_state=trainer.last_metric_state)
            t_train += time.perf_counter() - t0
            table.end_pass()
            print(f"pass {p}: loss={m['loss']:.4f} auc={m['auc']:.4f} "
                  f"count={m['count']:.0f}")
        n_total = int(m["count"])
        ds.close()
        print(f"data: {kind}")
        print(f"convert: {t_conv:.2f}s  parse: {t_parse:.2f}s  "
              f"features: {table.n_features:,}")
        print(f"train: {n_total} samples in {t_train:.2f}s = "
              f"{n_total / t_train:,.0f} samples/s  final AUC {m['auc']:.4f}")


if __name__ == "__main__":
    main()
