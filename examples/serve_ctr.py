#!/usr/bin/env python
"""Train -> export -> serve: the serving-side story end to end.

The reference ships a C++ AnalysisPredictor + HTTP/Go/R clients
(/root/reference/paddle/fluid/inference/); here the equivalent loop is a
few lines over the exported StableHLO artifact: the packaged
``ScoringServer`` (inference/server.py — POST /score with slot-text
lines, /healthz, multi-model routing), driven end to end.

    python examples/serve_ctr.py            # train + export + demo request
    python examples/serve_ctr.py --port 0   # pick a free port and stay up
"""

import argparse
import json
import os
import sys
import tempfile

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

# this image's sitecustomize forces jax_platforms="axon,cpu" (the real-TPU
# tunnel, a single-client resource reserved for bench.py) over the env var;
# pin CPU before any backend init so the example runs anywhere.  Delete
# these two lines to run on a real TPU deployment.
import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")


def build_artifact(work: str) -> tuple[str, "object"]:
    """Quick synth training run, then export; returns (artifact_dir, conf)."""
    from paddlebox_tpu.config import SparseTableConfig, TrainerConfig
    from paddlebox_tpu.data.dataset import PadBoxSlotDataset
    from paddlebox_tpu.data.synth import make_synth_config, write_synth_files
    from paddlebox_tpu.inference import export_model
    from paddlebox_tpu.models import CtrDnn
    from paddlebox_tpu.sparse.table import SparseTable
    from paddlebox_tpu.train.trainer import Trainer

    S, DENSE, B = 4, 4, 32
    conf = make_synth_config(
        n_sparse_slots=S, dense_dim=DENSE, batch_size=B, max_feasigns_per_ins=16
    )
    files = write_synth_files(
        os.path.join(work, "data"), n_files=2, ins_per_file=512,
        n_sparse_slots=S, vocab_per_slot=1000, dense_dim=DENSE, seed=1,
    )
    ds = PadBoxSlotDataset(conf, read_threads=2)
    ds.set_filelist(files)
    ds.load_into_memory()
    tconf = SparseTableConfig(embedding_dim=8)
    model = CtrDnn(S, tconf.row_width, dense_dim=DENSE, hidden=(64, 32))
    table = SparseTable(tconf)
    trainer = Trainer(model, tconf, TrainerConfig(auc_buckets=1 << 16))
    table.begin_pass(ds.unique_keys())
    metrics = trainer.train_from_dataset(ds, table)
    table.end_pass()
    print(f"trained: auc={metrics['auc']:.4f}")
    art = os.path.join(work, "artifact")
    kcap = conf.batch_key_capacity or (B * conf.max_feasigns_per_ins)
    export_model(
        model, trainer.params, table, art,
        batch_size=B, key_capacity=kcap, dense_dim=DENSE,
        feed_conf=conf,  # self-contained artifact: serving needs no config
    )
    ds.close()
    return art, conf


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--port", type=int, default=None,
                    help="serve forever on this port (0 = pick free)")
    args = ap.parse_args()

    from paddlebox_tpu.data.synth import write_synth_files
    from paddlebox_tpu.inference import ScoringServer

    work = tempfile.mkdtemp(prefix="pbox_serve_")
    art, _conf = build_artifact(work)  # feed schema rides IN the artifact
    server = ScoringServer()
    server.register("ctr", art)  # feed schema comes from the artifact
    port = server.start(port=args.port or 0)
    print(f"serving on http://127.0.0.1:{port}/score "
          f"(also /score/ctr, /healthz, /models)")

    if args.port is None:
        # demo mode: fire one request against ourselves, print, exit
        import urllib.request

        demo_files = write_synth_files(
            os.path.join(work, "demo"), n_files=1, ins_per_file=8,
            n_sparse_slots=4, vocab_per_slot=1000, dense_dim=4, seed=9,
        )
        with open(demo_files[0], "rb") as f:
            req = urllib.request.Request(
                f"http://127.0.0.1:{port}/score", data=f.read(), method="POST"
            )
        with urllib.request.urlopen(req, timeout=30) as resp:
            print("scores:", json.load(resp)["scores"])
        server.stop()
    else:
        server.wait()  # foreground until killed


if __name__ == "__main__":
    main()
