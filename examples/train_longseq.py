#!/usr/bin/env python
"""Long-sequence CTR training example: DIN-style behavior attention.

One behavior slot (click history: file order == behavior order) feeds an
attention tower next to the standard pooled-CVM features; long sequences
shard over a ``seq`` mesh axis with ring attention.  The reference has no
long-sequence path (SURVEY.md §5.7) — this is the framework's beyond-parity
capability, driven through the SAME Dataset/Trainer lifecycle as every
other model.

    python examples/train_longseq.py [--seq-mesh N] [--impl ring|ulysses]

(--seq-mesh needs N devices: on CPU export
 XLA_FLAGS=--xla_force_host_platform_device_count=8 JAX_PLATFORMS=cpu)
"""

import argparse
import os
import sys
import tempfile

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

# this image's sitecustomize forces jax_platforms="axon,cpu" (the real-TPU
# tunnel, a single-client resource reserved for bench.py) over the env var;
# pin CPU before any backend init so the example runs anywhere.  Delete
# these two lines to run on a real TPU deployment.
import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--seq-mesh", type=int, default=0,
                    help="shard the sequence axis over N devices (0 = off)")
    ap.add_argument("--impl", default="ring", choices=["ring", "ulysses"])
    ap.add_argument("--passes", type=int, default=3)
    ap.add_argument("--max-seq-len", type=int, default=64)
    args = ap.parse_args()

    import jax
    import numpy as np

    from paddlebox_tpu.config import SparseTableConfig, TrainerConfig
    from paddlebox_tpu.data.dataset import PadBoxSlotDataset
    from paddlebox_tpu.data.synth import make_synth_config, write_synth_files
    from paddlebox_tpu.models import LongSeqCtrDnn
    from paddlebox_tpu.sparse.table import SparseTable
    from paddlebox_tpu.train.trainer import Trainer

    S, DENSE, B = 8, 8, 256
    MAX_KEYS_PER_SLOT = 24
    conf = make_synth_config(
        n_sparse_slots=S, dense_dim=DENSE, batch_size=B,
        max_feasigns_per_ins=args.max_seq_len + 16,
        # capacity must cover the worst batch (B * S * keys-per-slot) or the
        # feed silently clips tail keys — the behavior sequences included
        batch_key_capacity=B * S * MAX_KEYS_PER_SLOT,
        sequence_slot="slot0",  # slot0's keys double as the behavior sequence
        max_seq_len=args.max_seq_len,
    )

    seq_mesh = None
    if args.seq_mesh:
        from jax.sharding import Mesh

        from paddlebox_tpu.parallel.sequence import SEQ_AXIS

        devs = jax.devices()
        if len(devs) < args.seq_mesh:
            raise SystemExit(
                f"--seq-mesh {args.seq_mesh} needs {args.seq_mesh} devices, "
                f"have {len(devs)}"
            )
        seq_mesh = Mesh(np.array(devs[: args.seq_mesh]), (SEQ_AXIS,))

    tconf = SparseTableConfig(embedding_dim=16, learning_rate=0.5,
                              initial_range=0.05)
    model = LongSeqCtrDnn(
        S, tconf.row_width, dense_dim=DENSE, hidden=(256, 128),
        max_seq_len=args.max_seq_len, n_heads=4, head_dim=16,
        seq_mesh=seq_mesh, seq_impl=args.impl,
    )
    table = SparseTable(tconf, seed=0)
    trainer = Trainer(
        model, tconf, TrainerConfig(dense_lr=3e-3, auc_buckets=1 << 16),
        seed=0,
    )

    with tempfile.TemporaryDirectory() as td:
        files = write_synth_files(
            td, n_files=2, ins_per_file=2048, n_sparse_slots=S,
            vocab_per_slot=5000, dense_dim=DENSE, seed=7,
            max_keys_per_slot=MAX_KEYS_PER_SLOT,
        )
        ds = PadBoxSlotDataset(conf, read_threads=2)
        ds.set_filelist(files)
        ds.load_into_memory()
        for p in range(args.passes):
            ds.local_shuffle(seed=p)
            table.begin_pass(ds.unique_keys())
            m = trainer.train_from_dataset(ds, table)
            table.end_pass()
            mesh_note = (
                f" [seq-mesh {args.seq_mesh}x {args.impl}]" if seq_mesh else ""
            )
            print(
                f"pass {p}{mesh_note}: loss={m['loss']:.4f} "
                f"auc={m['auc']:.4f} steps={m['steps']}"
            )
        ds.close()


if __name__ == "__main__":
    main()
