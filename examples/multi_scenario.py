#!/usr/bin/env python
"""Multi-scenario training + two-tower retrieval, end to end.

THREE scenarios — a feed CTR tower, a CVR tower over a slot subset with
its own create-threshold, and a two-tower retrieval objective — train
against ONE shared SparseTable through MultiScenarioTrainer: one pass
per round over the union working set, scenario mini-batches interleaved,
per-scenario AUC/loss separately attributable in telemetry.

Then the serving split:

  * the retrieval scenario publishes its item tower as an ANN artifact
    (publish_ann_base + fp32 delta chain) and a Syncer'd ScoringServer
    answers POST /retrieve with top-k item keys — per-scenario serving
    policy (deadline, linger) attached via set_serving_policy;
  * the feed scenario goes ONLINE through the streaming plane
    (TailingFileSource -> MiniPassScheduler -> StreamingTrainer ->
    DeadlinePublishPolicy tagged with the scenario name) under its own
    freshness deadline, hot-synced into the same server.

    python examples/multi_scenario.py [--passes 3] [--stream-seconds 6]
"""

import argparse
import json
import os
import sys
import tempfile
import threading
import time
import urllib.request

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

# run-anywhere guard: pin CPU before any backend init (see day_loop.py)
import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--passes", type=int, default=3)
    ap.add_argument("--stream-seconds", type=float, default=6.0)
    ap.add_argument("--staleness", type=float, default=1.5,
                    help="feed scenario's freshness budget (s)")
    args = ap.parse_args()

    import numpy as np

    from paddlebox_tpu.config import (
        ScenarioServingConfig,
        SparseTableConfig,
        TrainerConfig,
    )
    from paddlebox_tpu.data.dataset import PadBoxSlotDataset
    from paddlebox_tpu.data.synth import (
        make_synth_config,
        stream_line,
        write_synth_files,
    )
    from paddlebox_tpu.inference import ScoringServer
    from paddlebox_tpu.models import CtrDnn, TwoTower, WideDeep
    from paddlebox_tpu.scenarios import MultiScenarioTrainer, ScenarioSpec
    from paddlebox_tpu.serving_sync import Publisher, Syncer
    from paddlebox_tpu.sparse.table import SparseTable

    S, DENSE, B, VOCAB = 4, 4, 64, 50
    work = tempfile.mkdtemp(prefix="pbox_scenarios_")
    conf = make_synth_config(n_sparse_slots=S, dense_dim=DENSE,
                             batch_size=B, max_feasigns_per_ins=16)
    files = write_synth_files(
        os.path.join(work, "data"), n_files=2, ins_per_file=512,
        n_sparse_slots=S, vocab_per_slot=VOCAB, dense_dim=DENSE, seed=7,
    )

    tconf = SparseTableConfig(embedding_dim=8, learning_rate=0.5,
                              initial_range=0.05)
    table = SparseTable(tconf, seed=0)
    W = tconf.row_width

    # -- the three scenarios over ONE table --------------------------------- #
    specs = [
        ScenarioSpec(
            "feed", CtrDnn(S, W, dense_dim=DENSE, hidden=(32, 16)),
            trainer_conf=TrainerConfig(dense_lr=3e-3, auc_buckets=1 << 12),
            seed=1,
        ),
        ScenarioSpec(
            "cvr", WideDeep(S, W, dense_dim=DENSE, hidden=(16,)),
            slot_mask=(0, 1, 2),       # slot 3 is item-only: absent here
            create_threshold=0.0,      # pull-time admission override
            trainer_conf=TrainerConfig(dense_lr=3e-3, auc_buckets=1 << 12),
            seed=2,
        ),
        ScenarioSpec(
            "retrieval",
            TwoTower(S, W, item_slots=(3,), dense_dim=DENSE,
                     hidden=(32, 16), temperature=0.05),
            kind="retrieval",
            trainer_conf=TrainerConfig(dense_lr=3e-3, auc_buckets=1 << 12),
            seed=3,
        ),
    ]
    mst = MultiScenarioTrainer(tconf, specs)

    datasets = {}
    for name in mst.scenario_names():
        ds = PadBoxSlotDataset(conf, read_threads=2)
        ds.set_filelist(files)
        ds.load_into_memory()
        datasets[name] = ds

    for p in range(args.passes):
        res = mst.train_pass(datasets, table)
        line = "  ".join(
            f"{n}: auc={m.get('auc', 0):.3f} loss={m['loss']:.3f}"
            for n, m in res.items()
        )
        print(f"[pass {p}] {line}")
    for ds in datasets.values():
        ds.close()

    # -- retrieval serving: ANN artifact -> /retrieve ------------------------ #
    ann_root = os.path.join(work, "publish-ann")
    pub = Publisher(ann_root, staging_dir=os.path.join(work, "stage-ann"))
    lo, hi = 3 * VOCAB + 1, 4 * VOCAB  # slot 3 owns this key range
    pub.publish_ann_base("r0", table, item_key_lo=lo, item_key_hi=hi,
                         meta={"scenario": "retrieval"})

    server = ScoringServer()
    # per-scenario serving policy: tight deadline, no linger for retrieval
    server.set_serving_policy("retrieval", ScenarioServingConfig(
        name="retrieval", deadline_ms=150.0, batch_linger_ms=0.0,
    ))
    syn_r = Syncer(ann_root, server, "retrieval",
                   cache_dir=os.path.join(work, "cache-ann"),
                   poll_interval_s=0.1)
    syn_r.poll_once()
    port = server.start(port=0)

    q = np.random.default_rng(5).normal(size=(2, tconf.embedding_dim))
    req = urllib.request.Request(
        f"http://127.0.0.1:{port}/retrieve/retrieval",
        data=json.dumps({"queries": q.tolist(), "k": 5,
                         "tier": "int8"}).encode(),
        method="POST")
    with urllib.request.urlopen(req, timeout=30) as r:
        out = json.loads(r.read())
    print(f"[retrieve] top-5 item keys for query 0: "
          f"{out['results'][0]['keys']} (tier={out['tier']}, "
          f"{out['n_items']} items)")

    # -- feed scenario goes online: streaming plane, own deadline ------------ #
    from paddlebox_tpu.streaming import (
        DeadlinePublishPolicy,
        MiniPassScheduler,
        StreamingTrainer,
        TailingFileSource,
    )

    feed_root = os.path.join(work, "publish-feed")
    feed_pub = Publisher(feed_root,
                         staging_dir=os.path.join(work, "stage-feed"))
    feed_tr = mst.trainers["feed"]
    kcap = B * conf.max_feasigns_per_ins
    feed_pub.publish_base("base", feed_tr.model, feed_tr.params, table,
                          lineage="feed-warm", batch_size=B,
                          key_capacity=kcap, dense_dim=DENSE, feed_conf=conf)
    syn_f = Syncer(feed_root, server, "feed",
                   cache_dir=os.path.join(work, "cache-feed"),
                   poll_interval_s=0.1)
    syn_f.poll_once()
    syn_f.start()

    stream = os.path.join(work, "stream")
    os.makedirs(stream)
    source = TailingFileSource(stream, poll_interval_s=0.02)
    sched = MiniPassScheduler(source, conf, window_records=2 * B,
                              window_seconds=0.5)
    # the scenario name IS the publish tag prefix: every delta this plane
    # ships is attributable to the feed scenario in the donefile
    policy = DeadlinePublishPolicy(feed_pub, args.staleness,
                                   scheduler=sched, tag_prefix="feed")
    runner = StreamingTrainer(
        feed_tr, table, sched, policy=policy, model=feed_tr.model,
        served_seq_fn=lambda: (server.model_version("feed") or {}).get("seq"),
    )
    source.start()
    sched.start()

    def writer():
        rng = np.random.default_rng(1)
        t0 = time.monotonic()
        with open(os.path.join(stream, "part-000"), "w", buffering=1) as fh:
            while time.monotonic() - t0 < args.stream_seconds:
                fh.write(stream_line(rng, 1, n_sparse_slots=S,
                                     dense_dim=DENSE,
                                     hot_keys=(5, 1005, 2005, 3005)))
                time.sleep(1 / 300.0)
        runner.stop()

    threading.Thread(target=writer, daemon=True).start()
    summary = runner.run()
    fresh = summary.get("last_freshness_s")
    print(f"[stream] feed scenario online: {summary['windows']} windows, "
          f"{summary['publishes']} publishes, last freshness "
          f"{fresh and round(fresh, 2)}s (budget {args.staleness}s)")

    syn_f.stop()
    server.stop()
    print("workdir:", work)


if __name__ == "__main__":
    main()
