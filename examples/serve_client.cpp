// Minimal non-Python serving client for the CTR scoring endpoint.
//
// The reference ships C/Go/R inference clients next to its
// AnalysisPredictor stack (/root/reference/paddle/fluid/inference/,
// goapi/, capi/); here serving is an HTTP endpoint over the StableHLO
// artifact (examples/serve_ctr.py + inference/predictor.py), so a client
// in any language is a few dozen lines of socket code.  This one POSTs
// canonical slot-text lines to /score and prints the returned JSON.
//
// Build:  g++ -O2 -o serve_client examples/serve_client.cpp
// Usage:  ./serve_client <host> <port> < lines.txt
//         (lines = the same slot text the trainer parses:
//          "<n> v1..vn" per slot in config order)

#include <arpa/inet.h>
#include <csignal>
#include <netdb.h>
#include <sys/socket.h>
#include <unistd.h>

#include <cstdio>
#include <cstring>
#include <iostream>
#include <sstream>
#include <string>

static int dial(const char* host, const char* port) {
  addrinfo hints{}, *res = nullptr;
  hints.ai_family = AF_UNSPEC;
  hints.ai_socktype = SOCK_STREAM;
  if (getaddrinfo(host, port, &hints, &res) != 0) return -1;
  int fd = -1;
  for (addrinfo* p = res; p; p = p->ai_next) {
    fd = socket(p->ai_family, p->ai_socktype, p->ai_protocol);
    if (fd < 0) continue;
    if (connect(fd, p->ai_addr, p->ai_addrlen) == 0) break;
    close(fd);
    fd = -1;
  }
  freeaddrinfo(res);
  return fd;
}

static bool send_all(int fd, const std::string& s) {
  size_t off = 0;
  while (off < s.size()) {
    ssize_t n = write(fd, s.data() + off, s.size() - off);
    if (n <= 0) return false;
    off += static_cast<size_t>(n);
  }
  return true;
}

int main(int argc, char** argv) {
  // an early server close must surface as the write-error path below, not
  // kill the process silently mid-write
  std::signal(SIGPIPE, SIG_IGN);
  if (argc != 3) {
    std::fprintf(stderr, "usage: %s <host> <port> < slot_lines.txt\n",
                 argv[0]);
    return 2;
  }
  std::ostringstream body_s;
  body_s << std::cin.rdbuf();
  const std::string body = body_s.str();
  if (body.empty()) {
    std::fprintf(stderr, "no input lines on stdin\n");
    return 2;
  }

  int fd = dial(argv[1], argv[2]);
  if (fd < 0) {
    std::perror("connect");
    return 1;
  }
  std::ostringstream req;
  req << "POST /score HTTP/1.1\r\n"
      << "Host: " << argv[1] << "\r\n"
      << "Content-Type: text/plain\r\n"
      << "Content-Length: " << body.size() << "\r\n"
      << "Connection: close\r\n\r\n"
      << body;
  if (!send_all(fd, req.str())) {
    std::perror("write");
    close(fd);
    return 1;
  }

  std::string resp;
  char buf[4096];
  ssize_t n;
  while ((n = read(fd, buf, sizeof buf)) > 0) resp.append(buf, n);
  close(fd);

  const size_t hdr_end = resp.find("\r\n\r\n");
  if (hdr_end == std::string::npos ||
      resp.compare(0, 7, "HTTP/1.") != 0 ||
      resp.find(" 200 ") > 12) {
    std::fprintf(stderr, "bad response:\n%s\n", resp.c_str());
    return 1;
  }
  std::cout << resp.substr(hdr_end + 4) << "\n";
  return 0;
}
