#!/usr/bin/env python
"""End-to-end CTR-DNN training example: the user program the reference's
test_paddlebox_datafeed.py template describes, on this framework.

Runs the full production shape: day loop -> preload/train overlap across
passes -> pass lifecycle -> streaming AUC -> base/delta checkpoints.

    python examples/train_ctr_dnn.py [--multichip] [--days 2] [--passes 3]
"""

import argparse
import os
import sys
import tempfile

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

# this image's sitecustomize forces jax_platforms="axon,cpu" (the real-TPU
# tunnel, a single-client resource reserved for bench.py) over the env var;
# pin CPU before any backend init so the example runs anywhere.  Delete
# these two lines to run on a real TPU deployment.
import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--multichip", action="store_true")
    ap.add_argument("--days", type=int, default=2)
    ap.add_argument("--passes", type=int, default=3, help="passes per day")
    ap.add_argument("--batch-size", type=int, default=256)
    ap.add_argument("--ins-per-pass", type=int, default=4096)
    args = ap.parse_args()

    from paddlebox_tpu.checkpoint import CheckpointManager
    from paddlebox_tpu.config import SparseTableConfig, TrainerConfig
    from paddlebox_tpu.data.dataset import DatasetFactory
    from paddlebox_tpu.data.synth import make_synth_config, write_synth_files
    from paddlebox_tpu.models import CtrDnn

    S, DENSE = 8, 8
    work = tempfile.mkdtemp(prefix="pbox_example_")
    conf = make_synth_config(
        n_sparse_slots=S, dense_dim=DENSE, batch_size=args.batch_size
    )
    tconf = SparseTableConfig(embedding_dim=8)
    trconf = TrainerConfig(auc_buckets=1 << 16)
    model = CtrDnn(S, tconf.row_width, dense_dim=DENSE, hidden=(128, 64))

    if args.multichip:
        from paddlebox_tpu.parallel import (
            MultiChipTrainer,
            ShardedSparseTable,
            make_mesh,
        )

        mesh = make_mesh()
        table = ShardedSparseTable(tconf, mesh)
        trainer = MultiChipTrainer(model, tconf, mesh, trconf)
        print(f"mesh: {mesh.devices.size} devices")
    else:
        from paddlebox_tpu.sparse.table import SparseTable
        from paddlebox_tpu.train.trainer import Trainer

        table = SparseTable(tconf)
        trainer = Trainer(model, tconf, trconf)

    ckpt = CheckpointManager(os.path.join(work, "ckpt"))
    ds = DatasetFactory().create_dataset("BoxPSDataset", conf, read_threads=4)

    # pass p trains while pass p+1 preloads (the reference's double-buffered
    # day pipeline, SURVEY.md §3.4)
    def files_for(day, p):
        return write_synth_files(
            os.path.join(work, f"day{day}-p{p}"), n_files=2,
            ins_per_file=args.ins_per_pass // 2, n_sparse_slots=S,
            vocab_per_slot=5000, dense_dim=DENSE, seed=day * 100 + p,
        )

    for day in range(args.days):
        date = f"202607{20 + day:02d}"
        ds.set_date(date)
        ds.set_filelist(files_for(day, 0))
        ds.preload_into_memory()
        for p in range(args.passes):
            ds.wait_preload_done()  # pass p's data becomes current
            if p + 1 < args.passes:
                # kick off pass p+1's read NOW so it overlaps training
                ds.set_filelist(files_for(day, p + 1))
                ds.preload_into_memory()
            table.begin_pass(ds.unique_keys())
            metrics = trainer.train_from_dataset(ds, table)
            table.end_pass()
            print(
                f"day {date} pass {p}: loss={metrics['loss']:.4f} "
                f"auc={metrics['auc']:.4f} count={metrics['count']:.0f}"
            )
        params, opt = trainer.dense_state()
        if day == 0:
            ckpt.save_base(date, table, params, opt)
        else:
            ckpt.save_delta(date, table, params, opt)
        print(f"day {date}: checkpoint saved, table rows={table.n_features}")
        evicted = table.shrink()
        print(f"day {date}: shrink evicted {evicted} cold features")

    ds.close()
    print("done; artifacts in", work)


if __name__ == "__main__":
    main()
