#!/usr/bin/env python
"""The online model delivery loop, end to end: train → publish → sync →
score, with the server staying up and minutes-fresh the whole time.

This is the serving half of a BandaryGithub/PaddleBox production day
(the reference's xbox base/delta publish + the online PS consuming it):

  pass 0:  publish_base   — full artifact (programs + sparse snapshot)
  pass k:  publish_delta  — rows touched this pass + re-frozen dense
                            programs (KBs/MBs, never the whole table)
  serving: a Syncer follows the donefile and hot-applies each delta into
           the LIVE model between requests — no restart, no reload, and
           scores equal a full export at the same pass bit-for-bit.

    python examples/online_delivery.py [--passes 3]
"""

import argparse
import json
import os
import sys
import tempfile
import urllib.request

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

# this image's sitecustomize forces jax_platforms="axon,cpu" (the real-TPU
# tunnel, a single-client resource) over the env var; the example must run
# anywhere, so pin CPU before any backend init — same guard as day_loop.py
import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--passes", type=int, default=3,
                    help="delta passes to publish after the base")
    args = ap.parse_args()

    from paddlebox_tpu.config import SparseTableConfig, TrainerConfig
    from paddlebox_tpu.data.dataset import PadBoxSlotDataset
    from paddlebox_tpu.data.synth import make_synth_config, write_synth_files
    from paddlebox_tpu.inference import ScoringServer
    from paddlebox_tpu.models import CtrDnn
    from paddlebox_tpu.serving_sync import Publisher, Syncer
    from paddlebox_tpu.sparse.table import SparseTable
    from paddlebox_tpu.train.trainer import Trainer

    S, DENSE, B = 4, 2, 32
    conf = make_synth_config(n_sparse_slots=S, dense_dim=DENSE,
                             batch_size=B, max_feasigns_per_ins=8)
    tconf = SparseTableConfig(embedding_dim=8)
    model = CtrDnn(S, tconf.row_width, dense_dim=DENSE, hidden=(16, 8))
    table = SparseTable(tconf, seed=0)
    trainer = Trainer(model, tconf, TrainerConfig(auc_buckets=1 << 12),
                      seed=0)

    work = tempfile.mkdtemp(prefix="pbox_delivery_")
    root = os.path.join(work, "publish")
    kcap = B * conf.max_feasigns_per_ins

    def train_pass(i):
        files = write_synth_files(
            os.path.join(work, f"d{i}"), n_files=1, ins_per_file=256,
            n_sparse_slots=S, vocab_per_slot=200, dense_dim=DENSE,
            seed=10 + i,
        )
        ds = PadBoxSlotDataset(conf, read_threads=1)
        ds.set_filelist(files)
        ds.load_into_memory()
        table.begin_pass(ds.unique_keys())
        metrics = trainer.train_from_dataset(ds, table)
        table.end_pass()
        ds.close()
        return metrics

    # -- trainer side: base, then the serving plane ------------------------- #
    pub = Publisher(root, staging_dir=os.path.join(work, "staging"))
    m = train_pass(0)
    pub.publish_base("pass0", model, trainer.params, table,
                     lineage="pass0",
                     batch_size=B, key_capacity=kcap, dense_dim=DENSE,
                     feed_conf=conf)
    print(f"pass 0: auc={m['auc']:.4f} -> published base "
          f"({table.n_features} features)")

    # -- serving side: live server + sync agent ----------------------------- #
    server = ScoringServer()
    syncer = Syncer(root, server, "live",
                    cache_dir=os.path.join(work, "cache"),
                    poll_interval_s=0.2)
    syncer.poll_once()
    port = server.start(port=0)
    body = b"1 0 2 7 9 2 11 3 2 5 1 1 8 2 0.5 0.25\n"

    def score():
        req = urllib.request.Request(
            f"http://127.0.0.1:{port}/score/live", data=body, method="POST")
        with urllib.request.urlopen(req, timeout=30) as r:
            return json.loads(r.read())["scores"][0]

    def models():
        with urllib.request.urlopen(
                f"http://127.0.0.1:{port}/models", timeout=30) as r:
            return json.loads(r.read())["models"]["live"]

    print(f"serving on :{port}; first score = {score():.6f}")

    # -- the freshness loop: train, publish a delta, watch it hot-apply ----- #
    for i in range(1, args.passes + 1):
        m = train_pass(i)
        entry = pub.publish_delta(f"pass{i}", table, model,
                                  trainer.params, lineage=f"pass{i}")
        applied = syncer.poll_once()  # in production the agent thread polls
        info = models()
        print(
            f"pass {i}: auc={m['auc']:.4f} -> delta {entry.n_rows} rows "
            f"(applied {applied}); live = base {info['base_tag']} + "
            f"{info['deltas_applied']} deltas, age "
            f"{info['age_seconds']:.1f}s; score = {score():.6f}"
        )

    server.stop()
    print("delivery loop done;", work)


if __name__ == "__main__":
    main()
